"""Workload controllers + garbage collector — the kube-controller-manager
analog (SURVEY.md §2.3: "each controller = informer→workqueue→sync loop").

Representative set per the reference's pkg/controller/*:

  ReplicaSetController   replica_set.go — syncReplicaSet/manageReplicas:
                         diff desired vs actual owned pods, create/delete
  DeploymentController   deployment/ — rollout via template-hashed ReplicaSets
                         (RollingUpdate with maxSurge/maxUnavailable)
  JobController          job/ — run pods to completion (completions/parallelism)
  GarbageCollector       garbagecollector/ — cascading delete of orphans whose
                         controller ownerReference points at a vanished owner

The workqueue is collapsed to a full reconcile pass per tick() — the same
level-triggered semantics (sync is idempotent, diff-driven), minus the
per-key scheduling, which only matters for fairness at scale.
"""

from __future__ import annotations

import copy as copy_module
import hashlib
import itertools
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from .store import ClusterStore, _key_of


def _is_finished(pod: t.Pod) -> bool:
    return pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED)


def _is_ready(pod: t.Pod) -> bool:
    """Bound, running ("" phase = harness objects without lifecycle), and
    passing its readiness probe — the Ready CONDITION, which gates ordered
    StatefulSet rollout and RS/DS ready counts in the reference, not just
    the phase."""
    return (
        bool(pod.node_name)
        and pod.phase in ("", t.PHASE_RUNNING)
        and pod.ready
    )


def _controller_of(pod: t.Pod) -> Optional[t.OwnerReference]:
    for ref in pod.owner_references:
        if ref.controller:
            return ref
    return None


def _stamp(template: t.Pod, name: str, namespace: str, owner: t.OwnerReference) -> t.Pod:
    import copy

    q = copy.copy(template)
    q.name = name
    q.namespace = namespace
    q.node_name = ""
    q.phase = t.PHASE_PENDING
    q.owner_references = (owner,)
    q.uid = f"{namespace}/{name}"
    q.labels = dict(template.labels)
    return q


class ReplicaSetController:
    """replica_set.go — syncReplicaSet: adopt matching orphans, then
    manageReplicas (create the shortfall / delete the excess, preferring
    pending and unready pods for deletion — getPodsToDelete's ranking)."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self._seq = itertools.count()

    def _owned(self, rs: t.ReplicaSet) -> List[t.Pod]:
        out = []
        for pod in self.store.list_pods():
            if pod.namespace != rs.namespace:
                continue
            ctrl = _controller_of(pod)
            if ctrl is not None:
                if ctrl.uid == rs.uid:
                    out.append(pod)
            elif rs.selector is not None and rs.selector.matches(pod.labels):
                # adoption: matching orphan gains the controller ref
                import copy

                q = copy.copy(pod)
                q.owner_references = (
                    t.OwnerReference(kind="ReplicaSet", name=rs.name, uid=rs.uid),
                )
                self.store.update_pod(q)
                out.append(q)
        return out

    def sync(self, rs: t.ReplicaSet) -> None:
        owned = self._owned(rs)
        active = [p for p in owned if not _is_finished(p)]
        diff = rs.replicas - len(active)
        if diff > 0:
            owner = t.OwnerReference(kind="ReplicaSet", name=rs.name, uid=rs.uid)
            for _ in range(diff):
                name = f"{rs.name}-{next(self._seq):05d}"
                self.store.add_pod(
                    _stamp(rs.template or t.Pod(name="x"), name, rs.namespace, owner)
                )
        elif diff < 0:
            # delete excess: pending (unscheduled) first, then unready, then by name
            ranked = sorted(
                active,
                key=lambda p: (bool(p.node_name), _is_ready(p), p.name),
            )
            doomed = ranked[: -rs.replicas] if rs.replicas else ranked
            for p in doomed:
                self.store.delete_pod(p.uid)
            gone = {p.uid for p in doomed}
            active = [p for p in active if p.uid not in gone]
        ready = sum(1 for p in active if _is_ready(p))
        if ready != rs.ready_replicas:
            self.store.update_workload("ReplicaSet", replace(rs, ready_replicas=ready))

    def tick(self) -> None:
        for rs in self.store.list_objects("ReplicaSet"):
            self.sync(rs)


def _template_hash(template: Optional[t.Pod]) -> str:
    """pod-template-hash: stable digest of the rollout-relevant template
    fields (deployment_util.go — ComputeHash)."""
    if template is None:
        return "0"
    h = hashlib.sha256()
    h.update(repr((
        sorted(template.requests.items()),
        sorted(template.labels.items()),
        template.tolerations,
        template.node_selector,
        template.affinity,
        template.topology_spread,
        template.priority,
        template.host_ports,
        template.pvcs,
        template.resource_claims,
        template.scheduling_gates,
        template.images,
        template.run_seconds,
    )).encode())
    return h.hexdigest()[:10]


class DeploymentController:
    """deployment/sync.go — getAllReplicaSetsAndSyncRevision + the rolling
    update loop (rolling.go — reconcileNewReplicaSet/reconcileOldReplicaSets):
    scale the template-hashed new RS up within maxSurge, old RSes down within
    maxUnavailable, delete old RSes once drained."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def sync(self, d: t.Deployment) -> None:
        hash_ = _template_hash(d.template)
        new_name = f"{d.name}-{hash_}"
        mine = [
            rs
            for rs in self.store.list_objects("ReplicaSet")
            if rs.namespace == d.namespace
            and any(r.uid == d.uid for r in rs.owner_references)
        ]
        new_rs = next((rs for rs in mine if rs.name == new_name), None)
        if new_rs is None:
            tmpl = None
            if d.template is not None:
                import copy

                tmpl = copy.copy(d.template)
                tmpl.labels = {**d.template.labels, "pod-template-hash": hash_}
            sel = d.selector or (
                t.LabelSelector.of(**d.template.labels) if d.template else None
            )
            new_rs = t.ReplicaSet(
                name=new_name,
                namespace=d.namespace,
                replicas=0,
                selector=sel,
                template=tmpl,
                owner_references=(
                    t.OwnerReference(kind="Deployment", name=d.name, uid=d.uid),
                ),
            )
            self.store.add_workload("ReplicaSet", new_rs)
        old = [rs for rs in mine if rs.name != new_name]

        total = new_rs.replicas + sum(rs.replicas for rs in old)
        ready_total = new_rs.ready_replicas + sum(rs.ready_replicas for rs in old)
        if new_rs.replicas > d.replicas:
            # the Deployment itself was scaled down: shrink the new RS directly
            self.store.update_workload(
                "ReplicaSet", replace(new_rs, replicas=d.replicas)
            )
        else:
            # scale new RS up within the surge budget
            allowed = d.replicas + d.max_surge - total
            if allowed > 0 and new_rs.replicas < d.replicas:
                grown = min(d.replicas, new_rs.replicas + allowed)
                self.store.update_workload(
                    "ReplicaSet", replace(new_rs, replicas=grown)
                )
        # scale old RSes down within the availability budget
        can_remove = ready_total - (d.replicas - d.max_unavailable)
        for rs in sorted(old, key=lambda r: r.name):
            if can_remove <= 0:
                break
            if rs.replicas > 0:
                drop = min(rs.replicas, can_remove)
                self.store.update_workload(
                    "ReplicaSet", replace(rs, replicas=rs.replicas - drop)
                )
                can_remove -= drop
        for rs in old:
            if rs.replicas == 0 and rs.ready_replicas == 0 and rs.key in self.store.replicasets:
                self.store.delete_workload("ReplicaSet", rs.key)

    def tick(self) -> None:
        for d in self.store.list_objects("Deployment"):
            self.sync(d)


# marker applied to succeeded Job pods once their completion has been added
# to status.succeeded — the finalizer-removal half of job tracking
_COUNTED_MARK = "batch.kubernetes.io/completion-counted"


class JobController:
    """job_controller.go — syncJob: keep min(parallelism, remaining) pods
    active until `completions` pods have succeeded; stamp completionTime when
    done (consumed by the TTL-after-finished controller)."""

    def __init__(self, store: ClusterStore, clock=None):
        from .queue import Clock

        self.store = store
        self.clock = clock or Clock()
        self._seq = itertools.count()

    def sync(self, job: t.Job) -> None:
        if job.completion_time >= 0:
            # Job status is authoritative once complete (the reference never
            # un-completes a Job): PodGC may have deleted the succeeded pods,
            # and recounting them would respawn the whole workload.
            return
        owned = [
            p
            for p in self.store.list_pods()
            if p.namespace == job.namespace
            and any(r.uid == job.uid for r in p.owner_references)
        ]
        # once-only completion accounting (the reference's finalizer-based
        # job tracking): each succeeded pod increments status.succeeded
        # exactly once and is then marked, so PodGC deleting it later can
        # never lose (or double-count) a completion
        fresh = [
            p
            for p in owned
            if p.phase == t.PHASE_SUCCEEDED and _COUNTED_MARK not in p.labels
        ]
        for p in fresh:
            q = copy_module.copy(p)
            q.labels = {**p.labels, _COUNTED_MARK: "true"}
            self.store.update_pod_status(q)
        succeeded = job.succeeded + len(fresh)
        active = [p for p in owned if not _is_finished(p)]
        want_active = min(job.parallelism, max(0, job.completions - succeeded))
        owner = t.OwnerReference(kind="Job", name=job.name, uid=job.uid)
        for _ in range(want_active - len(active)):
            name = f"{job.name}-{next(self._seq):05d}"
            tmpl = job.template or t.Pod(name="x", run_seconds=1.0)
            self.store.add_pod(_stamp(tmpl, name, job.namespace, owner))
        for p in active[want_active:] if want_active < len(active) else []:
            self.store.delete_pod(p.uid)
        done_now = succeeded >= job.completions and job.completion_time < 0
        if succeeded != job.succeeded or len(active) != job.active or done_now:
            self.store.update_workload(
                "Job",
                replace(
                    job,
                    succeeded=succeeded,
                    active=len(active),
                    completion_time=(
                        self.clock.now() if done_now else job.completion_time
                    ),
                ),
            )

    def tick(self) -> None:
        for job in self.store.list_objects("Job"):
            self.sync(job)


class ExpandController:
    """pkg/controller/volume/expand — expand_controller.go: a BOUND claim
    whose request grew past its volume's capacity is resized, provided its
    StorageClass allows expansion (allowVolumeExpansion).  The reference
    calls the CSI driver and leaves filesystem resize to the kubelet; the
    hollow trade collapses both into the PV capacity update (copy-on-write
    so watchers and the delta encoder see a fresh object).  Shrinking is
    never performed — the reference rejects it at validation."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def tick(self) -> None:
        classes = {
            sc.name: sc
            for sc in (self.store.list_objects("StorageClass") if "StorageClass" in self.store.objects else ())
        }
        for pvc in self.store.list_pvcs():
            if not pvc.volume_name:
                continue
            pv = self.store.pvs.get(pvc.volume_name)
            if pv is None or pv.claim_ref != pvc.key:
                continue  # not actually BOUND to this claim (phase gate)
            if pvc.request <= pv.capacity:
                continue
            sc = classes.get(pvc.storage_class)
            if sc is None or not sc.allow_volume_expansion:
                continue
            self.store.update_pv(replace(pv, capacity=pvc.request))


class GarbageCollector:
    """garbagecollector/ — the dependency graph reduced to one cascading rule:
    an object whose controller ownerReference names a vanished uid is deleted.
    Covers Deployment→ReplicaSet→Pod and Job→Pod chains transitively (a pass
    per level; tick until quiescent for full cascades)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def _live_uids(self) -> set:
        live = set()
        # one lock-consistent pass: the object tables, pods and nodes all
        # mutate in place under the store lock while other components run
        with self.store.transaction():
            for table in self.store.objects.values():
                for obj in table.values():
                    uid = getattr(obj, "uid", "")
                    if uid:
                        live.add(uid)
            # pods and nodes can own objects too (EndpointSlice<-Service is
            # the common case, but Pod- and Node-owned objects exist in the
            # reference)
            for pod in self.store.pods.values():
                live.add(pod.uid)
            for name in self.store.nodes:
                live.add(f"node/{name}")
        return live

    def tick(self) -> int:
        """One pass; returns number of objects deleted.  Covers every
        registered kind (CRDs included) whose objects carry owner_references,
        then pods — the reference GC's dependency graph walks all GVRs the
        same way (garbagecollector/graph_builder.go monitors every
        deletable resource)."""
        deleted = 0
        live = self._live_uids()
        with self.store.transaction():
            tables = {
                kind: list(table.values())
                for kind, table in self.store.objects.items()
            }
        for kind, objs in tables.items():
            for obj in objs:
                refs = getattr(obj, "owner_references", ())
                ctrl = next((r for r in refs if r.controller), None)
                if ctrl is not None and ctrl.uid not in live:
                    self.store.delete_object(kind, _key_of(obj))
                    deleted += 1
        live = self._live_uids()
        for pod in self.store.list_pods():
            ctrl = _controller_of(pod)
            if ctrl is not None and ctrl.uid not in live:
                self.store.delete_pod(pod.uid)
                deleted += 1
        return deleted


class StatefulSetController:
    """statefulset/stateful_set_control.go — UpdateStatefulSet: stable ordinal
    identities `name-0 .. name-N-1`.  OrderedReady (default): ordinal i is
    created only after 0..i-1 are ready, and scale-down removes the highest
    ordinal one at a time; Parallel creates/deletes all at once."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def _pod_name(self, sts, ordinal: int) -> str:
        return f"{sts.name}-{ordinal}"

    def sync(self, sts) -> None:
        owner = t.OwnerReference(kind="StatefulSet", name=sts.name, uid=sts.uid)
        by_ordinal: Dict[int, t.Pod] = {}
        for pod in self.store.list_pods():
            if pod.namespace == sts.namespace and any(
                r.uid == sts.uid for r in pod.owner_references
            ):
                try:
                    by_ordinal[int(pod.name.rsplit("-", 1)[1])] = pod
                except (IndexError, ValueError):
                    pass
        # a finished pod never becomes ready again: delete it and treat the
        # ordinal as vacant so it is recreated under the same identity
        for o, pod in list(by_ordinal.items()):
            if _is_finished(pod):
                self.store.delete_pod(pod.uid)
                del by_ordinal[o]
        ordered = sts.pod_management_policy == "OrderedReady"
        # create missing ordinals (in order; gate on predecessor readiness)
        for i in range(sts.replicas):
            if i in by_ordinal:
                if ordered and not _is_ready(by_ordinal[i]):
                    break  # wait for this ordinal before touching later ones
                continue
            tmpl = sts.template or t.Pod(name="x")
            pod = _stamp(tmpl, self._pod_name(sts, i), sts.namespace, owner)
            self.store.add_pod(pod)
            if ordered:
                break  # one at a time
        # delete excess ordinals: highest first, one per round when ordered
        excess = sorted((o for o in by_ordinal if o >= sts.replicas), reverse=True)
        for o in excess if not ordered else excess[:1]:
            self.store.delete_pod(by_ordinal[o].uid)
        ready = sum(
            1 for o, p in by_ordinal.items() if o < sts.replicas and _is_ready(p)
        )
        if ready != sts.ready_replicas:
            self.store.update_object("StatefulSet", replace(sts, ready_replicas=ready))

    def tick(self) -> None:
        for sts in self.store.list_objects("StatefulSet"):
            self.sync(sts)


class DaemonSetController:
    """daemon/daemon_controller.go — syncDaemonSet: one pod per eligible node.
    Since 1.12 daemon pods go through the default scheduler, pinned with a
    nodeAffinity on metadata.name (here: the kubernetes.io/hostname label the
    Node carries) — NodeShouldRunDaemonPod reduced to unschedulable/taint
    checks against the template's tolerations."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def _eligible(self, ds, node: t.Node) -> bool:
        tmpl = ds.template or t.Pod(name="x")
        if node.unschedulable:
            # daemon pods tolerate unschedulable only if template says so
            if not any(
                tol.key == "node.kubernetes.io/unschedulable" for tol in tmpl.tolerations
            ):
                return False
        for taint in node.taints:
            # NoSchedule AND NoExecute are both hard filters in this
            # framework's scheduler (ops/filters.py), so both gate eligibility
            if taint.effect == t.PREFER_NO_SCHEDULE:
                continue
            if not any(tol.tolerates(taint) for tol in tmpl.tolerations):
                return False
        return True

    def sync(self, ds) -> None:
        owner = t.OwnerReference(kind="DaemonSet", name=ds.name, uid=ds.uid)
        have: Dict[str, t.Pod] = {}
        for pod in self.store.list_pods():
            if pod.namespace == ds.namespace and any(
                r.uid == ds.uid for r in pod.owner_references
            ):
                if _is_finished(pod):
                    # daemon pods must run for the node's lifetime: a
                    # Succeeded/Failed daemon pod is deleted and recreated
                    # (daemon_controller.go treats failed pods this way)
                    self.store.delete_pod(pod.uid)
                    continue
                target = pod.node_name or _pinned_node(pod)
                if target:
                    have[target] = pod
        want = {
            node.name for node in self.store.list_nodes()
            if self._eligible(ds, node)
        }
        for name in sorted(want - set(have)):
            tmpl = ds.template or t.Pod(name="x")
            pod = _stamp(tmpl, f"{ds.name}-{name}", ds.namespace, owner)
            # pin via required node affinity on the hostname label (the
            # scheduler still runs filters — resources, ports, etc.)
            pod.affinity = t.Affinity(
                required_node_terms=(
                    t.NodeSelectorTerm(
                        match_expressions=(
                            t.NodeSelectorRequirement(
                                key=t.LABEL_HOSTNAME, operator=t.OP_IN, values=(name,)
                            ),
                        )
                    ),
                )
            )
            self.store.add_pod(pod)
        for name in set(have) - want:
            self.store.delete_pod(have[name].uid)
        ready = sum(1 for n, p in have.items() if n in want and _is_ready(p))
        if ds.desired_number_scheduled != len(want) or ds.number_ready != ready:
            self.store.update_object(
                "DaemonSet",
                replace(ds, desired_number_scheduled=len(want), number_ready=ready),
            )

    def tick(self) -> None:
        for ds in self.store.list_objects("DaemonSet"):
            self.sync(ds)


def _pinned_node(pod: t.Pod) -> str:
    """Node a daemon pod is pinned to via its hostname affinity ("" if none)."""
    if pod.affinity is None:
        return ""
    for term in pod.affinity.required_node_terms:
        for req in term.match_expressions:
            if req.key == t.LABEL_HOSTNAME and req.operator == t.OP_IN and req.values:
                return req.values[0]
    return ""


class CronJobController:
    """cronjob/cronjob_controllerv2.go — syncCronJob: spawn a Job each period;
    concurrencyPolicy Allow (default) / Forbid (skip while one is active) /
    Replace (delete the active one first)."""

    def __init__(self, store: ClusterStore, clock=None):
        from .queue import Clock

        self.store = store
        self.clock = clock or Clock()

    def sync(self, cj) -> None:
        if cj.suspend:
            return
        now = self.clock.now()
        last = cj.last_schedule_time
        if last >= 0 and now - last < cj.period_seconds:
            return
        active = [
            j
            for j in self.store.list_objects("Job")
            if j.namespace == cj.namespace
            and any(r.uid == cj.uid for r in j.owner_references)
            and not j.complete
        ]
        if active:
            if cj.concurrency_policy == "Forbid":
                # missed run skipped entirely (not queued for catch-up)
                self.store.update_object(
                    "CronJob", replace(cj, last_schedule_time=now)
                )
                return
            if cj.concurrency_policy == "Replace":
                for j in active:
                    self.store.delete_object("Job", j.key)
        seq = int(now // max(cj.period_seconds, 1e-9))
        job = t.Job(
            name=f"{cj.name}-{seq}",
            namespace=cj.namespace,
            completions=cj.completions,
            parallelism=cj.parallelism,
            template=cj.job_template,
            owner_references=(
                t.OwnerReference(kind="CronJob", name=cj.name, uid=cj.uid),
            ),
        )
        if job.key not in self.store.jobs:
            self.store.add_object("Job", job)
        self.store.update_object("CronJob", replace(cj, last_schedule_time=now))

    def tick(self) -> None:
        for cj in self.store.list_objects("CronJob"):
            self.sync(cj)


class HPAController:
    """podautoscaler/horizontal.go + replica_calculator.go — the core ratio
    rule: desired = ceil(current * metricValue / target), no-op inside the
    tolerance band, clamped to [min,max]; scales the target Deployment."""

    def __init__(self, store: ClusterStore, metrics=None):
        # metrics(namespace, pods) -> average metric value per pod; pods is
        # the target's current pod list (the metrics-server role)
        self.store = store
        self.metrics = metrics

    def sync(self, hpa) -> None:
        if self.metrics is None or hpa.target_kind != "Deployment":
            return
        d = self.store.get_object("Deployment", f"{hpa.namespace}/{hpa.target_name}")
        if d is None:
            return
        pods = [
            p
            for p in self.store.list_pods()
            if p.namespace == hpa.namespace
            and d.selector is not None
            and d.selector.matches(p.labels)
            and not _is_finished(p)
        ]
        # ratio applies to the scale subresource's spec.replicas (the
        # reference's currentReplicas), NOT the observed pod count — pods only
        # feed the metric average (replica_calculator.go GetMetricReplicas)
        current = d.replicas
        if current == 0 or not pods:
            return
        value = self.metrics(hpa.namespace, pods)
        ratio = value / hpa.target_value if hpa.target_value else 1.0
        desired = current
        if abs(ratio - 1.0) > hpa.tolerance:
            import math

            desired = math.ceil(current * ratio)
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        if desired != d.replicas:
            self.store.update_object("Deployment", replace(d, replicas=desired))
        if hpa.current_replicas != current or hpa.desired_replicas != desired:
            self.store.update_object(
                "HorizontalPodAutoscaler",
                replace(hpa, current_replicas=current, desired_replicas=desired),
            )

    def tick(self) -> None:
        for hpa in self.store.list_objects("HorizontalPodAutoscaler"):
            self.sync(hpa)


class NamespaceController:
    """namespace/namespace_controller.go — a Terminating namespace drains:
    delete every object in it across all kinds, then remove the namespace
    (the deletion finalizer's syncNamespaceFromKey)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def tick(self) -> None:
        for ns in self.store.list_objects("Namespace"):
            if ns.phase != "Terminating":
                continue
            remaining = 0
            for pod in self.store.list_pods():
                if pod.namespace == ns.name:
                    self.store.delete_pod(pod.uid)
                    remaining += 1
            for pdb in self.store.list_pdbs():
                if pdb.namespace == ns.name:
                    self.store.delete_pdb(pdb.key)
                    remaining += 1
            for pvc in self.store.list_pvcs():
                if pvc.namespace == ns.name:
                    self.store.delete_pvc(pvc.key)
                    remaining += 1
            with self.store.transaction():
                tables = {
                    kind: list(table.values())
                    for kind, table in self.store.objects.items()
                }
            for kind, objs in tables.items():
                if kind == "Namespace":
                    continue
                for obj in objs:
                    if getattr(obj, "namespace", None) == ns.name:
                        self.store.delete_object(kind, _key_of(obj))
                        remaining += 1
            if remaining == 0:
                self.store.delete_object("Namespace", ns.name)


class PodGCController:
    """podgc/gc_controller.go — three sweeps: orphaned pods bound to vanished
    nodes (force-deleted), unscheduled terminating pods, and terminated pods
    beyond the --terminated-pod-gc-threshold (oldest first)."""

    def __init__(self, store: ClusterStore, terminated_threshold: int = 12500):
        self.store = store
        self.terminated_threshold = terminated_threshold

    def tick(self) -> int:
        deleted = 0
        for pod in self.store.list_pods():
            if pod.node_name and pod.node_name not in self.store.nodes:
                self.store.delete_pod(pod.uid)
                deleted += 1
        finished = sorted(
            (p for p in self.store.list_pods() if _is_finished(p)),
            # oldest first by finish time (stamped by the kubelet; untimed
            # pods sort first = oldest), uid as the deterministic tie-break
            key=lambda p: (p.finished_at, p.uid),
        )
        for pod in finished[: max(0, len(finished) - self.terminated_threshold)]:
            self.store.delete_pod(pod.uid)
            deleted += 1
        return deleted


class TTLAfterFinishedController:
    """ttlafterfinished/ttlafterfinished_controller.go — delete Jobs whose
    ttlSecondsAfterFinished has elapsed since completion (pods cascade via GC)."""

    def __init__(self, store: ClusterStore, clock=None):
        from .queue import Clock

        self.store = store
        self.clock = clock or Clock()

    def tick(self) -> None:
        now = self.clock.now()
        for job in self.store.list_objects("Job"):
            if (
                job.ttl_seconds_after_finished is not None
                and job.completion_time >= 0
                and now - job.completion_time >= job.ttl_seconds_after_finished
            ):
                self.store.delete_object("Job", job.key)


class NodeIPAMController:
    """pkg/controller/nodeipam (range_allocator.go): assign each node a
    disjoint /24 from the cluster CIDR as spec.podCIDR; freed ranges are
    reused lowest-first when nodes go away."""

    def __init__(self, store: ClusterStore, cluster_prefix: str = "10.128"):
        self.store = store
        self.cluster_prefix = cluster_prefix  # /16 carved into /24s

    def tick(self) -> None:
        used = set()
        for nd in self.store.list_nodes():
            if nd.pod_cidr.startswith(self.cluster_prefix + "."):
                try:
                    used.add(int(nd.pod_cidr.split(".")[2]))
                except (IndexError, ValueError):
                    pass
        free = (i for i in range(256) if i not in used)
        for nd in sorted(self.store.list_nodes(), key=lambda n: n.name):
            if nd.pod_cidr:
                continue
            idx = next(free, None)
            if idx is None:
                return  # cluster CIDR exhausted
            q = copy_module.copy(nd)
            q.pod_cidr = f"{self.cluster_prefix}.{idx}.0/24"
            self.store.update_node(q)
            used.add(idx)


class ServiceAccountController:
    """pkg/controller/serviceaccount — serviceaccounts_controller (ensure the
    "default" ServiceAccount exists in every active namespace) fused with the
    token controller (tokens_controller: mint a bearer token per SA and
    register it with the authenticator; the token Secret is collapsed onto
    the SA object)."""

    def __init__(self, store: ClusterStore, authenticator=None):
        from ..api import cluster as c

        self._c = c
        self.store = store
        self.authn = authenticator
        self._minted: Dict[str, str] = {}  # SA key -> token
        self._mint_seq = itertools.count()

    def tick(self) -> None:
        c = self._c
        # revocation FIRST, against last tick's state: an SA that vanished —
        # or was deleted AND recreated between ticks (its live token field no
        # longer matches the minted credential) — loses the old credential
        for key, token in list(self._minted.items()):
            cur = self.store.get_object("ServiceAccount", key)
            if cur is None or cur.token != token:
                if self.authn is not None:
                    self.authn.remove_token(token)
                del self._minted[key]
        namespaces = {"default"} | {
            ns.name
            for ns in self.store.list_objects("Namespace")
            if ns.phase == "Active"
        }
        for ns in sorted(namespaces):
            if self.store.get_object("ServiceAccount", f"{ns}/default") is None:
                self.store.add_object(
                    "ServiceAccount", c.ServiceAccount(name="default", namespace=ns)
                )
        for sa in list(self.store.list_objects("ServiceAccount")):
            if sa.token:
                continue
            # nonce keeps a recreated SA from inheriting its predecessor's
            # credential (the reference mints a fresh random Secret)
            nonce = next(self._mint_seq)
            token = (
                f"sa-token-{hashlib.sha1(f'{sa.uid}:{nonce}'.encode()).hexdigest()[:16]}"
            )
            minted = copy_module.copy(sa)
            minted.token = token
            self.store.update_object("ServiceAccount", minted)
            self._minted[sa.key] = token
            if self.authn is not None:
                self.authn.add_token(
                    token,
                    sa.username,
                    groups=(
                        "system:serviceaccounts",
                        f"system:serviceaccounts:{sa.namespace}",
                    ),
                )


class AttachDetachController:
    """pkg/controller/volume/attachdetach — reconciler.go: converge the
    actual attachment state (NodeStatus.VolumesAttached) onto the desired
    state (every PV referenced through the PVCs of a non-finished pod bound
    to the node).  Detach happens when the last using pod leaves; nodes
    whose set is already correct are not touched (a node update would churn
    the delta encoder's identity fingerprints for nothing)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def tick(self) -> None:
        # claimRef -> PV index built once per tick: the steady-state no-op
        # pass must not pay O(pods x PVs) linear rescans
        pv_by_claim = {
            pv.claim_ref: pv.name
            for pv in self.store.list_pvs()
            if pv.claim_ref
        }
        desired: Dict[str, set] = {}
        for pod in self.store.list_pods():
            if not pod.node_name or _is_finished(pod):
                continue
            for claim in pod.pvcs:
                key = f"{pod.namespace}/{claim}"
                pvc = self.store.pvcs.get(key)
                pv = (
                    pvc.volume_name
                    if pvc is not None and pvc.volume_name
                    else pv_by_claim.get(key)
                )
                if pv is not None:
                    desired.setdefault(pod.node_name, set()).add(pv)
        for nd in self.store.list_nodes():
            want = tuple(sorted(desired.get(nd.name, ())))
            if tuple(nd.volumes_attached) != want:
                q = copy_module.copy(nd)
                q.volumes_attached = want
                self.store.update_node(q)


class ResourceClaimController:
    """pkg/controller/resourceclaim/controller.go reduced to the DRA-lite
    model: materialize a generated ResourceClaim per (pod, claim-template
    slot), reserve it for the pod once bound (status.reservedFor), and
    release + delete generated claims when their owner pod finishes or
    disappears (the ownerRef cascade)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    @staticmethod
    def _claim_name(pod: t.Pod, i: int) -> str:
        return f"{pod.name}-claim-{i}"

    def tick(self) -> None:
        from ..api import cluster as c

        live: Dict[str, t.Pod] = {
            p.uid: p for p in self.store.list_pods() if not _is_finished(p)
        }
        wanted = set()
        for pod in live.values():
            for i, ref in enumerate(pod.resource_claims):
                key = f"{pod.namespace}/{self._claim_name(pod, i)}"
                wanted.add(key)
                cur = self.store.get_object("ResourceClaim", key)
                if cur is None:
                    self.store.add_object(
                        "ResourceClaim",
                        c.ResourceClaim(
                            name=self._claim_name(pod, i),
                            namespace=pod.namespace,
                            device_class=ref.device_class,
                            count=ref.count,
                            owner_pod_uid=pod.uid,
                            reserved_for=(pod.uid,) if pod.node_name else (),
                            allocated=bool(pod.node_name),
                        ),
                    )
                elif bool(pod.node_name) != cur.allocated or (
                    (pod.uid in cur.reserved_for) != bool(pod.node_name)
                ):
                    q = copy_module.copy(cur)
                    q.allocated = bool(pod.node_name)
                    q.reserved_for = (pod.uid,) if pod.node_name else ()
                    self.store.update_object("ResourceClaim", q)
        for claim in list(self.store.list_objects("ResourceClaim")):
            if not claim.owner_pod_uid:
                continue  # standalone user claim: not ours to manage
            if claim.key not in wanted:
                # owner gone or finished: release and GC the generated claim
                self.store.delete_object("ResourceClaim", claim.key)


class CertificatesController:
    """pkg/controller/certificates — the approver (approver.go sarApprove
    policy reduced to group membership) + signer (issue status.certificate
    for approved CSRs) + cleaner (certificate_controller's GC of stale
    CSRs after --csr-cleaner-interval; denied/expired requests age out)."""

    AUTO_APPROVE_SIGNERS = (
        "kubernetes.io/kubelet-serving",
        "kubernetes.io/kube-apiserver-client-kubelet",
    )
    TTL_S = 3600.0  # cleaner horizon for denied/issued CSRs

    def __init__(self, store: ClusterStore, clock=None):
        from .queue import Clock

        self.store = store
        self.clock = clock or Clock()  # one clock domain with the siblings
        self._seen: Dict[str, float] = {}  # csr uid -> first-observed time

    def tick(self) -> None:
        now = self.clock.now()
        listed = list(self.store.list_objects("CertificateSigningRequest"))
        # CSRs deleted by anyone else must not leak _seen entries forever
        live = {csr.uid for csr in listed}
        for uid in [u for u in self._seen if u not in live]:
            del self._seen[uid]
        for csr in listed:
            # age runs from first observation; "unset" is tracked separately
            # from the timestamp value (a FakeClock legitimately starts at 0)
            if csr.uid not in self._seen:
                self._seen[csr.uid] = now
                if csr.created_at:
                    self._seen[csr.uid] = csr.created_at
            if csr.status == "Pending":
                q = copy_module.copy(csr)
                ok = csr.signer_name in self.AUTO_APPROVE_SIGNERS and (
                    "system:nodes" in csr.groups
                    or csr.username.startswith("system:node:")
                )
                q.status = "Approved" if ok else "Denied"
                q.created_at = self._seen[csr.uid]
                self.store.update_object("CertificateSigningRequest", q)
                csr = q
            if csr.status == "Approved" and not csr.certificate:
                q = copy_module.copy(csr)
                digest = hashlib.sha1(
                    f"{csr.name}:{csr.username}:{csr.signer_name}".encode()
                ).hexdigest()
                q.certificate = f"-----BEGIN CERTIFICATE-----\n{digest}\n-----END CERTIFICATE-----"
                self.store.update_object("CertificateSigningRequest", q)
                csr = q
            if csr.status in ("Denied", "Approved"):
                if now - self._seen[csr.uid] > self.TTL_S:
                    self.store.delete_object(
                        "CertificateSigningRequest", csr.key
                    )
                    self._seen.pop(csr.uid, None)


class ControllerManager:
    """cmd/kube-controller-manager — runs the controller set; tick() is one
    reconcile round across all of them (deployment before replicaset so a
    rollout's RS scaling lands in the same round; cronjob before job so a
    spawned Job's pods land in the same round; HPA after metrics exist)."""

    def __init__(self, store: ClusterStore, clock=None, metrics=None,
                 authenticator=None):
        from .network import EndpointSliceController

        self.store = store
        self.nodeipam = NodeIPAMController(store)
        self.serviceaccounts = ServiceAccountController(store, authenticator)
        self.deployments = DeploymentController(store)
        self.replicasets = ReplicaSetController(store)
        self.statefulsets = StatefulSetController(store)
        self.daemonsets = DaemonSetController(store)
        self.cronjobs = CronJobController(store, clock=clock)
        self.jobs = JobController(store, clock=clock)
        self.hpa = HPAController(store, metrics=metrics)
        self.endpointslices = EndpointSliceController(store)
        self.namespaces = NamespaceController(store)
        self.podgc = PodGCController(store)
        self.ttl = TTLAfterFinishedController(store, clock=clock)
        self.attachdetach = AttachDetachController(store)
        self.expand = ExpandController(store)
        self.resourceclaims = ResourceClaimController(store)
        self.certificates = CertificatesController(store, clock=clock)
        self.gc = GarbageCollector(store)

    def tick(self) -> None:
        self.nodeipam.tick()
        self.serviceaccounts.tick()
        self.hpa.tick()
        self.deployments.tick()
        self.replicasets.tick()
        self.statefulsets.tick()
        self.daemonsets.tick()
        self.cronjobs.tick()
        self.jobs.tick()
        self.endpointslices.tick()
        self.namespaces.tick()
        self.podgc.tick()
        self.ttl.tick()
        self.attachdetach.tick()
        self.expand.tick()
        self.resourceclaims.tick()
        self.certificates.tick()
        self.gc.tick()

    def tick_until_quiescent(self, max_rounds: int = 20) -> None:
        for _ in range(max_rounds):
            before = self.store._rv
            self.tick()
            if self.store._rv == before:
                return
