"""Phase tracing — spans over the scheduling cycle.

reference: component-base/tracing (OpenTelemetry spans in apiserver/kubelet;
SURVEY.md §5 notes the scheduler itself is metrics-first with per-extension-
point histograms).  Here: lightweight spans feeding the Metrics histograms
(<phase>_duration_seconds), plus an optional jax.profiler bridge so a bench
run can emit a real XLA trace for profile-guided work.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from .metrics import Metrics


class Tracer:
    def __init__(self, metrics: Optional[Metrics] = None):
        self.metrics = metrics or Metrics()

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.metrics.observe(f"{name}_duration_seconds", time.perf_counter() - t0)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace (TensorBoard-compatible) around a region — the
    jax-native analog of the reference's pprof endpoints."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
