"""End-to-end span tracing — per-pod trace trees across the control plane.

reference: component-base/tracing (OpenTelemetry spans in the apiserver and
kubelet; SURVEY.md §5 notes the scheduler itself is metrics-first with
per-extension-point histograms).  This module is the in-process analog of
that layer plus the distributed-trace propagation the reference delegates to
the OTel SDK:

  Span            trace_id / span_id / parent_id / attributes / events over
                  a perf_counter interval, tagged with the emitting component
                  (apiserver, queue, scheduler, kubelet, bench).
  TraceCollector  thread-safe ring of finished spans + the pod-context table
                  (uid -> latest SpanContext).  `enabled` is THE hot-path
                  gate, mirroring klog.V(n).enabled: every instrumentation
                  site checks it before allocating anything.
  Tracer          per-component handle: contextvar-based current-span for
                  same-thread parentage, `span_for_pod` for the explicit
                  pod-attached context that follows a pod across the
                  apiserver -> queue -> scheduling cycle -> binding cycle ->
                  kubelet sync boundary (components share no thread, so the
                  contextvar alone cannot carry the trace; the reference
                  threads a Context through the request the same way).

Pod context lives in a uid-keyed table on the collector rather than as an
attribute on the Pod object: pods are shallow-cloned constantly
(types.pod_clone, copy.copy status writes) and a carried attribute would
alternately leak through and vanish across those copies; the uid survives
every clone.

Exporters: `chrome_trace()` emits trace-event JSON loadable in Perfetto /
chrome://tracing (one pid per component, one tid per trace, "X" complete
events in microseconds); `tree_text()` renders parent-child trees for test
assertions.  `device_trace` (unchanged) bridges to jax.profiler for a real
XLA trace alongside the host spans.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import random
import time
from collections import OrderedDict, deque
from contextvars import ContextVar
from typing import Deque, Dict, Iterator, List, NamedTuple, Optional, Tuple, Union
from ..analysis.lockcheck import make_lock


class SpanContext(NamedTuple):
    """The propagatable half of a span (OTel SpanContext)."""

    trace_id: str
    span_id: str


# id generation: a random per-process base + counter is ~20x cheaper than
# uuid4 per span and still unique across the collectors of one process
_rng = random.Random(os.urandom(8))
_ID_BASE = _rng.getrandbits(64)
_id_seq = itertools.count(1)


def _new_id() -> str:
    return f"{(_ID_BASE + next(_id_seq)) & 0xFFFFFFFFFFFFFFFF:016x}"


class Span:
    """One timed operation.  start/end are time.perf_counter() values; the
    exporter rebases them to microseconds."""

    __slots__ = (
        "name", "component", "trace_id", "span_id", "parent_id",
        "start", "end", "attributes", "events",
    )

    def __init__(
        self,
        name: str,
        component: str = "",
        trace_id: str = "",
        parent_id: str = "",
        start: Optional[float] = None,
        attributes: Optional[Dict[str, object]] = None,
    ):
        self.name = name
        self.component = component
        self.trace_id = trace_id or _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start = time.perf_counter() if start is None else start
        self.end: Optional[float] = None
        self.attributes: Dict[str, object] = attributes or {}
        self.events: List[Tuple[float, str, Dict[str, object]]] = []

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None else time.perf_counter()) - self.start

    def add_event(self, name: str, **attrs: object) -> None:
        """Point-in-time annotation (OTel span events)."""
        self.events.append((time.perf_counter(), name, attrs))

    def finish(self, end: Optional[float] = None) -> None:
        self.end = time.perf_counter() if end is None else end

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Span({self.name!r}, component={self.component!r}, "
            f"trace={self.trace_id[:8]}, span={self.span_id[:8]}, "
            f"parent={self.parent_id[:8] if self.parent_id else '-'})"
        )


# same-thread parentage (OTel context API reduced to one contextvar)
_CURRENT: ContextVar[Optional[Span]] = ContextVar("ktpu_current_span", default=None)


def current_span() -> Optional[Span]:
    """The thread/task-local active span (None outside any span).  klog's
    backend reads this to stamp trace_id/span_id onto every entry."""
    return _CURRENT.get()


def current_trace_id() -> str:
    """The active span's trace id ("" outside any span / tracing off) —
    the flight recorder stamps it so a post-mortem record joins back to
    the Perfetto trace of the cycle that produced it."""
    s = _CURRENT.get()
    return s.trace_id if s is not None else ""


class TraceCollector:
    """Thread-safe in-process span ring + pod-context table.

    `enabled` is read un-locked on every hot-path check (a Python bool read
    is atomic); flipping it mid-run only starts/stops NEW spans.  The
    default is ENABLED (the issue's acceptance: tracing is opt-OUT) — span
    cost is ~1-2 µs each at cycle/pod granularity and the ring bounds
    memory; perf-sensitive callers inject TraceCollector(enabled=False)
    (the bench harness does) or flip set_enabled(False).  The scheduler
    detaches a pod's context when its Deleted event arrives, so a
    recreated namespace/name does not chain into the dead pod's trace."""

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 max_pod_contexts: int = 65536):
        self.enabled = enabled
        self._lock = make_lock("TraceCollector._lock")
        self._spans: Deque[Span] = deque(maxlen=capacity)
        # spans silently evicted by the ring wrapping: attribution reports
        # and trace exports read this to FLAG an incomplete trace instead of
        # under-counting phases (scheduler/attribution.py)
        self.spans_dropped: int = 0
        # uid -> latest SpanContext, LRU-bounded: a long-lived process tracing
        # millions of pods must not grow this table without bound
        self._pod_ctx: "OrderedDict[str, SpanContext]" = OrderedDict()
        self._max_pod_contexts = max_pod_contexts

    # -- span sink --
    def add(self, span: Span) -> None:
        with self._lock:
            if (self._spans.maxlen is not None
                    and len(self._spans) == self._spans.maxlen):
                self.spans_dropped += 1
            self._spans.append(span)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._pod_ctx.clear()
            self.spans_dropped = 0

    def spans(self, name: Optional[str] = None,
              trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def traces(self) -> Dict[str, List[Span]]:
        """trace_id -> spans, in arrival order."""
        out: Dict[str, List[Span]] = {}
        for s in self.spans():
            out.setdefault(s.trace_id, []).append(s)
        return out

    # -- pod-context propagation --
    def attach_pod(self, pod_uid: str, ctx: SpanContext) -> None:
        with self._lock:
            self._pod_ctx[pod_uid] = ctx
            self._pod_ctx.move_to_end(pod_uid)
            while len(self._pod_ctx) > self._max_pod_contexts:
                self._pod_ctx.popitem(last=False)

    def pod_context(self, pod_uid: str) -> Optional[SpanContext]:
        with self._lock:
            return self._pod_ctx.get(pod_uid)

    def detach_pod(self, pod_uid: str) -> None:
        with self._lock:
            self._pod_ctx.pop(pod_uid, None)

    # -- exporters --
    def chrome_trace(self) -> Dict:
        """Chrome trace-event JSON (the Perfetto / chrome://tracing format):
        one pid per component, one tid per trace, complete ("X") events in
        microseconds rebased to the earliest span."""
        spans = [s for s in self.spans() if s.end is not None]
        events: List[Dict] = []
        pids: Dict[str, int] = {}
        tids: Dict[str, int] = {}
        t0 = min((s.start for s in spans), default=0.0)
        for s in spans:
            pid = pids.setdefault(s.component or "process", len(pids) + 1)
            tid = tids.setdefault(s.trace_id, len(tids) + 1)
            args = {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
            }
            args.update({k: str(v) for k, v in s.attributes.items()})
            events.append({
                "name": s.name,
                "cat": s.component or "process",
                "ph": "X",
                "ts": round((s.start - t0) * 1e6, 3),
                "dur": round((s.end - s.start) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            })
            for ts, name, attrs in s.events:
                events.append({
                    "name": name,
                    "cat": s.component or "process",
                    "ph": "i",
                    "s": "t",
                    "ts": round((ts - t0) * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": {k: str(v) for k, v in attrs.items()},
                })
        for comp, pid in pids.items():
            events.append({
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": comp},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            # Perfetto ignores otherData; consumers (harness summary line,
            # attribution reports) read it to flag incomplete traces —
            # spans_dropped > 0 means the ring wrapped and phase totals
            # under-count
            "otherData": {
                "spans_dropped": self.spans_dropped,
                "spans_exported": len(spans),
                "capacity": self._spans.maxlen,
            },
        }

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def tree_text(self, trace_id: Optional[str] = None) -> str:
        """Indented parent-child dump for test assertions and debugging."""
        lines: List[str] = []
        for tid, spans in self.traces().items():
            if trace_id is not None and tid != trace_id:
                continue
            by_id = {s.span_id: s for s in spans}
            children: Dict[str, List[Span]] = {}
            roots: List[Span] = []
            for s in spans:
                if s.parent_id and s.parent_id in by_id:
                    children.setdefault(s.parent_id, []).append(s)
                else:
                    roots.append(s)
            lines.append(f"trace {tid}")

            def walk(span: Span, depth: int) -> None:
                dur = f"{span.duration_s * 1e3:.3f}ms"
                lines.append(
                    f"{'  ' * depth}- {span.name} [{span.component}] {dur}"
                )
                for c in sorted(children.get(span.span_id, []),
                                key=lambda s: s.start):
                    walk(c, depth + 1)

            for r in sorted(roots, key=lambda s: s.start):
                walk(r, 1)
        return "\n".join(lines)


_DEFAULT = TraceCollector()


def default_collector() -> TraceCollector:
    """The process-wide collector components fall back to when none is
    injected — the analog of OTel's global TracerProvider."""
    return _DEFAULT


def set_enabled(on: bool) -> None:
    """Flip the process-wide collector.  A pod's trace only connects when
    every component writes to ONE collector, so the supported opt-out modes
    are: this global switch (all defaulted components at once), or injecting
    the SAME explicit collector — enabled or disabled — into every component
    (Scheduler(collector=...), APIServer(tracer=Tracer(col, ...)),
    HollowKubelet(tracer=...)); disabling only the scheduler's collector
    leaves defaulted apiserver/kubelet tracers running on the global one."""
    _DEFAULT.enabled = on


ParentLike = Union[None, Span, SpanContext]


def _resolve_parent(parent: ParentLike) -> Optional[SpanContext]:
    if parent is None:
        return None
    if isinstance(parent, Span):
        return parent.context
    return parent


class Tracer:
    """Per-component span factory over a collector."""

    def __init__(self, collector: Optional[TraceCollector] = None,
                 component: str = ""):
        self.collector = collector if collector is not None else _DEFAULT
        self.component = component

    @property
    def enabled(self) -> bool:
        """The cheap hot-path gate (klog.V(n).enabled shape): callers must
        check this before building span attributes."""
        return self.collector.enabled

    @contextlib.contextmanager
    def span(self, name: str, parent: ParentLike = None,
             **attributes: object) -> Iterator[Optional[Span]]:
        """Timed span; parent = explicit context, else the contextvar's
        current span, else a new trace root.  Yields None when disabled."""
        if not self.collector.enabled:
            yield None
            return
        ctx = _resolve_parent(parent)
        if ctx is None:
            cur = _CURRENT.get()
            if cur is not None:
                ctx = cur.context
        sp = Span(
            name,
            component=self.component,
            trace_id=ctx.trace_id if ctx else "",
            parent_id=ctx.span_id if ctx else "",
            attributes=dict(attributes) if attributes else None,
        )
        token = _CURRENT.set(sp)
        try:
            yield sp
        finally:
            _CURRENT.reset(token)
            sp.finish()
            self.collector.add(sp)

    @contextlib.contextmanager
    def span_for_pod(self, pod_uid: str, name: str,
                     **attributes: object) -> Iterator[Optional[Span]]:
        """Span parented under the pod's attached context (falling back to
        the current span / a new root), re-attaching itself as the pod's
        latest context — the cross-component chain a pod's trace follows."""
        if not self.collector.enabled:
            yield None
            return
        parent = self.collector.pod_context(pod_uid)
        with self.span(name, parent=parent, **attributes) as sp:
            if sp is not None:
                self.collector.attach_pod(pod_uid, sp.context)
            yield sp

    def record_span(self, name: str, start: float, end: Optional[float] = None,
                    parent: ParentLike = None, pod_uid: Optional[str] = None,
                    **attributes: object) -> Optional[Span]:
        """Record an already-elapsed interval (e.g. queue wait measured
        enqueue -> pop) as a finished span.  With pod_uid the span joins and
        re-attaches the pod's context chain."""
        if not self.collector.enabled:
            return None
        ctx = _resolve_parent(parent)
        if ctx is None and pod_uid is not None:
            ctx = self.collector.pod_context(pod_uid)
        if ctx is None:
            cur = _CURRENT.get()
            if cur is not None:
                ctx = cur.context
        sp = Span(
            name,
            component=self.component,
            trace_id=ctx.trace_id if ctx else "",
            parent_id=ctx.span_id if ctx else "",
            start=start,
            attributes=dict(attributes) if attributes else None,
        )
        sp.finish(end)
        self.collector.add(sp)
        if pod_uid is not None:
            self.collector.attach_pod(pod_uid, sp.context)
        return sp


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace (TensorBoard-compatible) around a region — the
    jax-native analog of the reference's pprof endpoints, and the device
    half of a bench round's host-span trace."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def incremental_attrs(hoist_cache) -> dict:
    """Span attributes attributing a kernel step's incremental warm-cycle
    state (ops/incremental.py — HoistCache): whether the resident class
    hoist was hit/patched/rebuilt, the wave's unique-class count, and the
    dirty-node fraction the patch covered — stamped onto the pipeline's
    `device.step` and the scheduler's `batch.kernel` spans so BENCH_r06 can
    attribute the warm-cycle win.  None / unarmed cache -> {}."""
    if hoist_cache is None:
        return {}
    last = getattr(hoist_cache, "last", None)
    if not last or last.get("action") in (None, "none"):
        return {}
    return {
        "hoist_cache": last["action"],
        "unique_classes": last["unique_classes"],
        "dirty_node_fraction": last["dirty_node_fraction"],
        "hoist_cols": last["patched_cols"],
    }


def mesh_attrs(mesh) -> dict:
    """Span attributes identifying the device mesh a kernel step ran on, so
    traces attribute time per route+mesh (stamped onto the pipeline's
    `device.step` and the scheduler's `batch.kernel` spans, and mirrored
    into the bench JSON as `n_shards`).  mesh=None -> the single-device
    path (n_shards 1)."""
    return {"n_shards": int(mesh.size) if mesh is not None else 1}
