"""ClusterStore — the in-process cluster-state hub.

Plays the role the apiserver+etcd pair plays for the reference's scheduler in
scheduler_perf (SURVEY.md §3.5: real apiserver, in-process, nodes as bare API
objects): a strongly-ordered object store with monotonically increasing
resourceVersion and level-triggered watch fan-out (one event stream -> N
subscribers, the cacher pattern from apiserver/pkg/storage/cacher).

Besides the dedicated hot-path tables (nodes, pods, PDBs), the store carries a
**dynamic kind registry**: `register_kind()` creates a new keyed table with
full add/update/delete + watch semantics at runtime.  This is the framework's
CustomResourceDefinition mechanism (the apiextensions-apiserver analog —
reference: staging/src/k8s.io/apiextensions-apiserver serves user-defined
types through the same generic registry.Store the built-ins use); built-in
workload kinds (ReplicaSet, Deployment, Job, ...) are simply pre-registered
kinds in the same tables, exactly as CRDs and built-ins share one storage
layer in the reference.

Single-writer by design (one lock around mutations) — the framework's answer
to the reference's optimistic-concurrency CAS: there is exactly one scheduler
mutating bindings in-process, so CAS degenerates to serialized apply.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..api import types as t
from ..analysis.lockcheck import make_rlock


@dataclass(frozen=True)
class Event:
    kind: str  # Added | Modified | ModifiedStatus | Deleted
    obj_type: str  # Node | Pod | PDB | any registered kind
    obj: object
    resource_version: int
    # previous object on Modified events (QueueingHints compare old vs new —
    # framework/types.go ClusterEvent carries oldObj/newObj the same way)
    old: object = None


# kinds every store starts with (the reference's built-in API groups); more
# arrive via register_kind (the CRD path)
BUILTIN_KINDS = (
    "ReplicaSet",
    "Deployment",
    "Job",
    "StatefulSet",
    "DaemonSet",
    "CronJob",
    "Service",
    "EndpointSlice",
    "Namespace",
    "PriorityClass",
    "ResourceQuota",
    "LimitRange",
    "HorizontalPodAutoscaler",
    "Role",
    "RoleBinding",
    "FlowSchema",
    "PriorityLevelConfiguration",
    "StorageClass",
    "ResourceSlice",
    "DeviceClass",
    "ResourceClaim",
    "CertificateSigningRequest",
    "Event",
    "ServiceAccount",
)


def _key_of(obj) -> str:
    """namespace/name key (metav1 ObjectMeta identity)."""
    key = getattr(obj, "key", None)
    if key is not None:
        return key
    ns = getattr(obj, "namespace", "")
    name = getattr(obj, "name", "")
    return f"{ns}/{name}" if ns else name


class ClusterStore:
    def __init__(self) -> None:
        # re-entrant: watchers are invoked under the lock and may read back
        self._lock = make_rlock("ClusterStore._lock")
        self._rv = 0
        # cluster lineage: uids are deterministic (namespace/name), so a
        # crash-restart checkpoint written against ANOTHER store instance
        # could replay colliding uids into this one.  The checkpoint stamps
        # this id and restore() ignores a lineage mismatch — the analog of
        # the reference checking it is talking to the same cluster before
        # trusting local state.  Stable across restarts (the replacement
        # incarnation reattaches to the SAME store), unique per cluster.
        self.lineage = uuid.uuid4().hex
        self.nodes: Dict[str, t.Node] = {}
        self.pods: Dict[str, t.Pod] = {}  # by uid
        self.pdbs: Dict[str, t.PodDisruptionBudget] = {}  # by namespace/name
        self.pvs: Dict[str, t.PersistentVolume] = {}  # by name
        self.pvcs: Dict[str, t.PersistentVolumeClaim] = {}  # by namespace/name
        # dynamic kind registry: kind -> {key -> obj}
        self.objects: Dict[str, Dict[str, object]] = {k: {} for k in BUILTIN_KINDS}
        self._watchers: List[Callable[[Event], None]] = []

    def transaction(self):
        """The store's re-entrant lock, for callers performing multi-object
        read-modify-write sequences (e.g. volume binding's match-then-commit).
        The reference relies on apiserver optimistic concurrency
        (resourceVersion conflict on racing writers); this in-process analog
        serializes the sequence instead."""
        return self._lock

    # --- CRD mechanism ---
    def register_kind(self, kind: str) -> None:
        """Create a new object table at runtime — the CustomResourceDefinition
        path (apiextensions-apiserver: established CRDs get REST storage wired
        into the same generic registry as built-ins)."""
        with self._lock:
            if kind in ("Node", "Pod", "PDB"):
                raise ValueError(f"{kind} is a dedicated table")
            if kind not in self.objects:
                self.objects[kind] = {}

    def kinds(self) -> List[str]:
        return ["Node", "Pod", "PDB", *self.objects.keys()]

    # --- watch ---
    def watch(self, fn: Callable[[Event], None], replay: bool = True) -> None:
        """Subscribe; replay=True first delivers synthetic Added events for
        current state (the LIST half of LIST+WATCH)."""
        with self._lock:
            if replay:
                for nd in self.nodes.values():
                    fn(Event("Added", "Node", nd, self._rv))
                for p in self.pods.values():
                    fn(Event("Added", "Pod", p, self._rv))
                for pv in self.pvs.values():
                    fn(Event("Added", "PV", pv, self._rv))
                for pvc in self.pvcs.values():
                    fn(Event("Added", "PVC", pvc, self._rv))
            self._watchers.append(fn)

    def unwatch(self, fn: Callable[[Event], None]) -> None:
        """Drop a subscription (watch channel close) — no-op if absent."""
        with self._lock:
            try:
                self._watchers.remove(fn)
            except ValueError:
                pass

    def _emit(self, ev: Event) -> None:
        for fn in self._watchers:
            fn(ev)

    def _bump(self) -> int:
        self._rv += 1
        return self._rv

    # --- nodes ---
    def add_node(self, node: t.Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self._emit(Event("Added", "Node", node, self._bump()))

    def update_node(self, node: t.Node) -> None:
        with self._lock:
            old = self.nodes.get(node.name)
            self.nodes[node.name] = node
            self._emit(Event("Modified", "Node", node, self._bump(), old=old))

    def delete_node(self, name: str) -> None:
        with self._lock:
            nd = self.nodes.pop(name, None)
            if nd is not None:
                self._emit(Event("Deleted", "Node", nd, self._bump()))

    # --- pods ---
    def add_pod(self, pod: t.Pod) -> None:
        with self._lock:
            self.pods[pod.uid] = pod
            self._emit(Event("Added", "Pod", pod, self._bump()))

    def update_pod(self, pod: t.Pod) -> None:
        with self._lock:
            old = self.pods.get(pod.uid)
            self.pods[pod.uid] = pod
            self._emit(Event("Modified", "Pod", pod, self._bump(), old=old))

    def update_pod_status(self, pod: t.Pod) -> None:
        """The pods/{name}/status subresource: status-only writes (e.g.
        nominatedNodeName, phase) — watchers can tell them apart so the
        scheduler's queue does not treat them as spec changes (the reference's
        isPodUpdated check)."""
        with self._lock:
            self.pods[pod.uid] = pod
            self._emit(Event("ModifiedStatus", "Pod", pod, self._bump()))

    def delete_pod(self, uid: str) -> None:
        with self._lock:
            p = self.pods.pop(uid, None)
            if p is not None:
                self._emit(Event("Deleted", "Pod", p, self._bump()))

    # --- generic objects (built-in workload kinds + CRDs) ---
    def _table(self, kind: str) -> Dict[str, object]:
        try:
            return self.objects[kind]
        except KeyError:
            raise KeyError(f"kind {kind!r} not registered (register_kind first)")

    def add_object(self, kind: str, obj) -> None:
        with self._lock:
            self._table(kind)[_key_of(obj)] = obj
            self._emit(Event("Added", kind, obj, self._bump()))

    def update_object(self, kind: str, obj) -> None:
        with self._lock:
            self._table(kind)[_key_of(obj)] = obj
            self._emit(Event("Modified", kind, obj, self._bump()))

    def delete_object(self, kind: str, key: str) -> None:
        with self._lock:
            obj = self._table(kind).pop(key, None)
            if obj is not None:
                self._emit(Event("Deleted", kind, obj, self._bump()))

    def get_object(self, kind: str, key: str):
        with self._lock:
            return self._table(kind).get(key)

    # --- snapshot LISTs (lock-consistent reads for concurrent components) ---
    # The dedicated tables (pods/nodes/pvs/pvcs) mutate IN PLACE under the
    # store lock; a component thread iterating .values() directly races the
    # writers ("dictionary changed size during iteration" under the soak).
    # Controllers, the proxier, kubelets and the apiserver take these
    # snapshots instead — the informer-cache LIST, one lock hold per pass.
    # Point reads (d.get(key)) stay lock-free: atomic under CPython.
    def list_pods(self) -> List[t.Pod]:
        with self._lock:
            return list(self.pods.values())

    def list_nodes(self) -> List[t.Node]:
        with self._lock:
            return list(self.nodes.values())

    def list_pvs(self) -> List[t.PersistentVolume]:
        with self._lock:
            return list(self.pvs.values())

    def list_pvcs(self) -> List[t.PersistentVolumeClaim]:
        with self._lock:
            return list(self.pvcs.values())

    def list_pdbs(self) -> List[t.PodDisruptionBudget]:
        with self._lock:
            return list(self.pdbs.values())

    def list_node_names(self) -> List[str]:
        with self._lock:
            return list(self.nodes)

    def list_objects(self, kind: str, namespace: Optional[str] = None) -> list:
        with self._lock:
            out = list(self._table(kind).values())
        if namespace is not None:
            out = [o for o in out if getattr(o, "namespace", "") == namespace]
        return out

    # --- workload aliases (original controller-facing API) ---
    @property
    def replicasets(self) -> Dict[str, t.ReplicaSet]:
        return self.objects["ReplicaSet"]  # type: ignore[return-value]

    @property
    def deployments(self) -> Dict[str, t.Deployment]:
        return self.objects["Deployment"]  # type: ignore[return-value]

    @property
    def jobs(self) -> Dict[str, t.Job]:
        return self.objects["Job"]  # type: ignore[return-value]

    def add_workload(self, kind: str, obj) -> None:
        self.add_object(kind, obj)

    def update_workload(self, kind: str, obj) -> None:
        self.update_object(kind, obj)

    def delete_workload(self, kind: str, key: str) -> None:
        self.delete_object(kind, key)

    # --- PodDisruptionBudgets (the preemption evaluator's PDB lister) ---
    def add_pdb(self, pdb: t.PodDisruptionBudget) -> None:
        with self._lock:
            self.pdbs[pdb.key] = pdb
            self._emit(Event("Added", "PDB", pdb, self._bump()))

    def update_pdb(self, pdb: t.PodDisruptionBudget) -> None:
        with self._lock:
            self.pdbs[pdb.key] = pdb
            self._emit(Event("Modified", "PDB", pdb, self._bump()))

    def delete_pdb(self, key: str) -> None:
        with self._lock:
            pdb = self.pdbs.pop(key, None)
            if pdb is not None:
                self._emit(Event("Deleted", "PDB", pdb, self._bump()))

    # --- storage objects (PV/PVC — the volumebinding plugin's informers) ---
    def add_pv(self, pv) -> None:
        with self._lock:
            self.pvs[pv.name] = pv
            self._emit(Event("Added", "PV", pv, self._bump()))

    def update_pv(self, pv) -> None:
        with self._lock:
            self.pvs[pv.name] = pv
            self._emit(Event("Modified", "PV", pv, self._bump()))

    def delete_pv(self, name: str) -> None:
        with self._lock:
            pv = self.pvs.pop(name, None)
            if pv is not None:
                self._emit(Event("Deleted", "PV", pv, self._bump()))

    def add_pvc(self, pvc) -> None:
        with self._lock:
            self.pvcs[pvc.key] = pvc
            self._emit(Event("Added", "PVC", pvc, self._bump()))

    def update_pvc(self, pvc) -> None:
        with self._lock:
            self.pvcs[pvc.key] = pvc
            self._emit(Event("Modified", "PVC", pvc, self._bump()))

    def delete_pvc(self, key: str) -> None:
        with self._lock:
            pvc = self.pvcs.pop(key, None)
            if pvc is not None:
                self._emit(Event("Deleted", "PVC", pvc, self._bump()))

    def bind(self, pod_uid: str, node_name: str) -> None:
        """The pods/{name}/binding subresource (defaultbinder's POST)."""
        with self._lock:
            p = self.pods[pod_uid]
            bound = replace_pod_nodename(p, node_name)
            self.pods[pod_uid] = bound
            self._emit(Event("Modified", "Pod", bound, self._bump()))


def replace_pod_nodename(pod: t.Pod, node_name: str) -> t.Pod:
    """Shallow copy with node_name set (types.pod_clone — the one shared
    clone idiom; field objects stay shared per copy-on-write)."""
    return t.pod_clone(pod, node_name=node_name)
