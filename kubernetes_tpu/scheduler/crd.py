"""CustomResourceDefinition machinery — the apiextensions-apiserver analog.

reference: staging/src/k8s.io/apiextensions-apiserver — a CRD object
(customresourcedefinitions.apiextensions.k8s.io) declares group/names/scope
plus a list of VERSIONS, each with a structural OpenAPI v3 schema and
served/storage flags; established CRDs get REST storage wired into the same
generic registry the built-ins use (pkg/apiserver/customresource_handler.go),
every write is validated against the version's structural schema
(pkg/apiserver/validation), and objects persist at the single storage version
(conversion strategy None = field-preserving apiVersion rewrite).

Here: `CRDRegistry` owns the definitions, validates custom objects on
create/update through the APIServer's admission phase, rejects unserved
versions, and rewrites api_version to the storage version — on top of
store.register_kind's dynamic tables (the shared generic-registry layer).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class CRDInvalid(Exception):
    """Definition rejected at create time (apiextensions validation)."""


class CRValidationError(Exception):
    """Custom object rejected by the version's structural schema."""


@dataclass(frozen=True)
class CRDVersion:
    """apiextensions/v1 — CustomResourceDefinitionVersion (reduced)."""

    name: str  # e.g. "v1alpha1"
    served: bool = True
    storage: bool = False
    # reduced structural OpenAPI v3: {"type": "object", "properties": {...},
    # "required": [...]}; nested properties/items/enum/minimum/maximum/
    # pattern-free subset
    schema: Optional[Dict] = None


@dataclass
class CustomResourceDefinition:
    """apiextensions/v1 — CustomResourceDefinition (scheduling-framework
    surface: names, scope, versions; no webhook conversion — strategy None)."""

    group: str
    kind: str
    plural: str
    versions: Tuple[CRDVersion, ...] = ()
    scope: str = "Namespaced"  # or "Cluster"
    namespace: str = ""  # cluster-scoped object itself
    established: bool = False  # status condition, set on successful create

    @property
    def name(self) -> str:
        return f"{self.plural}.{self.group}"

    @property
    def key(self) -> str:
        return self.name

    def storage_version(self) -> str:
        return next(v.name for v in self.versions if v.storage)

    def version(self, name: str) -> Optional[CRDVersion]:
        return next((v for v in self.versions if v.name == name), None)


@dataclass
class CustomResource:
    """A dynamic object instance (unstructured.Unstructured reduced):
    identity + free-form spec dict, validated by the CRD's schema."""

    api_version: str  # "group/version"
    kind: str
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    spec: Dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    # integer checked specially (bool is an int subclass); number accepts both
}


def validate_schema_value(schema: Dict, value, path: str = "spec") -> List[str]:
    """Structural-schema validation (the pkg/apiserver/validation subset):
    type, properties, required, items, enum, minimum/maximum.  Returns a list
    of error strings (empty = valid)."""
    errs: List[str] = []
    ty = schema.get("type")
    if ty == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            return [f"{path}: expected integer, got {type(value).__name__}"]
    elif ty == "number":
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return [f"{path}: expected number, got {type(value).__name__}"]
    elif ty in _TYPES:
        if not isinstance(value, _TYPES[ty]):
            return [f"{path}: expected {ty}, got {type(value).__name__}"]
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{path}: {value!r} not in enum {schema['enum']}")
    if "minimum" in schema and isinstance(value, (int, float)) and value < schema["minimum"]:
        errs.append(f"{path}: {value} < minimum {schema['minimum']}")
    if "maximum" in schema and isinstance(value, (int, float)) and value > schema["maximum"]:
        errs.append(f"{path}: {value} > maximum {schema['maximum']}")
    if ty == "object" and isinstance(value, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", ()):
            if req not in value:
                errs.append(f"{path}.{req}: required field missing")
        for k, v in value.items():
            sub = props.get(k)
            if sub is None:
                # structural schemas prune unknown fields unless
                # x-kubernetes-preserve-unknown-fields; we REJECT (strictest)
                if not schema.get("x-kubernetes-preserve-unknown-fields"):
                    errs.append(f"{path}.{k}: unknown field")
            else:
                errs.extend(validate_schema_value(sub, v, f"{path}.{k}"))
    if ty == "array" and isinstance(value, list) and "items" in schema:
        for i, v in enumerate(value):
            errs.extend(validate_schema_value(schema["items"], v, f"{path}[{i}]"))
    return errs


class CRDRegistry:
    """Owns definitions; the APIServer consults it on every write to a
    registered custom kind (the customresource_handler analog)."""

    KIND = "CustomResourceDefinition"

    def __init__(self, store):
        self.store = store
        store.register_kind(self.KIND)
        self._by_kind: Dict[str, CustomResourceDefinition] = {}

    # -- definition lifecycle --
    def create(self, crd: CustomResourceDefinition) -> CustomResourceDefinition:
        if not crd.versions:
            raise CRDInvalid("at least one version required")
        storages = [v for v in crd.versions if v.storage]
        if len(storages) != 1:
            raise CRDInvalid("exactly one storage version required")
        if not any(v.served for v in crd.versions):
            raise CRDInvalid("at least one served version required")
        names = {v.name for v in crd.versions}
        if len(names) != len(crd.versions):
            raise CRDInvalid("duplicate version names")
        from .store import BUILTIN_KINDS

        reserved = {"Node", "Pod", "PDB", "PV", "PVC", self.KIND, *BUILTIN_KINDS}
        if crd.kind in reserved or not crd.kind:
            raise CRDInvalid(f"kind {crd.kind!r} conflicts with a built-in")
        existing = self._by_kind.get(crd.kind)
        if existing is not None and existing.name != crd.name:
            raise CRDInvalid(f"kind {crd.kind!r} already owned by {existing.name}")
        self.store.register_kind(crd.kind)
        crd.established = True  # Established condition: storage is wired
        self.store.add_object(self.KIND, crd)
        self._by_kind[crd.kind] = crd
        return crd

    def delete(self, name: str) -> None:
        """Dropping a CRD deletes its instances (the reference's CR garbage
        collection on CRD deletion) — the dynamic table stays registered but
        empty (tables are cheap; kind re-creation re-establishes)."""
        crd = next((c for c in self._by_kind.values() if c.name == name), None)
        if crd is None:
            return
        for obj in list(self.store.list_objects(crd.kind)):
            self.store.delete_object(crd.kind, obj.key)
        self.store.delete_object(self.KIND, name)
        del self._by_kind[crd.kind]

    def definition_for(self, kind: str) -> Optional[CustomResourceDefinition]:
        return self._by_kind.get(kind)

    # -- custom-object admission --
    def admit(self, obj: CustomResource) -> CustomResource:
        """Validate a custom object write: version must be served, spec must
        pass that version's structural schema; the stored copy carries the
        STORAGE version (conversion strategy None — field passthrough)."""
        crd = self._by_kind.get(obj.kind)
        if crd is None:
            raise CRValidationError(f"no CustomResourceDefinition for kind {obj.kind!r}")
        _, _, vname = obj.api_version.partition("/")
        ver = crd.version(vname or obj.api_version)
        if ver is None:
            raise CRValidationError(
                f"unknown version {obj.api_version!r} for {crd.name}"
            )
        if not ver.served:
            raise CRValidationError(f"version {ver.name} of {crd.name} is not served")
        if ver.schema is not None:
            errs = validate_schema_value(ver.schema, obj.spec)
            if errs:
                raise CRValidationError("; ".join(errs))
        storage = crd.storage_version()
        if (vname or obj.api_version) != storage:
            obj.api_version = f"{crd.group}/{storage}"
        return obj
