"""Leases + leader election + node lifecycle — the failure-detection stack.

reference (SURVEY.md §5):
  - kubelet heartbeats as coordination.k8s.io Lease objects
    (pkg/kubelet/nodelease); here: Lease records renewed against the store
  - pkg/controller/nodelifecycle/node_lifecycle_controller.go: nodes whose
    lease goes stale past the 40 s grace become NotReady, get the
    node.kubernetes.io/unreachable:NoExecute taint, and their pods are
    evicted after tolerationSeconds (default 300 s)
  - client-go tools/leaderelection: active-passive HA via lease CAS
    (15 s lease / 10 s renew / 2 s retry)

All clocks injectable (FakeClock) for deterministic tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from ..api import types as t
from .queue import Clock
from .store import ClusterStore

UNREACHABLE_TAINT_KEY = "node.kubernetes.io/unreachable"
NOT_READY_TAINT_KEY = "node.kubernetes.io/not-ready"
DEFAULT_GRACE_S = 40.0
DEFAULT_EVICTION_S = 300.0

LEASE_DURATION_S = 15.0
RENEW_DEADLINE_S = 10.0
RETRY_PERIOD_S = 2.0


@dataclass
class Lease:
    holder: str
    renew_time: float
    resource_version: int = 0


class LeaseStore:
    """coordination.k8s.io-style lease table with compare-and-swap semantics
    (the optimistic-concurrency primitive every reference component HA story
    rests on)."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._leases: Dict[str, Lease] = {}

    def get(self, name: str) -> Optional[Lease]:
        return self._leases.get(name)

    def try_acquire_or_renew(self, name: str, holder: str, duration_s: float) -> bool:
        """CAS: acquire if absent/expired/ours; fail if another live holder."""
        now = self.clock.now()
        cur = self._leases.get(name)
        if cur is not None and cur.holder != holder and now < cur.renew_time + duration_s:
            return False
        rv = (cur.resource_version + 1) if cur else 1
        self._leases[name] = Lease(holder=holder, renew_time=now, resource_version=rv)
        return True

    def renew_node_heartbeat(self, node_name: str) -> None:
        self.try_acquire_or_renew(f"node/{node_name}", node_name, float("inf"))


class LeaderElector:
    """tools/leaderelection — LeaderElector.Run reduced to tick()."""

    def __init__(self, leases: LeaseStore, identity: str, name: str = "kube-scheduler"):
        self.leases = leases
        self.identity = identity
        self.name = name

    def tick(self) -> bool:
        """Attempt acquire/renew; returns True while this identity leads."""
        return self.leases.try_acquire_or_renew(self.name, self.identity, LEASE_DURATION_S)

    @property
    def is_leader(self) -> bool:
        cur = self.leases.get(self.name)
        return cur is not None and cur.holder == self.identity


class NodeLifecycleController:
    """node_lifecycle_controller.go: stale heartbeat -> unreachable taint ->
    taint-based eviction after tolerationSeconds."""

    def __init__(
        self,
        store: ClusterStore,
        leases: LeaseStore,
        grace_s: float = DEFAULT_GRACE_S,
        eviction_s: float = DEFAULT_EVICTION_S,
    ):
        self.store = store
        self.leases = leases
        self.grace_s = grace_s
        self.eviction_s = eviction_s
        self._tainted_at: Dict[str, float] = {}

    def tick(self) -> List[str]:
        """Reconcile once; returns uids of pods evicted this pass."""
        now = self.leases.clock.now()
        evicted: List[str] = []
        for name, node in list(self.store.nodes.items()):
            lease = self.leases.get(f"node/{name}")
            stale = lease is None or now > lease.renew_time + self.grace_s
            has_taint = any(tn.key == UNREACHABLE_TAINT_KEY for tn in node.taints)
            if stale and not has_taint:
                node2 = _copy_node(node)
                node2.taints = tuple(node.taints) + (
                    t.Taint(key=UNREACHABLE_TAINT_KEY, effect=t.NO_EXECUTE),
                )
                self.store.update_node(node2)
                self._tainted_at[name] = now
            elif not stale and has_taint:
                node2 = _copy_node(node)
                node2.taints = tuple(
                    tn for tn in node.taints if tn.key != UNREACHABLE_TAINT_KEY
                )
                self.store.update_node(node2)
                self._tainted_at.pop(name, None)
        # taint-based eviction (NoExecute + tolerationSeconds)
        for uid, pod in list(self.store.pods.items()):
            if not pod.node_name:
                continue
            tainted = self._tainted_at.get(pod.node_name)
            if tainted is None:
                continue
            deadline = tainted + self._toleration_window(pod)
            if now >= deadline:
                self.store.delete_pod(uid)
                evicted.append(uid)
        return evicted

    def _toleration_window(self, pod: t.Pod) -> float:
        for tol in pod.tolerations:
            if tol.key in (UNREACHABLE_TAINT_KEY, "") and tol.effect in (t.NO_EXECUTE, ""):
                if tol.toleration_seconds is None:
                    return float("inf")  # tolerates forever
                return float(tol.toleration_seconds)
        return self.eviction_s  # default added by admission in the reference


def _copy_node(node: t.Node) -> t.Node:
    import copy

    return copy.copy(node)
