"""Leases + leader election + node lifecycle — the failure-detection stack.

reference (SURVEY.md §5):
  - kubelet heartbeats as coordination.k8s.io Lease objects
    (pkg/kubelet/nodelease); here: Lease records renewed against the store
  - pkg/controller/nodelifecycle/node_lifecycle_controller.go: nodes whose
    lease goes stale past the 40 s grace become NotReady, get the
    node.kubernetes.io/unreachable:NoExecute taint, and their pods are
    evicted after tolerationSeconds (default 300 s)
  - client-go tools/leaderelection: active-passive HA via lease CAS
    (15 s lease / 10 s renew / 2 s retry)

All clocks injectable (FakeClock) for deterministic tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from ..api import types as t
from .queue import Clock
from .store import ClusterStore

UNREACHABLE_TAINT_KEY = "node.kubernetes.io/unreachable"
NOT_READY_TAINT_KEY = "node.kubernetes.io/not-ready"
DEFAULT_GRACE_S = 40.0
DEFAULT_EVICTION_S = 300.0

LEASE_DURATION_S = 15.0
RENEW_DEADLINE_S = 10.0
RETRY_PERIOD_S = 2.0


@dataclass
class Lease:
    holder: str
    renew_time: float
    resource_version: int = 0


class LeaseStore:
    """coordination.k8s.io-style lease table with compare-and-swap semantics
    (the optimistic-concurrency primitive every reference component HA story
    rests on)."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._leases: Dict[str, Lease] = {}

    def get(self, name: str) -> Optional[Lease]:
        return self._leases.get(name)

    def try_acquire_or_renew(self, name: str, holder: str, duration_s: float) -> bool:
        """CAS: acquire if absent/expired/ours; fail if another live holder."""
        now = self.clock.now()
        cur = self._leases.get(name)
        if cur is not None and cur.holder != holder and now < cur.renew_time + duration_s:
            return False
        rv = (cur.resource_version + 1) if cur else 1
        self._leases[name] = Lease(holder=holder, renew_time=now, resource_version=rv)
        return True

    def renew_node_heartbeat(self, node_name: str) -> None:
        self.try_acquire_or_renew(f"node/{node_name}", node_name, float("inf"))


class LeaderElector:
    """tools/leaderelection — LeaderElector.Run reduced to tick()."""

    def __init__(self, leases: LeaseStore, identity: str,
                 name: str = "kube-scheduler",
                 lease_duration_s: float = LEASE_DURATION_S):
        self.leases = leases
        self.identity = identity
        self.name = name
        self.lease_duration_s = lease_duration_s

    def tick(self) -> bool:
        """Attempt acquire/renew; returns True while this identity leads."""
        return self.leases.try_acquire_or_renew(
            self.name, self.identity, self.lease_duration_s
        )

    @property
    def is_leader(self) -> bool:
        cur = self.leases.get(self.name)
        return cur is not None and cur.holder == self.identity


class HAReplica:
    """One scheduler replica of an active/standby pair — the LeaderElector
    run loop with the takeover protocol attached.

    Both replicas tick() on their retry period; only the lease holder owns a
    live Scheduler.  The standby holds NO scheduler at all (a fresh takeover
    LISTs the world exactly like a restarted process — the crash-only rule),
    so when the active dies silently (kill -9: it simply stops renewing) the
    standby's first successful CAS after lease expiry triggers:

      build scheduler (factory) -> restore() (checkpoint + relist + WAL
      replay + forced hoist re-fingerprint) -> record the blackout

    Blackout = (lease-clock time past the dead leader's expiry when the CAS
    landed) + (real seconds the takeover build+restore took), observed into
    `failover_duration_seconds`; every leadership change bumps
    `leader_election_transitions_total` and emits a `leader.takeover` span.
    The pair-level invariant (tests): takeover completes within ONE lease
    duration of the expiry, and placements stay bit-identical to a
    never-failed scheduler."""

    def __init__(self, identity: str, leases: LeaseStore, make_scheduler,
                 name: str = "kube-scheduler",
                 lease_duration_s: float = LEASE_DURATION_S,
                 metrics=None, tracer=None,
                 killed_site: Optional[str] = None):
        self.identity = identity
        self.elector = LeaderElector(
            leases, identity, name=name, lease_duration_s=lease_duration_s
        )
        self.make_scheduler = make_scheduler
        self.metrics = metrics
        self.tracer = tracer
        self.scheduler = None
        self.dead = False  # a killed active stops ticking (kill -9 semantics)
        self._was_leader = False
        # the chaos kill.* site that felled the leader this standby replaces
        # (the takeover drivers — scheduler.ha_takeover — stamp it from
        # ProcessKilled.fault) — restore() records the recovery under that
        # site so injected/recovered counts reconcile; None for organic
        # takeovers (no injected fault)
        self.killed_site: Optional[str] = killed_site

    def kill(self) -> None:
        """Simulate kill -9 on this replica: it stops renewing (the lease
        simply expires) and its scheduler instance is abandoned mid-state —
        never drained, never flushed (Scheduler.detach marks it inert the
        way the OS would reclaim a dead process)."""
        self.dead = True
        if self.scheduler is not None:
            self.scheduler.detach()
        from .. import chaos

        chaos.revive()  # the latch belongs to the dead replica, not the pair

    def tick(self) -> bool:
        """One leaderelection retry-period step; returns True while this
        replica leads.  A dead replica never ticks (its lease decays)."""
        if self.dead:
            return False
        import time as _t

        prev = self.elector.leases.get(self.elector.name)
        lead = self.elector.tick()
        if lead and not self._was_leader:
            t0 = _t.perf_counter()
            # blackout's lease-clock half: how long past the previous
            # holder's expiry the takeover CAS landed (0 on first election
            # or an uncontended hand-back)
            blackout = 0.0
            if prev is not None and prev.holder != self.identity:
                expiry = prev.renew_time + self.elector.lease_duration_s
                blackout = max(0.0, self.elector.leases.clock.now() - expiry)
            self.scheduler = self.make_scheduler()
            self.scheduler.restore(killed_site=self.killed_site)
            dt = _t.perf_counter() - t0
            m = self.metrics if self.metrics is not None else self.scheduler.metrics
            m.inc("leader_election_transitions_total")
            m.observe("failover_duration_seconds", blackout + dt)
            tr = self.tracer if self.tracer is not None else self.scheduler.tracer
            if tr is not None and tr.enabled:
                tr.record_span(
                    "leader.takeover", start=t0, end=t0 + dt,
                    identity=self.identity,
                    previous=prev.holder if prev is not None else "",
                    blackout_s=round(blackout, 6),
                )
        self._was_leader = lead
        return lead


class NodeLifecycleController:
    """node_lifecycle_controller.go: stale heartbeat -> unreachable taint ->
    taint-based eviction after tolerationSeconds."""

    def __init__(
        self,
        store: ClusterStore,
        leases: LeaseStore,
        grace_s: float = DEFAULT_GRACE_S,
        eviction_s: float = DEFAULT_EVICTION_S,
    ):
        self.store = store
        self.leases = leases
        self.grace_s = grace_s
        self.eviction_s = eviction_s
        self._tainted_at: Dict[str, float] = {}

    def tick(self) -> List[str]:
        """Reconcile once; returns uids of pods evicted this pass."""
        now = self.leases.clock.now()
        evicted: List[str] = []
        for node in self.store.list_nodes():
            name = node.name
            lease = self.leases.get(f"node/{name}")
            stale = lease is None or now > lease.renew_time + self.grace_s
            has_taint = any(tn.key == UNREACHABLE_TAINT_KEY for tn in node.taints)
            if stale and not has_taint:
                node2 = _copy_node(node)
                node2.taints = tuple(node.taints) + (
                    t.Taint(key=UNREACHABLE_TAINT_KEY, effect=t.NO_EXECUTE),
                )
                self.store.update_node(node2)
                self._tainted_at[name] = now
            elif not stale and has_taint:
                node2 = _copy_node(node)
                node2.taints = tuple(
                    tn for tn in node.taints if tn.key != UNREACHABLE_TAINT_KEY
                )
                self.store.update_node(node2)
                self._tainted_at.pop(name, None)
        # taint-based eviction (NoExecute + tolerationSeconds)
        for pod in self.store.list_pods():
            uid = pod.uid
            if not pod.node_name:
                continue
            tainted = self._tainted_at.get(pod.node_name)
            if tainted is None:
                continue
            deadline = tainted + self._toleration_window(pod)
            if now >= deadline:
                self.store.delete_pod(uid)
                evicted.append(uid)
        return evicted

    def _toleration_window(self, pod: t.Pod) -> float:
        for tol in pod.tolerations:
            if tol.key in (UNREACHABLE_TAINT_KEY, "") and tol.effect in (t.NO_EXECUTE, ""):
                if tol.toleration_seconds is None:
                    return float("inf")  # tolerates forever
                return float(tol.toleration_seconds)
        return self.eviction_s  # default added by admission in the reference


def _copy_node(node: t.Node) -> t.Node:
    import copy

    return copy.copy(node)
