"""Event recorder (client-go tools/record — EventRecorder): the scheduler's
Scheduled / FailedScheduling / Preempted event stream, kept in-process as the
scheduling-decision log for parity debugging (SURVEY.md §5 observability).

When constructed with a store, events are ALSO published as "Event" API
objects with the reference's count aggregation (tools/record —
EventAggregator: identical (reason, object, node, message) bumps count and
lastSeen instead of minting a new object) — which is what `kubectl get
events` lists.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import List, Optional
from ..analysis.lockcheck import make_lock


@dataclass(frozen=True)
class SchedulingEvent:
    reason: str  # Scheduled | FailedScheduling | Preempted
    pod: str
    node: str = ""
    message: str = ""


class EventRecorder:
    def __init__(self, capacity: int = 100_000, store=None,
                 publish_limit: int = 10_000, publish_qps: float = 200.0,
                 publish_burst: int = 512, metrics=None):
        self._lock = make_lock("EventRecorder._lock")
        self.events: List[SchedulingEvent] = []
        self.capacity = capacity
        self._store = store
        # events_publish_dropped_total: API-object publications the token
        # bucket refused.  Before this counter the drop was SILENT — the
        # in-memory decision log stayed complete while `kubectl get events`
        # quietly thinned out, with nothing on /metrics to say so.
        self._metrics = metrics
        self._seq = 0
        self._agg: dict = {}  # aggregation key -> Event object key
        # bounded Event-object footprint: oldest objects are deleted past the
        # limit (the reference bounds events with an etcd TTL instead)
        self.publish_limit = publish_limit
        self._published = deque()  # (obj key, agg key), insertion order
        # API-object publication is rate limited, dropping excess — the
        # reference's EventBroadcaster likewise drops events when the sink
        # can't keep up (client-go tools/record — record.go channel overflow;
        # the in-memory decision log above stays complete either way)
        self._qps = publish_qps
        self._tokens = float(publish_burst)
        self._burst = float(publish_burst)
        self._last_refill = time.monotonic()

    def record(self, reason: str, pod: str, node: str = "", message: str = "") -> None:
        with self._lock:
            if len(self.events) < self.capacity:
                self.events.append(SchedulingEvent(reason, pod, node, message))
            if self._store is not None:
                now = time.monotonic()
                self._tokens = min(
                    self._burst, self._tokens + (now - self._last_refill) * self._qps
                )
                self._last_refill = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    self._publish(reason, pod, node, message)
                elif self._metrics is not None:
                    self._metrics.inc("events_publish_dropped_total")

    def _publish(self, reason: str, pod: str, node: str, message: str) -> None:
        from ..api.cluster import ClusterEvent

        ns, _, name = pod.partition("/")
        if not name:
            ns, name = "default", pod
        now = time.time()
        # aggregation key — the reference's aggregator key reduced
        key = f"{ns}/{name}.{reason}.{node}.{message}"
        existing = self._agg.get(key)
        if existing is not None:
            cur = self._store.get_object("Event", existing)
            if cur is not None:
                cur.count += 1
                cur.last_seen = now
                self._store.update_object("Event", cur)
                return
        self._seq += 1
        ev = ClusterEvent(
            name=f"{name}.{self._seq:08x}",
            namespace=ns,
            reason=reason,
            involved_object=f"Pod/{ns}/{name}",
            node=node,
            message=message,
            first_seen=now,
            last_seen=now,
        )
        self._store.add_object("Event", ev)
        self._agg[key] = ev.key
        self._published.append((ev.key, key))
        while len(self._published) > self.publish_limit:
            old_key, old_agg = self._published.popleft()
            self._store.delete_object("Event", old_key)
            if self._agg.get(old_agg) == old_key:
                del self._agg[old_agg]

    def by_reason(self, reason: str) -> List[SchedulingEvent]:
        with self._lock:
            return [e for e in self.events if e.reason == reason]
