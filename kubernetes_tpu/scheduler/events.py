"""Event recorder (client-go tools/record — EventRecorder): the scheduler's
Scheduled / FailedScheduling / Preempted event stream, kept in-process as the
scheduling-decision log for parity debugging (SURVEY.md §5 observability)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class SchedulingEvent:
    reason: str  # Scheduled | FailedScheduling | Preempted
    pod: str
    node: str = ""
    message: str = ""


class EventRecorder:
    def __init__(self, capacity: int = 100_000):
        self._lock = threading.Lock()
        self.events: List[SchedulingEvent] = []
        self.capacity = capacity

    def record(self, reason: str, pod: str, node: str = "", message: str = "") -> None:
        with self._lock:
            if len(self.events) < self.capacity:
                self.events.append(SchedulingEvent(reason, pod, node, message))

    def by_reason(self, reason: str) -> List[SchedulingEvent]:
        with self._lock:
            return [e for e in self.events if e.reason == reason]
