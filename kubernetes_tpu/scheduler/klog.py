"""Structured + contextual logging — the klog v2 analog.

reference: k8s.io/klog/v2 as adopted through component-base/logs: call sites
write `logger.V(4).info("Scheduled pod", pod=..., node=...)` — a message plus
key-value pairs, never format strings — and the backend renders text
(`"msg" k=v k=v`) or JSON (component-base/logs/json).  Contextual logging:
`logger.with_values(pod=...)` returns a child whose pairs prefix every entry
(klog.LoggerWithValues).

Verbosity: entries at V(n) emit only when n <= the configured verbosity
(klog's -v flag).  The default sink appends to an in-memory ring (tests,
parity debugging); `to_stderr()`/`to_json_stderr()` stream instead.

Trace-log correlation: an entry emitted inside an active tracing span
(scheduler/tracing.py — the contextvar current span) carries that span's
trace_id/span_id as trailing key-value pairs, the way the reference's
otelhttp-instrumented handlers stamp log lines — so one pod's log entries
join up with its span tree.
"""

from __future__ import annotations

import json
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, Optional, Tuple

from .tracing import current_span
from ..analysis.lockcheck import make_lock


@dataclass(frozen=True)
class Entry:
    ts: float
    level: int  # verbosity the entry was emitted at (0 = always)
    severity: str  # INFO | ERROR
    msg: str
    kv: Tuple[Tuple[str, object], ...]

    def text(self) -> str:
        pairs = " ".join(f"{k}={v!r}" for k, v in self.kv)
        return f'{self.severity[0]} "{self.msg}"' + (f" {pairs}" if pairs else "")

    def json(self) -> str:
        return json.dumps(
            {"ts": self.ts, "v": self.level, "severity": self.severity,
             "msg": self.msg, **dict(self.kv)},
            default=str,
        )


class Logger:
    """The shared backend + a context prefix (LoggerWithValues chain)."""

    def __init__(
        self,
        verbosity: int = 2,
        sink: Optional[Callable[[Entry], None]] = None,
        _parent: Optional["Logger"] = None,
        _ctx: Tuple[Tuple[str, object], ...] = (),
    ):
        if _parent is not None:
            self._root = _parent._root
        else:
            self._root = self
            self.verbosity = verbosity
            self.ring: Deque[Entry] = deque(maxlen=10_000)
            self._sink = sink
            self._lock = make_lock("Logger._lock")
        self._ctx = _ctx

    # -- klog surface --
    def V(self, level: int) -> "_Leveled":
        return _Leveled(self, level)

    def info(self, msg: str, **kv) -> None:
        self._emit(0, "INFO", msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit(0, "ERROR", msg, kv)

    def with_values(self, **kv) -> "Logger":
        """Contextual child: these pairs prefix every entry it emits."""
        return Logger(_parent=self, _ctx=self._ctx + tuple(kv.items()))

    # -- wiring --
    def _emit(self, level: int, severity: str, msg: str, kv: Dict) -> None:
        root = self._root
        if level > root.verbosity:
            return
        pairs = self._ctx + tuple(kv.items())
        sp = current_span()
        if sp is not None:
            pairs += (("trace_id", sp.trace_id), ("span_id", sp.span_id))
        e = Entry(time.time(), level, severity, msg, pairs)
        with root._lock:
            root.ring.append(e)
            if root._sink is not None:
                root._sink(e)

    def entries(self, msg: Optional[str] = None) -> list:
        root = self._root
        with root._lock:
            out = list(root.ring)
        return [e for e in out if msg is None or e.msg == msg]

    def to_stderr(self) -> "Logger":
        self._root._sink = lambda e: print(e.text(), file=sys.stderr)
        return self

    def to_json_stderr(self) -> "Logger":
        """component-base/logs/json — the structured JSON backend."""
        self._root._sink = lambda e: print(e.json(), file=sys.stderr)
        return self


class _Leveled:
    def __init__(self, logger: Logger, level: int):
        self._logger = logger
        self._level = level

    @property
    def enabled(self) -> bool:  # klog V(n).Enabled()
        return self._level <= self._logger._root.verbosity

    def info(self, msg: str, **kv) -> None:
        self._logger._emit(self._level, "INFO", msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._logger._emit(self._level, "ERROR", msg, kv)
