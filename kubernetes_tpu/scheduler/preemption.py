"""Host orchestration for batched preemption (ops/preempt.py).

Mirrors the CPU evaluator's semantics exactly (scheduler/plugins/cpu.py —
DefaultPreemption, which stays the oracle): per failed pod, candidate victims
are the lower-priority bound pods per node in the SAME reprieve order the CPU
path uses (PDB-violating first, then non-violating, each by (-priority, uid),
over NodeInfo.pods order = snapshot bound order), the device scan reprieves
them against the preemptor's fit, and the host applies
pickOneNodeForPreemption's lexicographic key.

Scope gate (`applicable`): pods whose Filter outcome could depend on pairwise
state, host ports, or volume/claim topology fall back to the CPU evaluator —
the gate preserves oracle behavior while the fit-bound majority vectorizes.

State is incremental across one failure loop: an eviction updates the victim
node's row and usage in place; PDB-budget changes (watched objects) are
fingerprinted per call and invalidate the per-priority victim tables.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as t
from ..api.snapshot import EncodingMeta, pod_effective_requests
from ..ops.scores import infer_score_config


def _split_pdb_violating(pods, pdbs):
    # the CPU evaluator's exact split (plugins/cpu.py — _split_pdb_violating)
    remaining = {pdb.key: pdb.disruptions_allowed for pdb in pdbs}
    violating, non_violating = [], []
    for q in pods:
        hit = [pdb for pdb in pdbs if pdb.matches(q)]
        if any(remaining[pdb.key] <= 0 for pdb in hit):
            violating.append(q)
        else:
            for pdb in hit:
                remaining[pdb.key] -= 1
            non_violating.append(q)
    return violating, non_violating


class BatchedPreemption:
    """One failure loop's resident state: per-node bound pods + usage, the
    encoded cycle arrays, and per-(priority, pdb-state) victim tables."""

    def __init__(self, arr, meta: EncodingMeta, snap, store, queue):
        self.arr = arr
        self.meta = meta
        self.store = store
        self.queue = queue
        self.scale = np.asarray(meta.resource_scale, dtype=np.int64)
        self.resources = list(meta.resources)
        self.node_idx: Dict[str, int] = {
            name: i for i, name in enumerate(meta.node_names)
        }
        self.pod_row: Dict[str, int] = {}
        for k in range(meta.n_pods):
            self.pod_row.setdefault(meta.pod_names[k], k)
        n = len(meta.node_names)
        self.node_pods: List[List[t.Pod]] = [[] for _ in range(n)]
        self.used_raw = np.zeros((n, len(self.resources)), dtype=np.int64)
        for q in snap.bound_pods:
            i = self.node_idx.get(q.node_name)
            if i is not None:
                self.node_pods[i].append(q)
                self.used_raw[i] += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
        # pairwise anywhere in the cluster state? (existing pods' anti terms
        # can constrain ANY preemptor, so their mere presence gates).  Derived
        # from the FRESH post-bind snapshot, not the cycle's pre-bind arrays:
        # anti-affinity pods bound earlier in this very batch must gate too.
        self._has_anti = any(
            q.affinity is not None and q.affinity.required_pod_anti_affinity
            for q in snap.bound_pods
        )
        self._level_cache: Dict[Tuple, Tuple] = {}

    # --- gate ---
    def applicable(self, pod: t.Pod) -> bool:
        if pod.name not in self.pod_row:
            return False  # not in this cycle's encoding (shouldn't happen)
        if pod.host_ports or pod.pvcs or pod.resource_claims:
            return False
        if self._has_anti:
            return False
        if pod.topology_spread:
            return False
        a = pod.affinity
        if a is not None and (
            a.required_pod_affinity or a.required_pod_anti_affinity
        ):
            return False
        return True

    # --- victim tables ---
    def _pdb_fp(self):
        pdbs = list(getattr(self.store, "pdbs", {}).values())
        return tuple((p.key, p.disruptions_allowed) for p in pdbs), pdbs

    def _tables(self, priority: int):
        fp, pdbs = self._pdb_fp()
        key = (priority, fp)
        ent = self._level_cache.get(key)
        if ent is None:
            n = len(self.node_pods)
            ordered: List[List[Tuple[t.Pod, bool]]] = []
            vmax = 1
            for pods in self.node_pods:
                lower = [q for q in pods if q.priority < priority]
                violating, non_violating = _split_pdb_violating(lower, pdbs)
                row = [
                    (q, True)
                    for q in sorted(violating, key=lambda q: (-q.priority, q.uid))
                ] + [
                    (q, False)
                    for q in sorted(
                        non_violating, key=lambda q: (-q.priority, q.uid)
                    )
                ]
                ordered.append(row)
                vmax = max(vmax, len(row))
            V = 1 << (vmax - 1).bit_length() if vmax > 1 else 1
            N = self.arr.N
            R = len(self.resources)
            vict_req = np.zeros((N, V, R), dtype=np.int64)
            vict_prio = np.zeros((N, V), dtype=np.int32)
            vict_viol = np.zeros((N, V), dtype=bool)
            vict_valid = np.zeros((N, V), dtype=bool)
            for i, row in enumerate(ordered):
                for j, (q, viol) in enumerate(row):
                    vict_req[i, j] = pod_effective_requests(q, self.resources)
                    vict_prio[i, j] = q.priority
                    vict_viol[i, j] = viol
                    vict_valid[i, j] = True
            # scale exactly like the encoder (ceil division; gcd scales are
            # exact so sums commute with the encoded node_used)
            vict_req_s = -(-vict_req // self.scale)
            ent = (ordered, vict_req_s.astype(np.int32), vict_prio, vict_viol,
                   vict_valid)
            self._level_cache[key] = ent
        return ent

    # --- the evaluation (one failed pod) ---
    def evaluate(self, pod: t.Pod) -> Optional[Tuple[str, List[t.Pod]]]:
        from ..ops.preempt import preempt_eval

        ordered, vict_req, vict_prio, vict_viol, vict_valid = self._tables(
            pod.priority
        )
        N = self.arr.N
        R = len(self.resources)
        used_s = np.zeros((N, R), dtype=np.int32)
        n = len(self.node_pods)
        used_s[:n] = -(-self.used_raw // self.scale)
        nom_raw = np.zeros((N, R), dtype=np.int64)
        has_nom = np.zeros(N, dtype=bool)
        for uid, (q, node) in self.queue.nominated.items():
            if uid == pod.uid or q.priority < pod.priority:
                continue
            i = self.node_idx.get(node)
            if i is not None:
                nom_raw[i] += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
                has_nom[i] = True
        nom_s = (-(-nom_raw // self.scale)).astype(np.int32)
        cand, nvio, vmax, vsum, vcnt, is_victim = (
            np.asarray(x)
            for x in preempt_eval(
                self.arr,
                np.int32(self.pod_row[pod.name]),
                used_s,
                nom_s,
                has_nom,
                vict_req,
                vict_prio,
                vict_viol,
                vict_valid,
            )
        )
        if not cand.any():
            return None
        # pickOneNodeForPreemption's lexicographic order, lowest node index
        # breaking ties (plugins/cpu.py key)
        idx = np.flatnonzero(cand)
        order = np.lexsort((idx, vcnt[idx], vsum[idx], vmax[idx], nvio[idx]))
        best = int(idx[order[0]])
        victims = [ordered[best][j][0] for j in np.flatnonzero(is_victim[best])]
        return self.meta.node_names[best], victims

    # --- incremental state update after an eviction ---
    def apply_eviction(self, node_name: str, victims: List[t.Pod]) -> None:
        i = self.node_idx[node_name]
        gone = {q.uid for q in victims}
        self.node_pods[i] = [q for q in self.node_pods[i] if q.uid not in gone]
        for q in victims:
            self.used_raw[i] -= np.array(
                pod_effective_requests(q, self.resources), dtype=np.int64
            )
        # victim tables reference the old row on this node only: RE-derive the
        # row (split + reprieve order) from scratch — an evicted non-violating
        # victim frees the PDB budget it consumed, which can flip later pods'
        # violating flag, exactly as the CPU evaluator would see on its next
        # PostFilter call.  (Arrays are private to this loop: in-place patch.)
        for (priority, fp), ent in self._level_cache.items():
            ordered, vict_req, vict_prio, vict_viol, vict_valid = ent
            _, pdbs = self._pdb_fp()
            lower = [q for q in self.node_pods[i] if q.priority < priority]
            violating, non_violating = _split_pdb_violating(lower, pdbs)
            row = [
                (q, True)
                for q in sorted(violating, key=lambda q: (-q.priority, q.uid))
            ] + [
                (q, False)
                for q in sorted(non_violating, key=lambda q: (-q.priority, q.uid))
            ]
            ordered[i] = row
            vict_req[i] = 0
            vict_prio[i] = 0
            vict_viol[i] = False
            vict_valid[i] = False
            for j, (q, viol) in enumerate(row[: vict_req.shape[1]]):
                vict_req[i, j] = -(
                    -np.array(
                        pod_effective_requests(q, self.resources), dtype=np.int64
                    )
                    // self.scale
                )
                vict_prio[i, j] = q.priority
                vict_viol[i, j] = viol
                vict_valid[i, j] = True
