"""Host orchestration for batched preemption (ops/preempt.py).

Mirrors the CPU evaluator's semantics exactly (scheduler/plugins/cpu.py —
DefaultPreemption, which stays the oracle): per failed pod, candidate victims
are the lower-priority bound pods per node in the SAME reprieve order the CPU
path uses (PDB-violating first, then non-violating, each by (-priority, uid),
over NodeInfo.pods order = snapshot bound order), the device scan reprieves
them against the preemptor's fit, and the host applies
pickOneNodeForPreemption's lexicographic key.

Scope gate (`applicable`): pods whose Filter outcome could depend on pairwise
state, host ports, or volume/claim topology fall back to the CPU evaluator —
the gate preserves oracle behavior while the fit-bound majority vectorizes.

State is incremental across one failure loop: an eviction updates the victim
node's row and usage in place; PDB-budget changes (watched objects) are
fingerprinted per call and invalidate the per-priority victim tables.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as t
from ..api.snapshot import EncodingMeta, pod_effective_requests
from ..ops.scores import infer_score_config


def _split_pdb_violating(pods, pdbs):
    # the CPU evaluator's exact split (plugins/cpu.py — _split_pdb_violating)
    remaining = {pdb.key: pdb.disruptions_allowed for pdb in pdbs}
    violating, non_violating = [], []
    for q in pods:
        hit = [pdb for pdb in pdbs if pdb.matches(q)]
        if any(remaining[pdb.key] <= 0 for pdb in hit):
            violating.append(q)
        else:
            for pdb in hit:
                remaining[pdb.key] -= 1
            non_violating.append(q)
    return violating, non_violating


class BatchedPreemption:
    """One failure loop's resident state: per-node bound pods + usage, the
    encoded cycle arrays, and per-(priority, pdb-state) victim tables."""

    def __init__(self, arr, meta: EncodingMeta, snap, store, queue):
        self.arr = arr
        self.meta = meta
        self.store = store
        self.queue = queue
        self.scale = np.asarray(meta.resource_scale, dtype=np.int64)
        self.resources = list(meta.resources)
        self.node_idx: Dict[str, int] = {
            name: i for i, name in enumerate(meta.node_names)
        }
        self.pod_row: Dict[str, int] = {}
        for k in range(meta.n_pods):
            self.pod_row.setdefault(meta.pod_names[k], k)
        n = len(meta.node_names)
        self.node_pods: List[List[t.Pod]] = [[] for _ in range(n)]
        self.used_raw = np.zeros((n, len(self.resources)), dtype=np.int64)
        for q in snap.bound_pods:
            i = self.node_idx.get(q.node_name)
            if i is not None:
                self.node_pods[i].append(q)
                self.used_raw[i] += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
        # pairwise anywhere in the cluster state? (existing pods' anti terms
        # can constrain ANY preemptor, so their mere presence gates).  Derived
        # from the FRESH post-bind snapshot, not the cycle's pre-bind arrays:
        # anti-affinity pods bound earlier in this very batch must gate too.
        self._has_anti = any(
            q.affinity is not None and q.affinity.required_pod_anti_affinity
            for q in snap.bound_pods
        )
        self._level_cache: Dict[Tuple, Tuple] = {}
        # wave state (evaluate-many batching): device stats for up to _WAVE
        # same-priority preemptors computed against one state snapshot, plus
        # the monotone log of node indices dirtied since — the sequential
        # commit pass repairs exactly those nodes on host (phases A-C for a
        # single node are O(V) numpy work)
        self._pending: List[str] = []  # uids awaiting wave membership
        self._pending_pods: Dict[str, t.Pod] = {}
        self._waves: Dict[int, dict] = {}  # priority -> live wave
        self._dirty_log: List[int] = []
        self.wave_hits = 0  # evaluations served from a wave (tests/bench)
        self.single_hits = 0
        self._alloc_np: Optional[np.ndarray] = None

    # --- gate ---
    def applicable(self, pod: t.Pod) -> bool:
        if pod.name not in self.pod_row:
            return False  # not in this cycle's encoding (shouldn't happen)
        if pod.host_ports or pod.pvcs or pod.resource_claims:
            return False
        if self._has_anti:
            return False
        if pod.topology_spread:
            return False
        a = pod.affinity
        if a is not None and (
            a.required_pod_affinity or a.required_pod_anti_affinity
        ):
            return False
        return True

    # --- victim tables ---
    def _pdb_fp(self):
        pdbs = list(getattr(self.store, "pdbs", {}).values())
        return tuple((p.key, p.disruptions_allowed) for p in pdbs), pdbs

    def _tables(self, priority: int):
        fp, pdbs = self._pdb_fp()
        key = (priority, fp)
        ent = self._level_cache.get(key)
        if ent is None:
            n = len(self.node_pods)
            ordered: List[List[Tuple[t.Pod, bool]]] = []
            vmax = 1
            for pods in self.node_pods:
                lower = [q for q in pods if q.priority < priority]
                violating, non_violating = _split_pdb_violating(lower, pdbs)
                row = [
                    (q, True)
                    for q in sorted(violating, key=lambda q: (-q.priority, q.uid))
                ] + [
                    (q, False)
                    for q in sorted(
                        non_violating, key=lambda q: (-q.priority, q.uid)
                    )
                ]
                ordered.append(row)
                vmax = max(vmax, len(row))
            V = 1 << (vmax - 1).bit_length() if vmax > 1 else 1
            N = self.arr.N
            R = len(self.resources)
            vict_req = np.zeros((N, V, R), dtype=np.int64)
            vict_prio = np.zeros((N, V), dtype=np.int32)
            vict_viol = np.zeros((N, V), dtype=bool)
            vict_valid = np.zeros((N, V), dtype=bool)
            for i, row in enumerate(ordered):
                for j, (q, viol) in enumerate(row):
                    vict_req[i, j] = pod_effective_requests(q, self.resources)
                    vict_prio[i, j] = q.priority
                    vict_viol[i, j] = viol
                    vict_valid[i, j] = True
            # scale exactly like the encoder (ceil division; gcd scales are
            # exact so sums commute with the encoded node_used)
            vict_req_s = -(-vict_req // self.scale)
            ent = (ordered, vict_req_s.astype(np.int32), vict_prio, vict_viol,
                   vict_valid)
            self._level_cache[key] = ent
        return ent

    # --- evaluate-many batching (the preemptor axis) ---
    # max preemptors per device program; 0 disables waves entirely (every
    # evaluation single — the A/B baseline).  The EFFECTIVE K additionally
    # scales down with the victim-table size so the wave's intermediates
    # stay under _WAVE_BYTES (see _wave_cap) — a fixed 64 at 20k nodes with
    # dense victim tables would materialize hundreds of MB per program
    _WAVE = int(os.environ.get("KTPU_PREEMPT_WAVE", "64"))
    # byte budget for one wave's [K, N, V]-shaped intermediates (is_victim
    # + the scan's per-slot flags dominate; stats rows are [K, N] noise)
    _WAVE_BYTES = int(
        os.environ.get("KTPU_PREEMPT_WAVE_BYTES", str(256 * 1024 * 1024))
    )

    def _wave_cap(self, V: int) -> int:
        """Preemptors per wave so K·N·V stays under the byte budget:
        ~2 bytes per (K, N, V) cell (bool is_victim + scan slot flags) plus
        the [K, N] int32 stat rows."""
        per_k = 2 * self.arr.N * max(1, V) + 32 * self.arr.N
        return max(1, min(self._WAVE, self._WAVE_BYTES // per_k))

    def prefetch(self, pods: List[t.Pod]) -> None:
        """Register the failure loop's upcoming preemptors so evaluate()
        can serve them from batched waves.  Pods outside the gate, or
        currently nominated (their self-exclusion from the nominated
        reservation is per-preemptor — not wave-shareable), stay on the
        single-pod path."""
        if self._WAVE <= 0:
            return  # waves disabled (A/B baseline)
        for q in pods:
            if self.applicable(q) and q.uid not in self.queue.nominated:
                self._pending.append(q.uid)
                self._pending_pods[q.uid] = q

    def _nominated_raw(
        self, priority: int, N: int, R: int, exclude_uid: Optional[str] = None
    ):
        """RAW (unscaled) nominated reservations per node for a preemptor of
        this priority.  The ONE accumulation convention: sum raw int64
        requests, then ceil-scale the SUM once — every consumer (wave
        build, single eval, dirty-node repair) must scale identically or
        wave-served and single-served decisions drift at scaled-unit
        boundaries."""
        nom_raw = np.zeros((N, R), dtype=np.int64)
        has_nom = np.zeros(N, dtype=bool)
        for uid, (q, node) in self.queue.nominated.items():
            if uid == exclude_uid or q.priority < priority:
                continue
            i = self.node_idx.get(node)
            if i is not None:
                nom_raw[i] += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
                has_nom[i] = True
        return nom_raw, has_nom

    def _nominated_arrays(self, priority: int, N: int, R: int):
        """Scaled nominated reservations for a wave of this priority (no
        per-preemptor exclusion: nominated pods never join waves)."""
        nom_raw, has_nom = self._nominated_raw(priority, N, R)
        return (-(-nom_raw // self.scale)).astype(np.int32), has_nom

    def _build_wave(self, first: t.Pod) -> None:
        """One device program for the next _WAVE pending preemptors sharing
        `first`'s priority, against the CURRENT state snapshot.  Keyed by
        priority: interleaved priorities in the failure loop each keep
        their own live wave instead of evicting each other's."""
        from ..ops.preempt import preempt_eval_wave

        prio = first.priority
        fp, _ = self._pdb_fp()
        ordered, vict_req, vict_prio, vict_viol, vict_valid = self._tables(
            prio
        )
        k_cap = self._wave_cap(vict_valid.shape[1])
        members: List[t.Pod] = []
        rest: List[str] = []
        for uid in self._pending:
            q = self._pending_pods.get(uid)
            if q is None:
                continue
            if q.priority == prio and len(members) < k_cap:
                members.append(q)
            else:
                rest.append(uid)
        self._pending = rest
        for q in members:
            self._pending_pods.pop(q.uid, None)
        N = self.arr.N
        R = len(self.resources)
        used_s = np.zeros((N, R), dtype=np.int32)
        n = len(self.node_pods)
        used_s[:n] = -(-self.used_raw // self.scale)
        nom_s, has_nom = self._nominated_arrays(prio, N, R)
        # pow2-bucket K (pad with row 0 repeats; padded outputs unread) so
        # varying member counts reuse one jit trace per bucket instead of
        # compiling a fresh [K, N] program per count — same convention as
        # the snapshot encoder's shape buckets
        K = len(members)
        Kp = 1 << max(0, (K - 1).bit_length())
        rows = [self.pod_row[q.name] for q in members]
        idxs = np.array(rows + [rows[0]] * (Kp - K), dtype=np.int32)
        out = preempt_eval_wave(
            self.arr, idxs, used_s, nom_s, has_nom,
            vict_req, vict_prio, vict_viol, vict_valid,
        )
        cand, nvio, vmax, vsum, vcnt, is_victim, static = (
            np.asarray(x) for x in out
        )
        self._waves[prio] = {
            "uid_to_i": {q.uid: i for i, q in enumerate(members)},
            "fp": fp,
            "mark": len(self._dirty_log),  # dirt before this = already seen
            "cand": cand, "nvio": nvio, "vmax": vmax, "vsum": vsum,
            "vcnt": vcnt, "is_victim": is_victim, "static": static,
        }
        if self._alloc_np is None:
            self._alloc_np = np.asarray(self.arr.node_alloc)

    def _host_node_stats(self, pod: t.Pod, static_ok: bool, n: int):
        """Phases A-C for ONE node on host, against CURRENT state — the
        exact repair for nodes dirtied after a wave's device snapshot.
        Mirrors ops/preempt.py per slot: same reprieve order (the live
        table row), same fit form (req <= alloc - used, zero-request
        resources never block), same ok2 nominated re-check."""
        ordered, *_ = self._tables(pod.priority)
        row = ordered[n]
        alloc = self._alloc_np[n].astype(np.int64)
        used = -(-self.used_raw[n] // self.scale)
        req = np.array(
            pod_effective_requests(pod, self.resources), dtype=np.int64
        )
        req_s = -(-req // self.scale)
        # same raw-sum-then-ceil convention as _nominated_arrays /
        # _evaluate_single — per-pod ceils would over-reserve by up to one
        # scaled unit per nominated pod and flip boundary decisions
        nom_raw_row = np.zeros_like(used)
        has_nom = False
        for uid, (q, node) in self.queue.nominated.items():
            if uid == pod.uid or q.priority < pod.priority:
                continue
            if self.node_idx.get(node) == n:
                nom_raw_row += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
                has_nom = True
        nom = -(-nom_raw_row // self.scale)

        def fit(u):
            return bool(np.all((req_s == 0) | (req_s <= alloc - u)))

        vreqs = [
            -(
                -np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
                // self.scale
            )
            for q, _ in row
        ]
        base = used + nom - (
            np.sum(vreqs, axis=0) if vreqs else np.zeros_like(used)
        )
        okA = bool(static_ok) and fit(base)
        used_cur = base
        victims: List[Tuple[t.Pod, bool]] = []
        for (q, viol), vr in zip(row, vreqs):
            trial = used_cur + vr
            if okA and fit(trial):
                used_cur = trial  # reprieved
            elif okA:
                victims.append((q, viol))
        vcnt = len(victims)
        ok2 = fit(used_cur - nom) if (has_nom and vcnt > 0) else True
        nvio = sum(1 for _, viol in victims if viol)
        vmax = max(
            (q.priority for q, _ in victims),
            default=np.iinfo(np.int32).min,
        )
        vsum = sum(q.priority for q, _ in victims)
        cand = okA and ok2 and vcnt > 0
        return cand, nvio, vmax, vsum, vcnt, [q for q, _ in victims]

    def note_nomination_cleared(self, pod: t.Pod) -> None:
        """The failure loop is about to clear this pod's nomination: the
        freed reservation changes later preemptors' view of that node."""
        ent = self.queue.nominated.get(pod.uid)
        if ent is not None:
            i = self.node_idx.get(ent[1])
            if i is not None:
                self._dirty_log.append(i)

    def _wave_decide(self, pod: t.Pod) -> Optional[Tuple[str, List[t.Pod]]]:
        w = self._waves[pod.priority]
        i = w["uid_to_i"][pod.uid]
        dirty = sorted(set(self._dirty_log[w["mark"]:]))
        over = {
            n: self._host_node_stats(pod, w["static"][i, n], n)
            for n in dirty
        }
        cand = w["cand"][i]
        nvio, vmax, vsum, vcnt = (
            w["nvio"][i], w["vmax"][i], w["vsum"][i], w["vcnt"][i]
        )
        if over:
            cand, nvio, vmax, vsum, vcnt = (
                a.copy() for a in (cand, nvio, vmax, vsum, vcnt)
            )
            for n, (c, nv, vm, vs, vc, _) in over.items():
                cand[n], nvio[n], vmax[n], vsum[n], vcnt[n] = (
                    c, nv, vm, vs, vc
                )
        if not cand.any():
            return None
        idx = np.flatnonzero(cand)
        order = np.lexsort((idx, vcnt[idx], vsum[idx], vmax[idx], nvio[idx]))
        best = int(idx[order[0]])
        if best in over:
            victims = over[best][5]
        else:
            ordered, *_ = self._tables(pod.priority)
            victims = [
                ordered[best][j][0]
                for j in np.flatnonzero(w["is_victim"][i, best])
            ]
        return self.meta.node_names[best], victims

    # --- the evaluation (one failed pod) ---
    def evaluate(self, pod: t.Pod) -> Optional[Tuple[str, List[t.Pod]]]:
        """Wave-served when the pod was prefetched (one device program per
        _WAVE same-priority preemptors + exact host repair of dirtied
        nodes); single device program otherwise.  Decisions identical
        either way (tests/test_preemption_batched.py — wave cases)."""
        w = self._waves.get(pod.priority)
        if w is not None and self._pdb_fp()[0] != w["fp"]:
            del self._waves[pod.priority]  # PDB moved: snapshot stale
            w = None
        if (
            w is None or pod.uid not in w["uid_to_i"]
        ) and pod.uid in self._pending_pods:
            self._build_wave(pod)
            w = self._waves.get(pod.priority)
        if w is not None and pod.uid in w["uid_to_i"]:
            self.wave_hits += 1
            return self._wave_decide(pod)
        self.single_hits += 1
        return self._evaluate_single(pod)

    def _evaluate_single(
        self, pod: t.Pod
    ) -> Optional[Tuple[str, List[t.Pod]]]:
        from ..ops.preempt import preempt_eval

        ordered, vict_req, vict_prio, vict_viol, vict_valid = self._tables(
            pod.priority
        )
        N = self.arr.N
        R = len(self.resources)
        used_s = np.zeros((N, R), dtype=np.int32)
        n = len(self.node_pods)
        used_s[:n] = -(-self.used_raw // self.scale)
        nom_raw, has_nom = self._nominated_raw(
            pod.priority, N, R, exclude_uid=pod.uid
        )
        nom_s = (-(-nom_raw // self.scale)).astype(np.int32)
        cand, nvio, vmax, vsum, vcnt, is_victim = (
            np.asarray(x)
            for x in preempt_eval(
                self.arr,
                np.int32(self.pod_row[pod.name]),
                used_s,
                nom_s,
                has_nom,
                vict_req,
                vict_prio,
                vict_viol,
                vict_valid,
            )
        )
        if not cand.any():
            return None
        # pickOneNodeForPreemption's lexicographic order, lowest node index
        # breaking ties (plugins/cpu.py key)
        idx = np.flatnonzero(cand)
        order = np.lexsort((idx, vcnt[idx], vsum[idx], vmax[idx], nvio[idx]))
        best = int(idx[order[0]])
        victims = [ordered[best][j][0] for j in np.flatnonzero(is_victim[best])]
        return self.meta.node_names[best], victims

    # --- incremental state update after an eviction ---
    def apply_eviction(self, node_name: str, victims: List[t.Pod]) -> None:
        i = self.node_idx[node_name]
        # waves built before this eviction repair this node from the log
        # (the nomination that follows a successful preemption lands on the
        # SAME node, so one entry covers both state changes)
        self._dirty_log.append(i)
        gone = {q.uid for q in victims}
        self.node_pods[i] = [q for q in self.node_pods[i] if q.uid not in gone]
        for q in victims:
            self.used_raw[i] -= np.array(
                pod_effective_requests(q, self.resources), dtype=np.int64
            )
        # victim tables reference the old row on this node only: RE-derive the
        # row (split + reprieve order) from scratch — an evicted non-violating
        # victim frees the PDB budget it consumed, which can flip later pods'
        # violating flag, exactly as the CPU evaluator would see on its next
        # PostFilter call.  (Arrays are private to this loop: in-place patch.)
        for (priority, fp), ent in self._level_cache.items():
            ordered, vict_req, vict_prio, vict_viol, vict_valid = ent
            _, pdbs = self._pdb_fp()
            lower = [q for q in self.node_pods[i] if q.priority < priority]
            violating, non_violating = _split_pdb_violating(lower, pdbs)
            row = [
                (q, True)
                for q in sorted(violating, key=lambda q: (-q.priority, q.uid))
            ] + [
                (q, False)
                for q in sorted(non_violating, key=lambda q: (-q.priority, q.uid))
            ]
            ordered[i] = row
            vict_req[i] = 0
            vict_prio[i] = 0
            vict_viol[i] = False
            vict_valid[i] = False
            for j, (q, viol) in enumerate(row[: vict_req.shape[1]]):
                vict_req[i, j] = -(
                    -np.array(
                        pod_effective_requests(q, self.resources), dtype=np.int64
                    )
                    // self.scale
                )
                vict_prio[i, j] = q.priority
                vict_viol[i, j] = viol
                vict_valid[i, j] = True
