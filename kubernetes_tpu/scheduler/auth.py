"""Authentication + RBAC authorization — the apiserver security layers.

reference: staging/src/k8s.io/apiserver/pkg/authentication (token authenticator
chain) and plugin/pkg/auth/authorizer/rbac/rbac.go — func (r *RBACAuthorizer)
Authorize: resolve the user's Roles through bindings, allow iff any PolicyRule
covers (verb, resource, name) in the request's namespace.  ClusterRoles bound
by ClusterRoleBindings grant cluster-wide; Roles (or ClusterRoles referenced by
a RoleBinding) grant within the binding's namespace.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from ..api import cluster as c
from .store import ClusterStore

SYSTEM_MASTERS = "system:masters"  # the always-allowed group (reference: rbac.go)


class TokenAuthenticator:
    """Static token table — the authenticator chain reduced to its bearer-token
    member (apiserver/pkg/authentication/token/tokenfile)."""

    def __init__(self) -> None:
        self._tokens: Dict[str, c.UserInfo] = {}

    def add_token(self, token: str, user: str, groups: Iterable[str] = ()) -> None:
        self._tokens[token] = c.UserInfo(name=user, groups=tuple(groups))

    def remove_token(self, token: str) -> None:
        """Credential revocation (the tokens_controller deletes the token
        Secret when its ServiceAccount goes away)."""
        self._tokens.pop(token, None)

    def authenticate(self, token: Optional[str]) -> Optional[c.UserInfo]:
        """-> UserInfo, or None (unauthenticated => request rejected upstream)."""
        if token is None:
            return None
        return self._tokens.get(token)


def _rule_allows(rule: c.PolicyRule, verb: str, resource: str, name: str) -> bool:
    # rbac/v1 helpers — VerbMatches/ResourceMatches/ResourceNameMatches
    if "*" not in rule.verbs and verb not in rule.verbs:
        return False
    if "*" not in rule.resources and resource not in rule.resources:
        return False
    if rule.resource_names and name not in rule.resource_names:
        return False
    return True


class RBACAuthorizer:
    def __init__(self, store: ClusterStore):
        self.store = store

    def _subject_matches(self, sub: c.Subject, user: c.UserInfo) -> bool:
        if sub.kind == "User":
            return sub.name == user.name
        if sub.kind == "Group":
            return sub.name in user.groups
        if sub.kind == "ServiceAccount":
            # Subject.name carries "namespace:name" (rbac/v1 splits these
            # into two fields; folded here) — serviceaccount MakeUsername
            return user.name == f"system:serviceaccount:{sub.name}"
        return False

    def _roles_for(self, user: c.UserInfo, namespace: str):
        """Yield (role, scope_namespace) pairs the user holds for requests in
        `namespace` — the VisitRulesFor walk."""
        roles: Dict[str, c.Role] = self.store.objects["Role"]  # type: ignore[assignment]
        bindings = self.store.list_objects("RoleBinding")
        for rb in bindings:  # type: ignore[assignment]
            if not any(self._subject_matches(s, user) for s in rb.subjects):
                continue
            # ClusterRoleBinding (namespace "") grants everywhere; RoleBinding
            # grants only inside its own namespace
            if rb.namespace and rb.namespace != namespace:
                continue
            role_key = (
                f"{rb.role_namespace}/{rb.role_name}"
                if rb.role_namespace
                else rb.role_name
            )
            role = roles.get(role_key)
            if role is not None:
                yield role

    def authorize(
        self, user: c.UserInfo, verb: str, resource: str, namespace: str = "", name: str = ""
    ) -> bool:
        if SYSTEM_MASTERS in user.groups:
            return True
        for role in self._roles_for(user, namespace):
            for rule in role.rules:
                if _rule_allows(rule, verb, resource, name):
                    return True
        return False


def bind_cluster_role(
    store: ClusterStore,
    binding_name: str,
    role_name: str,
    subjects: Iterable[Tuple[str, str]],
) -> None:
    """Convenience: create a ClusterRoleBinding to the ClusterRole role_name."""
    store.add_object(
        "RoleBinding",
        c.RoleBinding(
            name=binding_name,
            namespace="",
            role_name=role_name,
            role_namespace="",
            subjects=tuple(c.Subject(kind=k, name=n) for k, n in subjects),
        ),
    )
