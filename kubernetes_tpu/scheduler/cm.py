"""Container managers — the kubelet's cm/ subsystems beyond devices.

  CPUManagerStatic   pkg/kubelet/cm/cpumanager (static policy): pods of the
                     guaranteed tier requesting INTEGER CPUs get exclusive
                     cores carved from the node's shared pool; admission
                     fails when no whole cores remain (the kubelet's
                     UnexpectedAdmissionError path, same as devicemanager).
                     Allocation prefers the lowest-numbered free cores —
                     the reference's takeByTopology without the socket
                     hierarchy (nodes here have no core topology model).

  EvictionManager    pkg/kubelet/eviction (eviction_manager.go): when the
                     node comes under memory pressure, evict pods in
                     reclaim order until below threshold, and surface the
                     pressure as the memory-pressure NoSchedule taint so
                     the scheduler stops adding load (the reference sets a
                     node CONDITION that the NodeLifecycle controller
                     turns into this taint; the kubelet writes the taint
                     directly here — one hop shorter, same visible
                     contract).  "Usage" is the sum of running pods'
                     memory requests: the hollow runtime has no real RSS,
                     so pressure arises from overcommit paths that bypass
                     the scheduler (direct binds, daemons) — exactly the
                     case the reference's eviction manager exists for.

QoS (v1 qos.GetPodQOS) reduced to the model's fields: pods with no cpu AND
no memory request are BestEffort; everything else is Burstable, and
Burstable pods requesting whole CPUs play the Guaranteed role for CPU
pinning (the object model carries requests but not limits — documented
deviation, PARITY.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..api import types as t
from .store import ClusterStore

MEMORY_PRESSURE_TAINT_KEY = "node.kubernetes.io/memory-pressure"

QOS_BEST_EFFORT = "BestEffort"
QOS_BURSTABLE = "Burstable"


def pod_qos(pod: t.Pod) -> str:
    """qos.GetPodQOS reduced to requests-presence (no limits in the model)."""
    if pod.requests.get(t.CPU, 0) <= 0 and pod.requests.get(t.MEMORY, 0) <= 0:
        return QOS_BEST_EFFORT
    return QOS_BURSTABLE


class CPUAllocationError(Exception):
    pass


class CPUManagerStatic:
    """Exclusive-core accounting for one node (cpumanager static policy).

    With a CheckpointManager the assignments survive kubelet restart, the
    same cm/cpumanager/state checkpoint contract the devicemanager analog
    follows (a restarted kubelet must not double-assign cores that running
    containers still hold)."""

    def __init__(self, n_cpus: int, checkpoints=None, node_name: str = ""):
        self.n_cpus = n_cpus
        self.assignments: Dict[str, Tuple[int, ...]] = {}  # pod uid -> cores
        self._ckpt = checkpoints
        self._ckpt_name = f"cpumanager-{node_name or 'node'}"
        if checkpoints is not None:
            saved = checkpoints.load(self._ckpt_name)
            if saved:
                self.assignments = {
                    uid: tuple(cores) for uid, cores in saved.items()
                }

    def _persist(self) -> None:
        if self._ckpt is not None:
            self._ckpt.save(
                self._ckpt_name,
                {uid: list(c) for uid, c in self.assignments.items()},
            )

    def _free(self) -> List[int]:
        used: Set[int] = set()
        for cores in self.assignments.values():
            used.update(cores)
        return [c for c in range(self.n_cpus) if c not in used]

    @staticmethod
    def wants_exclusive(pod: t.Pod) -> int:
        """Whole-CPU count for pods in the guaranteed-for-CPU tier
        (integer cpu request in millis), else 0 (shared pool)."""
        req = pod.requests.get(t.CPU, 0)
        if req > 0 and req % 1000 == 0:
            return req // 1000
        return 0

    def allocate(self, pod: t.Pod) -> Tuple[int, ...]:
        """Idempotent per pod uid; raises CPUAllocationError when fewer
        whole cores remain than requested."""
        if pod.uid in self.assignments:
            return self.assignments[pod.uid]
        n = self.wants_exclusive(pod)
        if n == 0:
            return ()
        free = self._free()
        if len(free) < n:
            raise CPUAllocationError(
                f"want {n} exclusive CPUs, {len(free)} free of {self.n_cpus}"
            )
        cores = tuple(free[:n])  # lowest-numbered free cores
        self.assignments[pod.uid] = cores
        self._persist()
        return cores

    def free(self, pod_uid: str) -> None:
        if self.assignments.pop(pod_uid, None) is not None:
            self._persist()


class EvictionManager:
    """Node-pressure eviction for one node (synchronize() per kubelet tick)."""

    #: evict when running memory requests exceed this fraction of
    #: allocatable (the reference's memory.available hard threshold,
    #: expressed as a fraction of capacity)
    MEMORY_HARD_FRACTION = 0.95

    def __init__(self, store: ClusterStore, node_name: str,
                 pod_uids=None):
        """pod_uids: optional callable yielding this node's pod uids (the
        kubelet passes its watch-fed worker map, keeping the per-tick cost
        O(node pods) — the kubelet's no-cluster-scans contract); without
        it, falls back to scanning the store (standalone use)."""
        self.store = store
        self.node_name = node_name
        self._pod_uids = pod_uids

    def _running_pods(self) -> List[t.Pod]:
        if self._pod_uids is not None:
            pods = (self.store.pods.get(uid) for uid in self._pod_uids())
        else:
            pods = self.store.list_pods()
        return [
            p
            for p in pods
            if p is not None
            and p.node_name == self.node_name
            and p.phase not in (t.PHASE_SUCCEEDED, t.PHASE_FAILED)
        ]

    def synchronize(self) -> List[str]:
        """One eviction pass; returns evicted pod uids.  Ranks victims the
        way eviction/helpers.go does with the signals the model has:
        BestEffort first, then lowest priority, then largest memory
        request (usage stand-in); stops as soon as the node is below the
        threshold again."""
        node = self.store.nodes.get(self.node_name)
        if node is None:
            return []
        alloc = node.allocatable.get(t.MEMORY, 0)
        if alloc <= 0:
            return []
        limit = int(alloc * self.MEMORY_HARD_FRACTION)
        pods = self._running_pods()
        used = sum(p.requests.get(t.MEMORY, 0) for p in pods)
        evicted: List[str] = []
        if used > limit:
            ranked = sorted(
                pods,
                key=lambda p: (
                    pod_qos(p) != QOS_BEST_EFFORT,  # BestEffort first
                    p.priority,
                    -p.requests.get(t.MEMORY, 0),
                    p.name,
                ),
            )
            for p in ranked:
                if used <= limit:
                    break
                import copy

                q = copy.copy(self.store.pods[p.uid])
                q.phase = t.PHASE_FAILED
                self.store.update_pod_status(q)
                used -= p.requests.get(t.MEMORY, 0)
                evicted.append(p.uid)
        # the pressure taint reflects the POST-eviction state
        self._sync_taint(used > limit)
        return evicted

    def _sync_taint(self, pressure: bool) -> None:
        node = self.store.nodes.get(self.node_name)
        if node is None:
            return
        has = any(tn.key == MEMORY_PRESSURE_TAINT_KEY for tn in node.taints)
        if pressure == has:
            return
        import copy

        q = copy.copy(node)
        if pressure:
            q.taints = tuple(node.taints) + (
                t.Taint(key=MEMORY_PRESSURE_TAINT_KEY, value="",
                        effect=t.NO_SCHEDULE),
            )
        else:
            q.taints = tuple(
                tn for tn in node.taints
                if tn.key != MEMORY_PRESSURE_TAINT_KEY
            )
        self.store.update_node(q)
