"""ComponentConfig: SchedulerConfiguration — the KubeSchedulerConfiguration
analog (pkg/scheduler/apis/config/types.go — KubeSchedulerConfiguration /
KubeSchedulerProfile) as dataclasses + YAML loading, with defaulting and
validation in the same spirit as apis/config/{v1,validation}.

The TPUScore section configures the batched offload path (the north star's
out-of-tree plugin's pluginConfig: sidecar address, batch window, fallback
deadline); mode="cpu" disables offload entirely — the mandated fallback.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from ..ops.scores import ScoreConfig
from .extender import ExtenderConfig


@dataclass(frozen=True)
class PluginSpec:
    name: str
    weight: float = 1.0
    enabled: bool = True


@dataclass(frozen=True)
class TPUScoreArgs:
    """pluginConfig for the TPU offload (north star: sidecar address, batch
    window, deadline -> CPU fallback)."""

    sidecar_address: str = "local"  # "local" = in-process kernels, no gRPC hop
    batch_window_ms: float = 5.0
    deadline_ms: float = 1000.0
    mesh_devices: int = 1


@dataclass(frozen=True)
class Profile:
    scheduler_name: str = "default-scheduler"
    plugins: Tuple[PluginSpec, ...] = ()
    # percentageOfNodesToScore: honored by the CPU path's filter fan-out
    # (adaptive numFeasibleNodesToFind formula when 0, rotating cursor);
    # default 100 = full deterministic scoring; batch/TPU paths always score
    # everything (D3)
    percentage_of_nodes_to_score: int = 100
    tpu_score: Optional[TPUScoreArgs] = None
    # InterPodAffinityArgs.hardPodAffinityWeight (pluginConfig; default 1)
    hard_pod_affinity_weight: float = 1.0
    # NodeResourcesFitArgs.scoringStrategy (pluginConfig):
    # LeastAllocated | MostAllocated | RequestedToCapacityRatio
    fit_strategy: str = "LeastAllocated"
    # RequestedToCapacityRatio shape points (utilization%%, score 0..10)
    rtcr_shape: Tuple[Tuple[float, float], ...] = ((0.0, 0.0), (100.0, 10.0))


@dataclass(frozen=True)
class SchedulerConfiguration:
    profiles: Tuple[Profile, ...] = (Profile(),)
    # HTTP extenders (apis/config — KubeSchedulerConfiguration.Extenders);
    # honored on the CPU path for wire compatibility with existing extenders —
    # the batched paths use the gRPC sidecar instead (scheduler/extender.py)
    extenders: Tuple["ExtenderConfig", ...] = ()
    parallelism: int = 16  # reference default goroutine fan-out; informational here
    # >0: the CPU path's binding cycle (PreBind/Bind/PostBind) runs on this
    # many worker threads, overlapping the next pod's scheduling cycle — the
    # reference's async bindingCycle goroutine.  0 = synchronous binding.
    binding_workers: int = 0
    # unschedulable-retry backoff: initial step, the CAP (a fixed uncapped
    # doubling would park pods for minutes after a long outage), and a
    # multiplicative jitter fraction (each push matures at duration *
    # (1 + U[0, jitter))) — a sidecar outage parks whole waves at once, and
    # without jitter they all retry in one synchronized storm.  All three
    # are wired into the scheduler's PriorityQueue.
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    pod_backoff_jitter: float = 0.1
    feature_gates: Tuple[Tuple[str, bool], ...] = ()
    # "tpu" (batched XLA kernels) | "native" (batched C++ engine — the fast
    # CPU fallback) | "cpu" (per-pod plugin path — the reference's exact shape)
    mode: str = "tpu"
    # pipelined batch commits: defer the bind/events fan-out of cycle i−1
    # into cycle i's device-step window when provably serial-equivalent
    # (capacity reserves synchronously via cache.assume regardless; see
    # scheduler.py — _flush_deferred_binds).  KTPU_PIPELINE=0 also disables.
    pipeline_commit: bool = True

    def profile(self, name: str = "default-scheduler") -> Profile:
        for p in self.profiles:
            if p.scheduler_name == name:
                return p
        return self.profiles[0]

    def score_config(self, profile_name: str = "default-scheduler") -> ScoreConfig:
        """Lower a profile's plugin weights onto the kernel ScoreConfig."""
        prof = self.profile(profile_name)
        w = {s.name: s.weight for s in prof.plugins if s.enabled}
        disabled = {s.name for s in prof.plugins if not s.enabled}
        cfg = ScoreConfig(
            fit_weight=w.get("NodeResourcesFit", 1.0),
            balanced_weight=w.get("NodeResourcesBalancedAllocation", 1.0),
            taint_weight=w.get("TaintToleration", 3.0),
            node_affinity_weight=w.get("NodeAffinity", 2.0),
            spread_weight=w.get("PodTopologySpread", 2.0),
            interpod_weight=w.get("InterPodAffinity", 2.0),
            hard_pod_affinity_weight=prof.hard_pod_affinity_weight,
            fit_strategy=prof.fit_strategy,
            rtcr_shape=prof.rtcr_shape,
        )
        for name in disabled:
            key = {
                "NodeResourcesFit": "fit_weight",
                "NodeResourcesBalancedAllocation": "balanced_weight",
                "TaintToleration": "taint_weight",
                "NodeAffinity": "node_affinity_weight",
                "PodTopologySpread": "spread_weight",
                "InterPodAffinity": "interpod_weight",
            }.get(name)
            if key:
                cfg = replace(cfg, **{key: 0.0})
        return cfg


def validate(cfg: SchedulerConfiguration) -> List[str]:
    """apis/config/validation — ValidateKubeSchedulerConfiguration."""
    errs = []
    if not cfg.profiles:
        errs.append("at least one profile required")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errs.append("duplicate profile schedulerName")
    for p in cfg.profiles:
        if p.fit_strategy not in (
            "LeastAllocated", "MostAllocated", "RequestedToCapacityRatio"
        ):
            errs.append(f"{p.scheduler_name}: unknown fit scoringStrategy "
                        f"{p.fit_strategy!r}")
        if p.fit_strategy == "RequestedToCapacityRatio":
            xs = [q[0] for q in p.rtcr_shape]
            if len(xs) < 2 or len(xs) > 8 or any(
                b <= a for a, b in zip(xs, xs[1:])
            ):
                errs.append(f"{p.scheduler_name}: rtcr shape must be 2..8 "
                            "points with strictly increasing utilization")
        if not 0 <= p.percentage_of_nodes_to_score <= 100:
            errs.append(f"{p.scheduler_name}: percentageOfNodesToScore out of [0,100]")
        for s in p.plugins:
            if s.weight < 0:
                errs.append(f"{p.scheduler_name}/{s.name}: negative weight")
    if cfg.mode not in ("tpu", "native", "cpu"):
        errs.append(f"unknown mode {cfg.mode!r}")
    for e in cfg.extenders:
        if not e.url_prefix:
            errs.append("extender: urlPrefix required")
        if e.bind_verb and not e.filter_verb:
            errs.append(f"extender {e.url_prefix}: bindVerb requires filterVerb")
    if cfg.parallelism <= 0:
        errs.append("parallelism must be positive")
    if cfg.binding_workers < 0:
        errs.append("bindingWorkers must be >= 0")
    if cfg.pod_initial_backoff_seconds <= 0:
        errs.append("podInitialBackoffSeconds must be positive")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
    if cfg.pod_backoff_jitter < 0:
        errs.append("podBackoffJitter must be >= 0")
    return errs


def from_yaml(text: str) -> SchedulerConfiguration:
    """Load a KubeSchedulerConfiguration-shaped YAML document."""
    import yaml

    doc = yaml.safe_load(text) or {}
    profiles = []
    for prof in doc.get("profiles", [{}]):
        plugins = []
        for item in prof.get("plugins", []):
            plugins.append(
                PluginSpec(
                    name=item["name"],
                    weight=float(item.get("weight", 1.0)),
                    enabled=bool(item.get("enabled", True)),
                )
            )
        tpu = None
        if "tpuScore" in prof:
            a = prof["tpuScore"] or {}
            tpu = TPUScoreArgs(
                sidecar_address=a.get("sidecarAddress", "local"),
                batch_window_ms=float(a.get("batchWindowMs", 5.0)),
                deadline_ms=float(a.get("deadlineMs", 1000.0)),
                mesh_devices=int(a.get("meshDevices", 1)),
            )
        profiles.append(
            Profile(
                scheduler_name=prof.get("schedulerName", "default-scheduler"),
                plugins=tuple(plugins),
                percentage_of_nodes_to_score=int(prof.get("percentageOfNodesToScore", 100)),
                tpu_score=tpu,
            )
        )
    extenders = tuple(
        ExtenderConfig(
            url_prefix=e.get("urlPrefix", ""),
            filter_verb=e.get("filterVerb", ""),
            prioritize_verb=e.get("prioritizeVerb", ""),
            bind_verb=e.get("bindVerb", ""),
            preempt_verb=e.get("preemptVerb", ""),
            weight=float(e.get("weight", 1.0)),
            ignorable=bool(e.get("ignorable", False)),
            timeout_s=float(e.get("httpTimeout", 5.0)),
        )
        for e in doc.get("extenders") or []
    )
    cfg = SchedulerConfiguration(
        profiles=tuple(profiles) or (Profile(),),
        extenders=extenders,
        parallelism=int(doc.get("parallelism", 16)),
        pod_initial_backoff_seconds=float(doc.get("podInitialBackoffSeconds", 1.0)),
        pod_max_backoff_seconds=float(doc.get("podMaxBackoffSeconds", 10.0)),
        pod_backoff_jitter=float(doc.get("podBackoffJitter", 0.1)),
        feature_gates=tuple((k, bool(v)) for k, v in (doc.get("featureGates") or {}).items()),
        mode=doc.get("mode", "tpu"),
    )
    errs = validate(cfg)
    if errs:
        raise ValueError("; ".join(errs))
    return cfg
