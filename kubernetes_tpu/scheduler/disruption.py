"""Disruption controller — maintains PodDisruptionBudget status.

Analog of pkg/controller/disruption (type DisruptionController, sync →
trySync → updatePdbStatus): for each PDB, count the pods its selector
matches, the healthy subset, resolve min_available/max_unavailable into a
desired-healthy count, and publish disruptions_allowed — the number the
preemption evaluator (and the reference's Eviction API) is allowed to consume.

"Healthy" here = bound (has a nodeName) and, when the pod phase machinery is
in play (kubelet.py), phase Running — the reference's
pod.status.conditions[Ready] check reduced to the harness's lifecycle surface.
"""

from __future__ import annotations

from typing import List

from ..api import types as t
from .store import ClusterStore


def _is_healthy(pod: t.Pod) -> bool:
    if not pod.node_name:
        return False
    phase = getattr(pod, "phase", "")
    return phase in ("", "Running")


class DisruptionController:
    """Level-triggered reconcile over all PDBs (the workqueue collapsed to a
    full pass per tick, as every controller in this harness does)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def tick(self) -> List[t.PodDisruptionBudget]:
        """Reconcile every PDB's status; returns the updated objects."""
        out: List[t.PodDisruptionBudget] = []
        for pdb in self.store.list_pdbs():
            matching = [p for p in self.store.list_pods() if pdb.matches(p)]
            expected = len(matching)
            healthy = sum(1 for p in matching if _is_healthy(p))
            if pdb.min_available is not None:
                desired = min(pdb.min_available, expected)
            elif pdb.max_unavailable is not None:
                desired = max(0, expected - pdb.max_unavailable)
            else:
                desired = expected  # no budget field: nothing may be disrupted
            allowed = max(0, healthy - desired)
            if (
                pdb.disruptions_allowed == allowed
                and pdb.current_healthy == healthy
                and pdb.desired_healthy == desired
                and pdb.expected_pods == expected
            ):
                out.append(pdb)
                continue
            import copy

            pdb2 = copy.copy(pdb)
            pdb2.disruptions_allowed = allowed
            pdb2.current_healthy = healthy
            pdb2.desired_healthy = desired
            pdb2.expected_pods = expected
            self.store.update_pdb(pdb2)
            out.append(pdb2)
        return out
