"""Scheduler Framework — the plugin extension-point API and its runtime.

Analog of the reference's pkg/scheduler/framework/interface.go (one interface
per extension point: PreEnqueue, QueueingHint, PreFilter, Filter, PostFilter,
PreScore, Score+NormalizeScore, Reserve, Permit, PreBind, Bind, PostBind) and
framework/runtime/framework.go (frameworkImpl — RunFilterPlugins /
RunScorePlugins fan-out).  This host-side path IS the CPU fallback the north
star mandates: plugins here reproduce the kernels' semantics one pod at a time;
the TPU path replaces the per-pod Filter/Score fan-out with the batched kernel
while everything else (queue, binding cycle, preemption) is shared.

MaxNodeScore = 100 (interface.go — MaxNodeScore).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import types as t
from ..api.snapshot import Snapshot

MAX_NODE_SCORE = 100

# Status codes (framework/interface.go — Code)
SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
UNSCHEDULABLE_AND_UNRESOLVABLE = "UnschedulableAndUnresolvable"
ERROR = "Error"


@dataclass
class Status:
    code: str = SUCCESS
    reasons: Tuple[str, ...] = ()
    # the plugin that produced a failing status (framework.go stamps this via
    # Status.WithPlugin; the queue derives QueueingHint events from it)
    plugin: str = ""

    @property
    def ok(self) -> bool:
        return self.code == SUCCESS

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(UNSCHEDULABLE, reasons)


@dataclass
class CycleState:
    """Per-scheduling-cycle scratch shared between a plugin's extension points
    (framework/cycle_state.go — CycleState)."""

    data: Dict[str, object] = field(default_factory=dict)


@dataclass
class NodeInfo:
    """Aggregated scheduling view of one node (framework/types.go — NodeInfo)."""

    node: t.Node
    pods: List[t.Pod] = field(default_factory=list)
    requested: Dict[str, int] = field(default_factory=dict)

    def add_pod(self, pod: t.Pod, resources: Sequence[str]) -> None:
        from ..api.snapshot import pod_effective_requests

        self.pods.append(pod)
        for r, q in zip(resources, pod_effective_requests(pod, resources)):
            self.requested[r] = self.requested.get(r, 0) + q

    def remove_pod(self, pod: t.Pod, resources: Sequence[str]) -> None:
        from ..api.snapshot import pod_effective_requests

        self.pods = [q for q in self.pods if q.uid != pod.uid]
        for r, q in zip(resources, pod_effective_requests(pod, resources)):
            self.requested[r] = self.requested.get(r, 0) - q


class Plugin:
    """Base: a plugin implements any subset of the extension-point methods.
    Method absence == not registered at that point (the runtime checks with
    hasattr, mirroring the reference's per-point plugin lists)."""

    name: str = "Plugin"

    # PreEnqueue(pod) -> Status
    # EventsToRegister() -> list of event kinds that can make pods schedulable
    # PreFilter(state, snapshot, pod) -> Status
    # Filter(state, snapshot, pod, node_info) -> Status
    # PostFilter(state, snapshot, pod, filtered_statuses) -> (nominated_node, Status)
    # PreScore(state, snapshot, pod, nodes) -> Status
    # Score(state, snapshot, pod, node_info) -> float
    # NormalizeScore(state, snapshot, pod, scores) -> None (in place)
    # Reserve/Unreserve(state, snapshot, pod, node_name)
    # Permit(state, snapshot, pod, node_name) -> Status
    # PreBind/Bind/PostBind(state, snapshot, pod, node_name) -> Status


@dataclass
class PluginWeight:
    plugin: Plugin
    weight: float = 1.0


class Framework:
    """frameworkImpl: holds the enabled plugins per extension point and runs
    the fan-outs.  The Filter/Score fan-out here is the sequential CPU path;
    see ops/assign.py for the batched TPU equivalent.

    Observability: every plugin call at every extension point feeds the
    reference-named framework_extension_point_duration_seconds
    {extension_point, plugin} labeled histogram (metrics.go — the scheduler's
    per-extension-point latency attribution), and — when the tracer's
    collector is enabled — emits one child span per (extension point, plugin)
    under the current scheduling-cycle span.  The span path allocates nothing
    when tracing is off (the tracer.enabled gate, klog.V(n).enabled shape)."""

    def __init__(self, plugins: Sequence[PluginWeight], tracer=None, metrics=None):
        self.plugins = list(plugins)
        self.tracer = tracer
        self.metrics = metrics
        # (point, plugin) -> resolved _Hist: repeat observations skip the
        # metrics registry lock (Filter runs once per NODE per plugin)
        self._ep_hists: Dict[Tuple[str, str], object] = {}

    def _at(self, point: str) -> List[PluginWeight]:
        return [pw for pw in self.plugins if hasattr(pw.plugin, point)]

    def _run1(self, point: str, plugin: Plugin, fn, *args):
        """One plugin call at one extension point: labeled-histogram timing
        always, a child span only when tracing is enabled."""
        m = self.metrics
        tr = self.tracer
        if tr is not None and tr.enabled:
            with tr.span(f"{point}/{plugin.name}",
                         extension_point=point, plugin=plugin.name):
                return self._timed(point, plugin, fn, args) if m is not None else fn(*args)
        if m is None:
            return fn(*args)
        return self._timed(point, plugin, fn, args)

    def _ep_hist(self, point: str, name: str):
        key = (point, name)
        h = self._ep_hists.get(key)
        if h is None:
            h = self._ep_hists[key] = self.metrics.labeled_hist(
                "framework_extension_point_duration_seconds",
                extension_point=point, plugin=name,
            )
        return h

    def _timed(self, point: str, plugin: Plugin, fn, args):
        h = self._ep_hist(point, plugin.name)
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            h.observe(time.perf_counter() - t0)

    def run_pre_enqueue(self, pod: t.Pod) -> Status:
        for pw in self._at("PreEnqueue"):
            st = self._run1("PreEnqueue", pw.plugin, pw.plugin.PreEnqueue, pod)
            if not st.ok:
                return st
        return Status()

    def run_pre_filter(self, state: CycleState, snap: Snapshot, pod: t.Pod) -> Status:
        for pw in self._at("PreFilter"):
            st = self._run1("PreFilter", pw.plugin, pw.plugin.PreFilter,
                            state, snap, pod)
            if not st.ok:
                return st
        return Status()

    def run_filters(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, info: NodeInfo
    ) -> Status:
        """Filter is the one per-NODE fan-out: a span per (node, plugin) call
        would flood the collector ring at cluster scale (N·P spans per pod),
        so traced runs ACCUMULATE per-plugin durations into the CycleState
        and the scheduler flushes one aggregate Filter/<plugin> span per
        cycle (scheduler._find_feasible).  The labeled histogram still sees
        every call."""
        from dataclasses import replace as _replace

        tr = self.tracer
        tracing = tr is not None and tr.enabled
        for pw in self._at("Filter"):
            if tracing:
                # one perf_counter pair feeds BOTH the labeled histogram and
                # the per-cycle span accumulator
                t0 = time.perf_counter()
                st = pw.plugin.Filter(state, snap, pod, info)
                dt = time.perf_counter() - t0
                if self.metrics is not None:
                    self._ep_hist("Filter", pw.plugin.name).observe(dt)
                agg = state.data.setdefault("_filter_trace", {})
                cur = agg.get(pw.plugin.name)
                agg[pw.plugin.name] = (
                    (cur[0] + dt, cur[1] + 1) if cur else (dt, 1)
                )
            else:
                st = self._run1("Filter", pw.plugin, pw.plugin.Filter,
                                state, snap, pod, info)
            if not st.ok:
                return st if st.plugin else _replace(st, plugin=pw.plugin.name)
        return Status()

    def events_for_plugins(self, plugin_names) -> set:
        """Union of the named plugins' EventsToRegister — the cluster events
        that could make a pod they rejected schedulable (QueueingHint's
        registration half).  Unknown plugins contribute the wildcard."""
        from ..scheduler.queue import EV_ALL

        out: set = set()
        by_name = {pw.plugin.name: pw.plugin for pw in self.plugins}
        for name in plugin_names:
            plugin = by_name.get(name)
            evs = getattr(plugin, "EventsToRegister", None)
            if plugin is None or evs is None:
                out.add(EV_ALL)
            else:
                out.update(evs())
        return out or {EV_ALL}

    def hints_for_plugins(self, plugin_names) -> Dict[str, list]:
        """event kind -> [QueueingHintFn(obj, old, pod) -> bool] from the
        named plugins (scheduling_queue.go — QueueingHintFn: per-EVENT-OBJECT
        Queue/Skip, the precise half of the QueueingHint machinery).

        A kind appears only when EVERY named plugin registering it supplies a
        hint — one hintless registrant means that kind must wake
        unconditionally, so it is left out (the queue's conservative path)."""
        by_name = {pw.plugin.name: pw.plugin for pw in self.plugins}
        fns: Dict[str, list] = {}
        unconditional: set = set()
        for name in plugin_names:
            plugin = by_name.get(name)
            evs = getattr(plugin, "EventsToRegister", None)
            if plugin is None or evs is None:
                continue
            hint = getattr(plugin, "queueing_hint", None)
            for ev in evs():
                if hint is None:
                    unconditional.add(ev)
                else:
                    fns.setdefault(ev, []).append(
                        lambda obj, old, pod, _h=hint, _e=ev: _h(_e, obj, old, pod)
                    )
        return {ev: h for ev, h in fns.items() if ev not in unconditional}

    def run_post_filters(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, statuses: Dict[str, Status]
    ) -> Tuple[Optional[str], Status]:
        for pw in self._at("PostFilter"):
            nominated, st = self._run1("PostFilter", pw.plugin,
                                       pw.plugin.PostFilter,
                                       state, snap, pod, statuses)
            if st.ok:
                return nominated, st
        return None, Status.unschedulable("no postfilter plugin succeeded")

    def run_pre_score(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, nodes: List[NodeInfo]
    ) -> None:
        for pw in self._at("PreScore"):
            self._run1("PreScore", pw.plugin, pw.plugin.PreScore,
                       state, snap, pod, nodes)

    def run_scores(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, infos: List[NodeInfo]
    ) -> np.ndarray:
        """Weighted sum over Score plugins with per-plugin NormalizeScore —
        RunScorePlugins (framework.go ~:900).  One span/observation covers a
        plugin's whole node fan-out including NormalizeScore (the reference
        times RunScorePlugins per plugin the same way)."""
        total = np.zeros(len(infos), dtype=np.float32)
        for pw in self._at("Score"):
            raw = self._run1("Score", pw.plugin, self._score_one,
                             pw.plugin, state, snap, pod, infos)
            total += np.float32(pw.weight) * raw
        return total

    @staticmethod
    def _score_one(plugin, state, snap, pod, infos) -> np.ndarray:
        raw = np.array(
            [np.float32(plugin.Score(state, snap, pod, ni)) for ni in infos],
            dtype=np.float32,
        )
        if hasattr(plugin, "NormalizeScore"):
            plugin.NormalizeScore(state, snap, pod, raw)
        return raw

    def run_reserve(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Reserve"):
            st = self._run1("Reserve", pw.plugin, pw.plugin.Reserve,
                            state, snap, pod, node_name)
            if not st.ok:
                self.run_unreserve(state, snap, pod, node_name)
                return st
        return Status()

    def run_unreserve(self, state, snap, pod, node_name) -> None:
        for pw in reversed(self._at("Unreserve")):
            self._run1("Unreserve", pw.plugin, pw.plugin.Unreserve,
                       state, snap, pod, node_name)

    def run_permit(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Permit"):
            st = self._run1("Permit", pw.plugin, pw.plugin.Permit,
                            state, snap, pod, node_name)
            if not st.ok:
                return st
        return Status()

    def run_pre_bind(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("PreBind"):
            st = self._run1("PreBind", pw.plugin, pw.plugin.PreBind,
                            state, snap, pod, node_name)
            if not st.ok:
                return st
        return Status()

    def run_bind(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Bind"):
            st = self._run1("Bind", pw.plugin, pw.plugin.Bind,
                            state, snap, pod, node_name)
            if st.code != "Skip":
                return st
        return Status(ERROR, ("no bind plugin",))

    def run_post_bind(self, state, snap, pod, node_name) -> None:
        for pw in self._at("PostBind"):
            self._run1("PostBind", pw.plugin, pw.plugin.PostBind,
                       state, snap, pod, node_name)
