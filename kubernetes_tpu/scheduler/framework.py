"""Scheduler Framework — the plugin extension-point API and its runtime.

Analog of the reference's pkg/scheduler/framework/interface.go (one interface
per extension point: PreEnqueue, QueueingHint, PreFilter, Filter, PostFilter,
PreScore, Score+NormalizeScore, Reserve, Permit, PreBind, Bind, PostBind) and
framework/runtime/framework.go (frameworkImpl — RunFilterPlugins /
RunScorePlugins fan-out).  This host-side path IS the CPU fallback the north
star mandates: plugins here reproduce the kernels' semantics one pod at a time;
the TPU path replaces the per-pod Filter/Score fan-out with the batched kernel
while everything else (queue, binding cycle, preemption) is shared.

MaxNodeScore = 100 (interface.go — MaxNodeScore).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import types as t
from ..api.snapshot import Snapshot

MAX_NODE_SCORE = 100

# Status codes (framework/interface.go — Code)
SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
UNSCHEDULABLE_AND_UNRESOLVABLE = "UnschedulableAndUnresolvable"
ERROR = "Error"


@dataclass
class Status:
    code: str = SUCCESS
    reasons: Tuple[str, ...] = ()
    # the plugin that produced a failing status (framework.go stamps this via
    # Status.WithPlugin; the queue derives QueueingHint events from it)
    plugin: str = ""

    @property
    def ok(self) -> bool:
        return self.code == SUCCESS

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(UNSCHEDULABLE, reasons)


@dataclass
class CycleState:
    """Per-scheduling-cycle scratch shared between a plugin's extension points
    (framework/cycle_state.go — CycleState)."""

    data: Dict[str, object] = field(default_factory=dict)


@dataclass
class NodeInfo:
    """Aggregated scheduling view of one node (framework/types.go — NodeInfo)."""

    node: t.Node
    pods: List[t.Pod] = field(default_factory=list)
    requested: Dict[str, int] = field(default_factory=dict)

    def add_pod(self, pod: t.Pod, resources: Sequence[str]) -> None:
        from ..api.snapshot import pod_effective_requests

        self.pods.append(pod)
        for r, q in zip(resources, pod_effective_requests(pod, resources)):
            self.requested[r] = self.requested.get(r, 0) + q

    def remove_pod(self, pod: t.Pod, resources: Sequence[str]) -> None:
        from ..api.snapshot import pod_effective_requests

        self.pods = [q for q in self.pods if q.uid != pod.uid]
        for r, q in zip(resources, pod_effective_requests(pod, resources)):
            self.requested[r] = self.requested.get(r, 0) - q


class Plugin:
    """Base: a plugin implements any subset of the extension-point methods.
    Method absence == not registered at that point (the runtime checks with
    hasattr, mirroring the reference's per-point plugin lists)."""

    name: str = "Plugin"

    # PreEnqueue(pod) -> Status
    # EventsToRegister() -> list of event kinds that can make pods schedulable
    # PreFilter(state, snapshot, pod) -> Status
    # Filter(state, snapshot, pod, node_info) -> Status
    # PostFilter(state, snapshot, pod, filtered_statuses) -> (nominated_node, Status)
    # PreScore(state, snapshot, pod, nodes) -> Status
    # Score(state, snapshot, pod, node_info) -> float
    # NormalizeScore(state, snapshot, pod, scores) -> None (in place)
    # Reserve/Unreserve(state, snapshot, pod, node_name)
    # Permit(state, snapshot, pod, node_name) -> Status
    # PreBind/Bind/PostBind(state, snapshot, pod, node_name) -> Status


@dataclass
class PluginWeight:
    plugin: Plugin
    weight: float = 1.0


class Framework:
    """frameworkImpl: holds the enabled plugins per extension point and runs
    the fan-outs.  The Filter/Score fan-out here is the sequential CPU path;
    see ops/assign.py for the batched TPU equivalent."""

    def __init__(self, plugins: Sequence[PluginWeight]):
        self.plugins = list(plugins)

    def _at(self, point: str) -> List[PluginWeight]:
        return [pw for pw in self.plugins if hasattr(pw.plugin, point)]

    def run_pre_enqueue(self, pod: t.Pod) -> Status:
        for pw in self._at("PreEnqueue"):
            st = pw.plugin.PreEnqueue(pod)
            if not st.ok:
                return st
        return Status()

    def run_pre_filter(self, state: CycleState, snap: Snapshot, pod: t.Pod) -> Status:
        for pw in self._at("PreFilter"):
            st = pw.plugin.PreFilter(state, snap, pod)
            if not st.ok:
                return st
        return Status()

    def run_filters(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, info: NodeInfo
    ) -> Status:
        from dataclasses import replace as _replace

        for pw in self._at("Filter"):
            st = pw.plugin.Filter(state, snap, pod, info)
            if not st.ok:
                return st if st.plugin else _replace(st, plugin=pw.plugin.name)
        return Status()

    def events_for_plugins(self, plugin_names) -> set:
        """Union of the named plugins' EventsToRegister — the cluster events
        that could make a pod they rejected schedulable (QueueingHint's
        registration half).  Unknown plugins contribute the wildcard."""
        from ..scheduler.queue import EV_ALL

        out: set = set()
        by_name = {pw.plugin.name: pw.plugin for pw in self.plugins}
        for name in plugin_names:
            plugin = by_name.get(name)
            evs = getattr(plugin, "EventsToRegister", None)
            if plugin is None or evs is None:
                out.add(EV_ALL)
            else:
                out.update(evs())
        return out or {EV_ALL}

    def hints_for_plugins(self, plugin_names) -> Dict[str, list]:
        """event kind -> [QueueingHintFn(obj, old, pod) -> bool] from the
        named plugins (scheduling_queue.go — QueueingHintFn: per-EVENT-OBJECT
        Queue/Skip, the precise half of the QueueingHint machinery).

        A kind appears only when EVERY named plugin registering it supplies a
        hint — one hintless registrant means that kind must wake
        unconditionally, so it is left out (the queue's conservative path)."""
        by_name = {pw.plugin.name: pw.plugin for pw in self.plugins}
        fns: Dict[str, list] = {}
        unconditional: set = set()
        for name in plugin_names:
            plugin = by_name.get(name)
            evs = getattr(plugin, "EventsToRegister", None)
            if plugin is None or evs is None:
                continue
            hint = getattr(plugin, "queueing_hint", None)
            for ev in evs():
                if hint is None:
                    unconditional.add(ev)
                else:
                    fns.setdefault(ev, []).append(
                        lambda obj, old, pod, _h=hint, _e=ev: _h(_e, obj, old, pod)
                    )
        return {ev: h for ev, h in fns.items() if ev not in unconditional}

    def run_post_filters(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, statuses: Dict[str, Status]
    ) -> Tuple[Optional[str], Status]:
        for pw in self._at("PostFilter"):
            nominated, st = pw.plugin.PostFilter(state, snap, pod, statuses)
            if st.ok:
                return nominated, st
        return None, Status.unschedulable("no postfilter plugin succeeded")

    def run_pre_score(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, nodes: List[NodeInfo]
    ) -> None:
        for pw in self._at("PreScore"):
            pw.plugin.PreScore(state, snap, pod, nodes)

    def run_scores(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, infos: List[NodeInfo]
    ) -> np.ndarray:
        """Weighted sum over Score plugins with per-plugin NormalizeScore —
        RunScorePlugins (framework.go ~:900)."""
        total = np.zeros(len(infos), dtype=np.float32)
        for pw in self._at("Score"):
            raw = np.array(
                [np.float32(pw.plugin.Score(state, snap, pod, ni)) for ni in infos],
                dtype=np.float32,
            )
            if hasattr(pw.plugin, "NormalizeScore"):
                pw.plugin.NormalizeScore(state, snap, pod, raw)
            total += np.float32(pw.weight) * raw
        return total

    def run_reserve(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Reserve"):
            st = pw.plugin.Reserve(state, snap, pod, node_name)
            if not st.ok:
                self.run_unreserve(state, snap, pod, node_name)
                return st
        return Status()

    def run_unreserve(self, state, snap, pod, node_name) -> None:
        for pw in reversed(self._at("Unreserve")):
            pw.plugin.Unreserve(state, snap, pod, node_name)

    def run_permit(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Permit"):
            st = pw.plugin.Permit(state, snap, pod, node_name)
            if not st.ok:
                return st
        return Status()

    def run_pre_bind(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("PreBind"):
            st = pw.plugin.PreBind(state, snap, pod, node_name)
            if not st.ok:
                return st
        return Status()

    def run_bind(self, state, snap, pod, node_name) -> Status:
        for pw in self._at("Bind"):
            st = pw.plugin.Bind(state, snap, pod, node_name)
            if st.code != "Skip":
                return st
        return Status(ERROR, ("no bind plugin",))

    def run_post_bind(self, state, snap, pod, node_name) -> None:
        for pw in self._at("PostBind"):
            pw.plugin.PostBind(state, snap, pod, node_name)
