"""Volume binder — PreBind-time PVC→PV binding + dynamic provisioning.

reference: pkg/scheduler/framework/plugins/volumebinding/binder.go —
SchedulerVolumeBinder: FindPodVolumes picks static matches / provisionable
classes during filtering (in this framework that feasibility half lives in
api/volumes.resolve_pod, shared by all execution paths), and BindPodVolumes
commits them at PreBind: bind matched static PVs (claimRef ↔ volumeName) and
create PVs for claims whose StorageClass has a provisioner (the external
provisioner collapsed in-process, like every other external component here).

Provisioned-PV topology: the class's allowedTopology when set; otherwise the
selected node's zone label when present (the common zonal-provisioner shape),
else pinned to the node's hostname (local-volume shape).
"""

from __future__ import annotations

import hashlib
from dataclasses import replace
from typing import Dict, Optional

from ..api import types as t
from .store import ClusterStore


def _node_topology(node: t.Node) -> tuple:
    zone = node.labels.get(t.LABEL_ZONE)
    if zone is not None:
        return ((t.LABEL_ZONE, zone),)
    return ((t.LABEL_HOSTNAME, node.name),)


def _matches_node(topology: tuple, node: t.Node) -> bool:
    """Same semantics as volumes._topology_term's lowering: pairs sharing a
    key OR their values (TopologySelectorTerm.matchLabelExpressions carries
    values[] per key), distinct keys AND — a class allowing zone-0 OR
    zone-1 must provision in either, not in the empty zone-0∧zone-1."""
    by_key: dict = {}
    for k, v in topology:
        by_key.setdefault(k, set()).add(v)
    return all(node.labels.get(k) in vs for k, vs in by_key.items())


def bind_pod_volumes(store: ClusterStore, pod: t.Pod, node_name: str) -> Optional[str]:
    """Bind every unbound claim of `pod` for placement on `node_name`.
    Returns an error string (PreBind failure → pod requeues) or None.

    Runs under the store's transaction lock: concurrent binding workers
    (binding_workers > 0) must not both match the same unbound PV — the
    find-then-write sequence here is check-and-commit, and the in-process
    store has no resourceVersion conflict to catch the race."""
    with store.transaction():
        return _bind_pod_volumes_locked(store, pod, node_name)


def _bind_pod_volumes_locked(
    store: ClusterStore, pod: t.Pod, node_name: str
) -> Optional[str]:
    node = store.nodes.get(node_name)
    if node is None:
        return f"node {node_name!r} vanished before volume binding"
    classes: Dict[str, object] = store.objects.get("StorageClass", {})
    for claim_name in pod.pvcs:
        pvc = store.pvcs.get(f"{pod.namespace}/{claim_name}")
        if pvc is None:
            continue  # missing claims were filtered upstream
        if pvc.volume_name:
            # already bound — possibly by a same-batch sibling AFTER this
            # pod's verdict was computed: re-check the volume reaches us
            pv = store.pvs.get(pvc.volume_name)
            if pv is None or not _matches_node(pv.allowed_topology, node):
                return (
                    f"claim {pvc.key!r} bound to volume {pvc.volume_name!r} "
                    f"which is not reachable from {node_name}"
                )
            continue
        # static match first (binder.go prefers pre-provisioned PVs)
        static = sorted(
            (
                pv
                for pv in store.pvs.values()
                if not pv.claim_ref
                and pv.storage_class == pvc.storage_class
                and pv.capacity >= pvc.request
                and _matches_node(pv.allowed_topology, node)
            ),
            # smallest satisfying volume (pv_controller's findBestMatch), name tie-break
            key=lambda pv: (pv.capacity, pv.name),
        )
        if static:
            pv = replace(static[0], claim_ref=pvc.key)
            store.update_pv(pv)
        else:
            sc = classes.get(pvc.storage_class)
            if sc is None or not sc.provisioner:
                return (
                    f"claim {pvc.key!r}: no matching PersistentVolume on "
                    f"{node_name} and storage class {pvc.storage_class!r} "
                    "does not provision"
                )
            if sc.allowed_topology and not _matches_node(
                tuple(sc.allowed_topology), node
            ):
                # the class cannot provision where the pod landed (e.g. a
                # same-batch sibling consumed the static PV this verdict
                # relied on): fail PreBind, pod retries
                return (
                    f"claim {pvc.key!r}: class {sc.name!r} cannot provision "
                    f"a volume reachable from {node_name}"
                )
            # the hash disambiguates ns/name pairs whose dash-joined forms
            # collide (the reference names provisioned PVs by claim UID)
            tag = hashlib.sha1(pvc.key.encode()).hexdigest()[:8]
            pv = t.PersistentVolume(
                name=f"pvc-{pvc.namespace}-{pvc.name}-{tag}",
                capacity=pvc.request,
                storage_class=pvc.storage_class,
                allowed_topology=tuple(sc.allowed_topology) or _node_topology(node),
                claim_ref=pvc.key,
            )
            store.add_pv(pv)
        store.update_pvc(replace(pvc, volume_name=pv.name))
    return None
