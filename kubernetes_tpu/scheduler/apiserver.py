"""APIServer facade — the generic server's handler chain, in-process.

reference: staging/src/k8s.io/apiserver/pkg/server/config.go —
DefaultBuildHandlerChain: panic-recovery -> timeout -> authentication ->
audit -> Priority&Fairness -> authorization -> admission -> registry store.
This facade reproduces that order over the in-process ClusterStore: each
`handle()` call is one API request.  Components that want the unfiltered
fast path (the scheduler's own binding loop, the harness) keep talking to
ClusterStore directly — the reference's loopback client is similarly exempted
from APF (the "exempt" priority level).

Also owns the Service ClusterIP allocator (the core/v1 Service REST strategy's
ipallocator — pkg/registry/core/service/ipallocator).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..api import cluster as c
from ..api import types as t
from .admission import AdmissionChain, AdmissionDenied, Attributes, PolicyPlugin
from .auth import RBACAuthorizer, TokenAuthenticator
from .flowcontrol import APFController, Request, RequestRejected
from .store import ClusterStore


class Unauthenticated(Exception):
    """HTTP 401."""


class Forbidden(Exception):
    """HTTP 403."""


# kind -> RBAC resource name (lowercased plural, the RESTMapper's job)
_RESOURCES = {
    "Pod": "pods",
    "Node": "nodes",
    "PDB": "poddisruptionbudgets",
    "Service": "services",
    "EndpointSlice": "endpointslices",
    "Namespace": "namespaces",
    "ReplicaSet": "replicasets",
    "Deployment": "deployments",
    "Job": "jobs",
    "StatefulSet": "statefulsets",
    "DaemonSet": "daemonsets",
    "CronJob": "cronjobs",
    "PriorityClass": "priorityclasses",
    "ResourceQuota": "resourcequotas",
    "LimitRange": "limitranges",
    "HorizontalPodAutoscaler": "horizontalpodautoscalers",
    "Role": "roles",
    "RoleBinding": "rolebindings",
    "Lease": "leases",
    "PV": "persistentvolumes",
    "PVC": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "DeviceClass": "deviceclasses",
}


def resource_of(kind: str) -> str:
    return _RESOURCES.get(kind, kind.lower() + "s")


@dataclass
class AuditEvent:
    """audit/v1 — the fields that matter for the log (apiserver/pkg/audit)."""

    user: str
    verb: str
    resource: str
    namespace: str
    name: str
    allowed: bool
    reason: str = ""


class ClusterIPAllocator:
    """pkg/registry/core/service/ipallocator — sequential allocator over a
    /16 service CIDR with reuse of freed addresses."""

    def __init__(self, prefix: str = "10.96"):
        self.prefix = prefix
        self._next = 1
        self._free: List[int] = []
        self._used: set = set()

    def allocate(self) -> str:
        if self._free:
            n = self._free.pop()
        else:
            n = self._next
            self._next += 1
        self._used.add(n)
        return f"{self.prefix}.{n >> 8 & 0xFF}.{n & 0xFF}"

    def release(self, ip: str) -> None:
        parts = ip.split(".")
        n = (int(parts[2]) << 8) | int(parts[3])
        if n in self._used:
            self._used.discard(n)
            self._free.append(n)


class MetricsServer:
    """The /metrics exposition route — a minimal HTTP server over the
    in-process registry (staging/src/k8s.io/component-base/metrics/legacyregistry
    served through the generic server's /metrics handler).  `render` is a
    zero-arg callable returning the Prometheus text body
    (Metrics.expose_text), re-evaluated per scrape; /healthz answers 200 ok
    so probes can target the same port.  port=0 binds an ephemeral port
    (returned by start())."""

    def __init__(self, render, host: str = "127.0.0.1", port: int = 0):
        import http.server
        import threading

        srv_render = render

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] == "/metrics":
                    body = srv_render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/healthz":
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # pragma: no cover — quiet scrapes
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-exposition",
        )

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class APIServer:
    def __init__(
        self,
        store: ClusterStore,
        authenticator: Optional[TokenAuthenticator] = None,
        policies: Optional[PolicyPlugin] = None,
        webhooks: tuple = (),
        total_concurrency: int = 600,
        queue_wait_s: float = 5.0,
        tracer=None,
        metrics=None,
    ):
        from .tracing import Tracer

        self.store = store
        self.queue_wait_s = queue_wait_s
        # each handle() call is one traced request (the reference wraps the
        # handler chain in an otelhttp span the same way); a created Pod
        # inherits the request span as its trace root, so the pod's queue /
        # scheduling / kubelet spans all join one tree
        self.tracer = tracer or Tracer(component="apiserver")
        self.authn = authenticator or TokenAuthenticator()
        self.authz = RBACAuthorizer(store)
        self.apf = APFController(store, total_concurrency=total_concurrency)
        self.admission = AdmissionChain.default(store, policies, webhooks)
        self.audit_log: List[AuditEvent] = []
        self.ips = ClusterIPAllocator()
        # the registry the /metrics route serves (scheduler/metrics.py —
        # usually the scheduler's own Metrics, injected so one scrape
        # covers the whole control plane); lazily created when absent so
        # metrics_text() always renders valid exposition
        from .metrics import Metrics

        self.metrics = metrics if metrics is not None else Metrics()
        self._metrics_server: Optional[MetricsServer] = None
        from .crd import CRDRegistry

        # apiextensions: dynamic kinds with per-version structural schemas
        self.crds = CRDRegistry(store)

    # -- the /metrics route --
    def metrics_text(self) -> str:
        """The Prometheus text body GET /metrics serves — the full registry
        (counters, gauges, labeled series, streaming-histogram buckets)."""
        return self.metrics.expose_text()

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Start (idempotently) the HTTP exposition server for this
        apiserver's registry; returns the bound port.  KTPU_METRICS=<port>
        is the env-knob spelling harness/bench runs use."""
        if self._metrics_server is None:
            self._metrics_server = MetricsServer(
                self.metrics_text, host=host, port=port
            )
            self._metrics_server.start()
        return self._metrics_server.port

    def stop_metrics(self) -> None:
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    # -- the handler chain --
    def handle(
        self,
        token: Optional[str],
        verb: str,
        kind: str,
        obj: object = None,
        namespace: str = "",
        name: str = "",
        impersonate_user: Optional[str] = None,
    ):
        """One request through the full chain.  Returns the stored object for
        writes / the object (list) for reads."""
        if not self.tracer.enabled:
            return self._handle(token, verb, kind, obj, namespace, name,
                                impersonate_user)
        with self.tracer.span(
            "apiserver.request", parent=None, verb=verb, kind=kind
        ) as sp:
            out = self._handle(token, verb, kind, obj, namespace, name,
                               impersonate_user)
            if verb == "create" and kind == "Pod" and sp is not None:
                uid = getattr(out, "uid", "")
                if uid:
                    # the request span becomes the pod's trace root: queue,
                    # scheduler and kubelet spans chain under it
                    sp.attributes["pod"] = uid
                    self.tracer.collector.attach_pod(uid, sp.context)
            return out

    def _handle(
        self,
        token: Optional[str],
        verb: str,
        kind: str,
        obj: object = None,
        namespace: str = "",
        name: str = "",
        impersonate_user: Optional[str] = None,
    ):
        resource = resource_of(kind)
        ns = namespace or getattr(obj, "namespace", "") or ""
        nm = name or getattr(obj, "name", "") or ""

        # authentication
        user = self.authn.authenticate(token)
        if user is None:
            self._audit("anonymous", verb, resource, ns, nm, False, "unauthenticated")
            raise Unauthenticated("invalid or missing bearer token")

        # impersonation (the Impersonate-User header; chain position matches
        # DefaultBuildHandlerChain: after authn, before audit/authz): the
        # AUTHENTICATED user needs the `impersonate` verb on `users`, then
        # the request proceeds as — and is audited as — the impersonated user
        if impersonate_user is not None:
            from ..api import cluster as c

            if not self.authz.authorize(user, "impersonate", "users", "", impersonate_user):
                self._audit(user.name, verb, resource, ns, nm, False,
                            f"cannot impersonate {impersonate_user!r}")
                raise Forbidden(
                    f'user "{user.name}" cannot impersonate user "{impersonate_user}"'
                )
            user = c.UserInfo(name=impersonate_user, groups=())

        # priority & fairness: classify + fair-queue; in this synchronous
        # facade the request must come out of dispatch() before proceeding
        # (exempt levels release immediately and never queue)
        req = Request(user=user.name, verb=verb, resource=resource, namespace=ns)
        self.apf.admit(req)  # raises RequestRejected (429) when queues overflow
        deadline = time.monotonic() + self.queue_wait_s
        while not req.released:
            self.apf.dispatch()
            if req.released:
                break
            if time.monotonic() > deadline:
                self.apf.cancel(req)  # dequeue (or return a late-released seat)
                raise RequestRejected(
                    f"request from {user.name!r} timed out waiting for a seat "
                    f"at level {req.level!r}"
                )
            time.sleep(0.001)  # seats held by concurrent callers

        try:
            # authorization
            if not self.authz.authorize(user, verb, resource, ns, nm):
                self._audit(user.name, verb, resource, ns, nm, False, "forbidden")
                raise Forbidden(
                    f'user "{user.name}" cannot {verb} resource "{resource}"'
                    + (f' in namespace "{ns}"' if ns else "")
                )

            # admission (writes only), then the registry
            if verb in ("create", "update"):
                attrs = Attributes(verb=verb, kind=kind, namespace=ns, obj=obj,
                                   user=user)
                obj = self.admission.run(attrs)  # raises AdmissionDenied (400)
                out = self._write(verb, kind, obj)
            elif verb == "delete":
                self._delete(kind, ns, nm)
                out = None
            elif verb == "list":
                out = self._list(kind, ns or None)
            elif verb == "get":
                out = self._get(kind, ns, nm)
            else:
                raise ValueError(f"unsupported verb {verb!r}")
            self._audit(user.name, verb, resource, ns, nm, True)
            return out
        finally:
            self.apf.finish(req)

    # -- registry dispatch --
    def _write(self, verb: str, kind: str, obj):
        if kind == "Pod":
            (self.store.add_pod if verb == "create" else self.store.update_pod)(obj)
        elif kind == "Node":
            (self.store.add_node if verb == "create" else self.store.update_node)(obj)
        elif kind == "PDB":
            (self.store.add_pdb if verb == "create" else self.store.update_pdb)(obj)
        elif kind == "PV":
            (self.store.add_pv if verb == "create" else self.store.update_pv)(obj)
        elif kind == "PVC":
            (self.store.add_pvc if verb == "create" else self.store.update_pvc)(obj)
        else:
            from .crd import CRDInvalid, CRValidationError

            if kind == "CustomResourceDefinition":
                if verb != "create":
                    raise ValueError("CRD updates not supported; delete + recreate")
                try:
                    return self.crds.create(obj)
                except CRDInvalid as e:
                    raise AdmissionDenied(str(e)) from e
            if self.crds.definition_for(kind) is not None:
                # custom kind: served-version check + structural-schema
                # validation + storage-version conversion
                # (customresource_handler.go — the validation admission)
                try:
                    obj = self.crds.admit(obj)
                except CRValidationError as e:
                    raise AdmissionDenied(str(e)) from e
            if kind == "Service" and verb == "create" and not obj.cluster_ip:
                obj.cluster_ip = self.ips.allocate()
            (self.store.add_object if verb == "create" else self.store.update_object)(
                kind, obj
            )
        return obj

    def _find_pod(self, ns: str, name: str):
        """Pods are stored by uid; API identity is namespace/name.  Try the
        defaulted-uid fast path, then scan (the registry's name index)."""
        p = self.store.pods.get(f"{ns}/{name}")
        if p is not None and p.namespace == ns and p.name == name:
            return p
        for p in self.store.list_pods():
            if p.namespace == ns and p.name == name:
                return p
        return None

    def _delete(self, kind: str, ns: str, name: str) -> None:
        key = f"{ns}/{name}" if ns else name
        if kind == "Pod":
            p = self._find_pod(ns, name)
            if p is not None:
                self.store.delete_pod(p.uid)
        elif kind == "Node":
            self.store.delete_node(name)
        elif kind == "PDB":
            self.store.delete_pdb(key)
        elif kind == "PV":
            self.store.delete_pv(name)
        elif kind == "PVC":
            self.store.delete_pvc(key)
        else:
            if kind == "CustomResourceDefinition":
                self.crds.delete(name)
                return
            if kind == "Service":
                svc = self.store.get_object("Service", key)
                if svc is not None and svc.cluster_ip:
                    self.ips.release(svc.cluster_ip)
            self.store.delete_object(kind, key)

    def _get(self, kind: str, ns: str, name: str):
        if kind == "Pod":
            return self._find_pod(ns, name)
        if kind == "Node":
            return self.store.nodes.get(name)
        if kind == "PDB":
            return self.store.pdbs.get(f"{ns}/{name}")
        if kind == "PV":
            return self.store.pvs.get(name)
        if kind == "PVC":
            return self.store.pvcs.get(f"{ns}/{name}")
        return self.store.get_object(kind, f"{ns}/{name}" if ns else name)

    def _list(self, kind: str, ns: Optional[str]):
        if kind == "Pod":
            return [p for p in self.store.list_pods()
                    if ns is None or p.namespace == ns]
        if kind == "Node":
            return self.store.list_nodes()
        if kind == "PDB":
            return [p for p in self.store.list_pdbs()
                    if ns is None or p.namespace == ns]
        if kind == "PV":
            return self.store.list_pvs()
        if kind == "PVC":
            return [p for p in self.store.list_pvcs()
                    if ns is None or p.namespace == ns]
        return self.store.list_objects(kind, ns)

    def _audit(self, user: str, verb: str, resource: str, ns: str, name: str,
               allowed: bool, reason: str = "") -> None:
        self.audit_log.append(
            AuditEvent(user, verb, resource, ns, name, allowed, reason)
        )
