"""Decision flight recorder — the scheduler's crash black box (ISSUE 13).

A bounded ring of the last K batch cycles' COMPACT decision records: wave
verdict fingerprints, the class-index fingerprint, dirty-column counts, the
diagnosis vectors (when KTPU_EXPLAIN ran), and trace ids — a few hundred
bytes of host dict per cycle, no device work, no O(P) state.  It piggybacks
on PR 7's checkpoint dir both ways: armed by it (an unarmed scheduler skips
recording entirely — nothing could ever dump the ring) and dumped into it
when the process dies on an enumerated kill site or a device wave needs
serial-replay recovery, so a crash or a parity miss ships with the
evidence:

    python -m kubernetes_tpu.analysis --flight [path]

Deviation note (PARITY.md): PR 7's kill discipline is that a dying
incarnation does NOTHING a SIGKILL'd process couldn't — the dump bends that
for diagnostics only: flight records are never read by restore(), never
fsync'd, and carry no placement authority (the airline black box written on
the way down; a production deployment would stream records out-of-process).
The ring itself lives in memory; KTPU_FLIGHT_K sizes it (default 64).
"""

from __future__ import annotations

import json
import os
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

from ..analysis.lockcheck import make_lock

FLIGHT_FILENAME = "flight.json"


def fingerprint(obj) -> str:
    """Stable 8-hex-digit content fingerprint (crc32) — two incarnations
    (or a replay) producing identical decisions produce identical
    fingerprints, so a parity miss is visible at a glance.  Cheap enough
    for the always-on per-cycle record: ndarrays hash their raw bytes
    (O(P) memcpy), dicts hash items INCREMENTALLY in insertion order (the
    scheduler's verdict dict fills in deterministic pending-pod order) —
    no sort, no monolithic repr string at 50k-pod scale."""
    if hasattr(obj, "tobytes"):
        return f"{zlib.crc32(obj.tobytes()) & 0xFFFFFFFF:08x}"
    if isinstance(obj, dict):
        crc = 0
        for k, v in obj.items():
            crc = zlib.crc32(f"{k}\x00{v}\x1e".encode(), crc)
        return f"{crc & 0xFFFFFFFF:08x}"
    return f"{zlib.crc32(repr(obj).encode()) & 0xFFFFFFFF:08x}"


class FlightRecorder:
    def __init__(self, directory: Optional[str] = None,
                 capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("KTPU_FLIGHT_K", "64"))
            except ValueError:
                # clamp-with-warning knob semantics (mesh_from_env style):
                # a typo in a purely diagnostic knob must never take the
                # scheduler down at construction
                capacity = 64
        self.capacity = max(1, capacity)
        self.directory = directory
        self._lock = make_lock("FlightRecorder._lock")
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._context: Dict = {}

    def annotate(self, **fields) -> None:
        """Run-level context merged into every later dump (not per-cycle —
        the ring holds those).  The open-loop replay driver stamps the
        arrival-trace fingerprint and its live cursor here each cycle, so
        a mid-stream kill's black box says WHERE in the trace it died."""
        with self._lock:
            self._context.update(fields)

    def record(self, **fields) -> None:
        """Append one cycle record (called once per profile batch — the
        record is a small host dict; the ring bounds total memory)."""
        with self._lock:
            self._seq += 1
            self._ring.append({"seq": self._seq, **fields})

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str = "") -> Optional[str]:
        """Write the ring to <directory>/flight.json (atomic rename; the
        last dump wins — the most recent death owns the black box).  None
        when no checkpoint directory is armed or the write fails: dumping
        evidence must never mask the fault it documents."""
        if not self.directory:
            return None
        with self._lock:
            context = dict(self._context)
        doc = {
            "version": 1,
            "reason": reason,
            "dumped_wall": time.time(),
            "capacity": self.capacity,
            "context": context,
            "records": self.records(),
        }
        path = os.path.join(self.directory, FLIGHT_FILENAME)
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            return None
        return path


def load_flight(path: str) -> Dict:
    """Parse a flight dump; raises ValueError on a missing/corrupt file
    (the --flight CLI maps that to exit 2 — unusable, never silently ok)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable flight dump {path}: {e}") from None
    if (not isinstance(doc, dict)
            or not isinstance(doc.get("records"), list)
            or not all(isinstance(r, dict) for r in doc["records"])):
        raise ValueError(f"not a flight dump: {path}")
    return doc


def render_flight(doc: Dict) -> str:
    """Human rendering for the post-mortem CLI: one line per cycle record,
    newest last, diagnosis summarized as its top reasons."""
    out = [
        f"flight recorder dump — reason: {doc.get('reason') or '<none>'}, "
        f"{len(doc['records'])} record(s) "
        f"(ring capacity {doc.get('capacity', '?')})"
    ]
    ctx = doc.get("context")
    if isinstance(ctx, dict) and ctx:
        # the run-level annotation block (annotate()): for an open-loop
        # kill this names the arrival trace and the offset it died at
        out.append("  context: " + " ".join(
            f"{k}={ctx[k]}" for k in sorted(ctx)
        ))
    for r in doc["records"]:
        line = (
            f"  #{r.get('seq', '?'):>4} {r.get('profile', '')} "
            f"pods={r.get('pods', '?')} scheduled={r.get('scheduled', '?')} "
            f"failed={r.get('failed', '?')} "
            f"verdicts={r.get('verdict_crc', '-')}"
        )
        if r.get("class_crc"):
            line += (f" classes={r.get('classes', '?')}@{r['class_crc']}"
                     f" dirty_cols={r.get('dirty_cols', -1)}")
        if r.get("trace_id"):
            line += f" trace={r['trace_id'][:8]}"
        mem = r.get("mem")
        if isinstance(mem, dict):
            # the HBM block (scheduler/memwatch.py): was the dying cycle
            # near the device-memory ceiling?
            line += (
                f" hbm[in_use={mem.get('in_use', '?')}"
                f" peak={mem.get('peak', '?')}"
                f" resident={mem.get('resident', '?')}"
                f" unaccounted={mem.get('unaccounted', '?')}"
                f" src={mem.get('source', '?')}]"
            )
        out.append(line)
        sli = r.get("sli_phases")
        if isinstance(sli, dict):
            # the per-wave SLI phase block (scheduler._sli_phase_block):
            # where did this cycle's bound pods spend their latency, and
            # which pod was slowest?
            mean = sli.get("mean_ms")
            mean = mean if isinstance(mean, dict) else {}
            dom = max(mean, key=lambda k: mean[k]) if mean else "?"
            worst = sli.get("worst")
            worst = worst if isinstance(worst, dict) else {}
            out.append(
                f"        sli x{sli.get('pods', '?')} pods:"
                f" dominant={dom}"
                f" mean_ms={{{', '.join(f'{k}={v}' for k, v in mean.items())}}}"
                f" worst={worst.get('pod', '?')}"
                f"@{worst.get('sli_ms', '?')}ms"
            )
        diagnosis = r.get("diagnosis")
        for d in diagnosis if isinstance(diagnosis, list) else []:
            if not isinstance(d, dict):
                continue  # structurally corrupt entry: skip, never crash
            counts = d.get("counts")
            top = sorted(counts.items() if isinstance(counts, dict) else [],
                         key=lambda kv: (-kv[1], kv[0]))[:3]
            out.append(
                f"        class@row{d.get('rep_row')} x{d.get('pods')} pods: "
                + (", ".join(f"{c} {lbl}" for lbl, c in top) or "<no counts>")
            )
    return "\n".join(out)
