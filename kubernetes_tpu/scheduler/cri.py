"""CRI — the kubelet's container-runtime boundary.

The reference kubelet talks to containerd/CRI-O over gRPC through the
Container Runtime Interface (cri-api/pkg/apis/runtime/v1 — RuntimeService:
RunPodSandbox/CreateContainer/StartContainer/...; ImageService: PullImage/
ListImages), and kuberuntime (pkg/kubelet/kuberuntime) is the only layer
that speaks it.  kubemark's hollow node swaps the real runtime for a fake
behind the SAME interface (pkg/kubemark/hollow_kubelet.go).

This module is that boundary in-process: kubelet.py depends only on the
RuntimeService/ImageService protocols; FakeCRI is the kubemark-style
clock-driven implementation (containers run for their configured
run_seconds then exit 0, or crash_after_seconds then exit 1).  A real
remote runtime would implement the same two protocols over a socket —
nothing in the kubelet would change.

Shapes kept from cri-api: sandboxes and containers are separate objects
with runtime-assigned IDs; containers belong to a sandbox and carry an
`attempt` (restart ordinal — the reference's ContainerMetadata.Attempt);
the sandbox owns the pod IP (what the CNI plugin returns through the
runtime); images are pulled by name and listed with sizes.
"""

from __future__ import annotations

import itertools
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Protocol

from .queue import Clock

# runtime_v1.PodSandboxState / ContainerState (reduced)
SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"
CONTAINER_CREATED = "CONTAINER_CREATED"
CONTAINER_RUNNING = "CONTAINER_RUNNING"
CONTAINER_EXITED = "CONTAINER_EXITED"


@dataclass(frozen=True)
class PodSandboxConfig:
    """runtime_v1.PodSandboxConfig (metadata only — the hollow trade)."""

    pod_uid: str
    pod_name: str = ""
    namespace: str = ""


@dataclass(frozen=True)
class ContainerConfig:
    """runtime_v1.ContainerConfig reduced to what drives the fake runtime:
    the image and the hollow workload's clock behavior."""

    name: str = "main"
    image: str = ""
    run_seconds: float = 0.0  # > 0: exit 0 after this long
    crash_after_seconds: float = 0.0  # > 0: exit 1 after this long


@dataclass
class SandboxStatus:
    id: str
    pod_uid: str
    state: str
    ip: str = ""


@dataclass
class ContainerStatus:
    id: str
    sandbox_id: str
    pod_uid: str
    name: str
    image: str
    state: str
    attempt: int = 0
    exit_code: int = 0
    started_at: float = 0.0


class RuntimeService(Protocol):
    """cri-api runtime_v1.RuntimeServiceClient (lifecycle subset)."""

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str: ...
    def stop_pod_sandbox(self, sandbox_id: str) -> None: ...
    def remove_pod_sandbox(self, sandbox_id: str) -> None: ...
    def create_container(
        self, sandbox_id: str, config: ContainerConfig
    ) -> str: ...
    def start_container(self, container_id: str) -> None: ...
    def stop_container(self, container_id: str) -> None: ...
    def remove_container(self, container_id: str) -> None: ...
    def list_pod_sandboxes(self) -> List[SandboxStatus]: ...
    def list_containers(self) -> List[ContainerStatus]: ...
    def pod_sandbox_status(self, sandbox_id: str) -> SandboxStatus: ...
    def container_status(self, container_id: str) -> ContainerStatus: ...


class ImageService(Protocol):
    """cri-api runtime_v1.ImageServiceClient (pull/list/remove subset)."""

    def pull_image(self, name: str) -> str: ...
    def list_images(self) -> Dict[str, int]: ...
    def remove_image(self, name: str) -> None: ...


class CRIError(Exception):
    """A runtime call against a missing/invalid object (the gRPC NotFound /
    InvalidArgument class of failures)."""


@dataclass
class _Sandbox:
    status: SandboxStatus
    next_attempt: Dict[str, int]


class FakeCRI:
    """kubemark's fake runtime behind the real interface: containers
    advance by clock alone.  tick() is the runtime's own event loop (a real
    CRI daemon runs containers without being asked); PLEG observes the
    results purely through list_containers()."""

    DEFAULT_IMAGE_BYTES = 100 * 1024 * 1024

    def __init__(self, clock: Clock,
                 ip_alloc: Optional[Callable[[str], str]] = None):
        self.clock = clock
        self._ip_alloc = ip_alloc or (lambda pod_uid: "")
        self.sandboxes: Dict[str, _Sandbox] = {}
        self.containers: Dict[str, "_Ctr"] = {}
        self.images: Dict[str, int] = {}
        self._seq = itertools.count()

    # --- RuntimeService ---
    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        sid = f"sb-{next(self._seq):06d}"
        self.sandboxes[sid] = _Sandbox(
            SandboxStatus(
                id=sid, pod_uid=config.pod_uid, state=SANDBOX_READY,
                ip=self._ip_alloc(config.pod_uid),
            ),
            next_attempt={},
        )
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        sb = self.sandboxes.get(sandbox_id)
        if sb is None:
            raise CRIError(f"sandbox {sandbox_id} not found")
        sb.status.state = SANDBOX_NOTREADY
        for c in self.containers.values():
            if c.status.sandbox_id == sandbox_id:
                self._exit(c, 137)  # SIGKILLed with the sandbox

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        # the reference requires containers removed first; be strict so the
        # kubelet's teardown ordering stays honest
        for c in self.containers.values():
            if c.status.sandbox_id == sandbox_id:
                raise CRIError(f"sandbox {sandbox_id} still has containers")
        self.sandboxes.pop(sandbox_id, None)

    def create_container(self, sandbox_id: str, config: ContainerConfig) -> str:
        sb = self.sandboxes.get(sandbox_id)
        if sb is None or sb.status.state != SANDBOX_READY:
            raise CRIError(f"sandbox {sandbox_id} not ready")
        attempt = sb.next_attempt.get(config.name, 0)
        sb.next_attempt[config.name] = attempt + 1
        cid = f"ctr-{next(self._seq):06d}"
        self.containers[cid] = _Ctr(
            ContainerStatus(
                id=cid, sandbox_id=sandbox_id, pod_uid=sb.status.pod_uid,
                name=config.name, image=config.image,
                state=CONTAINER_CREATED, attempt=attempt,
            ),
            config,
        )
        return cid

    def start_container(self, container_id: str) -> None:
        c = self.containers.get(container_id)
        if c is None or c.status.state != CONTAINER_CREATED:
            raise CRIError(f"container {container_id} not startable")
        c.status.state = CONTAINER_RUNNING
        c.status.started_at = self.clock.now()

    def stop_container(self, container_id: str) -> None:
        c = self.containers.get(container_id)
        if c is None:
            raise CRIError(f"container {container_id} not found")
        if c.status.state == CONTAINER_RUNNING:
            self._exit(c, 137)

    def remove_container(self, container_id: str) -> None:
        c = self.containers.get(container_id)
        if c is not None and c.status.state == CONTAINER_RUNNING:
            raise CRIError(f"container {container_id} is running")
        self.containers.pop(container_id, None)

    def list_pod_sandboxes(self) -> List[SandboxStatus]:
        return [sb.status for sb in self.sandboxes.values()]

    def list_containers(self) -> List[ContainerStatus]:
        return [c.status for c in self.containers.values()]

    def pod_sandbox_status(self, sandbox_id: str) -> SandboxStatus:
        sb = self.sandboxes.get(sandbox_id)
        if sb is None:
            raise CRIError(f"sandbox {sandbox_id} not found")
        return sb.status

    def container_status(self, container_id: str) -> ContainerStatus:
        c = self.containers.get(container_id)
        if c is None:
            raise CRIError(f"container {container_id} not found")
        return c.status

    # --- ImageService ---
    def pull_image(self, name: str) -> str:
        if name not in self.images:
            # deterministic nominal size (the hollow registry) — crc32, not
            # hash(): Python string hashing is randomized per process and
            # would make NodeStatus.Images non-reproducible across runs
            self.images[name] = self.DEFAULT_IMAGE_BYTES + (
                zlib.crc32(name.encode()) & 0xFFFF
            )
        return name

    def list_images(self) -> Dict[str, int]:
        return dict(self.images)

    def remove_image(self, name: str) -> None:
        self.images.pop(name, None)

    # --- the runtime's own clock loop ---
    def tick(self) -> None:
        now = self.clock.now()
        for c in self.containers.values():
            st, cfg = c.status, c.config
            if st.state != CONTAINER_RUNNING:
                continue
            if cfg.crash_after_seconds > 0 and (
                now - st.started_at >= cfg.crash_after_seconds
            ):
                self._exit(c, 1)
            elif cfg.run_seconds > 0 and now - st.started_at >= cfg.run_seconds:
                self._exit(c, 0)

    @staticmethod
    def _exit(c: "_Ctr", code: int) -> None:
        c.status.state = CONTAINER_EXITED
        c.status.exit_code = code


@dataclass
class _Ctr:
    status: ContainerStatus
    config: ContainerConfig
