"""Cycle attribution engine — where does a scheduling cycle's wall go?

ROADMAP standing rule 1: every perf PR must attribute cycle time from a
captured trace BEFORE optimizing.  PR 1's span trees were export-only
(Perfetto JSON); this module turns a TraceCollector's spans into a
machine-readable per-cycle breakdown plus a rendered table, so
`bench.harness --stream --attribution` and bench.py self-report where the
cycle goes (and BENCH artifacts carry the proof that the round loop —
the device kernel phase — dominates the warm cycle).

Model.  Spans OVERLAP by design (the whole point of the pipelined loop is
that `encode_overlap` runs concurrently with the previous wave's
`device.step`), so naive duration sums double-count.  Attribution is a
timeline sweep instead: within each cycle window, every instant is
attributed to exactly ONE phase — the highest-priority span active at that
instant — and instants covered by no span fall into `unattributed`.  Phase
fractions therefore sum to exactly 1.0 of cycle wall time, and host work
hidden under a running device step is correctly charged to the device
(it costs no wall).  This is the self-time / critical-path view: the
device kernel is the cycle's spine; host phases only surface where they
STICK OUT of it.

Phases (span name -> phase; priority high -> low):

  device_kernel    batch.kernel / device.step — the jitted filter/score/
                   commit program (the O(C²K) round loop lives here)
  allgather_stitch stitch / allgather spans, when a sharded path emits them
                   (the [C,N] score stitch is inside the jit today, so this
                   reads 0 unless a kernel-side span lands)
  hoist_update     hoist.update — the resident class-hoist patch/rebuild
                   (ops/incremental.py), a sub-phase of the encode window
  host_encode      batch.encode / encode_overlap — snapshot delta-encode +
                   dispatch
  decode           decode_overlap — verdict fetch -> {pod: node} dict
  bind_commit      batch.commit / commit_overlap / binding.cycle / bind —
                   the bind/commit fan-out
  queue_wait       queue.wait — pods waiting in the activeQ (lowest
                   priority: it only surfaces where the scheduler is
                   otherwise idle)
  other            any traced span outside the table (apiserver requests,
                   kubelet sync, chaos recovery, ...)
  unattributed     cycle wall covered by no span at all

Cycle windows are anchored on the cycle-level spans (`batch.cycle` when the
scheduler drove the run, else `device.step` / `batch.kernel` for the raw
pipelined loop): cycle k spans [anchor_k.start, anchor_{k+1}.start), the
last one extends to the latest span end.  Spans before the first anchor
(warmup encode) are reported in the run totals' pre-window, not any cycle.

`spans_dropped` from the collector is carried through: a wrapped ring means
phases under-count, so reports flag `complete: False` instead of lying.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# span name -> phase
PHASE_OF: Dict[str, str] = {
    "batch.kernel": "device_kernel",
    "device.step": "device_kernel",
    # kernel-interior sub-phase spans (bench/profiling.py —
    # merge_profile_spans): they live INSIDE device-kernel windows, so the
    # sweep charges their instants to device_kernel exactly as before; the
    # sub-phase split is reported one level down (report["device_subphases"])
    **{f"device.{p}": "device_kernel" for p in (
        "hoist", "score", "normalize", "round_loop", "speculate", "repair",
        "commit", "unowned",
    )},
    "stitch": "allgather_stitch",
    "allgather": "allgather_stitch",
    "hoist.update": "hoist_update",
    "batch.encode": "host_encode",
    "encode_overlap": "host_encode",
    "decode_overlap": "decode",
    "batch.commit": "bind_commit",
    "commit_overlap": "bind_commit",
    "binding.cycle": "bind_commit",
    "bind": "bind_commit",
    "queue.wait": "queue_wait",
}

# sweep priority: at any instant the highest-priority active phase owns it
PHASE_PRIORITY: Dict[str, int] = {
    "device_kernel": 7,
    "allgather_stitch": 6,
    "hoist_update": 5,
    "host_encode": 4,
    "decode": 3,
    "bind_commit": 2,
    "other": 1,
    "queue_wait": 0,
}

PHASES: Tuple[str, ...] = (
    "device_kernel", "allgather_stitch", "hoist_update", "host_encode",
    "decode", "bind_commit", "queue_wait", "other", "unattributed",
)

# cycle anchors, in preference order: the scheduler's cycle root, else the
# pipelined loop's device step, else the scheduler-path kernel span
_ANCHOR_NAMES = ("batch.cycle", "device.step", "batch.kernel")


def phase_of(name: str) -> str:
    return PHASE_OF.get(name, "other")


def _sweep(intervals: Sequence[Tuple[float, float, str]],
           w0: float, w1: float) -> Dict[str, float]:
    """Attribute every instant of [w0, w1] to the highest-priority phase
    active there (or `unattributed`).  intervals: (start, end, phase)."""
    out = {p: 0.0 for p in PHASES}
    if w1 <= w0:
        return out
    # clip to the window, drop empties
    clipped = []
    for s, e, p in intervals:
        s, e = max(s, w0), min(e, w1)
        if e > s:
            clipped.append((s, e, p))
    bounds = sorted({w0, w1, *(s for s, _, _ in clipped),
                     *(e for _, e, _ in clipped)})
    # O(S·B) segment scan is fine at trace scale (<= 65536 spans per ring,
    # and cycle windows see a tiny slice of that); a heap sweep would only
    # matter past that
    events: List[Tuple[float, int, int]] = []  # (t, +1/-1, interval idx)
    for i, (s, e, _p) in enumerate(clipped):
        events.append((s, 1, i))
        events.append((e, -1, i))
    events.sort(key=lambda t: (t[0], -t[1]))
    active: Dict[int, str] = {}
    ei = 0
    for bi in range(len(bounds) - 1):
        t0, t1 = bounds[bi], bounds[bi + 1]
        while ei < len(events) and events[ei][0] <= t0:
            _, kind, i = events[ei]
            if kind > 0:
                active[i] = clipped[i][2]
            else:
                active.pop(i, None)
            ei += 1
        dt = t1 - t0
        if active:
            p = max(active.values(), key=lambda ph: PHASE_PRIORITY.get(ph, 1))
            out[p] += dt
        else:
            out["unattributed"] += dt
    return out


def _fractions(phases: Dict[str, float], wall: float) -> Dict[str, Dict[str, float]]:
    return {
        p: {
            "seconds": round(s, 6),
            "fraction": round(s / wall, 4) if wall > 0 else 0.0,
        }
        for p, s in phases.items()
    }


def attribute_spans(collector_or_spans, spans_dropped: Optional[int] = None,
                    device_subphases: Optional[Dict] = None) -> Dict:
    """The attribution report: per-cycle and whole-run phase breakdowns.

    Accepts a TraceCollector (reads .spans() and .spans_dropped) or a bare
    span iterable (pass spans_dropped explicitly for completeness
    flagging).  Returns a machine-readable dict — embedded in bench/harness
    JSON artifacts next to route_trace_counts; render_attribution() prints
    it as a table.

    `device_subphases` (bench/profiling.subphase_table, when the run
    captured a `--profile` device trace) embeds the kernel-interior
    sub-phase table one level below `device_kernel`: its fractions are
    shares WITHIN the device kernel (they sum to 1.0 there), and
    render_attribution nests the rows under the device_kernel line so one
    report answers both "which phase" and "which kernel region"."""
    if hasattr(collector_or_spans, "spans"):
        spans = collector_or_spans.spans()
        if spans_dropped is None:
            spans_dropped = getattr(collector_or_spans, "spans_dropped", 0)
    else:
        spans = list(collector_or_spans)
    spans_dropped = int(spans_dropped or 0)
    finished = [s for s in spans if s.end is not None]
    if not finished:
        return {
            "wall_s": 0.0, "n_cycles": 0, "n_spans": 0,
            "phases": _fractions({p: 0.0 for p in PHASES}, 0.0),
            "dominant_phase": None, "cycles": [],
            "spans_dropped": spans_dropped, "complete": spans_dropped == 0,
        }
    intervals = [(s.start, s.end, phase_of(s.name)) for s in finished]
    t_min = min(s.start for s in finished)
    t_max = max(s.end for s in finished)

    anchors: List = []
    for name in _ANCHOR_NAMES:
        anchors = sorted((s for s in finished if s.name == name),
                         key=lambda s: s.start)
        if anchors:
            break
    boundaries = [a.start for a in anchors] + [t_max]

    # bucket each interval into the cycle windows it overlaps (bisect on
    # the sorted window boundaries): per-cycle sweeps then only see their
    # own spans — O(S log C + overlaps) instead of O(C·S), which matters
    # when a long --stream run fills the 65536-span ring across thousands
    # of cycles
    n_cyc = len(anchors)
    buckets: List[List[Tuple[float, float, str]]] = [[] for _ in range(n_cyc)]
    for iv in intervals:
        s, e, _p = iv
        k0 = max(0, bisect.bisect_right(boundaries, s) - 1)
        k1 = min(n_cyc - 1, bisect.bisect_left(boundaries, e) - 1)
        for k in range(k0, k1 + 1):
            if s < boundaries[k + 1] and e > boundaries[k]:
                buckets[k].append(iv)

    cycles: List[Dict] = []
    for k in range(n_cyc):
        w0, w1 = boundaries[k], boundaries[k + 1]
        ph = _sweep(buckets[k], w0, w1)
        wall = w1 - w0
        c = {
            "cycle": k,
            "anchor": anchors[k].name,
            "wall_s": round(wall, 6),
            "phases": _fractions(ph, wall),
        }
        attrs = anchors[k].attributes or {}
        for key in ("wave", "pods", "n_shards"):
            if key in attrs:
                c[key] = attrs[key]
        cycles.append(c)

    # run totals over the cycle region (first anchor -> last end); the
    # pre-window (cold encode/warmup before any cycle anchor) is reported
    # separately so cycle fractions stay honest
    run0 = boundaries[0] if anchors else t_min
    totals = _sweep(intervals, run0, t_max)
    run_wall = t_max - run0
    nonzero = {p: s for p, s in totals.items() if p != "unattributed" and s > 0}
    dominant = max(nonzero, key=nonzero.get) if nonzero else None
    report = {
        "wall_s": round(run_wall, 6),
        "pre_window_s": round(run0 - t_min, 6),
        "n_cycles": len(anchors),
        "n_spans": len(finished),
        "phases": _fractions(totals, run_wall),
        "dominant_phase": dominant,
        "cycles": cycles,
        "spans_dropped": spans_dropped,
        "complete": spans_dropped == 0,
    }
    if device_subphases is not None:
        report["device_subphases"] = device_subphases
    return report


def render_attribution(report: Dict) -> str:
    """Human table for one attribution report (stderr next to the JSON
    artifact)."""
    lines = [
        f"cycle attribution: {report['n_cycles']} cycles, "
        f"{report['wall_s']:.3f}s wall, {report['n_spans']} spans"
        + ("" if report["complete"] else
           f"  [INCOMPLETE: {report['spans_dropped']} spans dropped — "
           "phase totals under-count]")
    ]
    lines.append(f"{'phase':<18} {'seconds':>10} {'fraction':>9}")
    sub = report.get("device_subphases")
    for p in PHASES:
        d = report["phases"].get(p)
        if d is None or d["seconds"] == 0.0:
            continue
        mark = "  <- dominant" if p == report.get("dominant_phase") else ""
        lines.append(f"{p:<18} {d['seconds']:>10.4f} {d['fraction']:>9.1%}{mark}")
        if p == "device_kernel" and sub and not sub.get("incomplete"):
            # the kernel-interior split (bench/profiling.py): fractions are
            # WITHIN device_kernel (self-time shares, sum to 1.0 there)
            from ..bench.profiling import render_subphases

            lines.append(render_subphases(sub, indent="  . "))
    for c in report.get("cycles", [])[:32]:
        top = sorted(
            ((p, d["fraction"]) for p, d in c["phases"].items()
             if d["seconds"] > 0),
            key=lambda t: -t[1],
        )[:3]
        tops = ", ".join(f"{p} {f:.0%}" for p, f in top)
        lines.append(
            f"  cycle {c['cycle']:<3} {c['wall_s']:>9.4f}s  {tops}"
        )
    if len(report.get("cycles", [])) > 32:
        lines.append(f"  ... {len(report['cycles']) - 32} more cycles")
    return "\n".join(lines)
