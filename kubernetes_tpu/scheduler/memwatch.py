"""HBM telemetry plane — the live device-memory ledger (ISSUE 15).

Every observability plane so far meters *time*; device *memory* was an
analytic estimate (parallel/mesh.shard_hbm_estimate) checked only against
the compiled memory analysis at trace scale (KTPU012).  This module is the
measured half, the cAdvisor/`/metrics/resource` analog of the device
plane: upstream kubelet feeds metrics-server a live resource summary the
scheduler consumes; here the scheduler's own device plane gets one.

Three cooperating pieces:

  * per-device LIVE stats — ``Device.memory_stats()`` (``bytes_in_use`` /
    ``peak_bytes_in_use``) sampled at cycle boundaries, high-water kept
    across the run.  Backends without it (the CPU sim returns None) are
    recorded as unavailable, NEVER silently passed — KTPU012's discipline.
    ``jax.live_arrays()`` is the always-available fallback source: the sum
    of live device-array bytes the process holds (logical bytes — a
    replicated array counts once), so the ledger meters every backend.

  * a host-side CENSUS of every *resident* device buffer the framework
    owns — the DeltaEncoder's resident ClusterArrays table, the
    HoistCache's class matrices / usage rows / replicated memos, any
    IncState in flight — each entry sized through the partition rule
    table's FIELD_DIMS model (``partition_rules.field_bytes``), so the
    ledger and ``shard_hbm_estimate`` resolve one size model and can never
    drift onto different field sets.  ``matched`` compares the model
    against the buffer's true per-device bytes; a mismatch is a KTPU020
    finding (analysis/memrules.py), not a quiet coverage hole.

  * a LEAK SENTINEL: across warm cycles, *unaccounted* live device bytes
    (live minus census) growing monotonically is a failure — donation
    retiring a wave's buffers, a restore()/invalidate(), or a chaos
    wave-recovery must return the census to baseline.  The sentinel's
    verdict rides bench artifacts, the twelve-route tracer's per-route
    ``mem`` block, and KTPU020.

``KTPU_MEMWATCH=0`` disables the plane (default on — a census walk is a
few dict lookups per cycle; the live-array walk is O(live buffers), a few
dozen on the warm path).  Wired into PipelinedBatchLoop (cycle samples),
the Scheduler batch path (gauges next to the queue-depth family, flight-
recorder memory block), bench.py and ``bench.harness --stream``
(``hbm_peak_bytes`` / ``hbm_resident_bytes`` stamped top-level,
regression-gated), and the devicecheck tracer (per-route ``mem`` blocks
KTPU020 reconciles).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

# sentinel slack: unaccounted growth below this many bytes across the
# observed window is allocator noise (small host-staging vectors, jit
# bookkeeping), not a leak.  Exported so fixture tests and the README
# document ONE number.
SENTINEL_SLACK_BYTES = 1 << 18  # 256 KiB

# the sentinel needs at least this many samples (>= 2 deltas) before it
# will call a monotone rise a leak — one noisy delta is not a trend
SENTINEL_MIN_SAMPLES = 3

# rolling-window bound on the sentinel's sample history (the KTPU_FLIGHT_K
# pattern): the plane is always-on in a long-running scheduler, and a leak
# detector must not itself grow without bound.  A leak outlasting the
# window still flags — every delta inside the window is positive.
SENTINEL_WINDOW = 512


def memwatch_enabled() -> bool:
    """KTPU_MEMWATCH=0 disables the device-memory ledger (read per
    construction, so tests and operators flip it without a fresh
    process).  Default ON: the per-cycle cost is a census dict walk plus
    one live-array sweep."""
    return os.environ.get("KTPU_MEMWATCH", "") != "0"


# --------------------------------------------------------------------------
# measured side: device stats + live arrays
# --------------------------------------------------------------------------


def device_memory_stats() -> Dict[str, Any]:
    """Per-device ``memory_stats()`` snapshot: ``{"available": bool,
    "devices": [{device, bytes_in_use, peak_bytes_in_use}, ...],
    "bytes_in_use": total, "peak_bytes_in_use": total}``.

    Graceful on backends without stats (CPU sim returns None, some expose
    no method): the block says ``available: False`` and totals are 0 —
    recorded, never silently passed as a measurement (KTPU012's
    discipline; KTPU020 then reconciles on the live-array source and the
    route report shows WHY)."""
    import jax

    devices = []
    in_use = peak = 0
    available = False
    for d in jax.devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            devices.append({"device": str(d), "available": False})
            continue
        b = int(stats.get("bytes_in_use", 0))
        p = int(stats.get("peak_bytes_in_use", b))
        devices.append({
            "device": str(d), "available": True,
            "bytes_in_use": b, "peak_bytes_in_use": p,
        })
        in_use += b
        peak += p
        available = True
    return {
        "available": available,
        "devices": devices,
        "bytes_in_use": in_use,
        "peak_bytes_in_use": peak,
    }


def live_device_bytes() -> Dict[str, int]:
    """Total LOGICAL bytes of every live device array the process holds
    (``jax.live_arrays()``; a replicated array counts its logical size
    once) — the always-available measured source.  Deleted/donated arrays
    report nbytes through metadata even when their buffers are gone, so
    they are skipped via ``is_deleted`` where exposed."""
    import jax

    total = 0
    n = 0
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
        except Exception:
            pass
        try:
            total += int(a.nbytes)
            n += 1
        except Exception:
            continue
    return {"bytes": total, "arrays": n}


def _per_device_bytes(a) -> int:
    """True per-device bytes of one device array: the max over devices of
    the shard bytes resident there (replicated -> full size per device,
    node-sharded -> the slice).  Shard METADATA only — never reads values
    (safe on buffers about to be donated)."""
    per: Dict[Any, int] = {}
    try:
        shards = a.addressable_shards
    except Exception:
        return int(getattr(a, "nbytes", 0))
    for s in shards:
        try:
            per[s.device] = per.get(s.device, 0) + int(s.data.nbytes)
        except Exception:
            return int(getattr(a, "nbytes", 0))
    return max(per.values()) if per else int(getattr(a, "nbytes", 0))


# --------------------------------------------------------------------------
# the census: resident buffers sized through the FIELD_DIMS model
# --------------------------------------------------------------------------


def model_bytes_for(qualname: str, shape, n_shards: int = 1,
                    pod_shards: int = 1) -> Optional[int]:
    """Analytic per-shard bytes of one resident buffer via the partition
    rule table's size model: FIELD_DIMS dims symbols bound to the CONCRETE
    shape, then ``partition_rules.field_bytes`` (the same routine
    ``shard_hbm_estimate``'s resident_inputs term and KTPU015's threshold
    math sum) — one size model, so the ledger cannot drift from the
    estimate.  None for a qualname outside the model (the census marks it
    unmodeled; KTPU020 flags it)."""
    from ..parallel.partition_rules import FIELD_DIMS, field_bytes

    ent = FIELD_DIMS.get(qualname)
    if ent is None:
        return None
    dims, bits = ent
    if len(dims) != len(shape):
        return None
    env = {sym: int(s) for sym, s in zip(dims, shape)}
    if bits < 8:
        # bit-packed plane (ops/bitplane.py): the CONCRETE last axis is
        # already uint32 words — bind the symbol to the word capacity in
        # BITS so field_bytes' ceil(n/32) arithmetic reproduces the word-
        # padded layout byte-for-byte (the KTPU020 exact-equality contract;
        # per-shard word blocks divide evenly by construction)
        env[dims[-1]] = int(shape[-1]) * 32
    return field_bytes(qualname, env, n_shards, pod_shards=pod_shards)


def _census_entry(qualname: str, a, n_shards: int,
                  pod_shards: int = 1) -> Dict[str, Any]:
    shape = tuple(int(s) for s in a.shape)
    actual = _per_device_bytes(a)
    model = model_bytes_for(qualname, shape, n_shards, pod_shards=pod_shards)
    # model >= itemsize by construction (field_bytes clamps every dim to
    # >= 1 so an analytic budget is never zero); a zero-size concrete
    # buffer occupies no device bytes — not a drift, just empty
    matched = model is not None and (actual == 0 or model == actual)
    return {
        "qualname": qualname,
        "shape": shape,
        "nbytes": int(getattr(a, "nbytes", 0)),   # global logical bytes
        "per_shard_bytes": actual,                # true per-device bytes
        "model_bytes": model,                     # FIELD_DIMS-model bytes
        "matched": bool(matched),
    }


def census_buffers(arr=None, inc=None, encoder=None, hoist=None,
                   n_shards: int = 1, pod_shards: int = 1) -> Dict[str, Any]:
    """The host-side census of every resident device buffer the framework
    owns, deduped by buffer identity (an IncState's leaves ARE the
    HoistCache's device entries — one buffer, one entry):

      * ``arr``      a device-placed ClusterArrays (qualnames ``arr.*``)
      * ``inc``      an IncState of device leaves (qualnames ``inc.*``)
      * ``encoder``  a DeltaEncoder — its resident device-buffer table
      * ``hoist``    a HoistCache — statics, usage rows, replicated memos

    Returns ``{"entries": [...], "resident_bytes": global logical total,
    "per_shard_bytes": per-device total, "model_bytes": FIELD_DIMS-model
    total, "matched": every entry's model equals its true per-device
    bytes, "n_buffers": count}`` — ``matched`` is the KTPU020
    census-vs-model equality."""
    import dataclasses as _dc

    entries: List[Dict[str, Any]] = []
    seen: set = set()

    def add(qualname: str, a) -> None:
        if a is None or id(a) in seen:
            return
        if not hasattr(a, "shape"):
            return
        seen.add(id(a))
        try:
            if a.is_deleted():
                return  # donated/retired: no longer resident anywhere
        except Exception:
            pass
        entries.append(_census_entry(qualname, a, n_shards,
                                     pod_shards=pod_shards))

    if arr is not None:
        for f in _dc.fields(type(arr)):
            add(f"arr.{f.name}", getattr(arr, f.name))
    if encoder is not None:
        for name, ent in getattr(encoder, "_dev", {}).items():
            add(f"arr.{name}", ent[1])
    if hoist is not None:
        statics = getattr(hoist, "_statics", None)
        if statics is not None:
            for q, a in zip(("inc.stat_u", "inc.elig_u", "inc.traw_u",
                             "inc.naraw_u", "inc.img_u"), statics):
                add(q, a)
        usage = getattr(hoist, "_usage", None)
        if usage is not None:
            add("inc.base_u", usage[0])
            add("inc.fit_u", usage[1])
        for attr, q in (("_cls_ent", "inc.cls"), ("_req_ent", "inc.req_u")):
            ent = getattr(hoist, attr, None)
            if ent is not None:
                add(q, ent[1])
    if inc is not None:
        for name in inc._fields:
            add(f"inc.{name}", getattr(inc, name))
    return {
        "entries": entries,
        "resident_bytes": sum(e["nbytes"] for e in entries),
        "per_shard_bytes": sum(e["per_shard_bytes"] for e in entries),
        "model_bytes": sum(e["model_bytes"] or 0 for e in entries),
        "matched": all(e["matched"] for e in entries),
        "n_buffers": len(entries),
    }


# --------------------------------------------------------------------------
# the leak sentinel
# --------------------------------------------------------------------------


class LeakSentinel:
    """Monotone-growth detector over the per-cycle UNACCOUNTED live bytes
    (live minus census): a donated wave retiring, a restore()/
    invalidate(), or a chaos wave-recovery must return the process to
    baseline — unaccounted bytes rising on EVERY observed delta beyond
    ``slack_bytes`` total is a leak (a retained retired buffer, a cache
    entry surviving invalidation).  Single noisy deltas, shrinkage, or
    sub-slack drift all stay clean."""

    def __init__(self, slack_bytes: int = SENTINEL_SLACK_BYTES,
                 min_samples: int = SENTINEL_MIN_SAMPLES,
                 window: int = SENTINEL_WINDOW):
        from collections import deque

        self.slack_bytes = int(slack_bytes)
        self.min_samples = max(2, int(min_samples))
        self.samples = deque(maxlen=max(self.min_samples, int(window)))

    def observe(self, unaccounted_bytes: int) -> None:
        self.samples.append(int(unaccounted_bytes))

    def verdict(self) -> Dict[str, Any]:
        samples = list(self.samples)
        deltas = [b - a for a, b in zip(samples, samples[1:])]
        growth = (samples[-1] - samples[0]) if samples else 0
        leaking = (
            len(samples) >= self.min_samples
            and all(d > 0 for d in deltas)
            and growth > self.slack_bytes
        )
        return {
            "leaking": bool(leaking),
            "samples": samples,
            "deltas": deltas,
            "growth_bytes": int(growth),
            "slack_bytes": self.slack_bytes,
        }


# --------------------------------------------------------------------------
# the ledger
# --------------------------------------------------------------------------


class DeviceMemoryLedger:
    """The per-run device-memory ledger: cycle-boundary samples of the
    measured side (memory_stats where available, live arrays always),
    the resident-buffer census, high-water marks, gauges, and the leak
    sentinel — one object threaded through the pipelined loop, the
    scheduler batch path, and the twelve-route tracer.

    ``baseline()`` anchors the measured deltas (call it before the first
    placement so pre-existing process buffers — another route's leftovers,
    warmup constants — never count against this run)."""

    def __init__(self, mesh=None, metrics=None,
                 slack_bytes: int = SENTINEL_SLACK_BYTES):
        from ..parallel.mesh import mesh_axis_shards

        self.mesh = mesh
        # total device count (the KTPU012 measured/n division) plus the
        # per-axis split the size model divides by on a 2-D mesh
        self.n_shards = int(mesh.size) if mesh is not None else 1
        self.pod_shards, self.node_shards = mesh_axis_shards(mesh)
        self.metrics = metrics
        self.sentinel = LeakSentinel(slack_bytes=slack_bytes)
        self._baseline_live = 0
        self._baselined = False
        self.peak_live_bytes = 0          # high-water live delta vs baseline
        self.peak_stats_bytes = 0         # high-water memory_stats in-use
        self.peak_resident_bytes = 0      # high-water census (global)
        self.last_census: Optional[Dict[str, Any]] = None
        self.last_stats: Optional[Dict[str, Any]] = None
        self.memory_stats_available = False
        self.samples = 0
        self.census_matched = True
        # every UNMATCHED census entry seen across the run, first
        # occurrence per qualname — census_matched is an AND over all
        # samples, so the evidence must accumulate with it (a transient
        # cold-sample drift would otherwise produce a finding naming no
        # buffer)
        self.census_unmatched: Dict[str, Dict[str, Any]] = {}

    def baseline(self) -> None:
        """Anchor the measured side at the current live-byte level."""
        self._baseline_live = live_device_bytes()["bytes"]
        self._baselined = True

    def cycle_sample(self, arr=None, inc=None, encoder=None, hoist=None,
                     label: str = "") -> Dict[str, Any]:
        """One cycle-boundary observation: census the resident buffers,
        sample the measured side, feed the sentinel, raise the high-water
        marks, stamp the ``device_hbm_*`` gauge family.  Returns the
        sample dict (the per-route tracer embeds the final one)."""
        if not self._baselined:
            self.baseline()
        census = census_buffers(arr=arr, inc=inc, encoder=encoder,
                                hoist=hoist, n_shards=self.node_shards,
                                pod_shards=self.pod_shards)
        live = live_device_bytes()
        stats = device_memory_stats()
        live_delta = max(0, live["bytes"] - self._baseline_live)
        unaccounted = live["bytes"] - self._baseline_live \
            - census["resident_bytes"]
        self.sentinel.observe(unaccounted)
        self.peak_live_bytes = max(self.peak_live_bytes, live_delta)
        self.peak_resident_bytes = max(
            self.peak_resident_bytes, census["resident_bytes"])
        if stats["available"]:
            self.memory_stats_available = True
            self.peak_stats_bytes = max(
                self.peak_stats_bytes,
                stats["peak_bytes_in_use"] or stats["bytes_in_use"])
        self.last_census = census
        self.last_stats = stats
        self.census_matched = self.census_matched and census["matched"]
        for e in census["entries"]:
            if not e["matched"]:
                self.census_unmatched.setdefault(e["qualname"], e)
        self.samples += 1
        if self.metrics is not None:
            # the live family next to the queue-depth gauges: current,
            # peak (set_max high-water), resident census, unaccounted.
            # in-use reuses THIS sample's sweep (the one-sweep-per-cycle
            # promise) instead of calling in_use_bytes(), which would
            # walk the live arrays a second time on statless backends
            in_use = (stats["bytes_in_use"] if stats["available"]
                      else live_delta)
            self.metrics.set("device_hbm_in_use_bytes", in_use)
            self.metrics.set_max("device_hbm_peak_bytes",
                                 self.hbm_peak_bytes())
            self.metrics.set("device_hbm_resident_bytes",
                             census["resident_bytes"])
            self.metrics.set("device_hbm_unaccounted_bytes", unaccounted)
        return {
            "label": label,
            "live_bytes": live["bytes"],
            "live_delta_bytes": live_delta,
            "resident_bytes": census["resident_bytes"],
            "unaccounted_bytes": unaccounted,
            "census_matched": census["matched"],
            "memory_stats_available": stats["available"],
        }

    # -- read side --

    def source(self) -> str:
        return "memory_stats" if self.memory_stats_available \
            else "live_arrays"

    def in_use_bytes(self) -> int:
        if self.memory_stats_available and self.last_stats is not None:
            return int(self.last_stats["bytes_in_use"])
        return max(0, live_device_bytes()["bytes"] - self._baseline_live)

    def hbm_peak_bytes(self) -> int:
        """The measured high-water: memory_stats peak where the backend
        exposes one, else the live-array delta peak."""
        if self.memory_stats_available:
            return int(self.peak_stats_bytes)
        return int(self.peak_live_bytes)

    def per_shard_hbm_estimate(self) -> Optional[int]:
        """The analytic ``per_shard_hbm_bytes`` twin (bench.py's JSON
        field), derived from the dims of the resident buffers the last
        census actually saw — so a live `/metrics` scrape can carry the
        same scale-out story the artifact tells.  None when the census
        has no resident ClusterArrays (e.g. a donating loop: fresh
        per-wave transfers, nothing resident to size)."""
        c = self.last_census or {}
        shapes = {e["qualname"]: e["shape"] for e in c.get("entries", [])}
        pr = shapes.get("arr.pod_req")
        nu = shapes.get("arr.node_used")
        if not (pr and nu):
            return None
        tc = shapes.get("arr.term_counts0")
        u = shapes.get("inc.req_u")
        from ..ops import assign as A
        from ..parallel.mesh import shard_hbm_estimate

        chunk = A._INC_CHUNK if u else A._CHUNK
        return int(shard_hbm_estimate(
            pr[0], nu[0], self.node_shards, n_res=pr[1],
            n_terms=(tc[0] if tc else 1), chunk=chunk,
            u_classes=(u[0] if u else None), pod_shards=self.pod_shards,
        )["total"])

    def summary(self) -> Dict[str, Any]:
        """The artifact block: ``hbm_peak_bytes`` / ``hbm_resident_bytes``
        stamped top-level by bench.py and `--stream`, plus source,
        availability, the census match flag, and the sentinel verdict."""
        return {
            "hbm_peak_bytes": self.hbm_peak_bytes(),
            "hbm_resident_bytes": int(self.peak_resident_bytes),
            "memwatch": {
                "mesh_shape": [self.pod_shards, self.node_shards],
                "source": self.source(),
                "memory_stats_available": self.memory_stats_available,
                "samples": self.samples,
                "census_matched": self.census_matched,
                "n_buffers": (self.last_census or {}).get("n_buffers", 0),
                "sentinel": self.sentinel.verdict(),
            },
        }

    def memory_block(self) -> Dict[str, Any]:
        """The COMPACT block a flight-recorder record carries, so a
        post-mortem answers "were we near the ceiling when it died" —
        in-use, peak, resident census, unaccounted, source."""
        unacc = 0
        if self.sentinel.samples:
            unacc = self.sentinel.samples[-1]
        return {
            "in_use": self.in_use_bytes(),
            "peak": self.hbm_peak_bytes(),
            "resident": (self.last_census or {}).get("resident_bytes", 0),
            "unaccounted": unacc,
            "source": self.source(),
        }
