"""In-tree CPU plugins — the sequential fallback path.

Each class mirrors one reference plugin (pkg/scheduler/framework/plugins/...)
and delegates its semantics to the same helpers the parity oracle uses
(oracle/reference.py), so the CPU path, the TPU kernels, and the oracle share
one behavior definition.

Default enablement/weights: registry at the bottom (reference:
pkg/scheduler/framework/plugins/registry.go — NewInTreeRegistry +
apis/config/v1/default_plugins.go — getDefaultPlugins).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...api import types as t
from ...api.snapshot import Snapshot, pod_effective_requests
from ...oracle import reference as oref
from ..framework import (
    MAX_NODE_SCORE,
    CycleState,
    NodeInfo,
    Plugin,
    PluginWeight,
    Status,
)
from ..extender import ExtenderError
from ..queue import EV_NODE_ADD, EV_NODE_UPDATE, EV_POD_ADD, EV_POD_DELETE

f32 = np.float32


def _existing(snap: Snapshot, infos: Dict[str, NodeInfo]) -> List[Tuple[t.Pod, int]]:
    """(pod, node_index) ledger of running pods, in node order."""
    idx = {name: i for i, name in enumerate(infos)}
    out = []
    for name, ni in infos.items():
        for q in ni.pods:
            out.append((q, idx[name]))
    return out


class SchedulingGates(Plugin):
    """schedulinggates/scheduling_gates.go — PreEnqueue."""

    name = "SchedulingGates"

    def PreEnqueue(self, pod: t.Pod) -> Status:
        if pod.scheduling_gates:
            return Status.unschedulable(f"waiting for gates {pod.scheduling_gates}")
        return Status()


class TaintToleration(Plugin):
    """tainttoleration/taint_toleration.go — Filter + Score(reverse-normalized)."""

    name = "TaintToleration"

    _EVENTS = (EV_NODE_ADD, EV_NODE_UPDATE)

    def EventsToRegister(self):
        return self._EVENTS

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        if not oref._tolerates_all(pod, oref._node_taints(info.node)):
            return Status.unschedulable("node taint not tolerated")
        return Status()

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        return float(oref._intolerable_prefer_count(pod, oref._node_taints(info.node)))

    def NormalizeScore(self, state, snap, pod, scores: np.ndarray) -> None:
        mx = f32(scores.max()) if len(scores) else f32(0)
        if mx > 0:
            scores[:] = f32(MAX_NODE_SCORE) - f32(MAX_NODE_SCORE) * scores / mx
        else:
            scores[:] = f32(MAX_NODE_SCORE)


class NodeAffinity(Plugin):
    """nodeaffinity/node_affinity.go — Filter (required + nodeSelector) and
    Score (preferred terms, DefaultNormalizeScore)."""

    name = "NodeAffinity"

    _EVENTS = (EV_NODE_ADD, EV_NODE_UPDATE)

    def EventsToRegister(self):
        return self._EVENTS

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        if not oref._node_selection_ok(pod, info.node):
            return Status.unschedulable("node(s) didn't match Pod's node affinity/selector")
        return Status()

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        return float(oref._preferred_na_raw(pod, info.node))

    def NormalizeScore(self, state, snap, pod, scores: np.ndarray) -> None:
        mx = f32(scores.max()) if len(scores) else f32(0)
        scores[:] = scores * f32(MAX_NODE_SCORE) / mx if mx > 0 else f32(0.0)


class NodeName(Plugin):
    """nodename/node_name.go — Filter."""

    name = "NodeName"

    _EVENTS = (EV_NODE_ADD,)

    def EventsToRegister(self):
        return self._EVENTS

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        if pod.node_name and pod.node_name != info.node.name:
            return Status.unschedulable("node didn't match the requested node name")
        return Status()


class NodePorts(Plugin):
    """nodeports/node_ports.go — Filter."""

    name = "NodePorts"

    _EVENTS = (EV_NODE_ADD, EV_POD_DELETE)

    def EventsToRegister(self):
        return self._EVENTS

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        if oref._ports_conflict(pod, info.pods):
            return Status.unschedulable("node(s) didn't have free ports")
        return Status()


class NodeResourcesFit(Plugin):
    """noderesources/fit.go — Filter (fitsRequest over the shared ScaledState,
    the analog of computePodResourceRequest's PreFilter output) + Score
    (LeastAllocated / MostAllocated / RequestedToCapacityRatio per the
    profile's scoringStrategy pluginConfig)."""

    name = "NodeResourcesFit"

    def __init__(self, fit_strategy: str = "LeastAllocated",
                 rtcr_shape=((0.0, 0.0), (100.0, 10.0))):
        self.fit_strategy = fit_strategy
        self.rtcr_shape = tuple(rtcr_shape)

    _EVENTS = (EV_NODE_ADD, EV_NODE_UPDATE, EV_POD_DELETE)

    def EventsToRegister(self):
        return self._EVENTS

    def queueing_hint(self, event, obj, old, pod) -> bool:
        """noderesources/fit.go — isSchedulableAfterNodeChange: a Node/Update
        requeues a fit-rejected pod only if some allocatable GREW; shrinking
        or irrelevant updates (labels, heartbeats) cannot free capacity."""
        if event == EV_NODE_UPDATE and old is not None:
            new_alloc = getattr(obj, "allocatable", {})
            old_alloc = getattr(old, "allocatable", {})
            return any(
                v > old_alloc.get(r, 0) for r, v in new_alloc.items()
            )
        return True  # Node/Add and Pod/Delete always free capacity

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        req = sc.req_of(pod)
        short = (req > 0) & (sc.used[i] + req > sc.alloc[i])
        if np.any(short):
            # upstream fitError vocabulary ("Insufficient cpu"), first
            # failing resource — the reason the diagnosis renderer
            # aggregates (same attribution rule as ops/explain.py)
            return Status.unschedulable(
                f"Insufficient {sc.resources[int(np.argmax(short))]}"
            )
        return Status()

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        requested = sc.used[i] + sc.req_of(pod)
        if self.fit_strategy == "MostAllocated":
            return float(oref._most_allocated(requested, sc.alloc[i], sc.score_idx))
        if self.fit_strategy == "RequestedToCapacityRatio":
            return float(
                oref._rtcr(requested, sc.alloc[i], sc.score_idx, self.rtcr_shape)
            )
        return float(
            oref._least_allocated(requested, sc.alloc[i], sc.score_idx)
        )


class NodeResourcesBalancedAllocation(Plugin):
    """noderesources/balanced_allocation.go — Score."""

    name = "NodeResourcesBalancedAllocation"

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        return float(
            oref._balanced(sc.used[i] + sc.req_of(pod), sc.alloc[i], sc.score_idx)
        )


class PodTopologySpread(Plugin):
    """podtopologyspread/{filtering,scoring}.go — Filter skew check + Score."""

    name = "PodTopologySpread"

    _EVENTS = (EV_NODE_ADD, EV_POD_ADD, EV_POD_DELETE)

    def EventsToRegister(self):
        return self._EVENTS

    def queueing_hint(self, event, obj, old, pod) -> bool:
        """podtopologyspread/plugin.go — isSchedulableAfterPodChange: only an
        assigned pod matching one of the rejected pod's spread selectors (in
        its namespace — spread is namespace-scoped) can change the skew."""
        if event in (EV_POD_ADD, EV_POD_DELETE) and hasattr(obj, "labels"):
            return any(
                c.label_selector is not None
                and getattr(obj, "namespace", "") == pod.namespace
                and c.label_selector.matches(obj.labels)
                for c in pod.topology_spread
            )
        return True  # Node/Add introduces a new topology domain

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        ok, raw = oref._spread_eval(pod, sc.nodes, sc.node_ok_sel(pod), sc.existing, i)
        state.data.setdefault("spread_raw", {})[(pod.uid, i)] = raw
        if not ok:
            return Status.unschedulable("node(s) didn't satisfy topology spread")
        return Status()

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        raw = state.data.get("spread_raw", {}).get((pod.uid, i))
        if raw is None:
            _, raw = oref._spread_eval(pod, sc.nodes, sc.node_ok_sel(pod), sc.existing, i)
        return float(raw)

    def NormalizeScore(self, state, snap, pod, scores: np.ndarray) -> None:
        mx = f32(scores.max()) if len(scores) else f32(0)
        if mx > 0:
            scores[:] = f32(MAX_NODE_SCORE) - f32(MAX_NODE_SCORE) * scores / mx
        else:
            scores[:] = f32(MAX_NODE_SCORE)


class ImageLocality(Plugin):
    """imagelocality/image_locality.go — Score (no NormalizeScore): summed MB
    of the pod's images already on the node, threshold-scaled to [0,100]."""

    name = "ImageLocality"

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        from ...oracle.reference import _image_score

        return float(_image_score(pod, info.node))


class InterPodAffinity(Plugin):
    """interpodaffinity/filtering.go — Filter (required affinity with first-pod
    waiver, own + symmetric anti-affinity) + scoring.go — Score (preferred
    terms, both directions, min/max-normalized)."""

    name = "InterPodAffinity"

    _EVENTS = (EV_NODE_ADD, EV_POD_ADD, EV_POD_DELETE)

    def EventsToRegister(self):
        return self._EVENTS

    def __init__(self, hard_pod_affinity_weight: float = 1.0):
        self.hard_pod_affinity_weight = hard_pod_affinity_weight

    @staticmethod
    def _term_matches(term, pod_ns, obj) -> bool:
        ns = term.namespaces or (pod_ns,)
        return (
            term.label_selector is not None
            and getattr(obj, "namespace", "") in ns
            and term.label_selector.matches(obj.labels)
        )

    def queueing_hint(self, event, obj, old, pod) -> bool:
        """interpodaffinity/plugin.go — isSchedulableAfterPodChange: an ADDED
        assigned pod helps only if it matches a required-affinity selector;
        a DELETED one helps only if it matched an anti-affinity selector (a
        blocker left) or itself owned an anti term matching this pod."""
        if event not in (EV_POD_ADD, EV_POD_DELETE) or not hasattr(obj, "labels"):
            return True  # Node/Add: new placement options
        a = pod.affinity
        if event == EV_POD_ADD:
            # an added pod can only help this pod's own REQUIRED affinity;
            # symmetric anti-affinity only gains blockers from adds
            return a is not None and any(
                self._term_matches(tm, pod.namespace, obj)
                for tm in a.required_pod_affinity
            )
        # EV_POD_DELETE: a pod this plugin rejected may have no affinity of
        # its OWN — existing pods' symmetric anti terms also reject — so the
        # departed pod's anti terms must be checked even when a is None
        if a is not None and any(
            self._term_matches(tm, pod.namespace, obj)
            for tm in a.required_pod_anti_affinity
        ):
            return True
        oa = getattr(obj, "affinity", None)
        if oa is not None:
            onm = getattr(obj, "namespace", "")
            return any(
                self._term_matches(tm, onm, pod)
                for tm in oa.required_pod_anti_affinity
            )
        return False

    def Filter(self, state, snap, pod, info: NodeInfo) -> Status:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        if not oref._interpod_ok(pod, sc.nodes, sc.existing, i):
            return Status.unschedulable("node(s) didn't satisfy pod affinity/anti-affinity")
        return Status()

    def Score(self, state, snap, pod, info: NodeInfo) -> float:
        sc = state.data["scaled"]
        i = sc.index[info.node.name]
        return float(
            oref._interpod_pref_raw(
                pod, sc.nodes, sc.existing, i, self.hard_pod_affinity_weight
            )
        )

    def NormalizeScore(self, state, snap, pod, scores: np.ndarray) -> None:
        if not len(scores):
            return
        mx, mn = f32(scores.max()), f32(scores.min())
        if mx > mn:
            scores[:] = f32(MAX_NODE_SCORE) * (scores - mn) / (mx - mn)
        else:
            scores[:] = f32(0.0)


class VolumeBinding(Plugin):
    """volumebinding/volume_binding.go — PreBind: BindPodVolumes.  The
    feasibility half (FindPodVolumes) is folded into the snapshot by
    api/volumes.resolve_pod and shared with the batch paths; this plugin
    commits the chosen binding (static PV match or dynamic provisioning)."""

    name = "VolumeBinding"

    def __init__(self, store):
        self.store = store

    def PreBind(self, state, snap, pod, node_name) -> Status:
        if not pod.pvcs:
            return Status()
        from ..volumebinder import bind_pod_volumes

        err = bind_pod_volumes(self.store, pod, node_name)
        return Status() if err is None else Status.unschedulable(err)


class DefaultBinder(Plugin):
    """defaultbinder/default_binder.go — Bind: POST pods/{name}/binding."""

    name = "DefaultBinder"

    def __init__(self, store):
        self.store = store

    def Bind(self, state, snap, pod, node_name) -> Status:
        self.store.bind(pod.uid, node_name)
        return Status()


def _split_pdb_violating(
    pods: List[t.Pod], pdbs: List[t.PodDisruptionBudget]
) -> Tuple[List[t.Pod], List[t.Pod]]:
    """framework/preemption — filterPodsWithPDBViolation: a pod is "violating"
    if evicting it would exceed some matching PDB's disruptions_allowed,
    accounting for the evictions this very candidate set already charges."""
    remaining = {pdb.key: pdb.disruptions_allowed for pdb in pdbs}
    violating: List[t.Pod] = []
    non_violating: List[t.Pod] = []
    for q in pods:
        hit = [pdb for pdb in pdbs if pdb.matches(q)]
        if any(remaining[pdb.key] <= 0 for pdb in hit):
            violating.append(q)
        else:
            for pdb in hit:
                remaining[pdb.key] -= 1
            non_violating.append(q)
    return violating, non_violating


class DefaultPreemption(Plugin):
    """defaultpreemption/default_preemption.go + framework/preemption/
    preemption.go — Evaluator: PostFilter that picks victims on one node,
    evicts them, and nominates the node.

    Victim selection (SelectVictimsOnNode): remove all lower-priority pods;
    if the pod then passes every Filter, reprieve victims while still
    feasible — PDB-violating victims get reprieve priority first, then
    non-violating, each highest-priority-first — and count the PDB
    violations among the survivors.  Node choice (pickOneNodeForPreemption's
    lexicographic order): fewest PDB violations, lowest max victim priority,
    smallest priority sum, fewest victims, lowest node index.
    """

    name = "DefaultPreemption"

    def __init__(self, filter_fn, store, nominated_fn=None, extenders=()):
        self.filter_fn = filter_fn  # (state, snap, pod, NodeInfo) -> Status
        self.store = store
        # node_name -> [nominated pods] (the queue's nominator); preemption
        # must respect other preemptors' reservations (the reference's
        # SelectVictimsOnNode filters through RunFilterPluginsWithNominatedPods)
        self.nominated_fn = nominated_fn
        # preemption-capable extenders get the candidate map before the node
        # pick (extender.go — ProcessPreemption / SupportsPreemption)
        self.extenders = [e for e in extenders if e.cfg.preempt_verb]

    def PostFilter(self, state, snap, pod, statuses) -> Tuple[Optional[str], Status]:
        sc = state.data["scaled"]
        pdbs = list(getattr(self.store, "pdbs", {}).values())
        best = None  # ((violations, max_prio, sum_prio, count, node_idx), victims, name)
        # with preemption-capable extenders, ALL candidates are collected and
        # offered before the pick; without them the best is tracked streaming
        candidates: dict = {} if self.extenders else None
        for i, info in enumerate(sc.infos):
            lower = [q for q in info.pods if q.priority < pod.priority]
            if not lower:
                continue
            nom = [
                q
                for q in (self.nominated_fn(info.node.name) if self.nominated_fn else [])
                if q.uid != pod.uid and q.priority >= pod.priority
            ]
            nom_uids = {q.uid for q in nom}
            # nominated pods ride in the sim so their reservation holds and
            # they are never victims (they're not on the node, so not in lower)
            sim = NodeInfo(
                node=info.node,
                pods=[q for q in info.pods if q.priority >= pod.priority] + nom,
            )
            sc.push_sim(i, sim)
            try:
                if not self.filter_fn(state, snap, pod, sim).ok:
                    continue
                # reprieve: re-add while still feasible; violating pods first
                # so the final victim set avoids PDB damage when possible
                violating, non_violating = _split_pdb_violating(lower, pdbs)
                victims: List[t.Pod] = []
                viol_uids: set = set()
                n_violations = 0
                for group, counts in ((violating, True), (non_violating, False)):
                    for q in sorted(group, key=lambda q: (-q.priority, q.uid)):
                        sim.add_pod(q, sc.resources)
                        sc.refresh_sim(i, sim)
                        if self.filter_fn(state, snap, pod, sim).ok:
                            continue  # reprieved
                        sim.remove_pod(q, sc.resources)
                        sc.refresh_sim(i, sim)
                        victims.append(q)
                        if counts:
                            n_violations += 1
                            viol_uids.add(q.uid)
                if victims and nom:
                    # second pass of the two-pass nominated filter: feasibility
                    # must not DEPEND on a nominated pod that may never arrive
                    base = NodeInfo(
                        node=info.node,
                        pods=[q for q in sim.pods if q.uid not in nom_uids],
                    )
                    sc.refresh_sim(i, base)
                    if not self.filter_fn(state, snap, pod, base).ok:
                        continue
            finally:
                sc.pop_sim(i)
            if not victims:
                continue
            key = (
                n_violations,
                max(q.priority for q in victims),
                sum(q.priority for q in victims),
                len(victims),
                i,
            )
            if candidates is not None:
                # the pick happens after the extender round; each victim's
                # PDB classification from the reprieve pass rides along so a
                # trimmed set re-keys with the SAME semantics as streaming
                candidates[info.node.name] = (key, victims, viol_uids)
            else:
                if best is None or key < best[0]:
                    best = (key, victims, info.node.name)
        if candidates is not None:
            if not candidates:
                return None, Status.unschedulable("preemption: no candidates")
            node_map = {n: v for n, (_, v, _) in candidates.items()}
            for ext in self.extenders:
                try:
                    node_map = ext.process_preemption(pod, node_map)
                except ExtenderError as e:
                    if ext.cfg.ignorable:
                        continue
                    return None, Status.unschedulable(
                        f"preemption extender: {e}"
                    )
                if not node_map:
                    return None, Status.unschedulable(
                        "preemption: extenders rejected all candidates"
                    )
            best = None
            for node, kept in node_map.items():
                key0, orig, viol = candidates[node]
                kept_uids = {q.uid for q in kept}
                if kept_uids == {q.uid for q in orig}:
                    # untouched candidate: the streaming key stands as-is
                    key, chosen = key0, orig
                else:
                    # trimmed set: keep the ORIGINAL victim order and each
                    # victim's reprieve-time PDB classification (the
                    # reference echoes NumPDBViolations through the extender
                    # round rather than re-deriving it)
                    chosen = [q for q in orig if q.uid in kept_uids]
                    key = (
                        sum(1 for q in chosen if q.uid in viol),
                        max(q.priority for q in chosen),
                        sum(q.priority for q in chosen),
                        len(chosen),
                        key0[4],
                    )
                if best is None or key < best[0]:
                    best = (key, chosen, node)
        if best is None:
            return None, Status.unschedulable("preemption: no candidates")
        _, victims, node_name = best
        for q in victims:
            self.store.delete_pod(q.uid)
        return node_name, Status()


def default_plugins(
    store, filter_fn=None, nominated_fn=None, hard_pod_affinity_weight: float = 1.0,
    plugin_specs=(), extenders=(),
    fit_strategy: str = "LeastAllocated",
    rtcr_shape=((0.0, 0.0), (100.0, 10.0)),
) -> List[PluginWeight]:
    """The default profile — plugin set and weights mirroring
    default_plugins.go (NodeResourcesFit 1, BalancedAllocation 1,
    TaintToleration 3, NodeAffinity 2, PodTopologySpread 2, InterPodAffinity 2).

    `plugin_specs` (KubeSchedulerProfile.plugins) overrides per plugin name:
    weight replacement, or removal when enabled=False — the same lowering
    config.score_config applies for the batch kernels, so the two paths see
    one profile semantics."""
    # Score-plugin order mirrors the kernels' float32 accumulation order
    # (ops/assign.py: fit, balanced, taint, nodeAffinity, spread, image) so the
    # CPU path's weighted sum is bit-identical to the TPU/native paths.
    pls = [
        PluginWeight(SchedulingGates()),
        PluginWeight(NodeName()),
        PluginWeight(NodePorts()),
        PluginWeight(NodeResourcesFit(fit_strategy, rtcr_shape), 1.0),
        PluginWeight(NodeResourcesBalancedAllocation(), 1.0),
        PluginWeight(TaintToleration(), 3.0),
        PluginWeight(NodeAffinity(), 2.0),
        PluginWeight(PodTopologySpread(), 2.0),
        PluginWeight(InterPodAffinity(hard_pod_affinity_weight), 2.0),
        PluginWeight(ImageLocality(), 1.0),
    ]
    if filter_fn is not None:
        pls.append(
            PluginWeight(
                DefaultPreemption(filter_fn, store, nominated_fn, extenders)
            )
        )
    pls.append(PluginWeight(VolumeBinding(store)))
    pls.append(PluginWeight(DefaultBinder(store)))
    by_name = {s.name: s for s in plugin_specs}
    if by_name:
        # enabled=False disables the SCORE point only (weight 0) — exactly
        # what config.score_config does for the batch kernels, which always
        # keep feasibility filters.  Filters stay active on both paths.
        def _weight(pw: PluginWeight) -> float:
            s = by_name.get(getattr(pw.plugin, "name", ""))
            if s is None:
                return pw.weight
            return s.weight if s.enabled else 0.0

        pls = [PluginWeight(pw.plugin, _weight(pw)) for pw in pls]
    return pls


def default_registry() -> Dict[str, type]:
    """Name -> class registry (registry.go — NewInTreeRegistry)."""
    return {
        c.name: c
        for c in [
            SchedulingGates,
            NodeName,
            NodePorts,
            TaintToleration,
            NodeAffinity,
            NodeResourcesFit,
            NodeResourcesBalancedAllocation,
            PodTopologySpread,
            InterPodAffinity,
            ImageLocality,
            DefaultPreemption,
            VolumeBinding,
            DefaultBinder,
        ]
    }
