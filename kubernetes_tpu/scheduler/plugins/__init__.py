from .cpu import default_plugins, default_registry  # noqa: F401
