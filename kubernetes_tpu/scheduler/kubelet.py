"""Hollow kubelet — the kubemark analog (SURVEY.md §2.3 kubemark row: "real
kubelet code, mocked CRI/runtime"; §4: "run real code against fake backends").

A HollowKubelet plays the node agent's role against the in-process store:

  - watches for pods bound to its node (the reference's syncLoop source:
    pods with spec.nodeName == me), runs the pod phase machine
    Pending -> Running -> Succeeded (pods with run_seconds > 0 complete;
    others run forever — the service-pod shape)
  - heartbeats its node Lease every tick (pkg/kubelet/nodelease), which the
    NodeLifecycleController consumes for failure detection
  - publishes phase transitions through the pods/status subresource so the
    scheduler's queue ignores them (no spec change)

No CRI/container runtime is modeled: the pod "runs" by clock alone — exactly
kubemark's hollow_kubelet.go trade (pkg/kubemark).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api import types as t
from .leases import LeaseStore
from .queue import Clock
from .store import ClusterStore


class HollowKubelet:
    def __init__(
        self,
        store: ClusterStore,
        leases: LeaseStore,
        node_name: str,
        clock: Optional[Clock] = None,
    ):
        self.store = store
        self.leases = leases
        self.node_name = node_name
        self.clock = clock or leases.clock
        self._started_at: Dict[str, float] = {}  # pod uid -> Running since
        self._ip_seq = 0  # pod IP allocator cursor (status.podIP)

    def tick(self) -> None:
        """One syncLoop iteration: heartbeat + pod state machine."""
        self.leases.renew_node_heartbeat(self.node_name)
        now = self.clock.now()
        mine = set()
        for pod in list(self.store.pods.values()):
            if pod.node_name != self.node_name:
                continue
            mine.add(pod.uid)
            if pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                self._started_at.pop(pod.uid, None)
                continue
            if pod.phase in ("", t.PHASE_PENDING):
                # sandbox+containers "started": Pending -> Running
                self._set_phase(pod, t.PHASE_RUNNING)
                self._started_at[pod.uid] = now
            elif pod.phase == t.PHASE_RUNNING:
                started = self._started_at.setdefault(pod.uid, now)
                if pod.run_seconds > 0 and now - started >= pod.run_seconds:
                    self._set_phase(pod, t.PHASE_SUCCEEDED)
                    self._started_at.pop(pod.uid, None)
        # housekeeping: drop state for pods deleted while Running
        for uid in list(self._started_at):
            if uid not in mine:
                del self._started_at[uid]

    def _set_phase(self, pod: t.Pod, phase: str) -> None:
        import copy

        q = copy.copy(pod)
        q.phase = phase
        if phase == t.PHASE_RUNNING and not q.pod_ip:
            # status.podIP from the node's pod CIDR (nodeipam's per-node
            # 10.244.x.0/24 shape; the sandbox IP the CRI would report)
            q.pod_ip = self._alloc_ip()
        self.store.update_pod_status(q)

    def _alloc_ip(self) -> str:
        import zlib

        subnet = zlib.crc32(self.node_name.encode()) & 0xFF  # run-stable
        self._ip_seq += 1
        return f"10.244.{subnet}.{self._ip_seq & 0xFF}"


class HollowCluster:
    """kubemark's hollow-node fleet: one HollowKubelet per node in the store
    (nodes added later get a kubelet on the next tick)."""

    def __init__(self, store: ClusterStore, leases: LeaseStore):
        self.store = store
        self.leases = leases
        self.kubelets: Dict[str, HollowKubelet] = {}

    def tick(self) -> None:
        for name in self.store.nodes:
            if name not in self.kubelets:
                self.kubelets[name] = HollowKubelet(self.store, self.leases, name)
        for name in list(self.kubelets):
            if name not in self.store.nodes:
                del self.kubelets[name]
                continue
            self.kubelets[name].tick()
