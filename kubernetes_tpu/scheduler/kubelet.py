"""Hollow kubelet — the kubemark analog (SURVEY.md §2.3 kubemark row: "real
kubelet code, mocked CRI/runtime"; §4: "run real code against fake backends").

A HollowKubelet plays the node agent's role against the in-process store:

  - watches for pods bound to its node (the reference's syncLoop source:
    pods with spec.nodeName == me), runs the pod phase machine
    Pending -> Running -> Succeeded (pods with run_seconds > 0 complete;
    others run forever — the service-pod shape)
  - heartbeats its node Lease every tick (pkg/kubelet/nodelease), which the
    NodeLifecycleController consumes for failure detection
  - publishes phase transitions through the pods/status subresource so the
    scheduler's queue ignores them (no spec change)

No CRI/container runtime is modeled: the pod "runs" by clock alone — exactly
kubemark's hollow_kubelet.go trade (pkg/kubemark).
"""

from __future__ import annotations

from typing import Dict, List, Optional
from weakref import WeakKeyDictionary

from ..api import types as t
from .leases import LeaseStore
from .queue import Clock
from .store import ClusterStore

# store -> {node_name: dense index}.  Scoping CIDR indices to the store (not
# the allocator instance) keeps per-node /24s disjoint even when several
# HollowClusters / standalone HollowKubelets share one store, and gives the
# same node the same subnet across kubelet restarts.
_CIDR_REGISTRY: "WeakKeyDictionary[ClusterStore, Dict[str, int]]" = WeakKeyDictionary()


def _cidr_index_for(store: ClusterStore, node_name: str) -> int:
    table = _CIDR_REGISTRY.setdefault(store, {})
    if node_name not in table:
        table[node_name] = len(table)
    return table[node_name]


class HollowKubelet:
    def __init__(
        self,
        store: ClusterStore,
        leases: LeaseStore,
        node_name: str,
        clock: Optional[Clock] = None,
        pod_cidr_index: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
    ):
        from .checkpoint import CheckpointManager
        from .devicemanager import DeviceManager

        self.store = store
        self.leases = leases
        self.node_name = node_name
        self.clock = clock or leases.clock
        self._started_at: Dict[str, float] = {}  # pod uid -> Running since
        # cm/devicemanager analog: concrete device IDs per admitted pod,
        # checkpointed when a directory is given (restart-safe allocations)
        self.devices = DeviceManager(
            node_name,
            CheckpointManager(checkpoint_dir) if checkpoint_dir else None,
        )
        # pod CIDR: a disjoint per-node subnet index (nodeipam's per-node /24)
        self._cidr_index = (
            pod_cidr_index
            if pod_cidr_index is not None
            else _cidr_index_for(store, node_name)
        )

    def tick(self) -> None:
        """One syncLoop iteration: heartbeat + pod state machine."""
        self.leases.renew_node_heartbeat(self.node_name)
        now = self.clock.now()
        mine = set()
        inventory = None  # (slices, classes), fetched at most once per tick
        for pod in list(self.store.pods.values()):
            if pod.node_name != self.node_name:
                continue
            mine.add(pod.uid)
            if pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                self._started_at.pop(pod.uid, None)
                self.devices.free(pod.uid)  # terminated pods release devices
                continue
            if pod.phase in ("", t.PHASE_PENDING):
                if pod.resource_claims:
                    if inventory is None:  # fetched once per tick, lazily
                        inventory = (
                            self.store.list_objects("ResourceSlice"),
                            {dc.name: dc
                             for dc in self.store.list_objects("DeviceClass")},
                        )
                    if not self._admit_devices(pod, *inventory):
                        continue  # admission failed: pod marked Failed
                # sandbox+containers "started": Pending -> Running
                self._set_phase(pod, t.PHASE_RUNNING)
                self._started_at[pod.uid] = now
            elif pod.phase == t.PHASE_RUNNING:
                started = self._started_at.setdefault(pod.uid, now)
                if pod.run_seconds > 0 and now - started >= pod.run_seconds:
                    self._set_phase(pod, t.PHASE_SUCCEEDED)
                    self._started_at.pop(pod.uid, None)
        # housekeeping: drop state for pods deleted while Running
        for uid in list(self._started_at):
            if uid not in mine:
                del self._started_at[uid]
        for uid in list(self.devices.allocations):
            if uid not in mine:
                self.devices.free(uid)

    def _admit_devices(self, pod: t.Pod, slices, classes) -> bool:
        """devicemanager Allocate at admission; failure fails the pod (the
        reference's UnexpectedAdmissionError path)."""
        from .devicemanager import AllocationError

        try:
            self.devices.allocate(pod, slices, classes)
            return True
        except AllocationError:
            self._set_phase(pod, t.PHASE_FAILED)
            return False

    def _set_phase(self, pod: t.Pod, phase: str) -> None:
        import copy

        q = copy.copy(pod)
        q.phase = phase
        if phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
            q.finished_at = self.clock.now()
        if phase == t.PHASE_RUNNING and not q.pod_ip:
            # status.podIP from the node's pod CIDR (nodeipam's per-node
            # 10.244.x.0/24 shape; the sandbox IP the CRI would report)
            q.pod_ip = self._alloc_ip()
        self.store.update_pod_status(q)

    def _alloc_ip(self) -> str:
        """Lowest free host address in this node's /24 — collision-free
        across nodes (disjoint subnets) and within the node (scan live pods;
        max ~110 pods/node keeps this O(n)).  The subnet is the node's
        spec.podCIDR when the NodeIPAM controller assigned one; otherwise
        the process-local registry index."""
        node = self.store.nodes.get(self.node_name)
        if node is not None and node.pod_cidr:
            prefix = node.pod_cidr.rsplit(".", 1)[0]  # "10.128.3.0/24" -> 10.128.3
        else:
            # 10.192/12 block: disjoint from NodeIPAM's 10.128/16 and the
            # 10.96/16 service VIP range
            n = self._cidr_index
            prefix = f"10.{192 + (n >> 8 & 0x3F)}.{n & 0xFF}"
        in_use = {
            int(p.pod_ip.rsplit(".", 1)[1])
            for p in self.store.pods.values()
            if p.node_name == self.node_name and p.pod_ip.startswith(prefix + ".")
        }
        host = next(h for h in range(1, 255) if h not in in_use)
        return f"{prefix}.{host}"


class HollowCluster:
    """kubemark's hollow-node fleet: one HollowKubelet per node in the store
    (nodes added later get a kubelet on the next tick)."""

    def __init__(self, store: ClusterStore, leases: LeaseStore):
        self.store = store
        self.leases = leases
        self.kubelets: Dict[str, HollowKubelet] = {}

    def tick(self) -> None:
        for name in self.store.nodes:
            if name not in self.kubelets:
                self.kubelets[name] = HollowKubelet(self.store, self.leases, name)
        for name in list(self.kubelets):
            if name not in self.store.nodes:
                del self.kubelets[name]
                continue
            self.kubelets[name].tick()
