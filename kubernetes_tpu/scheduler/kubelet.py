"""Hollow kubelet — the kubemark analog, with the reference kubelet's actual
control structure (SURVEY.md §2.3 kubelet row; §3.4 call stack):

  - WATCH-driven config source: the kubelet subscribes to the store and
    routes only pods with spec.nodeName == me to per-pod WORKERS
    (pkg/kubelet/kubelet.go — syncLoop's config channel; config/apiserver.go).
    No O(cluster) scans per tick.
  - POD WORKERS: one serialized state machine per pod UID
    (pkg/kubelet/pod_workers.go — type podWorkers: per-pod goroutine fed by a
    channel; here a per-UID worker object whose update() entries apply in
    arrival order).  Workers own admission (device allocation), start,
    completion, crash/restart, and teardown.
  - CRI BOUNDARY: all container work goes through the RuntimeService/
    ImageService protocols (scheduler/cri.py — the cri-api analog): pull
    images, RunPodSandbox (the sandbox owns the pod IP, as the CNI result
    the runtime reports), CreateContainer/StartContainer, and the
    stop-container -> stop-sandbox -> remove teardown ordering
    (kuberuntime_manager.go — SyncPod/killPodWithSyncResult).  The wired
    implementation is FakeCRI — clock-driven containers, the kubemark
    trade (pkg/kubemark/hollow_kubelet.go) — but the kubelet would run
    unchanged against a remote runtime speaking the same protocols.
  - PLEG: the Pod Lifecycle Event Generator relists CRI container states
    and emits ContainerStarted/ContainerDied events that drive workers,
    exactly the reference's generic PLEG relist (pkg/kubelet/pleg/
    generic.go — func (g *GenericPLEG) Relist), keyed on (container id,
    state) so a restarted container's crash is a fresh event.
  - restartPolicy: a died container restarts (restartCount++, a NEW
    container at attempt+1) under Always / OnFailure-with-nonzero-exit,
    else the pod goes Succeeded/Failed (kuberuntime_manager.go —
    computePodActions' ShouldContainerBeRestarted).
  - node Lease heartbeat per tick (pkg/kubelet/nodelease), consumed by the
    NodeLifecycleController for failure detection; pulled images publish
    to NodeStatus.Images (what ImageLocality scores against).

Phase transitions publish through the pods/status subresource so the
scheduler's queue never mistakes them for spec changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple
from weakref import WeakKeyDictionary

from .. import chaos
from ..api import types as t
from . import cri as cri_mod
from .cri import (
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    ContainerConfig,
    CRIError,
    FakeCRI,
    PodSandboxConfig,
)
from .leases import LeaseStore
from .queue import Clock
from .store import ClusterStore, Event

# store -> {node_name: dense index}.  Scoping CIDR indices to the store (not
# the allocator instance) keeps per-node /24s disjoint even when several
# HollowClusters / standalone HollowKubelets share one store, and gives the
# same node the same subnet across kubelet restarts.
_CIDR_REGISTRY: "WeakKeyDictionary[ClusterStore, Dict[str, int]]" = WeakKeyDictionary()


def _cidr_index_for(store: ClusterStore, node_name: str) -> int:
    table = _CIDR_REGISTRY.setdefault(store, {})
    if node_name not in table:
        table[node_name] = len(table)
    return table[node_name]


@dataclass
class _PodWorker:
    """pod_workers.go — one serialized lifecycle machine per pod UID.  The
    worker owns the pod's sync state; updates apply in arrival order (the
    reference serializes via a per-pod channel; in-process, call order IS
    arrival order)."""

    pod: t.Pod
    admitted: bool = False
    terminated: bool = False  # reached Succeeded/Failed
    restarts: int = 0
    sandbox_id: str = ""  # CRI objects this worker owns
    container_id: str = ""


class PLEG:
    """pleg/generic.go — Relist: diff CRI container states (through
    RuntimeService.list_containers, nothing else) against the previous
    relist and emit lifecycle events.  Keyed on (container id, state):
    restarts create a NEW container, so a crash of the replacement is a
    fresh event even when the previous relist also saw an exited state."""

    def __init__(self, runtime: "cri_mod.RuntimeService"):
        self.runtime = runtime
        self._last: Dict[str, Tuple[str, str]] = {}

    def relist(self) -> List[Tuple[str, str]]:
        events: List[Tuple[str, str]] = []
        cur: Dict[str, Tuple[str, str]] = {}
        attempts: Dict[str, int] = {}
        for cs in self.runtime.list_containers():
            # newest ATTEMPT wins (ids are runtime-assigned and carry no
            # ordering contract — a remote runtime's are hashes)
            if cs.pod_uid not in cur or cs.attempt > attempts[cs.pod_uid]:
                cur[cs.pod_uid] = (cs.id, cs.state)
                attempts[cs.pod_uid] = cs.attempt
        for uid, (cid, state) in cur.items():
            if self._last.get(uid) != (cid, state):
                if state == CONTAINER_RUNNING:
                    events.append((uid, "ContainerStarted"))
                elif state == CONTAINER_EXITED:
                    events.append((uid, "ContainerDied"))
        for uid in self._last:
            if uid not in cur:
                events.append((uid, "ContainerRemoved"))
        self._last = cur
        return events


@dataclass
class _ProbeState:
    """One prober worker's result state (prober/worker.go — type worker:
    onHold/resultRun counters), keyed to a CONTAINER: a restarted container
    gets fresh counters (the reference spawns a new worker per container)."""

    container_id: str
    last_probe: float = float("-inf")
    fails: int = 0
    successes: int = 0
    result: Optional[bool] = None  # None = no result yet (probing not begun)


class ProbeManager:
    """prober/prober_manager.go — liveness/readiness probes, clock-driven.

    The reference runs one prober worker goroutine per (pod, container,
    probe kind), each ticking on its probe's period and feeding a results
    manager the kubelet's sync loop consults.  Here sync() is called from
    the kubelet's tick for each running worker: it runs whichever probes
    are DUE (period elapsed, initial delay passed) and returns the two
    consumable signals — "liveness says kill" and the pod's readiness.

    Probe outcomes come from the hollow contract on api.types.Probe
    (fail_after_seconds), the same clock trade FakeCRI makes for container
    lifecycles; thresholds and periods behave as the reference's
    (worker.go — doProbe: failure_threshold consecutive failures flip the
    result, success_threshold consecutive successes flip it back)."""

    def __init__(self, runtime: "cri_mod.RuntimeService", clock: Clock):
        self.runtime = runtime
        self.clock = clock
        self._state: Dict[Tuple[str, str], _ProbeState] = {}

    def remove(self, pod_uid: str) -> None:
        for kind in ("liveness", "readiness"):
            self._state.pop((pod_uid, kind), None)

    def _probe_one(self, w: "_PodWorker", kind: str,
                   probe: t.Probe, started_at: float) -> Optional[bool]:
        key = (w.pod.uid, kind)
        st = self._state.get(key)
        if st is None or st.container_id != w.container_id:
            st = self._state[key] = _ProbeState(container_id=w.container_id)
        now = self.clock.now()
        if now - started_at < probe.initial_delay_seconds:
            return st.result
        if now - st.last_probe >= probe.period_seconds:
            st.last_probe = now
            ok = not (
                probe.fail_after_seconds > 0
                and now - started_at >= probe.fail_after_seconds
            )
            if ok:
                st.successes += 1
                st.fails = 0
                if st.successes >= probe.success_threshold:
                    st.result = True
            else:
                st.fails += 1
                st.successes = 0
                if st.fails >= probe.failure_threshold:
                    st.result = False
        return st.result

    def sync(self, w: "_PodWorker") -> Tuple[bool, bool]:
        """Run due probes for this worker's current container.  Returns
        (liveness_kill, pod_ready).  No readiness probe -> always ready;
        a readiness probe holds the pod NOT ready until it has passed
        success_threshold times (the reference's initial readiness is
        Failure until proven)."""
        pod = w.pod
        if pod.liveness_probe is None and pod.readiness_probe is None:
            return False, True
        try:
            status = self.runtime.container_status(w.container_id)
        except CRIError:
            return False, pod.readiness_probe is None
        if status.state != CONTAINER_RUNNING:
            return False, pod.readiness_probe is None
        kill = False
        if pod.liveness_probe is not None:
            res = self._probe_one(w, "liveness", pod.liveness_probe,
                                  status.started_at)
            kill = res is False
        ready = True
        if pod.readiness_probe is not None:
            ready = self._probe_one(
                w, "readiness", pod.readiness_probe, status.started_at
            ) is True
        return kill, ready


class VolumeManager:
    """pkg/kubelet/volumemanager — the kubelet-side half of volume
    lifecycle: desired state (the volumes of admitted pods) reconciled
    against actual state (what is attached and mounted on THIS node).

    The control-plane half is the AttachDetachController, which converges
    NodeStatus.VolumesAttached; this manager's WaitForAttachAndMount
    (volumemanager/volume_manager.go) blocks a pod's containers until
    every PV its PVCs resolve to appears in that set, then records the
    mount.  Unmount happens at pod teardown; detach is again the
    controller's job once the last using pod leaves.  Hollow trade: mounts
    are bookkeeping (no filesystem), matching FakeCRI's container trade."""

    def __init__(self, store: ClusterStore, node_name: str):
        self.store = store
        self.node_name = node_name
        self.mounted: Dict[str, Tuple[str, ...]] = {}  # pod uid -> PV names

    def _resolve_pvs(self, pod: t.Pod) -> Optional[Tuple[str, ...]]:
        """PV names behind the pod's PVCs, or None while any claim is
        unbound (the volume binder / provisioner has not landed yet)."""
        pvs = []
        pv_by_claim = None
        for claim in pod.pvcs:
            key = f"{pod.namespace}/{claim}"
            pvc = self.store.pvcs.get(key)
            name = pvc.volume_name if pvc is not None else ""
            if not name:
                if pv_by_claim is None:
                    pv_by_claim = {
                        pv.claim_ref: pv.name
                        for pv in self.store.list_pvs()
                        if pv.claim_ref
                    }
                name = pv_by_claim.get(key, "")
            if not name:
                return None
            pvs.append(name)
        return tuple(pvs)

    def wait_for_attach_and_mount(self, pod: t.Pod) -> bool:
        """True once every volume is attached here AND recorded mounted —
        the SyncPod gate (kubelet.go calls this before containers)."""
        if not pod.pvcs:
            return True
        pvs = self._resolve_pvs(pod)
        if pvs is None:
            return False
        node = self.store.nodes.get(self.node_name)
        attached = set(node.volumes_attached) if node is not None else set()
        if not all(pv in attached for pv in pvs):
            return False
        self.mounted[pod.uid] = pvs
        return True

    def unmount(self, pod_uid: str) -> None:
        self.mounted.pop(pod_uid, None)


class HollowKubelet:
    def __init__(
        self,
        store: ClusterStore,
        leases: LeaseStore,
        node_name: str,
        clock: Optional[Clock] = None,
        pod_cidr_index: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        tracer=None,
    ):
        from .checkpoint import CheckpointManager
        from .devicemanager import DeviceManager
        from .tracing import Tracer

        self.store = store
        self.leases = leases
        self.node_name = node_name
        self.clock = clock or leases.clock
        # pod syncs join the pod's trace via the collector's pod-context
        # table (component-base/tracing: the kubelet's syncPod spans)
        self.tracer = tracer or Tracer(component="kubelet")
        self.workers: Dict[str, _PodWorker] = {}  # pod_workers.go map
        # the CRI boundary: everything container-shaped goes through these
        # two protocol objects (FakeCRI implements both — the kubemark
        # runtime; the sandbox IP callback is the CNI-result analog)
        self.cri = FakeCRI(self.clock, ip_alloc=lambda uid: self._alloc_ip())
        self.runtime: "cri_mod.RuntimeService" = self.cri
        self.images: "cri_mod.ImageService" = self.cri
        self.pleg = PLEG(self.runtime)
        self.prober = ProbeManager(self.runtime, self.clock)
        self.sync_failures = 0  # syncs contained by the tick loop's catch
        self.volumemanager = VolumeManager(store, node_name)
        # cm/devicemanager analog: concrete device IDs per admitted pod,
        # checkpointed when a directory is given (restart-safe allocations)
        self.devices = DeviceManager(
            node_name,
            CheckpointManager(checkpoint_dir) if checkpoint_dir else None,
        )
        # cm/cpumanager static policy + the eviction manager (scheduler/cm.py)
        from .cm import CPUManagerStatic, EvictionManager

        node = store.nodes.get(node_name)
        n_cpus = (
            node.allocatable.get(t.CPU, 0) // 1000 if node is not None else 0
        )
        self.cpumanager = CPUManagerStatic(
            n_cpus,
            CheckpointManager(checkpoint_dir) if checkpoint_dir else None,
            node_name,
        )
        self.eviction = EvictionManager(
            store, node_name, pod_uids=lambda: list(self.workers)
        )
        self._cidr_index = (
            pod_cidr_index
            if pod_cidr_index is not None
            else _cidr_index_for(store, node_name)
        )
        # TLS bootstrap analog (pkg/kubelet/certificate — the serving-cert
        # manager): file a CertificateSigningRequest on startup; the
        # Certificates controller approves+signs it and serving_certificate
        # returns the issued cert once available
        self._csr_name = f"{node_name}-serving"
        try:
            from ..api import cluster as c

            if store.get_object(
                "CertificateSigningRequest", self._csr_name
            ) is None:
                store.add_object(
                    "CertificateSigningRequest",
                    c.CertificateSigningRequest(
                        name=self._csr_name,
                        username=f"system:node:{node_name}",
                        groups=("system:nodes",),
                    ),
                )
        except KeyError:
            pass  # stores without the kind registered (reduced harnesses)
        # config source: route my pods' watch events to workers — the
        # kubelet's syncLoop 'config updates' channel.  Seed from a LIST
        # (informer semantics), then stay event-driven.
        for pod in store.list_pods():
            if pod.node_name == self.node_name:
                self._dispatch(pod, removed=False)
        store.watch(self._on_event, replay=False)  # seeded above: my pods only

    # --- config channel ---
    def _on_event(self, ev: Event) -> None:
        if ev.obj_type != "Pod":
            return
        pod = ev.obj
        if ev.kind == "Deleted":
            if pod.uid in self.workers:
                self._dispatch(pod, removed=True)
        elif getattr(pod, "node_name", "") == self.node_name:
            self._dispatch(pod, removed=False)

    def _teardown(self, w: _PodWorker) -> None:
        """killPodWithSyncResult's ordering: stop container -> remove
        container -> stop sandbox -> remove sandbox, then release devices.
        Container and sandbox steps swallow CRIError INDEPENDENTLY: a
        container already gone must not orphan its sandbox (which would
        stay in list_pod_sandboxes, IP held, forever)."""
        try:
            if w.container_id:
                self.runtime.stop_container(w.container_id)
                self.runtime.remove_container(w.container_id)
        except CRIError:
            pass  # already gone (crash-only: teardown is idempotent)
        try:
            if w.sandbox_id:
                self.runtime.stop_pod_sandbox(w.sandbox_id)
                self.runtime.remove_pod_sandbox(w.sandbox_id)
        except CRIError:
            pass
        w.container_id = w.sandbox_id = ""
        self.devices.free(w.pod.uid)
        self.cpumanager.free(w.pod.uid)
        self.prober.remove(w.pod.uid)
        self.volumemanager.unmount(w.pod.uid)

    def _dispatch(self, pod: t.Pod, removed: bool) -> None:
        """UpdatePod (pod_workers.go): create/feed the pod's worker."""
        if removed:
            w = self.workers.pop(pod.uid, None)
            if w is not None:
                self._teardown(w)
            return
        w = self.workers.get(pod.uid)
        if w is None:
            w = self.workers[pod.uid] = _PodWorker(pod=pod)
        else:
            w.pod = pod
        if pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
            w.terminated = True
            self._teardown(w)

    # --- the sync loop ---
    def tick(self) -> None:
        """One syncLoop iteration (syncLoopIteration's channel fan-in,
        sequenced): heartbeat, runtime advance, PLEG relist -> worker syncs,
        then housekeeping."""
        self.leases.renew_node_heartbeat(self.node_name)
        if not getattr(self, "_serving_cert", ""):
            # cache the issued serving cert EAGERLY (the CSR cleaner GCs
            # the request after its TTL); if the CSR vanished unissued,
            # re-file it (certificate manager rotation loop)
            if not self.serving_certificate():
                try:
                    from ..api import cluster as c

                    if self.store.get_object(
                        "CertificateSigningRequest", self._csr_name
                    ) is None:
                        self.store.add_object(
                            "CertificateSigningRequest",
                            c.CertificateSigningRequest(
                                name=self._csr_name,
                                username=f"system:node:{self.node_name}",
                                groups=("system:nodes",),
                            ),
                        )
                except KeyError:
                    pass
        self.cri.tick()  # the fake runtime's own event loop
        # node-pressure eviction BEFORE new syncs (the reference's eviction
        # manager runs on its own loop; per-tick ordering here keeps an
        # overcommitted node from starting even more work)
        self.eviction.synchronize()
        # PLEG events drive workers (syncLoopIteration's plegCh case)
        for uid, what in self.pleg.relist():
            w = self.workers.get(uid)
            if w is None or w.terminated:
                continue
            if what == "ContainerDied":
                self._sync_died(w)
        # config-driven syncs: admit + start pods whose worker is fresh.
        # Crash-consistent: one worker's sync dying (a CRI hiccup, an
        # injected kubelet.sync crash) must neither kill the tick loop nor
        # strand the pod — partial admission rolls back (devices/cpu freed,
        # admitted reset) and the un-admitted worker retries next tick.
        for uid, w in list(self.workers.items()):
            if w.terminated or w.admitted:
                continue
            try:
                self._sync_start(w)
            except Exception as e:  # noqa: BLE001 — per-pod containment
                self.sync_failures += 1
                if w.admitted:
                    # roll back the partial admission COMPLETELY through the
                    # CRI teardown path: an already-created sandbox (and its
                    # pod IP) must not orphan in the runtime while the retry
                    # creates a second one — teardown also frees devices,
                    # exclusive CPUs, probe state and mounts, idempotently
                    self._teardown(w)
                    w.admitted = False
                chaos.record_recovery(
                    "kubelet.sync", "retry_next_tick", tracer=self.tracer,
                    pod=uid, node=self.node_name, error=type(e).__name__,
                )
        # prober (prober_manager): due probes for every running container.
        # Liveness failure kills the container and routes through the SAME
        # died path as a crash (computePodActions sees an exited container;
        # restartPolicy decides restart vs pod failure); readiness feeds
        # the pod's Ready condition, which EndpointSlice consumes.
        for uid, w in list(self.workers.items()):
            if w.terminated or not w.admitted or not w.container_id:
                continue
            kill, ready = self.prober.sync(w)
            if kill:
                try:
                    self.runtime.stop_container(w.container_id)
                except CRIError:
                    pass
                self._sync_died(w)
                continue
            cur = self.store.pods.get(uid)
            if cur is not None and cur.ready != ready:
                q = self._status_copy(w.pod)
                q.ready = ready
                self.store.update_pod_status(q)
        # housekeeping (housekeepingCh): drop terminated workers whose pod
        # left the store (deletion events already handled; belt & braces),
        # and reclaim checkpoint-restored device allocations whose pod
        # vanished while the kubelet was down (no worker, no Deleted event)
        for uid in list(self.workers):
            if uid not in self.store.pods:
                self._dispatch(self.workers[uid].pod, removed=True)
        for uid in list(self.devices.allocations):
            cur = self.store.pods.get(uid)
            if cur is None or cur.node_name != self.node_name:
                self.devices.free(uid)
        for uid in list(self.cpumanager.assignments):
            cur = self.store.pods.get(uid)
            if cur is None or cur.node_name != self.node_name:
                self.cpumanager.free(uid)

    def serving_certificate(self) -> str:
        """The issued serving certificate, "" until the Certificates
        controller has approved and signed this kubelet's bootstrap CSR.
        Cached once observed: the CSR cleaner GCs issued requests after its
        TTL (certificate_controller's cleaner), but the cert itself lives
        with the kubelet."""
        if getattr(self, "_serving_cert", ""):
            return self._serving_cert
        try:
            csr = self.store.get_object(
                "CertificateSigningRequest", self._csr_name
            )
        except KeyError:
            return ""
        self._serving_cert = csr.certificate if csr is not None else ""
        return self._serving_cert

    def close(self) -> None:
        """Detach from the store's watch fan-out (a removed/restarted hollow
        node must stop consuming events — and being retained — forever)."""
        self.store.unwatch(self._on_event)

    # --- worker syncs (kuberuntime_manager.go — SyncPod over the CRI) ---
    def _start_container(self, w: _PodWorker) -> None:
        """CreateContainer + StartContainer inside the worker's sandbox."""
        pod = w.pod
        w.container_id = self.runtime.create_container(
            w.sandbox_id,
            ContainerConfig(
                name="main",
                image=pod.images[0] if pod.images else "",
                run_seconds=pod.run_seconds,
                crash_after_seconds=pod.crash_after_seconds,
            ),
        )
        self.runtime.start_container(w.container_id)

    def _sync_start(self, w: _PodWorker) -> None:
        """Traced SyncPod entry: admission + volumes + sandbox + containers
        under one kubelet.sync span chained onto the pod's trace."""
        if not self.tracer.enabled:
            return self._sync_start_inner(w)
        with self.tracer.span_for_pod(
            w.pod.uid, "kubelet.sync", pod=w.pod.uid, node=self.node_name
        ) as sp:
            self._sync_start_inner(w)
            if sp is not None:
                sp.attributes["admitted"] = w.admitted

    def _sync_start_inner(self, w: _PodWorker) -> None:
        pod = w.pod
        if pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
            w.terminated = True
            return
        if chaos.enabled():
            # injected sync crash: contained by tick()'s per-worker catch
            chaos.poke("kubelet.sync", tracer=self.tracer,
                       pod=pod.uid, node=self.node_name)
        # WaitForAttachAndMount gates SyncPod: containers must not start
        # until the AttachDetach controller has attached every volume here
        # (checked BEFORE device/cpu allocation so nothing is held while
        # waiting; un-admitted workers retry next tick)
        if not self.volumemanager.wait_for_attach_and_mount(pod):
            return
        if pod.resource_claims:
            from .devicemanager import AllocationError

            slices = self.store.list_objects("ResourceSlice")
            classes = {dc.name: dc for dc in self.store.list_objects("DeviceClass")}
            try:
                self.devices.allocate(pod, slices, classes)
            except AllocationError:
                # UnexpectedAdmissionError: the pod fails on the node
                w.terminated = True
                self._set_phase(pod, t.PHASE_FAILED)
                return
        from .cm import CPUAllocationError

        try:
            # exclusive cores for integer-CPU pods (cpumanager static
            # policy); fragmentation -> the same UnexpectedAdmissionError
            # path as devices
            self.cpumanager.allocate(pod)
        except CPUAllocationError:
            w.terminated = True
            self.devices.free(pod.uid)
            self._set_phase(pod, t.PHASE_FAILED)
            return
        w.admitted = True
        # SyncPod: EnsureImagesExist -> RunPodSandbox -> containers
        for img in pod.images:
            self.images.pull_image(img)
        if pod.images:
            self._publish_images()
        w.sandbox_id = self.runtime.run_pod_sandbox(
            PodSandboxConfig(
                pod_uid=pod.uid, pod_name=pod.name, namespace=pod.namespace
            )
        )
        self._start_container(w)
        # the sandbox owns the pod IP (the CNI result the runtime reports)
        ip = self.runtime.pod_sandbox_status(w.sandbox_id).ip
        self._set_phase(pod, t.PHASE_RUNNING, pod_ip=ip)

    def _sync_died(self, w: _PodWorker) -> None:
        """computePodActions — ShouldContainerBeRestarted: a CRASHED container
        restarts under Always/OnFailure (restartCount++, a NEW container at
        the next attempt), else the pod goes Failed; a clean exit is the
        hollow Job contract (run_seconds elapsed: the workload is DONE) and
        terminates Succeeded."""
        if self.tracer.enabled:
            with self.tracer.span_for_pod(
                w.pod.uid, "kubelet.sync_died", pod=w.pod.uid,
                node=self.node_name,
            ):
                return self._sync_died_inner(w)
        return self._sync_died_inner(w)

    def _sync_died_inner(self, w: _PodWorker) -> None:
        try:
            status = self.runtime.container_status(w.container_id)
        except CRIError:
            status = None
        failed = status is not None and status.exit_code != 0
        policy = w.pod.restart_policy or "Always"
        if failed and policy in ("Always", "OnFailure"):
            w.restarts += 1
            # remove the dead container, then create+start the replacement
            # (kuberuntime prunes dead attempts as it restarts)
            self.runtime.remove_container(w.container_id)
            self._start_container(w)
            q = self._status_copy(w.pod)
            q.restart_count = w.restarts
            # the replacement container has not passed its readiness probe:
            # Ready drops NOW (the reference drops the condition on restart),
            # not one tick later when the prober next runs
            q.ready = w.pod.readiness_probe is None
            self.store.update_pod_status(q)
            return
        w.terminated = True
        self._teardown(w)
        self._set_phase(w.pod, t.PHASE_FAILED if failed else t.PHASE_SUCCEEDED)

    def _publish_images(self) -> None:
        """NodeStatus.Images from the runtime's image list (what
        ImageLocality scores against) — only when something new landed, so
        steady state never rewrites the Node object (identity fingerprints
        in the delta encoder stay warm)."""
        import copy

        node = self.store.nodes.get(self.node_name)
        if node is None:
            return
        have = self.images.list_images()
        merged = {**node.images, **have}
        if merged != node.images:
            q = copy.copy(node)
            q.images = merged
            self.store.update_node(q)

    # --- status publication ---
    def _status_copy(self, pod: t.Pod) -> t.Pod:
        import copy

        cur = self.store.pods.get(pod.uid, pod)
        return copy.copy(cur)

    def _set_phase(self, pod: t.Pod, phase: str, pod_ip: str = "") -> None:
        q = self._status_copy(pod)
        q.phase = phase
        if phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
            q.finished_at = self.clock.now()
        if phase == t.PHASE_RUNNING and not q.pod_ip:
            # status.podIP = the sandbox IP the runtime reported (CNI
            # result through RunPodSandbox); allocator fallback for direct
            # callers outside a sandbox
            q.pod_ip = pod_ip or self._alloc_ip()
        if phase == t.PHASE_RUNNING:
            # Ready starts False under a readiness probe (initial readiness
            # is Failure until the probe passes success_threshold times)
            q.ready = pod.readiness_probe is None
        self.store.update_pod_status(q)

    def _alloc_ip(self) -> str:
        """Lowest free host address in this node's /24 — collision-free
        across nodes (disjoint subnets) and within the node (scan live pods;
        max ~110 pods/node keeps this O(n)).  The subnet is the node's
        spec.podCIDR when the NodeIPAM controller assigned one; otherwise
        the process-local registry index."""
        node = self.store.nodes.get(self.node_name)
        if node is not None and node.pod_cidr:
            prefix = node.pod_cidr.rsplit(".", 1)[0]  # "10.128.3.0/24" -> 10.128.3
        else:
            # 10.192/12 block: disjoint from NodeIPAM's 10.128/16 and the
            # 10.96/16 service VIP range
            n = self._cidr_index
            prefix = f"10.{192 + (n >> 8 & 0x3F)}.{n & 0xFF}"
        in_use = {
            int(p.pod_ip.rsplit(".", 1)[1])
            for p in self.store.list_pods()
            if p.node_name == self.node_name and p.pod_ip.startswith(prefix + ".")
        }
        host = next(h for h in range(1, 255) if h not in in_use)
        return f"{prefix}.{host}"


class HollowCluster:
    """kubemark's hollow-node fleet: one HollowKubelet per node in the store
    (nodes added later get a kubelet on the next tick)."""

    def __init__(self, store: ClusterStore, leases: LeaseStore):
        self.store = store
        self.leases = leases
        self.kubelets: Dict[str, HollowKubelet] = {}

    def tick(self) -> None:
        names = self.store.list_node_names()  # lock-consistent snapshot
        for name in names:
            if name not in self.kubelets:
                self.kubelets[name] = HollowKubelet(self.store, self.leases, name)
        names = set(names)
        for name in list(self.kubelets):
            if name not in names:
                self.kubelets.pop(name).close()
                continue
            self.kubelets[name].tick()
