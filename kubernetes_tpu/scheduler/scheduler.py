"""The Scheduler: pop -> schedule -> bind, in two execution modes.

Analog of pkg/scheduler/scheduler.go (type Scheduler, Run) and
schedule_one.go (ScheduleOne: schedulingCycle + bindingCycle):

  mode="cpu"  one pod per cycle through the plugin framework — the reference's
              exact shape (findNodesThatFitPod -> prioritizeNodes -> selectHost
              -> assume -> bind) and the mandated fallback path.
  mode="tpu"  drain the activeQ into a batch, lower the cache snapshot to
              device arrays, run the jitted filter/score/commit scan (+ gang
              fixpoint), bind all placements.  Decision-identical to cpu mode
              (both tie-break to the lowest node index; see parity tests).

Failure path (both modes): PostFilter/preemption may evict victims and
nominate a node; the pod then re-queues with backoff and the freed capacity is
visible to its retry — the reference's nominatedNodeName flow reduced to
requeue-after-evict (the nomination is not reserved against competing pods;
deviation noted, matching the reference's own best-effort nomination).

Watch wiring: new pending pods pass PreEnqueue into the activeQ (gated pods
wait in unschedulablePods for a Pod/Update); Node add/update and Pod delete
events MoveAllToActiveOrBackoffQueue — the QueueingHint machinery reduced to
event kinds.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import chaos
from ..api import types as t
from ..api.snapshot import Snapshot, encode_snapshot
from ..ops.scores import infer_score_config
from .cache import SchedulerCache
from .store import replace_pod_nodename
from .config import SchedulerConfiguration
from .events import EventRecorder
from .features import FeatureGates
from .framework import CycleState, Framework, NodeInfo, Status
from .metrics import Metrics, SLI_PHASES
from .plugins.cpu import default_plugins
from .queue import (
    EV_NODE_ADD,
    EV_NODE_UPDATE,
    EV_POD_ADD,
    EV_POD_DELETE,
    Clock,
    PriorityQueue,
)
from .state import ScaledState
from .store import ClusterStore, Event
from ..analysis.lockcheck import make_lock


def _sli_phase_block(wave_phases: Dict[str, Dict[str, float]]) -> dict:
    """Compact per-cycle phase summary for the flight recorder: per-phase
    mean/max across the cycle's bound pods plus the worst pod's full phase
    vector (a record stays a few hundred bytes at any wave size)."""
    n = len(wave_phases)
    total = {ph: 0.0 for ph in SLI_PHASES}
    peak = {ph: 0.0 for ph in SLI_PHASES}
    worst_uid, worst_sli, worst_vec = "", -1.0, {}
    for uid, phases in wave_phases.items():
        sli = sum(phases.values())
        if sli > worst_sli:
            worst_uid, worst_sli, worst_vec = uid, sli, phases
        for ph, v in phases.items():
            total[ph] += v
            if v > peak[ph]:
                peak[ph] = v
    return {
        "pods": n,
        "mean_ms": {ph: round(total[ph] / n * 1e3, 3) for ph in SLI_PHASES},
        "max_ms": {ph: round(peak[ph] * 1e3, 3) for ph in SLI_PHASES},
        "worst": {
            "pod": worst_uid,
            "sli_ms": round(worst_sli * 1e3, 3),
            "phases_ms": {ph: round(v * 1e3, 3)
                          for ph, v in worst_vec.items()},
        },
    }


class Scheduler:
    def __init__(
        self,
        store: ClusterStore,
        config: SchedulerConfiguration = SchedulerConfiguration(),
        clock: Optional[Clock] = None,
        logger=None,
        collector=None,
        metrics=None,
        checkpoint_dir: Optional[str] = None,
    ):
        from .tracing import TraceCollector, Tracer, default_collector

        self.store = store
        self.config = config
        self.features = FeatureGates(config.feature_gates)
        self.cache = SchedulerCache(store)
        # kill.post_assume injections stamp THIS scheduler's tracer/metrics
        # (and latch _dead) like every other kill site
        self.cache.kill_point = self._kill_point
        # simulated-process liveness: a kill.* chaos fault latches this (and
        # the module-wide chaos.killed()) so the dying instance's unwind —
        # deferred-bind flush, binding drains — does nothing a SIGKILL'd
        # process couldn't.  restart_scheduler() builds the replacement.
        self._dead = False
        # span tracing: callers may inject a TraceCollector (bench rounds use
        # a fresh one per run; pass TraceCollector(enabled=False) to opt out
        # of all span allocation); default = the process-wide collector
        self.collector: TraceCollector = (
            collector if collector is not None else default_collector()
        )
        self.tracer = Tracer(self.collector, component="scheduler")
        # KTPU_CHAOS_SEED / KTPU_FAULT_PLAN arm the fault injector for any
        # scheduler-driven process (idempotent; no-op when unset)
        chaos.maybe_install_from_env()
        self.queue = PriorityQueue(
            clock, tracer=Tracer(self.collector, component="queue"),
            initial_backoff_s=config.pod_initial_backoff_seconds,
            max_backoff_s=config.pod_max_backoff_seconds,
            backoff_jitter=config.pod_backoff_jitter,
        )
        # injectable registry: a crash-restart driver hands the SAME Metrics
        # to every incarnation, so counters/hists (the SLI included) span
        # restarts the way an external scrape target would see them
        self.metrics = metrics if metrics is not None else Metrics()
        # the headline SLI: true per-pod arrival -> bind latency
        # (metrics.go — pod_scheduling_sli_duration_seconds), stamped at
        # queue admission and observed at bind publication — batch waves,
        # deferred pipeline commits and the gang fixpoint all land here.
        # Cached handle: one lock per bound pod, no registry round-trip.
        self._sli_hist = self.metrics.hist("pod_scheduling_sli_duration_seconds")
        # per-wave introspection for the SLI-consistency tests (and
        # debugging): uid -> latest true SLI / kernel ordinal estimate.
        # Populated only while tracing is enabled (the cheap-gate contract:
        # no per-pod bookkeeping off the enabled path).
        self.last_wave_sli: Dict[str, float] = {}
        self.last_wave_estimates: Dict[str, float] = {}
        # per-pod SLI phase decomposition (queue_wait | wave_wait |
        # device_kernel | bind — metrics.py SLI_PHASES): labeled
        # StreamingHists observed at bind publication from the span
        # machinery's instants, so all per-pod bookkeeping here sits behind
        # the same tracer.enabled cheap gate as the spans themselves.
        # Cached handles, one lock per phase per bound pod.
        self._phase_hists = {
            ph: self.metrics.labeled_hist("pod_sli_phase_duration_seconds",
                                          phase=ph)
            for ph in SLI_PHASES
        }
        # uid -> (kernel dispatch instant, decision-ready instant): the
        # wave_wait/device_kernel/bind boundaries, stamped per kernel wave
        # from the commit-ordinal estimates and consumed at publication —
        # deferred binds keep their marks until the flush, so `bind`
        # honestly includes the deferral window.
        self._phase_marks: Dict[str, Tuple[float, float]] = {}
        # uid -> phase vector for pods bound this batch cycle (cleared at
        # each cycle start): the flight recorder stamps this per record so
        # a post-kill dump shows the latency anatomy of in-flight pods.
        self.last_wave_phases: Dict[str, Dict[str, float]] = {}
        # bounded worst-K exemplar heap for the open-loop observatory's
        # --sli-attribution report: (sli, seq, uid, phases), min-heap on
        # sli so the K worst survive; seq breaks ties (dicts don't compare)
        self._sli_worst: List[Tuple[float, int, str, Dict[str, float]]] = []
        self._sli_worst_seq = itertools.count()
        # binding-cycle worker threads publish concurrently on the CPU
        # path — heapq ops are not atomic, unlike the per-uid dict writes
        self._sli_worst_lock = make_lock("Scheduler._sli_worst_lock")
        try:
            self._sli_worst_k = max(
                1, int(os.environ.get("KTPU_OPEN_LOOP_EXEMPLARS", "5")))
        except ValueError:
            self._sli_worst_k = 5
        self.events = EventRecorder(store=store, metrics=self.metrics)
        from .klog import Logger

        # contextual logger (klog.LoggerWithValues shape); callers may pass
        # their own configured backend
        self.log = (logger or Logger()).with_values(component="scheduler")
        from .extender import HTTPExtender

        self.extenders = [HTTPExtender(e) for e in config.extenders]
        self._bind_pool = None
        self._bind_lock = make_lock("Scheduler._bind_lock")
        self._bind_futures: list = []
        if config.binding_workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._bind_pool = ThreadPoolExecutor(
                max_workers=config.binding_workers,
                thread_name_prefix="binding-cycle",
            )
        # findNodesThatFitPod's rotating cursor (schedule_one.go —
        # nextStartNodeIndex): spreads partial-scoring passes over the cluster
        self._next_start_node_index = 0
        # coscheduling waiting-pods map (framework/runtime/waiting_pods_map.go
        # + the coscheduling plugin's Permit-wait): gang members on the CPU
        # path hold their assumption here until minMember siblings arrive;
        # quorum binds all, quiescence without quorum rejects all — so the
        # sidecar-deadline fallback preserves all-or-nothing exactly like the
        # batch path's gang fixpoint (ops/gang.py)
        self._gang_waiting: Dict[str, List[Tuple[t.Pod, str, object, object]]] = {}
        # watch callbacks fire on whichever thread mutates the store (e.g.
        # binding-pool threads) — the waiting map needs its own lock
        self._gang_lock = make_lock("Scheduler._gang_lock")
        # one Framework per profile (frameworkForPod — pods select theirs by
        # spec.schedulerName); self.framework stays the default profile's
        self.frameworks: Dict[str, Framework] = {
            p.scheduler_name: Framework(
                default_plugins(
                    store,
                    filter_fn=self._filter_one,
                    nominated_fn=lambda n: self.queue.nominated_pods_for_node(n),
                    hard_pod_affinity_weight=p.hard_pod_affinity_weight,
                    plugin_specs=p.plugins,
                    extenders=self.extenders,
                    fit_strategy=p.fit_strategy,
                    rtcr_shape=p.rtcr_shape,
                ),
                tracer=self.tracer,
                metrics=self.metrics,
            )
            for p in config.profiles
        }
        self.default_profile_name = config.profiles[0].scheduler_name
        self.framework = self.frameworks[self.default_profile_name]
        # batch-cycle lead rotation (anti-starvation across profiles)
        self._last_profile_served: Optional[str] = None
        self._sidecar = None  # most-recent client (kept for tests/introspection)
        self._sidecars: Dict[str, object] = {}  # per-address lazy TPUScoreClients
        # batched-bind move coalescing: while a batch commit loop runs, watch
        # events' MoveAllToActiveOrBackoffQueue calls collapse into one move
        # per event kind at loop exit (the reference fires one move per
        # CLUSTER event; a 10k-pod batch bind is 10k events back-to-back)
        self._move_lock = make_lock("Scheduler._move_lock")
        self._move_coalesce: Optional[set] = None
        # resident incremental encoder for the batch path: cluster-side device
        # state persists across cycles, absorbing bind/delete deltas
        # (api/delta.py — the watch-cache analog)
        self._delta_enc = None
        # resident incremental device hoist for the non-gang batch kernel:
        # equivalence-class scores cached on device across cycles, dirty-node
        # patched per warm delta (ops/incremental.py; KTPU_INCREMENTAL=0 off)
        self._hoist_cache = None
        # pipelined batch commits: the bind/events/queue fan-out of cycle
        # i−1 is deferred into cycle i's device-step window (dispatch is
        # async) whenever that is provably serial-equivalent — capacity is
        # reserved synchronously through cache.assume either way, so every
        # encode sees identical bound state.  KTPU_PIPELINE=0 (or the
        # config knob) restores the fully synchronous commit.
        self._pipeline_commit = (
            config.pipeline_commit and os.environ.get("KTPU_PIPELINE") != "0"
        )
        self._deferred_binds: List[Tuple[t.Pod, str]] = []
        # wave WAL (streaming crash-consistency): while a commit wave is
        # between verdict and full publication, {"uids": [...],
        # "verdict_crc": str} rides every checkpoint save so restore() can
        # reconcile the killed wave to exactly-once publication
        self._wave_wal: Optional[Dict] = None
        # open-loop replay cursor (bench/loadgen.py stamps it per cycle):
        # the arrival trace's virtual clock + event offset checkpoint
        # alongside the WAL; restore() surfaces the dead leader's cursor
        # as `restored_cursor` for the surviving replay driver to verify
        self._replay_cursor: Optional[Dict] = None
        self.restored_cursor: Optional[Dict] = None
        # deferral engages only under run_until_idle's cycle stream: a
        # directly-called schedule_batch() keeps its contract that binds
        # are store-visible on return
        self._cycle_streaming = False
        # persistent XLA compilation cache (KTPU_COMPILE_CACHE_DIR): a second
        # scheduler process loads the north-star executable from disk
        # instead of re-paying the cold compile (ops/aot.py)
        if config.mode in ("tpu", "native"):
            from ..ops.aot import maybe_enable_compile_cache

            maybe_enable_compile_cache()
        # device mesh for the batch path's sharded routed step: KTPU_MESH
        # wins (validated/clamped — parallel/mesh.py), else the largest
        # profile meshDevices from TPUScoreArgs.  None = single-device, the
        # unchanged default.  Both batch branches thread it — the plain
        # routed call AND the gang host fixpoint (which never donates, so
        # sharding is safe there too); native/sidecar cycles stay
        # unsharded (the C++ engine is host-side; the sidecar runs its own
        # scheduler process).
        self.mesh = None
        if config.mode == "tpu":
            from ..parallel.mesh import mesh_from_env

            self.mesh = mesh_from_env()
            if self.mesh is None:
                md = max(
                    (p.tpu_score.mesh_devices for p in config.profiles
                     if p.tpu_score is not None),
                    default=1,
                )
                if md > 1:
                    # same validated clamp-with-warning (or None) semantics
                    # as the env knob — one resolution path for both
                    self.mesh = mesh_from_env(str(md), source="meshDevices")
        # crash-consistent state (checkpoint.py): KTPU_CHECKPOINT_DIR (or the
        # explicit arg) arms a kubelet-style checksummed checkpoint of the
        # assumed-pod ledger + deferred-commit WAL + SLI arrival stamps —
        # everything else rebuilds from LIST+WATCH (crash-only).  The ledger
        # checkpoints at every cache.assume/forget via the cache hook.
        self._ckpt = None
        ckpt_dir = checkpoint_dir or os.environ.get("KTPU_CHECKPOINT_DIR")
        if ckpt_dir:
            from .checkpoint import CheckpointManager

            self._ckpt = CheckpointManager(
                ckpt_dir, metrics=self.metrics, logger=self.log
            )
            self.cache.checkpoint_hook = self._checkpoint_state
        # decision flight recorder (flightrecorder.py): bounded in-memory
        # ring of per-cycle decision records (verdict/class fingerprints,
        # dirty columns, diagnosis vectors, trace ids), dumped into the
        # checkpoint dir on an enumerated kill site or a wave-recovery
        # parity event — the crash black box
        # `python -m kubernetes_tpu.analysis --flight` reads post-mortem
        from .flightrecorder import FlightRecorder

        self._flight = FlightRecorder(directory=ckpt_dir)
        self._last_diagnosis: List[dict] = []
        # HBM telemetry ledger (memwatch.py): per-device live stats +
        # resident-buffer census sampled at both batch-cycle boundaries
        # (next to the queue-depth gauges), device_hbm_* gauge family on
        # /metrics, and the compact memory block every flight-recorder
        # record carries — a post-mortem answers "were we near the
        # ceiling when it died".  KTPU_MEMWATCH=0 disables; non-tpu modes
        # own no device buffers, so nothing to meter there.
        from .memwatch import DeviceMemoryLedger, memwatch_enabled

        self._memwatch = (
            DeviceMemoryLedger(mesh=self.mesh, metrics=self.metrics)
            if config.mode == "tpu" and memwatch_enabled() else None
        )
        store.watch(self._on_event)

    # --- watch plumbing ---
    def _move_all(self, event_kind: str, obj=None, old=None) -> None:
        """MoveAllToActiveOrBackoffQueue, coalesced while a batch bind loop is
        active (one real move per distinct event kind at loop exit; the
        coalesced flush carries no event object, so parked pods' QueueingHint
        callbacks are skipped conservatively — they wake on kind match)."""
        with self._move_lock:
            if self._move_coalesce is not None:
                self._move_coalesce.add(event_kind)
                return
        self.queue.move_all_to_active_or_backoff(event_kind, obj=obj, old=old)

    @contextlib.contextmanager
    def _coalesced_moves(self):
        with self._move_lock:
            first = self._move_coalesce is None
            if first:
                self._move_coalesce = set()
        try:
            yield
        finally:
            if first:
                with self._move_lock:
                    kinds, self._move_coalesce = self._move_coalesce, None
                for k in sorted(kinds):
                    self.queue.move_all_to_active_or_backoff(k)

    def _on_event(self, ev: Event) -> None:
        if ev.obj_type == "Pod":
            pod = ev.obj
            if ev.kind == "Deleted":
                self.queue.delete(pod.uid)
                # a recreated pod reuses the namespace/name-derived uid: drop
                # the trace context so its spans start a FRESH trace instead
                # of chaining into the dead predecessor's
                self.collector.detach_pod(pod.uid)
                # a gang member deleted while Permit-waiting must release its
                # assumption and stop counting toward quorum
                if pod.pod_group:
                    dropped = False
                    with self._gang_lock:
                        waiters = self._gang_waiting.get(pod.pod_group)
                        if waiters is not None:
                            kept = [w for w in waiters if w[0].uid != pod.uid]
                            dropped = len(kept) != len(waiters)
                            if dropped and kept:
                                self._gang_waiting[pod.pod_group] = kept
                            elif dropped:
                                del self._gang_waiting[pod.pod_group]
                    if dropped:
                        self.cache.forget(pod.uid)
                self._move_all(EV_POD_DELETE, obj=pod)
            elif ev.kind == "ModifiedStatus":
                # status-only write: no requeue of THIS pod — but a bound pod
                # reaching a terminal phase releases capacity, which is an
                # AssignedPodDelete move event for waiting unschedulable pods
                if pod.node_name and pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                    self._move_all(EV_POD_DELETE, obj=pod)
            elif not pod.node_name:
                fw = self._fw(pod)
                if fw is None:
                    return  # another scheduler's pod: not queued, not failed
                st = fw.run_pre_enqueue(pod)
                if st.ok:
                    self.queue.add(pod)
                    self.metrics.inc("queue_incoming_pods_total")
                else:
                    self.queue.add_unschedulable(pod, {"Pod/Update"}, backoff=False)
            else:
                # assigned-pod add/update: a newly bound pod can satisfy
                # waiting pods' affinity/spread terms (AssignedPodAdd hint)
                self._move_all(EV_POD_ADD, obj=pod)
        elif ev.obj_type == "Node":
            self._move_all(
                EV_NODE_ADD if ev.kind == "Added" else EV_NODE_UPDATE,
                obj=ev.obj,
                old=getattr(ev, "old", None),
            )


    def _fw(self, pod: t.Pod) -> Optional[Framework]:
        """frameworkForPod (schedule_one.go): the profile the pod selects by
        spec.schedulerName, or None when no profile here serves that name —
        such pods are another scheduler's responsibility and are ignored."""
        return self.frameworks.get(pod.scheduler_name or self.default_profile_name)

    def _filter_one(self, state: CycleState, snap: Snapshot, pod: t.Pod, info: NodeInfo) -> Status:
        return self._fw(pod).run_filters(state, snap, pod, info)

    def _filter_with_nominated(
        self, state: CycleState, snap: Snapshot, pod: t.Pod, info: NodeInfo, i: int
    ) -> Status:
        """schedule_one.go — RunFilterPluginsWithNominatedPods: when
        equal-or-higher-priority pods are nominated onto this node, the pod
        must pass Filter both WITH their resources/affinity terms counted
        (resource-type plugins must respect the reservation) and WITHOUT them
        (anti-affinity against a nominated pod that may never arrive must not
        grant feasibility)."""
        nominated = [
            q
            for q in self.queue.nominated_pods_for_node(info.node.name)
            if q.uid != pod.uid and q.priority >= pod.priority
        ]
        if not nominated:
            return self._fw(pod).run_filters(state, snap, pod, info)
        sc = state.data["scaled"]
        sim = NodeInfo(node=info.node, pods=list(info.pods) + list(nominated))
        sc.push_sim(i, sim)
        try:
            st = self._fw(pod).run_filters(state, snap, pod, sim)
        finally:
            sc.pop_sim(i)
        if not st.ok:
            return st
        return self._fw(pod).run_filters(state, snap, pod, info)

    # --- findNodesThatFitPod helpers (CPU path) ---
    def _num_feasible_nodes_to_find(self, num_nodes: int, profile_name: str = "") -> int:
        """schedule_one.go — numFeasibleNodesToFind: percentageOfNodesToScore
        (0 = adaptive max(5, 50 - nodes/125)%), floored at
        minFeasibleNodesToFind = 100."""
        pct = self.config.profile(
            profile_name or self.default_profile_name
        ).percentage_of_nodes_to_score
        if pct == 0:
            pct = max(5, 50 - num_nodes // 125)
        if pct >= 100 or num_nodes <= 100:
            return num_nodes
        return max(100, num_nodes * pct // 100)

    def _find_feasible(self, state, snap, pod, infos):
        """Rotating-cursor filter fan-out with early stop at
        numFeasibleNodesToFind (the adaptive-sampling half of D3; the batch
        path always scores everything).  Traced runs flush ONE aggregate
        Filter/<plugin> span per cycle from the accumulator run_filters
        fills (per-(node, plugin) spans would flood the ring; see
        framework.run_filters)."""
        tracing = self.tracer.enabled
        t_f0 = time.perf_counter() if tracing else 0.0
        n = len(infos)
        want = self._num_feasible_nodes_to_find(
            n, pod.scheduler_name or self.default_profile_name
        )
        feasible: List[int] = []
        statuses: Dict[str, Status] = {}
        processed = 0
        start = self._next_start_node_index % n if n else 0
        for k in range(n):
            i = (start + k) % n
            processed += 1
            fst = self._filter_with_nominated(state, snap, pod, infos[i], i)
            if fst.ok:
                feasible.append(i)
                if len(feasible) >= want:
                    break
            else:
                statuses[infos[i].node.name] = fst
        if n:
            self._next_start_node_index = (start + processed) % n
        feasible.sort()  # deterministic tie-break stays index-ordered
        if tracing:
            # aggregate spans tile sequentially from the fan-out start: the
            # sum of per-plugin filter time, one span per plugin per cycle
            agg = state.data.pop("_filter_trace", None)
            if agg:
                off = t_f0
                for plugin_name, (dt, calls) in agg.items():
                    self.tracer.record_span(
                        f"Filter/{plugin_name}", start=off, end=off + dt,
                        extension_point="Filter", plugin=plugin_name,
                        nodes=calls,
                    )
                    off += dt
        return feasible, statuses

    def _extender_filter(self, pod, infos, feasible, statuses):
        """findNodesThatPassExtenders: each extender prunes the feasible set;
        transport failure from a non-ignorable extender fails the cycle."""
        from .extender import ExtenderError

        if not self.extenders or not feasible:
            return feasible, statuses, True
        names = [infos[i].node.name for i in feasible]
        for ext in self.extenders:
            if not ext.cfg.filter_verb:
                continue
            try:
                names, failed = ext.filter(pod, names)
            except ExtenderError as e:
                if ext.cfg.ignorable:
                    continue
                statuses["*extender*"] = Status.unschedulable(str(e))
                return [], statuses, False
            for node, reason in failed.items():
                statuses[node] = Status.unschedulable(f"extender: {reason}")
        keep = set(names)
        return [i for i in feasible if infos[i].node.name in keep], statuses, True

    def _extender_prioritize(self, pod, chosen, scores):
        from .extender import ExtenderError

        if not self.extenders:
            return scores
        names = [info.node.name for info in chosen]
        scores = list(scores)
        for ext in self.extenders:
            if not ext.cfg.prioritize_verb:
                continue
            try:
                prio = ext.prioritize(pod, names)
            except ExtenderError:
                continue  # a failed prioritize zeroes that extender's votes
            for j, name in enumerate(names):
                scores[j] += prio.get(name, 0.0)
        return scores

    # --- the CPU scheduling cycle (ScheduleOne) ---
    def schedule_one(self, pod: t.Pod) -> Optional[str]:
        """One pod through the plugin framework, wrapped in a
        scheduling.cycle span chained onto the pod's trace (queue-wait span
        -> this -> binding.cycle -> kubelet sync)."""
        if not self.tracer.enabled:
            return self._schedule_one_cycle(pod)
        with self.tracer.span_for_pod(
            pod.uid, "scheduling.cycle", pod=pod.uid
        ) as sp:
            node = self._schedule_one_cycle(pod)
            if sp is not None:
                sp.attributes["node"] = node or ""
            return node

    def _schedule_one_cycle(self, pod: t.Pod) -> Optional[str]:
        from ..api.volumes import resolve_snapshot

        t0 = time.perf_counter()
        cycle_move_seq = self.queue.move_seq  # moveRequestCycle guard
        snap = resolve_snapshot(self.cache.update_snapshot())
        # the popped pod may have gained folded volume/claim constraints
        pod = next((q for q in snap.pending_pods if q.uid == pod.uid), pod)
        infos = self.cache.node_infos(snap)
        state = CycleState()
        state.data["scaled"] = ScaledState(snap, infos)
        fw = self._fw(pod)
        if fw is None:
            return None  # another scheduler's pod (defensive; not enqueued)
        st = fw.run_pre_filter(state, snap, pod)
        feasible: List[int] = []
        statuses: Dict[str, Status] = {}
        if st.ok:
            feasible, statuses = self._find_feasible(state, snap, pod, infos)
            feasible, statuses, ext_ok = self._extender_filter(
                pod, infos, feasible, statuses
            )
            if not ext_ok:
                # extender transport failure is a cycle ERROR, not an
                # unschedulable verdict: no preemption (evicting victims
                # cannot help — the retry hits the same dead extender);
                # the pod just backs off (schedule_one.go handleSchedulingFailure
                # on a non-fitError)
                self.events.record(
                    "FailedScheduling", pod.uid,
                    message=str(statuses.get("*extender*", "extender error")),
                )
                self.queue.add_unschedulable(pod, backoff=True)
                self.metrics.inc("scheduling_attempts_error")
                return None
        if not feasible:
            nominated, pst = fw.run_post_filters(state, snap, pod, statuses)
            # fitError-shaped diagnosis from the per-plugin statuses this
            # cycle already holds (schedule_one.go — Diagnosis /
            # NodeToStatusMap), rendered by the SAME renderer the device
            # path's explain kernel uses (ops/explain.py)
            from ..ops.explain import dominant_reason, render_unschedulable

            counts: Dict[str, int] = {}
            metric_label: Dict[str, str] = {}
            for fst in statuses.values():
                reason = ((fst.reasons[0] if fst.reasons else "")
                          or fst.plugin or "unschedulable")
                counts[reason] = counts.get(reason, 0) + 1
                # bounded metric-label rule: plugin-stamped reasons are a
                # closed vocabulary (builtin plugins, static strings +
                # per-resource), but free-form sources (extender text)
                # would mint a new labeled series per distinct string —
                # collapse those so label cardinality stays bounded
                metric_label[reason] = reason if fst.plugin else "extender"
            if not statuses and not st.ok:
                # PreFilter rejection marks every node (schedule_one.go —
                # a PreFilter status fails the whole cluster at once)
                reason = ((st.reasons[0] if st.reasons else "")
                          or st.plugin or "PreFilter rejected")
                counts[reason] = len(infos)
                metric_label[reason] = (reason if st.plugin
                                        else "PreFilter rejected")
            # label-sorted: the accumulation order above follows the
            # rotating node cursor, so a tied dominant reason would flap
            # between runs without a deterministic insertion order
            counts = {k: counts[k] for k in sorted(counts)}
            if counts:
                self.metrics.inc_labeled(
                    "pod_unschedulable_reasons_total",
                    reason=metric_label[dominant_reason(counts)],
                )
            msg = render_unschedulable(len(infos), counts)
            if pst.ok:
                msg = msg.rstrip(".") + f"; preemption nominated {nominated}."
            self.events.record("FailedScheduling", pod.uid, message=msg)
            self.log.V(2).info("Unable to schedule pod", pod=pod.uid,
                               nodes=len(infos), failed=len(statuses),
                               nominated=nominated if pst.ok else "")
            if pst.ok and nominated:
                self.events.record("Preempted", pod.uid, node=nominated)
                self._nominate(pod, nominated)
            else:
                self._clear_nomination(pod)  # clearNominatedNode: stale
            # QueueingHints: park on the events the FAILING plugins registered.
            # When preemption just nominated a node the victims' deletions
            # already fired (in-process eviction is synchronous, unlike the
            # reference's watch) — the pod takes the plain backoff retry so it
            # returns to claim the freed capacity.
            # ... and if any move event fired DURING this cycle (e.g. a
            # concurrent binding's AssignedPodAdd), the pod saw a stale
            # snapshot: plain backoff, or its wake event is already gone
            failing = {s.plugin for s in statuses.values() if s.plugin}
            park = failing and not (pst.ok and nominated)
            hint_events = fw.events_for_plugins(failing) if park else None
            hints = fw.hints_for_plugins(failing) if park else None
            # move_seq compared inside add_unschedulable, under the queue lock
            self.queue.add_unschedulable(
                pod, hint_events, backoff=True, cycle_move_seq=cycle_move_seq,
                hints=hints,
            )
            self.metrics.inc("scheduling_attempts_unschedulable")
            return None
        chosen = [infos[i] for i in feasible]
        fw.run_pre_score(state, snap, pod, chosen)
        scores = fw.run_scores(state, snap, pod, chosen)
        scores = self._extender_prioritize(pod, chosen, scores)
        best = feasible[int(np.argmax(scores))]  # first max == lowest node index
        node_name = infos[best].node.name
        # assume: the cycle becomes pipelinable — the assumed pod occupies
        # capacity for the NEXT pod's cycle while this one's binding runs
        self.cache.assume(pod.uid, node_name)
        st = fw.run_permit(state, snap, pod, node_name)
        if not st.ok:
            self.cache.forget(pod.uid)
            self.queue.add_unschedulable(pod, backoff=True)
            return None
        # coscheduling Permit-wait: a gang member holds its assumption (the
        # capacity reservation IS the atomicity mechanism) until minMember
        # siblings are assumed or bound; the arrival that completes the
        # quorum binds every waiter
        if pod.pod_group and self.features.enabled("GangScheduling"):
            with self._gang_lock:
                waiters = self._gang_waiting.setdefault(pod.pod_group, [])
                # dedupe: a re-scheduled copy of an already-waiting member
                # (e.g. a metadata update re-queued it) must REPLACE its
                # entry, never inflate the quorum count
                waiters[:] = [w for w in waiters if w[0].uid != pod.uid]
                waiters.append((pod, node_name, state, snap))
                pg = snap.pod_groups.get(pod.pod_group)
                need = pg.min_member if pg else 1
                waiting_uids = {w[0].uid for w in waiters}
                bound = sum(
                    1
                    for q in snap.bound_pods
                    if q.pod_group == pod.pod_group and q.uid not in waiting_uids
                )
                if len(waiters) + bound < need:
                    return None  # waiting (assumed, not bound)
                waiters = self._gang_waiting.pop(pod.pod_group)
            out = None
            for wpod, wnode, wstate, wsnap in waiters:
                r = self._binding_cycle(wstate, wsnap, wpod, wnode, t0)
                if wpod.uid == pod.uid:
                    out = r
            return out
        if self._bind_pool is not None:
            # bindingCycle as its own goroutine (schedule_one.go: `go func()`)
            # overlapping the next pod's schedulingCycle
            fut = self._bind_pool.submit(
                self._binding_cycle_safe, state, snap, pod, node_name, t0
            )
            with self._bind_lock:
                self._bind_futures = [f for f in self._bind_futures if not f.done()]
                self._bind_futures.append(fut)
            return node_name  # optimistic: assumed
        return self._binding_cycle(state, snap, pod, node_name, t0)

    def _binding_cycle_safe(self, state, snap, pod, node_name, t0) -> Optional[str]:
        """Worker-thread entry: an unexpected exception must not silently
        strand the assumed pod (phantom capacity + a pod nobody retries)."""
        try:
            return self._binding_cycle(state, snap, pod, node_name, t0)
        except Exception as e:  # noqa: BLE001 — crash-only containment
            self.cache.forget(pod.uid)
            self.events.record("FailedScheduling", pod.uid,
                               message=f"binding error: {e}")
            self.queue.add_unschedulable(pod, backoff=True)
            self.metrics.inc("scheduling_attempts_error")
            return None

    def _binding_cycle(self, state, snap, pod, node_name, t0) -> Optional[str]:
        """PreBind -> Bind -> PostBind (+ extender binder precedence); failure
        forgets the assumption and requeues — schedule_one.go's bindingCycle.
        Traced as binding.cycle under the pod's context — the explicit
        pod-attached parent, NOT the contextvar, because this often runs on a
        binding-pool worker thread where no scheduling span is active."""
        if not self.tracer.enabled:
            return self._binding_cycle_inner(state, snap, pod, node_name, t0)
        with self.tracer.span_for_pod(
            pod.uid, "binding.cycle", pod=pod.uid, node=node_name
        ):
            return self._binding_cycle_inner(state, snap, pod, node_name, t0)

    def _binding_cycle_inner(self, state, snap, pod, node_name, t0) -> Optional[str]:
        fw = self._fw(pod) or self.framework
        st = fw.run_pre_bind(state, snap, pod, node_name)
        if st.ok:
            binder = next((e for e in self.extenders if e.cfg.bind_verb), None)
            if binder is not None:
                # extender binder takes precedence (extender.go — IsBinder);
                # the in-process store stands in for the apiserver the
                # extender would POST the Binding to
                err = binder.bind(pod, node_name)
                if err is None:
                    self.store.bind(pod.uid, node_name)
                    st = Status()
                else:
                    st = Status.unschedulable(f"extender bind: {err}")
            else:
                st = fw.run_bind(state, snap, pod, node_name)
        if not st.ok:
            self.cache.forget(pod.uid)
            self.queue.add_unschedulable(pod, backoff=True)
            return None
        fw.run_post_bind(state, snap, pod, node_name)
        self.queue.delete_nominated(pod.uid)
        self.events.record("Scheduled", pod.uid, node=node_name)
        self._observe_sli(pod.uid)
        dt = time.perf_counter() - t0
        self.metrics.observe("scheduling_attempt_duration_seconds", dt)
        self.metrics.inc("scheduling_attempts_scheduled")
        self.log.V(3).info("Scheduled pod", pod=pod.uid, node=node_name,
                           latency_ms=round(dt * 1e3, 2))
        return node_name

    def reject_incomplete_gangs(self) -> int:
        """Permit-timeout analog at a drain point: gangs still below quorum
        release their assumptions and requeue with backoff — the reference's
        WaitingPod.Reject fan-out (waiting_pods_map.go), and the CPU-path
        equivalent of the batch fixpoint revoking a failed group."""
        n = 0
        with self._gang_lock:
            drained = list(self._gang_waiting.items())
            self._gang_waiting.clear()
        for g, waiters in drained:
            for wpod, _wnode, _s, _sn in waiters:
                self.cache.forget(wpod.uid)
                self.events.record(
                    "FailedScheduling", wpod.uid,
                    message=f"gang {g} below quorum; Permit rejected",
                )
                self.queue.add_unschedulable(wpod, backoff=True)
                n += 1
        return n

    def wait_for_bindings(self) -> None:
        """Drain in-flight binding cycles (the reference's graceful shutdown
        waits on the binding goroutines the same way).  Also a drain point
        for the batch path's deferred commit fan-out."""
        if self._dead or chaos.killed():
            return  # a SIGKILL'd process drains nothing
        self._flush_deferred_binds()
        if self._bind_pool is None:
            return
        while True:
            with self._bind_lock:
                pending = [f for f in self._bind_futures if not f.done()]
                self._bind_futures = pending
            if not pending:
                return
            for f in pending:
                f.result()

    # --- crash-restart & failover (checkpoint.py + leases.py) ---
    def _kill_point(self, site: str) -> None:
        """An enumerated process-death site (chaos kill.* family): poke the
        injector and, when the plan kills here, mark this instance dead
        BEFORE the ProcessKilled unwinds — its finally-blocks must behave
        like a SIGKILL'd process (no flush, no drain, no checkpoint)."""
        if not chaos.enabled():
            return
        try:
            chaos.poke(site, tracer=self.tracer, metrics=self.metrics)
        except chaos.ProcessKilled:
            self._dead = True
            # black-box dump on the way down — diagnostic-only, never read
            # by restore() (flightrecorder.py documents the deviation from
            # the strict SIGKILL discipline)
            try:
                self._flight.dump(reason=site)
            except Exception:  # noqa: BLE001 — evidence must not mask the kill
                pass
            raise

    def _checkpoint_state(self) -> None:
        """Persist the crash-restart checkpoint (one fsync'd atomic file):
        assumed-pod ledger + deferred-commit WAL + SLI arrival ages — the
        state LIST+WATCH cannot reconstruct, nothing more (crash-only rule).
        Invoked from the cache hook at every assume/forget, at every WAL
        append, and at flush completion."""
        if self._ckpt is None or self._dead or chaos.killed():
            return
        from .checkpoint import save_scheduler_state

        save_scheduler_state(
            self._ckpt,
            self.cache.assumed_snapshot(),
            [(p.uid, node) for p, node in self._deferred_binds],
            self.queue.export_arrivals(),
            lineage=self.store.lineage,
            wave=self._wave_wal,
            cursor=self._replay_cursor,
            popped=self.queue.export_popped(),
        )

    def restore(self, killed_site: Optional[str] = None) -> Dict[str, int]:
        """The restart/takeover protocol: load the checkpoint, reconcile it
        against the relisted store, and leave the scheduler ready to resume
        the pipelined loop.  Designed to run on a FRESH instance (the
        constructor's watch replay already re-admitted every unbound pod
        and rebuilt the cache — the LIST half of crash-only recovery):

          1. restore arrival ages (SLI continuity — before any bind so the
             first post-restore publication observes the true wait)
          2. replay the deferred-commit WAL exactly once: an entry whose
             pod is already bound was published pre-crash (skip); an
             unbound entry's verdict was durably decided, so publish it now
             (the bind, its events and SLI land exactly once)
          3. reconcile assumed-but-unbound pods: their reservation died
             with the process and their verdict was never durably recorded
             — they stay requeued (watch replay re-admitted them with
             original arrival stamps) and reschedule deterministically
          4. force a full hoist re-fingerprint + fresh delta encoder: the
             resident device caches' identity lineage died with the old
             process (ops/incremental.py — invalidate)

        Safe (and cheap) when no checkpoint exists: pure crash-only rebuild.
        killed_site: the chaos kill.* site that felled the previous
        incarnation (from ProcessKilled.fault) — the recovery is recorded
        under that same site so per-site injected/recovered counts in the
        chaos artifact reconcile; None (organic takeover) records no chaos
        recovery.  Returns a small report dict for logs/tests."""
        t0 = time.perf_counter()
        report = {
            "wal_applied": 0, "wal_skipped": 0, "reconciled_assumed": 0,
            "restored_arrivals": 0, "restored_popped": 0, "wave_requeued": 0,
        }
        doc = None
        if self._ckpt is not None:
            from .checkpoint import load_scheduler_state

            doc = load_scheduler_state(self._ckpt)
        if doc is not None and doc["lineage"] != self.store.lineage:
            # a checkpoint written against a DIFFERENT cluster: uids are
            # deterministic (namespace/name), so replaying its WAL here
            # could bind colliding pods of an unrelated workload.  Not
            # corruption (the file is a valid checkpoint of some cluster) —
            # ignore it and rebuild crash-only.
            self.log.V(1).info(
                "Checkpoint from another cluster lineage ignored",
                checkpoint_lineage=doc["lineage"], store_lineage=self.store.lineage,
            )
            doc = None
        if doc:
            # the blackout (dead time since the last checkpoint) is real
            # wait the pods served: add it to every checkpointed age so the
            # SLI inflates honestly instead of forgiving the outage
            dead_s = (
                max(0.0, time.time() - doc["saved_wall"])
                if doc["saved_wall"] else 0.0
            )
            report["restored_arrivals"] = self.queue.restore_arrivals(
                {u: a + dead_s for u, a in doc["arrivals"].items()}
            )
            # pop stamps re-base with the same blackout shift and PIN: a
            # pod popped into a wave pre-kill keeps its original queue_wait
            # and the dead time lands in wave_wait, where it actually
            # passed (the phase-telescoping invariant survives restore)
            report["restored_popped"] = self.queue.restore_popped(
                {u: a + dead_s for u, a in doc.get("popped", {}).items()}
            )
            node_names = set(self.store.list_node_names())
            for uid, node in doc["wal"]:
                cur = self.store.pods.get(uid)
                if cur is None or node not in node_names:
                    report["wal_skipped"] += 1  # pod/node gone while dead
                    continue
                if cur.node_name:
                    report["wal_skipped"] += 1  # already applied pre-crash
                    continue
                self._publish_bind(uid, node)
                self.queue.delete(uid)  # drop the replay-admitted copy
                report["wal_applied"] += 1
            for uid, node in doc["assumed"].items():
                cur = self.store.pods.get(uid)
                if cur is not None and not cur.node_name:
                    # reservation died with the process, verdict never made
                    # it to the WAL: the pod is already requeued (watch
                    # replay) with its original arrival stamp — count it
                    report["reconciled_assumed"] += 1
            # wave WAL reconciliation: the commit wave in flight at the kill
            # splits three ways — published prefix (store shows the bind:
            # nothing to do), durable suffix (replayed by the deferred WAL
            # loop above), and the unpublished remainder, which the watch
            # replay already requeued; count it so tests can assert the
            # split is exhaustive (no pod lost, none double-published)
            wave = doc.get("wave")
            if wave:
                wal_uids = {u for u, _ in doc["wal"]}
                for uid in wave.get("uids", ()):
                    cur = self.store.pods.get(uid)
                    if cur is not None and not cur.node_name and uid not in wal_uids:
                        report["wave_requeued"] += 1
            # the dead leader's open-loop replay cursor (None outside the
            # load observatory): surfaced for the surviving replay driver —
            # the trace offset the standby resumes from (loadgen.py
            # verifies it against the generator's own position)
            self.restored_cursor = doc.get("cursor")
        # crash-only rule: resident device caches rebuild from scratch
        if self._hoist_cache is not None:
            self._hoist_cache.invalidate()
        self._delta_enc = None
        self._deferred_binds = []
        self.metrics.inc("scheduler_restarts_total")
        self._checkpoint_state()  # persist the clean post-restore slate
        dt = time.perf_counter() - t0
        if self.tracer.enabled:
            self.tracer.record_span(
                "scheduler.restore", start=t0, end=t0 + dt, **report
            )
        if killed_site is not None:
            # pair the recovery with the fault that killed the previous
            # incarnation, under the SAME site label the injection counted
            # against — per-site injected/recovered reconcile in the
            # chaos artifact (an organic takeover has no injected fault,
            # so it records nothing here)
            chaos.record_recovery(
                killed_site, "restore", tracer=self.tracer,
                metrics=self.metrics, start=t0, **report,
            )
        self.log.V(1).info("Scheduler state restored", **report)
        return report

    def detach(self) -> None:
        """Disconnect a DEAD incarnation from the store's watch fan-out —
        the restart driver's stand-in for the OS reclaiming a killed
        process's watch connections.  The instance stays inert afterwards
        (every drain/flush path early-returns on _dead)."""
        self._dead = True
        self.store.unwatch(self._on_event)
        self.store.unwatch(self.cache._on_event)

    # --- the TPU batch cycle ---
    def schedule_batch(self) -> Dict[str, Optional[str]]:
        """Drain the activeQ and schedule the whole batch in one cycle.

        Multi-profile batches group by spec.schedulerName and the (few)
        per-profile programs run back-to-back within THIS cycle (each kernel
        takes one static weight config; round 3 served one profile per cycle
        and requeued the rest, which serialized a mixed stream).  The
        round-robin lead now only decides which profile sees free capacity
        first; single-profile batches (the common case) take one program as
        before.  Gang members always ride ONE program — the PodGroup's
        first-seen member's profile — because a gang split across
        per-profile programs could never reach quorum in any of them
        (cross-profile gang livelock, round-3 advisor finding)."""
        t0 = time.perf_counter()
        if self.last_wave_phases:
            # per-cycle phase anatomy: this cycle's binds repopulate it
            self.last_wave_phases = {}
        self._sample_queue_depths()  # pre-drain: the activeQ's true depth
        batch: List[t.Pod] = self.queue.pop_all()
        if not batch:
            return {}
        with self.tracer.span("batch.cycle", pods=len(batch)):
            return self._schedule_batch_traced(batch, t0)

    def _sample_queue_depths(self) -> None:
        """Per-pool queue-depth gauges (activeQ / backoff / unschedulable /
        parked), sampled at each cycle boundary — one queue lock
        acquisition, four live gauges + four `_peak` high-water marks on
        /metrics (the reference exposes only the aggregate pending_pods;
        a retry storm and an event-starved park look identical there)."""
        for pool, v in self.queue.depths().items():
            self.metrics.set(f"queue_pool_{pool}_pods", v)
            self.metrics.set_max(f"queue_pool_{pool}_pods_peak", v)
        self._sample_device_memory()

    def _sample_device_memory(self) -> None:
        """Cycle-boundary HBM sample (memwatch.py), riding the same two
        boundary calls as the queue-depth gauges: live device stats
        (memory_stats where the backend exposes it, live arrays
        otherwise), the resident census (the delta encoder's device table
        + the hoist cache), the leak sentinel, and the device_hbm_* gauge
        family on /metrics."""
        if self._memwatch is None:
            return
        self._memwatch.cycle_sample(
            encoder=self._delta_enc, hoist=self._hoist_cache, label="cycle",
        )

    def _schedule_batch_traced(
        self, batch: List[t.Pod], t0: float
    ) -> Dict[str, Optional[str]]:
        names = [p.scheduler_name or self.default_profile_name for p in batch]
        gang_profile: Dict[str, str] = {}
        if self.features.enabled("GangScheduling"):
            # with the gate off, pod_group is inert everywhere — a pod must
            # keep its own profile, so no coalescing either
            for p, n in zip(batch, names):
                if p.pod_group and p.pod_group not in gang_profile:
                    gang_profile[p.pod_group] = n
        for k, p in enumerate(batch):
            if p.pod_group in gang_profile and names[k] != gang_profile[p.pod_group]:
                coalesced = gang_profile[p.pod_group]
                self.events.record(
                    "GangProfileCoalesced", p.uid,
                    message=(
                        f"PodGroup {p.pod_group} members span schedulerNames; "
                        f"scheduling gang under profile {coalesced!r}"
                    ),
                )
                names[k] = coalesced
        present = list(dict.fromkeys(names))  # first-appearance order
        if len(present) > 1 and self._last_profile_served in present:
            i = (present.index(self._last_profile_served) + 1) % len(present)
            present = present[i:] + present[:i]
        # the cycle's lead = the profile with first claim on capacity; the
        # NEXT cycle's lead rotates past it
        self._last_profile_served = present[0]
        result: Dict[str, Optional[str]] = {}
        n_failed = 0
        for profile_name in present:
            group = [p for p, n in zip(batch, names) if n == profile_name]
            r, nf = self._schedule_profile_batch(profile_name, group)
            result.update(r)
            n_failed += nf
        dt = time.perf_counter() - t0
        self.log.V(2).info("Batch scheduled", batch=len(batch),
                           profiles=len(present),
                           scheduled=len(batch) - n_failed,
                           unschedulable=n_failed,
                           duration_ms=round(dt * 1e3, 1))
        self.metrics.observe("batch_scheduling_duration_seconds", dt)
        self.metrics.inc("scheduling_attempts_scheduled", len(batch) - n_failed)
        self.metrics.inc("scheduling_attempts_unschedulable", n_failed)
        self.metrics.set("pending_pods", self.queue.pending_total)
        self._sample_queue_depths()  # post-commit: requeues landed
        return result

    def _schedule_profile_batch(
        self, profile_name: str, batch: List[t.Pod]
    ) -> Tuple[Dict[str, Optional[str]], int]:
        """One profile's slice of the cycle: encode → kernel (or sidecar /
        native engine) → bind → preempt-on-failure.  Returns (pod name ->
        node | None, #unschedulable).  Bindings apply to the store/cache
        synchronously, so the next profile's update_snapshot sees them."""
        from ..ops.gang import schedule_with_gangs

        snap = self.cache.update_snapshot()
        bound_uids = {p.uid for p in snap.bound_pods}
        batch_uids = {p.uid for p in batch}
        node_names = {nd.name for nd in snap.nodes}
        # reserve out-of-batch nominated pods (still in backoff after their
        # preemption) by treating them as bound to their nominated node — the
        # batched rendering of RunFilterPluginsWithNominatedPods' reservation
        # (conservative: reserves against the whole batch, not only
        # lower-priority members)
        reserved = [
            replace_pod_nodename(q, node)
            for uid, (q, node) in self.queue.nominated.items()
            if uid not in batch_uids and uid not in bound_uids and node in node_names
        ]
        snap = Snapshot(
            nodes=snap.nodes,
            pending_pods=[p for p in batch if p.uid not in bound_uids],
            bound_pods=snap.bound_pods + reserved,
            pod_groups=snap.pod_groups,
            pvs=snap.pvs,
            pvcs=snap.pvcs,
            storage_classes=snap.storage_classes,
            resource_slices=snap.resource_slices,
            device_classes=snap.device_classes,
        )
        gang = self.features.enabled("GangScheduling")
        prof = self.config.profile(profile_name)
        batch_fw = self.frameworks[profile_name]
        verdicts: Optional[Dict[str, Optional[str]]] = None  # uid -> node|None
        offload = prof.tpu_score is not None and prof.tpu_score.sidecar_address != "local"
        if offload:
            # the wire carries hardPodAffinityWeight but not arbitrary plugin
            # weights: a profile with customized score weights schedules
            # in-process (the kernels honor its ScoreConfig) rather than
            # receiving default-weight verdicts from the sidecar
            from dataclasses import replace as _dc_replace

            want_cfg = self.config.score_config(profile_name)
            if want_cfg != _dc_replace(
                type(want_cfg)(),
                hard_pod_affinity_weight=want_cfg.hard_pod_affinity_weight,
            ):
                offload = False
        if offload:
            # sidecar cycles have no async-dispatch window: publish the
            # previous cycle's deferred fan-out BEFORE any of this cycle's
            # commit work, preserving the serial loop's store/event order
            self._flush_deferred_binds()
            # offload to the gRPC sidecar; deadline/transport failure -> the
            # mandated CPU fallback (per-pod plugin path)
            from ..runtime import SidecarUnavailable, TPUScoreClient

            try:
                addr = prof.tpu_score.sidecar_address
                if self._sidecars.get(addr) is None:
                    # shares the scheduler's Metrics so the client's retry/
                    # degrade/health counters land in one scrape
                    self._sidecars[addr] = TPUScoreClient(
                        addr, metrics=self.metrics
                    )
                self._sidecar = self._sidecars[addr]
                # the RAW snapshot goes to the client: it fingerprints raw
                # node identity + storage state for its session delta, THEN
                # resolves volume/DRA constraints into plain requests +
                # affinity for the wire (which carries no PV/PVC schema)
                verdicts = self._sidecar.schedule(
                    snap,
                    deadline_ms=prof.tpu_score.deadline_ms,
                    gang=gang,
                    hard_pod_affinity_weight=prof.hard_pod_affinity_weight,
                )
            except SidecarUnavailable:
                self.metrics.inc("tpuscore_fallback_total")
                result = {}
                for pod in snap.pending_pods:
                    result[pod.name] = self.schedule_one(pod)
                # the fallback is a drain point: gangs still short of quorum
                # reject here (Permit timeout analog), preserving the batch
                # path's all-or-nothing outcome
                self.wait_for_bindings()
                self.reject_incomplete_gangs()
                # async binding cycles and gang waits resolve after the loop:
                # report the SETTLED placements, not the optimistic returns
                n_unbound = 0
                for pod in snap.pending_pods:
                    cur = self.store.pods.get(pod.uid)
                    result[pod.name] = (cur.node_name or None) if cur else None
                    n_unbound += result[pod.name] is None
                return result, n_unbound
        arr = meta = None  # encoded cycle arrays (batched preemption reuses them)
        # does this cycle's kernel path dispatch asynchronously?  Deferring
        # the fan-out is only worth anything when the next cycle (same
        # stream, usually the same branch) will have a device window to
        # hide it under — sidecar/native/gang cycles flush synchronously,
        # so deferring there just delays publication for zero overlap
        async_window = False
        if verdicts is None:
            base_cfg = self.config.score_config(profile_name)
            if (
                self._delta_enc is None
                or self._delta_enc.hpaw != base_cfg.hard_pod_affinity_weight
            ):
                from ..api.delta import DeltaEncoder

                self._delta_enc = DeltaEncoder(
                    hard_pod_affinity_weight=base_cfg.hard_pod_affinity_weight
                )
            # (arr stays host numpy below — with a mesh, the routed jit
            # transfers each cycle's fresh buffers directly into the
            # shard-wise layout per the kernel's in_specs; the resident
            # DEVICE placement path is the pipeline loop's encoder)
            with self.tracer.span("batch.encode", profile=profile_name):
                arr, meta = self._delta_enc.encode(snap)
            if chaos.enabled():
                # slow-host stall inside the encode window: latency only,
                # decisions and commit order must be unaffected
                chaos.poke("host.stall", tracer=self.tracer,
                           metrics=self.metrics)
            cfg = infer_score_config(arr, base_cfg)
            # resident incremental class-hoist state (ops/incremental.py;
            # never donated).  Serves the gang fixpoint too — revocations
            # only mask pod_valid, which the resident state excludes.  The
            # native engine stays dense; recovery replays stay dense by
            # design (cache-independent serial oracle).
            inc = None
            if self.config.mode != "native":
                from ..ops.assign import inc_route_applies

                if inc_route_applies(arr, cfg):
                    if self._hoist_cache is None:
                        from ..ops.incremental import HoistCache

                        self._hoist_cache = HoistCache(
                            mesh=self.mesh, tracer=self.tracer
                        )
                    inc = self._hoist_cache.ensure(arr, meta, cfg)
            ords = sweeps = None
            from .tracing import incremental_attrs, mesh_attrs

            with self.tracer.span(
                "batch.kernel", profile=profile_name, mode=self.config.mode,
                **mesh_attrs(self.mesh),
                **(incremental_attrs(self._hoist_cache) if inc is not None
                   else {}),
            ):
                t_k0 = time.perf_counter()
                if self.config.mode == "native":
                    from ..native import schedule_batch_native, schedule_with_gangs_native

                    # synchronous C++ engine: no async window — commit the
                    # previous cycle's deferred fan-out before it runs
                    self._flush_deferred_binds()
                    fn = schedule_with_gangs_native if gang else schedule_batch_native
                    choices = fn(arr, cfg)[0]
                    if not gang:
                        # the C++ engine commits strictly in pod order: the
                        # ordinal IS the index, and every pod is one sweep
                        ords = np.arange(meta.n_pods, dtype=np.int64)
                        sweeps = meta.n_pods
                elif gang:
                    # the gang fixpoint re-reads its input arrays across
                    # iterations, so it neither donates nor exposes a clean
                    # single-dispatch window — flush first
                    self._flush_deferred_binds()
                    try:
                        fault = (
                            chaos.poke("scheduler.step", tracer=self.tracer,
                                       metrics=self.metrics)
                            if chaos.enabled() else None
                        )
                        choices, _, ords, sweeps = schedule_with_gangs(
                            arr, cfg, with_ordinals=True, mesh=self.mesh,
                            inc=inc,
                        )
                        # kill.mid_step: process death with the fixpoint's
                        # device wave still in flight (the gang path never
                        # donates, but the step is just as unfetched) — a
                        # BaseException the wave-recovery except below can
                        # NOT catch; only a restart recovers
                        self._kill_point("kill.mid_step")
                        choices = np.asarray(choices)
                        if fault is not None and fault.action == "nan":
                            choices = chaos.poison(choices)
                        if chaos.poisoned_verdicts(
                            choices, len(meta.node_names)
                        ):
                            raise chaos.PoisonedWave(profile_name)
                    except Exception as e:  # noqa: BLE001 — wave recovery
                        choices, ords, sweeps = self._recover_batch_step(
                            arr, cfg, meta, e, gang=True
                        )
                else:
                    from ..ops.assign import (
                        donation_supported,
                        schedule_batch_ordinals_routed,
                    )

                    # async dispatch; `arr` is host numpy, so the jit call
                    # transfers fresh per-cycle device buffers — donation
                    # (where the backend honors it) hands those to XLA and
                    # can never poison a resident buffer (the host copy,
                    # which batched preemption reuses, stays valid)
                    try:
                        fault = (
                            chaos.poke("scheduler.step", tracer=self.tracer,
                                       metrics=self.metrics)
                            if chaos.enabled() else None
                        )
                        choices, _, ords, sweeps = (
                            schedule_batch_ordinals_routed(
                                arr, cfg, donate=donation_supported(),
                                mesh=self.mesh, inc=inc,
                            )
                        )
                        # kill.mid_step: process death with the device step
                        # (and any donated buffers) still in flight — a
                        # BaseException, so the wave-recovery except below
                        # can NOT catch it; only a restart recovers
                        self._kill_point("kill.mid_step")
                        # step i runs on device: the deferred bind/events
                        # fan-out of step i−1 executes NOW, inside the device
                        # window — the commit_overlap half of the pipeline
                        self._flush_deferred_binds()
                        choices = np.asarray(choices)
                        if fault is not None and fault.action == "nan":
                            choices = chaos.poison(choices)
                        if chaos.poisoned_verdicts(
                            choices, len(meta.node_names)
                        ):
                            raise chaos.PoisonedWave(profile_name)
                    except Exception as e:  # noqa: BLE001 — wave recovery
                        choices, ords, sweeps = self._recover_batch_step(
                            arr, cfg, meta, e
                        )
                    # only this branch has the async window the NEXT
                    # cycle's deferred fan-out would hide under; a
                    # same-profile stream keeps taking it
                    async_window = True
            uid_of = {p.name: p.uid for p in snap.pending_pods}
            if ords is not None:
                self._observe_wave_latency(
                    np.asarray(ords)[: meta.n_pods],
                    time.perf_counter() - t_k0,
                    int(sweeps),
                    # cheap-gate contract: the O(P) uids build only runs
                    # when tracing is on (its sole consumer is gated too)
                    uids=([uid_of[meta.pod_names[k]]
                           for k in range(meta.n_pods)]
                          if self.tracer.enabled else None),
                )
                if self.tracer.enabled and self.last_wave_estimates:
                    # phase-decomposition marks: the kernel dispatch instant
                    # and the pod's decision-ready instant (dispatch +
                    # commit-ordinal estimate).  Consumed at bind
                    # publication (_observe_sli_phases); a deferred bind
                    # keeps its marks until the flush, so `bind` honestly
                    # includes the deferral window.  A failed pod's marks
                    # are dropped in the commit loop; a retry re-stamps.
                    for uid, est in self.last_wave_estimates.items():
                        self._phase_marks[uid] = (t_k0, t_k0 + est)
            verdicts = {
                uid_of[meta.pod_names[k]]: (
                    meta.node_names[int(choices[k])] if int(choices[k]) >= 0 else None
                )
                for k in range(meta.n_pods)
            }
        result: Dict[str, Optional[str]] = {}
        failed: List[t.Pod] = []
        # Deferred-commit gate: capacity is reserved through cache.assume
        # synchronously either way (update_snapshot treats assumed pods as
        # bound), so the store/events/queue fan-out may lag into the NEXT
        # cycle's device window without changing any encode — PROVIDED the
        # fan-out's move events could wake nobody (no parked pods) and the
        # pod needs no volume commitment (bind_pod_volumes mutates storage
        # state the next encode fingerprints).  Anything else commits
        # synchronously, bit-for-bit the old loop.
        defer_ok = (
            self._pipeline_commit
            and self._cycle_streaming
            and async_window
            and self.queue.parked_total == 0
        )
        # bind fan-out + the preemption failure loop = the cycle's commit
        # step.  assumed_now tracks this cycle's reservations so a crash
        # mid-commit releases them (crash-only containment: a leaked assume
        # is phantom capacity every later encode would subtract forever)
        assumed_now: List[str] = []
        done: set = set()  # pod names whose commit disposition fully landed
        # wave WAL (streaming crash-consistency): before the first assume of
        # this commit wave, record its membership + verdict crc in the
        # checkpoint, so a kill anywhere inside the wave leaves restore()
        # enough to reconcile exactly-once publication — the published
        # prefix shows in the store, the durable suffix in the deferred
        # WAL, and the rest of these uids are the requeued remainder.
        # Built only when a checkpoint is armed (the crc is an O(P) pass);
        # cleared + re-persisted once the wave fully lands.
        if self._ckpt is not None:
            from .flightrecorder import fingerprint

            placed = {u: n for u, n in verdicts.items() if n is not None}
            self._wave_wal = {
                "uids": sorted(placed),
                "verdict_crc": fingerprint(
                    {u: placed[u] for u in sorted(placed)}
                ),
            }
        try:
            self._commit_profile_batch(
                profile_name, snap, verdicts, result, failed, defer_ok,
                assumed_now, done, arr, meta, batch_fw,
            )
        except Exception:
            self._release_crashed_commit(snap, done, assumed_now)
            raise
        finally:
            if self._wave_wal is not None:
                self._wave_wal = None
                # persist the cleared wave record; on a kill this is a no-op
                # (_checkpoint_state early-returns on killed()) so the wave
                # stays durable for the restore to reconcile
                self._checkpoint_state()
        self._flight_record(profile_name, snap, result, len(failed), meta)
        return result, len(failed)

    def _diagnose_failed(self, snap, result, arr, meta, failed) -> Dict[str, str]:
        """Device-path unschedulable diagnosis (ops/explain.py): one
        O(U_f·N) kernel evaluation over the FAILED equivalence classes,
        decoded through the class index to upstream-shaped per-pod messages
        against POST-CYCLE usage — cycle-start node_used plus the requests
        this cycle's commits placed (`result`), i.e. what the operator sees
        and the retry will face.  The per-class records land in the flight
        recorder; pod_unschedulable_reasons_total{reason} counts each failed
        pod under its dominant reason."""
        from ..ops.explain import diagnose_failed

        t0 = time.perf_counter()
        row_of = {name: k for k, name in enumerate(meta.pod_names)}
        node_row = {name: j for j, name in enumerate(meta.node_names)}
        used = np.array(arr.node_used, copy=True)
        for p in snap.pending_pods:
            k = row_of.get(p.name)
            j = node_row.get(result.get(p.name) or "")
            if k is not None and j is not None:
                used[j] += arr.pod_req[k]
        rows = [row_of[p.name] for p in failed if p.name in row_of]
        messages, dominant, records = diagnose_failed(arr, meta, rows, used)
        self._last_diagnosis = records
        msgs: Dict[str, str] = {}
        for p in failed:
            r = row_of.get(p.name)
            if r in messages:
                msgs[p.uid] = messages[r]
                self.metrics.inc_labeled(
                    "pod_unschedulable_reasons_total", reason=dominant[r]
                )
        dt = time.perf_counter() - t0
        self.metrics.observe("scheduling_explain_duration_seconds", dt)
        if self.tracer.enabled:
            self.tracer.record_span(
                "batch.explain", start=t0, end=t0 + dt,
                failed=len(failed), classes=len(records),
            )
        return msgs

    def _flight_record(self, profile_name, snap, result, n_failed, meta) -> None:
        """One compact decision record per profile batch for the flight
        recorder's ring — fingerprints, not payloads (a 50k-pod wave is a
        few hundred bytes here).  Armed by the checkpoint dir: without one
        nothing can ever dump the ring, so the warm cycle skips even the
        O(P) fingerprint passes."""
        if not self._flight.directory:
            return
        from .flightrecorder import fingerprint
        from .tracing import current_trace_id

        rec = {
            "ts": time.time(),
            "profile": profile_name,
            "mode": self.config.mode,
            "pods": len(snap.pending_pods),
            "scheduled": sum(1 for v in result.values() if v),
            "failed": n_failed,
            "verdict_crc": fingerprint(result),
            "trace_id": current_trace_id(),
        }
        if meta is not None:
            rec["classes"] = meta.n_classes
            if meta.pod_class is not None:
                rec["class_crc"] = fingerprint(meta.pod_class)
            rec["dirty_cols"] = (
                int(meta.dirty_nodes.size) if meta.dirty_nodes is not None
                else -1
            )
        if self._last_diagnosis:
            rec["diagnosis"] = self._last_diagnosis
        if self.last_wave_phases:
            # latency anatomy of the pods bound this cycle (tracer-gated,
            # like the vectors themselves): per-phase mean/max across the
            # wave plus the worst pod's full phase vector — a post-kill
            # dump answers "where were the in-flight pods spending time"
            rec["sli_phases"] = _sli_phase_block(self.last_wave_phases)
        if self._memwatch is not None:
            # the compact HBM block (memwatch.py — in-use/peak/resident/
            # unaccounted): a post-mortem reading the dump can answer
            # "were we near the device-memory ceiling when it died"
            rec["mem"] = self._memwatch.memory_block()
        self._flight.record(**rec)

    def _commit_profile_batch(
        self, profile_name, snap, verdicts, result, failed, defer_ok,
        assumed_now, done, arr, meta, batch_fw,
    ) -> None:
        with self.tracer.span("batch.commit", profile=profile_name), \
                self._coalesced_moves():
            for pod in snap.pending_pods:
                node_name = verdicts.get(pod.uid)
                if node_name and pod.pvcs:
                    # PreBind volume commitment (static match / provisioning);
                    # failure sends the pod down the ordinary retry path
                    from .volumebinder import bind_pod_volumes

                    err = bind_pod_volumes(self.store, pod, node_name)
                    if err is not None:
                        node_name = None
                if node_name:
                    # assume reserves capacity AND checkpoints the ledger
                    # (cache hook); kill.post_assume fires inside, between
                    # the in-memory reservation and its durable save
                    self.cache.assume(pod.uid, node_name)
                    assumed_now.append(pod.uid)
                    # kill.post_checkpoint: ledger durable, bind unpublished
                    # — restart must requeue (verdict not in the WAL yet)
                    self._kill_point("kill.post_checkpoint")
                    if defer_ok and not pod.pvcs:
                        self._deferred_binds.append((pod, node_name))
                        # WAL append-before-publication-window: a restart
                        # replays this verdict exactly once (restore())
                        self._checkpoint_state()
                        result[pod.name] = node_name
                        done.add(pod.name)
                        continue
                    self._publish_bind(pod.uid, node_name)
                    result[pod.name] = node_name
                    done.add(pod.name)
                else:
                    failed.append(pod)
                    result[pod.name] = None
                    # a failed pod's retry wave re-stamps fresh marks; keep
                    # the table bounded by pods awaiting publication
                    self._phase_marks.pop(pod.uid, None)
            if failed:
                # the preemption loop below reads AND mutates the store
                # (victim evictions); its view must match the serial loop's,
                # so the deferred fan-out lands first
                self._flush_deferred_binds()
            # on-demand unschedulable diagnosis (ops/explain.py —
            # KTPU_EXPLAIN=1): per-failed-class reason counts decoded to
            # upstream-shaped FailedScheduling messages.  Strictly off the
            # warm step: only failing cycles pay, and only for U_f classes.
            diag_msgs: Dict[str, str] = {}
            self._last_diagnosis = []
            if failed and arr is not None:
                from ..ops.explain import explain_enabled

                if explain_enabled():
                    diag_msgs = self._diagnose_failed(
                        snap, result, arr, meta, failed
                    )
            # failure path: preemption through the CPU PostFilter, then requeue.
            # Three lazily-maintained pieces, each invalidated only by what
            # actually stales it:
            #   snap2          fresh resolved snapshot + bound-priority counts
            #                  (None = rebuild); batched evictions update the
            #                  counts INCREMENTALLY instead of re-resolving
            #   state          the CPU PostFilter's what-if ScaledState — built
            #                  only when a pod actually takes the CPU branch
            #                  (node_infos + ScaledState are full-cluster scans:
            #                  ~40 ms/rebuild at 2k nodes, and the batched path
            #                  never reads them)
            #   batched        ops/preempt.py evaluator with its own
            #                  incremental ledger; dropped only when a CPU-path
            #                  eviction happens outside that ledger
            from collections import Counter

            state = None
            snap2 = None
            # snap2 freshness: True while snap2 exactly reflects the store
            # (no eviction since it was resolved).  Batched evictions flow
            # through the store only, so they DIRTY snap2 rather than
            # rebuilding it eagerly; the CPU what-if branch then re-resolves
            # only when actually stale instead of on every entry (ADVICE
            # r5: the unconditional re-resolve was a full-cluster scan per
            # entry with zero intervening evictions)
            snap2_fresh = False
            batched = None  # ops/preempt.py evaluator, shared across the loop
            use_batched = (
                arr is not None
                and self.features.enabled("BatchedPreemption")
                and self.features.enabled("DefaultPreemption")
            )
            min_bound_prio: Optional[int] = None
            bound_prios: Counter = Counter()
            for pod_i, pod in enumerate(failed):
                if snap2 is None:
                    from ..api.volumes import resolve_snapshot

                    snap2 = resolve_snapshot(self.cache.update_snapshot())
                    snap2_fresh = True
                    state = None  # what-if state pinned to the old snapshot
                    bound_prios = Counter(
                        q.priority for q in snap2.bound_pods
                    )
                    min_bound_prio = (
                        min(bound_prios) if bound_prios else None
                    )
                    if use_batched and batched is None:
                        from .preemption import BatchedPreemption

                        batched = BatchedPreemption(
                            arr, meta, snap2, self.store, self.queue
                        )
                        # evaluate-many: the rest of this failure loop is
                        # known now — batch the gate-passing preemptors
                        # into [K, N] device waves instead of one program
                        # per pod (preemption.py — prefetch/evaluate).
                        # Only the unprocessed suffix: a mid-loop rebuild
                        # (after a CPU-path eviction) must not refill wave
                        # slots with already-evaluated pods.
                        if min_bound_prio is not None:
                            batched.prefetch([
                                q for q in failed[pod_i:]
                                if q.priority > min_bound_prio
                            ])
                self.events.record("FailedScheduling", pod.uid,
                                   message=diag_msgs.get(pod.uid, ""))
                if min_bound_prio is None or pod.priority <= min_bound_prio:
                    if batched is not None:
                        batched.note_nomination_cleared(pod)
                    self._clear_nomination(pod)
                elif batched is not None and batched.applicable(pod):
                    # device-vectorized victim search (decision-identical to
                    # the CPU evaluator within its gate — see preemption.py)
                    res = batched.evaluate(pod)
                    if res is not None:
                        node_name, victims = res
                        for q in victims:
                            self.store.delete_pod(q.uid)
                            bound_prios[q.priority] -= 1
                            if bound_prios[q.priority] <= 0:
                                del bound_prios[q.priority]
                        min_bound_prio = (
                            min(bound_prios) if bound_prios else None
                        )
                        self.metrics.inc("preemption_victims", len(victims))
                        batched.apply_eviction(node_name, victims)
                        self.events.record("Preempted", pod.uid, node=node_name)
                        # a nomination carried from a prior cycle moves OFF
                        # its old node here — that node's reservation changes
                        # for later wave members too
                        batched.note_nomination_cleared(pod)
                        self._nominate(pod, node_name)
                        state = None  # CPU what-if (if built) is stale now
                        snap2_fresh = False  # eviction went through the store
                    else:
                        batched.note_nomination_cleared(pod)
                        self._clear_nomination(pod)
                else:
                    if state is None:
                        # lazy CPU what-if: only pods outside the batched
                        # gate pay for it (resolve + node_infos + ScaledState
                        # are full-cluster scans).  snap2 is reused VERBATIM
                        # while fresh; only an eviction since it was resolved
                        # (batched evictions reach it through the store
                        # alone) forces the re-resolve.
                        if not snap2_fresh:
                            from ..api.volumes import resolve_snapshot

                            snap2 = resolve_snapshot(
                                self.cache.update_snapshot()
                            )
                            snap2_fresh = True
                        infos = self.cache.node_infos(snap2)
                        state = CycleState()
                        state.data["scaled"] = ScaledState(snap2, infos)
                    nominated, pst = batch_fw.run_post_filters(state, snap2, pod, {})
                    if pst.ok and nominated:
                        self.events.record("Preempted", pod.uid, node=nominated)
                        self._nominate(pod, nominated)
                        state = None  # evictions changed the cluster: rebuild lazily
                        snap2 = None
                        if batched is not None:
                            batched = None  # CPU path evicted outside our ledger
                    else:
                        if batched is not None:
                            batched.note_nomination_cleared(pod)
                        self._clear_nomination(pod)
                self.queue.add_unschedulable(pod, backoff=True)
                done.add(pod.name)

    def _release_crashed_commit(
        self, snap, done: set, assumed_now: List[str]
    ) -> None:
        """A crash mid-commit must not leak: publish the already-deferred
        binds (they were assumed AND recorded — the committed prefix stays
        serial-equivalent), release every other assumption this cycle made
        (no phantom capacity), and requeue the pods whose disposition never
        landed (`done` = bound, deferred, or parked-with-backoff — anything
        else was left mid-air by the crash) so a surviving caller retries
        them.  The exception itself re-raises — crash-only containment,
        not swallowing."""
        t0 = time.perf_counter()
        try:
            self._flush_deferred_binds()
        except Exception:  # noqa: BLE001 — flush keeps its tail deferred
            pass  # the retained binds hold their assumes; a later drain retries
        deferred_uids = {p.uid for p, _ in self._deferred_binds}
        released = 0
        for uid in assumed_now:
            if uid in deferred_uids:
                continue  # still slated to bind: the reservation must hold
            cur = self.store.pods.get(uid)
            if cur is None or not cur.node_name:
                self.cache.forget(uid)
                released += 1
        requeued = 0
        for pod in snap.pending_pods:
            if pod.name in done or pod.uid in deferred_uids:
                continue
            cur = self.store.pods.get(pod.uid)
            if cur is not None and not cur.node_name:
                self.queue.add(pod)
                requeued += 1
        self.metrics.inc("scheduling_attempts_error")
        self.log.V(1).info("Batch commit crashed; released assumptions",
                           released=released, requeued=requeued)
        chaos.record_recovery(
            "scheduler.commit", "assume_release", tracer=self.tracer,
            metrics=self.metrics, start=t0, released=released,
            requeued=requeued,
        )

    def _recover_batch_step(self, arr, cfg, meta, err: BaseException,
                            gang: bool = False):
        """Serial-oracle replay of a batch wave that died on device (XLA
        runtime error or poisoned readback).  `arr` is host numpy — the
        non-donated source of truth; any donated per-call device buffers
        died with the wave.  The deferred fan-out of the PREVIOUS cycle
        flushes first (its store/event order must match the serial loop),
        then the same kernel re-runs synchronously without donation: the
        encoder and kernel are deterministic, so the replay's verdicts are
        bit-identical to the wave the fault killed — the chaos parity
        invariant (tests/test_chaos.py)."""
        t0 = time.perf_counter()
        self._flush_deferred_binds()
        if gang:
            from ..ops.gang import schedule_with_gangs

            choices, _, ords, sweeps = schedule_with_gangs(
                arr, cfg, with_ordinals=True, mesh=self.mesh
            )
        else:
            from ..ops.assign import schedule_batch_ordinals_routed

            choices, _, ords, sweeps = schedule_batch_ordinals_routed(
                arr, cfg, donate=False, mesh=self.mesh
            )
        choices = np.asarray(choices)
        if chaos.poisoned_verdicts(choices, len(meta.node_names)):
            raise chaos.PoisonedWave(
                "serial replay still poisoned — not a transient fault"
            ) from err
        self.metrics.inc("scheduling_wave_recoveries_total")
        self.log.V(1).info("Batch wave recovered by serial replay",
                           error=type(err).__name__)
        chaos.record_recovery(
            "scheduler.step", "serial_replay", tracer=self.tracer,
            metrics=self.metrics, start=t0, error=type(err).__name__,
        )
        # a wave that needed serial replay is parity evidence: dump the
        # decision ring next to the checkpoint so the miss ships with its
        # history (flightrecorder.py)
        self._flight.dump(reason=f"wave_recovery:{type(err).__name__}")
        return choices, ords, sweeps

    def _flush_deferred_binds(self) -> None:
        """Commit the deferred bind/events/queue fan-out of the previous
        batch cycle.  Runs inside the NEXT cycle's device-step window (the
        commit_overlap of the pipelined loop) or at a drain point — always
        before anything that reads bind-visible state the serial loop would
        have seen (preemption, run_until_idle exit, CPU fallback).

        Serial equivalence: every deferred pod was cache.assume()d at
        verdict time, so snapshots/encodes already counted it as bound; the
        deferral only moves the store publication, its watch fan-out (a
        no-op move — the gate required zero parked pods) and the Scheduled
        event later in wall time, never across an observable read."""
        if not self._deferred_binds or self._dead or chaos.killed():
            return  # nothing deferred, or a dead process publishes nothing
        binds, self._deferred_binds = self._deferred_binds, []
        t0 = time.perf_counter()
        k = 0
        try:
            with self._coalesced_moves():
                for k, (pod, node_name) in enumerate(binds):
                    # kill.mid_flush: process death part-way through the
                    # deferred fan-out — the published prefix survives in
                    # the store, the tail survives in the WAL; restore()
                    # replays exactly the unpublished suffix
                    self._kill_point("kill.mid_flush")
                    cur = self.store.pods.get(pod.uid)
                    if cur is None:
                        # deleted (or preempted) while deferred: the capacity
                        # reservation died with the Deleted event; never
                        # resurrect the pod as bound
                        self.cache.forget(pod.uid)
                        self._phase_marks.pop(pod.uid, None)
                        continue
                    if cur.node_name == node_name:
                        continue  # already published (a crashed flush retried)
                    self._publish_bind(pod.uid, node_name)
        except Exception:
            # publish crashed mid-fan-out: keep the failed bind and the
            # unprocessed tail deferred (their assumes stay held) so a later
            # flush or drain retries them — dropping them here would leak
            # the assumed capacity forever and lose the binds
            self._deferred_binds = binds[k:] + self._deferred_binds
            raise
        # flush complete: the WAL drains with it (exactly-once rule — a
        # later restart must not replay what the store already shows)
        self._checkpoint_state()
        dt = time.perf_counter() - t0
        self.metrics.observe("pipeline_deferred_commit_seconds", dt)
        if self.tracer.enabled:
            self.tracer.record_span(
                "commit_overlap", start=t0, end=t0 + dt, pods=len(binds),
            )

    def _publish_bind(self, pod_uid: str, node_name: str) -> None:
        """The bind publication fan-out, shared VERBATIM by the synchronous
        commit loop and the deferred flush (the two must never diverge):
        store bind + per-pod bind span + nomination cleanup + Scheduled
        event."""
        t_b0 = time.perf_counter()
        self.store.bind(pod_uid, node_name)
        if self.tracer.enabled:
            # instant per-pod bind mark on the pod's own trace chain (the
            # batch verdict crossing back to ONE pod)
            self.tracer.record_span(
                "bind", start=t_b0, pod_uid=pod_uid,
                pod=pod_uid, node=node_name,
            )
        self.queue.delete_nominated(pod_uid)
        self.events.record("Scheduled", pod_uid, node=node_name)
        self._observe_sli(pod_uid)

    def _observe_sli(self, pod_uid: str) -> None:
        """Record the pod's TRUE arrival -> bind latency (the headline SLI)
        at the instant its bind became durable — the synchronous commit
        loop, the deferred flush and the CPU binding cycle all call this at
        their publication point, so a deferred pod's SLI honestly includes
        the deferral."""
        arrived = self.queue.take_arrival(pod_uid)
        if arrived is None:
            self._phase_marks.pop(pod_uid, None)
            return  # bound outside the queue's lifecycle (direct store bind)
        now = time.perf_counter()
        sli = now - arrived
        self._sli_hist.observe(sli)
        if not self.tracer.enabled:
            return
        self._observe_sli_phases(pod_uid, arrived, now, sli)
        if pod_uid in self.last_wave_estimates:
            # per-wave introspection, scoped to the pods of the CURRENT
            # batch-kernel wave (the only producer of estimates): gating on
            # membership keeps the dict bounded by wave size on every bind
            # path — the CPU binding cycle and other non-batch paths never
            # populate estimates, so they never accumulate entries here
            self.last_wave_sli[pod_uid] = sli

    def _observe_sli_phases(
        self, pod_uid: str, arrived: float, now: float, sli: float
    ) -> None:
        """Decompose one pod's SLI into the four adjacent phase windows
        (metrics.py — SLI_PHASES) from the span machinery's instants: the
        queue's pop stamp and this wave's kernel marks.  The instants are
        clamped to a monotone chain arrived <= popped <= k0 <= ready <= now,
        so the phases telescope to EXACTLY the SLI sample — the attribution
        report's shares are exhaustive by construction.  Paths without
        kernel marks (CPU binding cycle, restore replays) collapse
        wave_wait/device_kernel to zero and attribute the remainder to
        queue_wait + bind."""
        marks = self._phase_marks.pop(pod_uid, None)
        popped = self.queue.take_popped(pod_uid)
        if popped is None:
            popped = arrived
        popped = min(max(arrived, popped), now)
        k0, ready = marks if marks is not None else (popped, popped)
        k0 = min(max(popped, k0), now)
        ready = min(max(k0, ready), now)
        phases = {
            "queue_wait": popped - arrived,
            "wave_wait": k0 - popped,
            "device_kernel": ready - k0,
            "bind": now - ready,
        }
        for ph, v in phases.items():
            self._phase_hists[ph].observe(v)
        if marks is not None:
            # batch-wave pods only: the flight recorder's per-cycle latency
            # anatomy (cleared at each batch-cycle start, so bounded)
            self.last_wave_phases[pod_uid] = phases
        # bounded worst-K exemplar heap (--sli-attribution): min-heap on
        # sli keeps the K worst; seq breaks ties so dicts never compare
        entry = (sli, next(self._sli_worst_seq), pod_uid, phases)
        with self._sli_worst_lock:
            if len(self._sli_worst) < self._sli_worst_k:
                heapq.heappush(self._sli_worst, entry)
            elif sli > self._sli_worst[0][0]:
                heapq.heapreplace(self._sli_worst, entry)

    def worst_sli_pods(self) -> List[dict]:
        """The K worst bound pods by true SLI (KTPU_OPEN_LOOP_EXEMPLARS,
        default 5), worst first, each with its phase vector — the
        --sli-attribution report's exemplar set (bench/loadgen.py exports
        their full span timelines as a Perfetto trace)."""
        return [
            {
                "pod": uid,
                "sli_ms": round(s * 1e3, 3),
                "phases_ms": {ph: round(v * 1e3, 3)
                              for ph, v in phases.items()},
            }
            for s, _, uid, phases in sorted(self._sli_worst, reverse=True)
        ]

    def _observe_wave_latency(
        self, ordinals: np.ndarray, t_kernel: float, sweeps: int,
        uids: Optional[List[str]] = None,
    ) -> None:
        """Per-pod estimated scheduling latency within one batch wave.

        The kernels report each pod's COMMIT ORDINAL — the index of the
        sequential device sweep (scan step / chunked round) that decided it
        — and the TOTAL sweep count including pod-axis padding (the bucket
        pad sweeps cost wall time too; normalizing by the max REAL ordinal
        would misattribute their share to the tail and jump across bucket
        boundaries).  Sweeps are near-uniform in cost, so pod i's decision
        became available ~(ordinal+1)/sweeps of the way through the kernel
        wall; that estimate is what turns batch mode's single wall time
        into a real p50/p90/p99 distribution (BASELINE.md's per-pod
        latency metric; the wave's encode/bind overheads are amortized
        constants and excluded — this measures scheduling decision
        latency)."""
        if ordinals.size == 0 or sweeps <= 0:
            return
        est = (ordinals.astype(np.float64) + 1.0) * (t_kernel / float(sweeps))
        self.metrics.observe_many(
            "scheduling_attempt_duration_estimate_seconds", est
        )
        if uids is not None and self.tracer.enabled:
            # per-pod introspection for the SLI-consistency check: the
            # kernel's ordinal estimate must order/bound like the true
            # host-measured SLI (tests/test_observability.py).  Both dicts
            # are PER-WAVE — last_wave_sli is cleared here (its entries for
            # this wave land later, at bind publication) so a long-lived
            # traced scheduler never accumulates per-pod state unboundedly.
            self.last_wave_estimates = dict(zip(uids, est.tolist()))
            self.last_wave_sli = {}

    def _nominate(self, pod: t.Pod, node_name: str) -> None:
        """Record the nomination (queue nominator) and publish it on the pod's
        status (the reference's PATCH of status.nominatedNodeName)."""
        import copy

        q = copy.copy(pod)
        q.nominated_node_name = node_name
        self.queue.add_nominated(q, node_name)
        if pod.uid in self.store.pods:
            self.store.update_pod_status(q)

    def _clear_nomination(self, pod: t.Pod) -> None:
        """clearNominatedNode: a failed retry that produced no fresh nomination
        must not leave a phantom reservation blocking the node."""
        import copy

        self.queue.delete_nominated(pod.uid)
        cur = self.store.pods.get(pod.uid)
        if cur is not None and cur.nominated_node_name:
            q = copy.copy(cur)
            q.nominated_node_name = ""
            self.store.update_pod_status(q)

    # --- driver ---
    def run_until_idle(self, max_cycles: Optional[int] = None,
                       stall_limit: int = 1000) -> None:
        """Schedule until the activeQ drains to a fixpoint (backoff and
        unschedulable pods wait for their clock/events — the test harness
        advances a FakeClock).

        With max_cycles=None (the default) this drains completely and raises
        RuntimeError if stall_limit consecutive cycles make no scheduling
        progress while the queue stays non-empty (event ping-pong livelock)
        — it never truncates silently.  An explicit max_cycles bounds the
        work and returns possibly-non-idle (soak tests drive incremental
        cycles this way on purpose)."""
        self._cycle_streaming = True  # deferred commits may span cycles here
        try:
            self._run_until_idle_loop(max_cycles, stall_limit)
        finally:
            self._cycle_streaming = False
            self._flush_deferred_binds()

    def _run_until_idle_loop(self, max_cycles, stall_limit) -> None:
        cycles = 0
        stall = 0
        while max_cycles is None or cycles < max_cycles:
            cycles += 1
            # progress = a pod bound, or the activeQ net-shrank (a popped pod
            # parked in backoff/unschedulable is normal quiescing, not
            # livelock — only an event source that immediately re-activates
            # failing pods keeps the length flat)
            q_before = len(self.queue)
            if self.config.mode in ("tpu", "native"):
                result = self.schedule_batch()
                scheduled = any(v is not None for v in result.values())
                if not result:
                    self.wait_for_bindings()  # sidecar-fallback cycles
                    if not len(self.queue):
                        return
            else:
                pod = self.queue.pop()
                if pod is None:
                    # a failed async bind may requeue a pod after the drain
                    self.wait_for_bindings()
                    pod = self.queue.pop()
                    if pod is None:
                        # quiescence = the Permit-timeout drain point: gangs
                        # still below quorum reject (members requeue w/backoff)
                        self.reject_incomplete_gangs()
                        return
                scheduled = self.schedule_one(pod) is not None
            stall = 0 if scheduled or len(self.queue) < q_before else stall + 1
            if max_cycles is None and stall >= stall_limit:
                self.wait_for_bindings()
                raise RuntimeError(
                    f"run_until_idle: no scheduling progress after {stall} "
                    f"consecutive cycles with {self.queue.pending_total} pods "
                    "still pending (non-quiescent workload)"
                )
        self.wait_for_bindings()


def reincarnate(dead: Scheduler) -> Scheduler:
    """Build (but do NOT restore) the replacement incarnation on a dead
    scheduler's store: same config / checkpoint dir, sharing the collector
    and Metrics so spans and the SLI span the restart like an external
    observer would see them.  The constructor's watch replay re-admits every
    unbound pod (the LIST half of crash-only recovery); the caller — either
    restart_scheduler or an HAReplica takeover — runs restore()."""
    sched = Scheduler(
        dead.store,
        dead.config,
        # the backoff clock is CLUSTER time, not process memory: a replay
        # driving a FakeClock (bench/loadgen.py) must see the replacement's
        # backoff maturity continue where the dead incarnation's left off,
        # or the restarted run diverges from the un-killed oracle
        clock=dead.queue.clock,
        collector=dead.collector,
        metrics=dead.metrics,
        checkpoint_dir=dead._ckpt.directory if dead._ckpt is not None else None,
    )
    # the replacement inherits the dead scheduler's PodGroups: they live in
    # the cache (seeded by the harness / gang controller), not the store's
    # watch replay
    sched.cache.pod_groups.update(dead.cache.pod_groups)
    # the event recorder models the APISERVER event sink, not process
    # memory: Scheduled/FailedScheduling events published before the kill
    # survive it (the bench artifact's scheduled count must span restarts)
    sched.events = dead.events
    return sched


def restart_scheduler(dead: Scheduler,
                      killed_site: Optional[str] = None) -> Scheduler:
    """The crash-restart driver step: given an incarnation a kill.* fault
    just killed (ProcessKilled escaped), detach its watch subscriptions (the
    OS reclaiming a dead process's connections), clear the kill latch, and
    bring up + restore() the replacement on the SAME store.  killed_site
    (ProcessKilled.fault.site) labels the recovery so it reconciles with
    the injection in the chaos artifact."""
    dead.detach()
    chaos.revive()
    sched = reincarnate(dead)
    sched.restore(killed_site=killed_site)
    return sched


def run_restartable(sched: Scheduler, max_restarts: int = 64) -> Tuple[Scheduler, int]:
    """Drive run_until_idle across kill.* chaos faults: every ProcessKilled
    is answered with a restart-from-checkpoint (restart_scheduler) and the
    loop resumes on the replacement.  Returns (final incarnation, #restarts).
    Non-kill exceptions propagate untouched — they are the live-process
    recovery paths' business, not a restart's."""
    restarts = 0
    while True:
        try:
            sched.run_until_idle()
            return sched, restarts
        except chaos.ProcessKilled as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            sched = restart_scheduler(sched, killed_site=e.fault.site)


def run_ha_restartable(
    sched: Scheduler, lease_duration_s: float = 0.25, max_restarts: int = 64,
) -> Tuple[Scheduler, int]:
    """run_restartable with the active/standby protocol: every kill -9 is
    answered by a standby LEADER TAKEOVER (leases.py — HAReplica) instead of
    a bare in-place restart.  The dead leader simply stops renewing; the
    standby's first successful lease CAS past expiry builds + restores the
    replacement, so every blackout lands in `failover_duration_seconds` and
    `leader_election_transitions_total` — the HA series the bench artifact
    stamps next to the SLI.  The short default lease keeps bench blackouts
    priced in fractions of a second (production uses the client-go 15 s)."""
    from .leases import HAReplica, LeaderElector, LeaseStore

    leases = LeaseStore()  # real clock: blackouts are real wall time
    leader = LeaderElector(
        leases, "sched-0", lease_duration_s=lease_duration_s
    )
    leader.tick()  # incarnation 0 is the initial leader
    restarts = 0
    while True:
        try:
            sched.run_until_idle()
            return sched, restarts
        except chaos.ProcessKilled as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            sched, leader = ha_takeover(
                sched, leases, leader, killed_site=e.fault.site,
                lease_duration_s=lease_duration_s,
                name=f"sched-{restarts}",
            )


def ha_takeover(dead: Scheduler, leases, leader, killed_site: Optional[str],
                lease_duration_s: float = 0.25,
                name: str = "sched-standby") -> Tuple[Scheduler, object]:
    """One standby leader takeover over a just-killed leader — the except
    body every kill-surviving driver shares (run_ha_restartable for snapshot
    rounds, bench/loadgen.replay_trace for the open-loop stream).

    The leader's renew loop (a background thread in client-go, ticking every
    retry period) was renewing right up to the kill — the drivers are
    synchronous here, so model its final renewal at the death instant.  The
    standby's blackout then measures death -> takeover (one lease expiry +
    build/restore), not lease staleness accumulated across the whole run
    segment.  Returns (restored replacement, its elector) — the next kill
    fells THAT leader."""
    from .leases import HAReplica

    leader.tick()
    dead.detach()
    chaos.revive()  # the latch belongs to the dead leader
    standby = HAReplica(
        name, leases,
        lambda d=dead: reincarnate(d),
        lease_duration_s=lease_duration_s,
        metrics=dead.metrics, tracer=dead.tracer,
        killed_site=killed_site,
    )
    # tick on the leaderelection retry period until the dead leader's
    # lease decays and the takeover CAS lands
    while not standby.tick():
        time.sleep(lease_duration_s / 10.0)
    return standby.scheduler, standby.elector
