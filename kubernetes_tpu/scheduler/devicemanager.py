"""Device manager + topology manager analog — concrete device allocation on
the node.

reference: pkg/kubelet/cm/devicemanager (type ManagerImpl — Allocate: pick
SPECIFIC device IDs for a container, record them in a checksummed checkpoint
so a kubelet restart doesn't double-hand-out devices) and
pkg/kubelet/cm/topologymanager (NUMA alignment: prefer an allocation whose
devices share one NUMA node — the best-effort policy).

The scheduler counts device CAPACITY (api/volumes._device_counts folds
ResourceSlices into per-node per-class counts the Fit kernel enforces); this
manager performs the node-local half: which exact devices a pod gets.
Devices advertise their NUMA node through the reserved attribute key "numa"
on the ResourceSlice device (DraDevice attributes); devices without it are
topology-agnostic.

Allocation policy (deterministic):
  1. candidate devices = the node's slice devices matching the claim's
     DeviceClass selector, minus already-allocated ones;
  2. prefer the single NUMA node that can satisfy the whole claim with the
     fewest spare devices (best-fit — topologymanager's bitmask preference
     reduced to one dimension); fall back to spanning NUMA nodes;
  3. within a NUMA node, lowest device name first.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import types as t
from .checkpoint import CheckpointManager

_NUMA_ATTR = "numa"


class AllocationError(Exception):
    """Admission failure: the pod cannot start on this node (the reference
    fails the pod with UnexpectedAdmissionError)."""


class DeviceManager:
    """Per-node device allocator.  State: pod uid -> {class -> [device ids]}."""

    def __init__(
        self,
        node_name: str,
        checkpoints: Optional[CheckpointManager] = None,
    ):
        self.node_name = node_name
        self.checkpoints = checkpoints
        self.allocations: Dict[str, Dict[str, List[str]]] = {}
        if checkpoints is not None:
            saved = checkpoints.load(self._ckpt_name())
            if saved:
                self.allocations = {
                    uid: {cls: list(ids) for cls, ids in per.items()}
                    for uid, per in saved.items()
                }

    def _ckpt_name(self) -> str:
        return f"devicemanager-{self.node_name}"

    def _persist(self) -> None:
        if self.checkpoints is not None:
            self.checkpoints.save(self._ckpt_name(), self.allocations)

    # ------------------------------------------------------------ inventory
    def _devices_for_class(self, slices, device_class) -> List[Tuple[str, str]]:
        """-> [(device id, numa node)] on this node matching the class."""
        out = []
        for sl in slices:
            if sl.node_name != self.node_name:
                continue
            for dev in sl.devices:
                if device_class.selector.matches(dev):
                    numa = dict(dev.attributes).get(_NUMA_ATTR, "")
                    out.append((f"{sl.driver}/{dev.name}", numa))
        return out

    def _in_use(self) -> set:
        return {
            dev
            for per in self.allocations.values()
            for ids in per.values()
            for dev in ids
        }

    # ------------------------------------------------------------- allocate
    def allocate(self, pod: t.Pod, slices, device_classes) -> Dict[str, List[str]]:
        """Admit `pod`: pick concrete devices for each of its claims.
        Idempotent per pod (restart-safe).  Raises AllocationError when the
        inventory cannot satisfy a claim."""
        wanted: Dict[str, int] = {}
        for claim in pod.resource_claims:
            wanted[claim.device_class] = wanted.get(claim.device_class, 0) + claim.count
        cached = self.allocations.get(pod.uid)
        if cached is not None:
            if {cls: len(ids) for cls, ids in cached.items()} == wanted:
                return cached
            # same uid, different claims: a recreated pod reusing the name —
            # the old allocation is stale, release it and allocate afresh
            self.free(pod.uid)
        if not pod.resource_claims:
            return {}
        picked: Dict[str, List[str]] = {}
        in_use = self._in_use()
        for claim in pod.resource_claims:
            dc = device_classes.get(claim.device_class)
            if dc is None:
                raise AllocationError(
                    f"unknown device class {claim.device_class!r}"
                )
            free = [
                (dev, numa)
                for dev, numa in self._devices_for_class(slices, dc)
                if dev not in in_use
            ]
            chosen = self._pick(free, claim.count)
            if chosen is None:
                raise AllocationError(
                    f"{claim.device_class}: want {claim.count}, "
                    f"{len(free)} free on {self.node_name}"
                )
            # extend, not assign: a pod may carry several claims for the
            # same class (resolve_pod sums them on the scheduler side)
            picked.setdefault(claim.device_class, []).extend(chosen)
            in_use.update(chosen)
        self.allocations[pod.uid] = picked
        self._persist()
        return picked

    @staticmethod
    def _pick(free: List[Tuple[str, str]], count: int) -> Optional[List[str]]:
        if count <= 0:
            return []
        if len(free) < count:
            return None
        by_numa: Dict[str, List[str]] = {}
        for dev, numa in free:
            by_numa.setdefault(numa, []).append(dev)
        # single-NUMA candidates, best-fit (fewest leftovers), then numa id
        fitting = sorted(
            (len(devs), numa)
            for numa, devs in by_numa.items()
            if numa and len(devs) >= count
        )
        if fitting:
            _, numa = fitting[0]
            return sorted(by_numa[numa])[:count]
        # spanning fallback: lowest device names across all NUMA nodes
        return sorted(dev for dev, _ in free)[:count]

    # ----------------------------------------------------------------- free
    def free(self, pod_uid: str) -> None:
        if self.allocations.pop(pod_uid, None) is not None:
            self._persist()

    def numa_aligned(self, pod_uid: str, slices) -> bool:
        """True when every allocated device of the pod sits on one NUMA node
        (the topologymanager's single-numa-node check, for tests/metrics)."""
        numa_of: Dict[str, str] = {}
        for sl in slices:
            if sl.node_name == self.node_name:
                for dev in sl.devices:
                    numa_of[f"{sl.driver}/{dev.name}"] = dict(dev.attributes).get(
                        _NUMA_ATTR, ""
                    )
        nodes = {
            numa_of.get(dev, "")
            for per in [self.allocations.get(pod_uid, {})]
            for ids in per.values()
            for dev in ids
        }
        return len(nodes - {""}) <= 1
