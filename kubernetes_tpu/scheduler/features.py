"""Feature gates (component-base/featuregate — pkg/features/kube_features.go).

A small typed registry: each gate has a maturity stage and a default; config
can flip non-GA gates.  Call sites check features.enabled("X").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

ALPHA, BETA, GA = "Alpha", "Beta", "GA"


@dataclass(frozen=True)
class Gate:
    name: str
    stage: str
    default: bool


_GATES: Dict[str, Gate] = {
    g.name: g
    for g in [
        Gate("TPUScore", BETA, True),  # batched TPU offload path
        Gate("GangScheduling", BETA, True),  # all-or-nothing PodGroups
        Gate("DefaultPreemption", GA, True),
        # device-vectorized victim search on the batch path (ops/preempt.py);
        # off -> every failed pod takes the CPU PostFilter evaluator
        Gate("BatchedPreemption", BETA, True),
        Gate("SchedulingGates", GA, True),
        Gate("NodeInclusionPolicy", ALPHA, False),  # spread honors taints (future)
        Gate("MatchLabelKeys", ALPHA, False),  # spread matchLabelKeys (future)
    ]
}


class FeatureGates:
    def __init__(self, overrides: Tuple[Tuple[str, bool], ...] = ()):
        self._enabled = {name: g.default for name, g in _GATES.items()}
        for name, val in overrides:
            if name not in _GATES:
                raise ValueError(f"unknown feature gate {name!r}")
            if _GATES[name].stage == GA and not val:
                raise ValueError(f"cannot disable GA gate {name}")
            self._enabled[name] = val

    def enabled(self, name: str) -> bool:
        return self._enabled[name]


DEFAULT_FEATURE_GATES = FeatureGates()
