"""Scheduler metrics (pkg/scheduler/metrics/metrics.go).

Prometheus when prometheus_client is importable, else a minimal in-process
registry with the same API — either way the same metric names as the
reference: scheduling_attempt_duration_seconds, pending_pods,
queue_incoming_pods_total, preemption_victims, framework_extension_point_duration_seconds.

Pipelined-cycle series (parallel/pipeline.py + scheduler.py deferred
commits; no reference analog — the reference never overlaps cycles):

  pipeline_cycle_seconds              per-wave dispatch→result wall
  pipeline_overlap_fraction           fraction of host encode/commit/decode
                                      hidden under in-flight device steps
  pipeline_deferred_commit_seconds    deferred bind fan-out flush (usually
                                      inside the next cycle's device-step
                                      window; at a drain point otherwise)
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

try:
    from prometheus_client import Counter, Gauge, Histogram, REGISTRY

    _PROM = True
except Exception:  # pragma: no cover
    _PROM = False


class _Hist:
    def __init__(self):
        self.samples: List[float] = []
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.samples.append(v)

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self.samples:
                return 0.0
            s = sorted(self.samples)
            return s[min(len(s) - 1, int(q * len(s)))]


LabelKey = Tuple[Tuple[str, str], ...]


class Metrics:
    """One instance per scheduler; simple registry (plus labeled-histogram
    series) + optional Prometheus mirroring."""

    def __init__(self, prometheus: bool = False):
        # counters/gauges are bumped from binding-cycle worker threads too
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = defaultdict(float)
        self.hists: Dict[str, _Hist] = defaultdict(_Hist)
        # labeled histogram series: name -> {sorted (k, v) label pairs -> _Hist}
        # (framework_extension_point_duration_seconds{extension_point, plugin}
        # — metrics.go declares it with exactly these labels)
        self.labeled_hists: Dict[str, Dict[LabelKey, _Hist]] = {}
        # labeled counter series, same keying
        # (framework_fault_recovery_total{site, action} — chaos/plan.py)
        self.labeled_counters: Dict[str, Dict[LabelKey, float]] = {}
        self._prom = {}
        if prometheus and _PROM:  # pragma: no cover - optional path
            self._prom = {
                "scheduling_attempt_duration_seconds": Histogram(
                    "scheduling_attempt_duration_seconds", "per-attempt latency"
                ),
                "pending_pods": Gauge("pending_pods", "pods waiting to schedule"),
                "queue_incoming_pods_total": Counter(
                    "queue_incoming_pods_total", "pods entering the queue"
                ),
            }

    def inc(self, name: str, v: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += v
        p = self._prom.get(name)
        if p is not None:
            p.inc(v)

    def set(self, name: str, v: float) -> None:
        with self._lock:
            self.gauges[name] = v
        p = self._prom.get(name)
        if p is not None:
            p.set(v)

    def labeled_hist(self, name: str, **labels: str) -> _Hist:
        """The histogram for one label combination, created on first use —
        callers on hot paths cache the returned _Hist so repeat observations
        skip the registry lock entirely."""
        key: LabelKey = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            series = self.labeled_hists.setdefault(name, {})
            h = series.get(key)
            if h is None:
                h = series[key] = _Hist()
            return h

    def observe_labeled(self, name: str, v: float, **labels: str) -> None:
        self.labeled_hist(name, **labels).observe(v)

    def inc_labeled(self, name: str, v: float = 1.0, **labels: str) -> None:
        """Labeled counter bump (framework_fault_recovery_total{site,action}
        and friends) — appears in snapshot() under the Prometheus-rendered
        name, one entry per label combination."""
        key: LabelKey = tuple(sorted((k, str(val)) for k, val in labels.items()))
        with self._lock:
            series = self.labeled_counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + v

    def labeled_counter_total(self, name: str) -> float:
        """Sum across all label combinations of one labeled counter."""
        with self._lock:
            return sum(self.labeled_counters.get(name, {}).values())

    @staticmethod
    def render_labels(key: LabelKey) -> str:
        """Prometheus exposition form for a label key:
        {extension_point="Filter",plugin="NodeResourcesFit"}."""
        return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"

    def snapshot(self):
        """Consistent copies for scrapers: (counters, gauges,
        {hist: (p50, p99, count)}).  Labeled series appear in the hist dict
        under their Prometheus-rendered name —
        name{label="value",...} — one entry per label combination."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = dict(self.hists)
            labeled = {
                name: dict(series) for name, series in self.labeled_hists.items()
            }
            for name, series in self.labeled_counters.items():
                for key, v in series.items():
                    counters[name + self.render_labels(key)] = v
        out_hists = {
            name: (h.quantile(0.5), h.quantile(0.99), len(h.samples))
            for name, h in hists.items()
        }
        for name, series in labeled.items():
            for key, h in series.items():
                out_hists[name + self.render_labels(key)] = (
                    h.quantile(0.5), h.quantile(0.99), len(h.samples)
                )
        return counters, gauges, out_hists

    def observe(self, name: str, v: float) -> None:
        # called from binding-cycle worker threads: the defaultdict __missing__
        # + sample append must be serialized like inc/set
        with self._lock:
            self.hists[name].observe(v)
        p = self._prom.get(name)
        if p is not None:
            p.observe(v)

    def observe_many(self, name: str, values) -> None:
        """Bulk-append samples (a batch wave's per-pod latency estimates:
        one observe() call per pod would serialize 50k lock round-trips)."""
        values = list(values)
        with self._lock:
            h = self.hists[name]
        with h._lock:
            h.samples.extend(float(v) for v in values)
        p = self._prom.get(name)
        if p is not None:  # pragma: no cover - optional path
            for v in values:
                p.observe(v)
