"""Scheduler metrics (pkg/scheduler/metrics/metrics.go).

Prometheus when prometheus_client is importable, else a minimal in-process
registry with the same API — either way the same metric names as the
reference: scheduling_attempt_duration_seconds, pending_pods,
queue_incoming_pods_total, preemption_victims, framework_extension_point_duration_seconds.

Headline SLI (metrics.go — pod_scheduling_sli_duration_seconds): the true
per-pod arrival → bind latency, stamped at queue admission
(scheduler/queue.py) and observed at bind publication — batch waves,
deferred pipeline commits and the gang fixpoint included.

Histograms are STREAMING: fixed exponential buckets (factor 2, 1 µs …
~134 s, +Inf), O(buckets) memory and O(log buckets) per observe — never
O(samples).  The previous _Hist appended every sample forever and re-sorted
the whole list per quantile query, which melts at millions of pods.
Quantiles are bucket-resolved with log-linear interpolation and clamped to
the observed [min, max]; worst-case relative error is one bucket ratio (2×),
typically far less (PARITY.md records the layout and bound).  Histograms
merge across waves/processes (StreamingHist.merge) and render in Prometheus
exposition format (Metrics.expose_text — served from the apiserver's
/metrics route and the sidecar HealthServer).

Pipelined-cycle series (parallel/pipeline.py + scheduler.py deferred
commits; no reference analog — the reference never overlaps cycles):

  pipeline_cycle_seconds              per-wave dispatch→result wall
  pipeline_overlap_fraction           fraction of host encode/commit/decode
                                      hidden under in-flight device steps
  pipeline_deferred_commit_seconds    deferred bind fan-out flush (usually
                                      inside the next cycle's device-step
                                      window; at a drain point otherwise)

High-availability / crash-restart series (scheduler.py restore() +
leases.py HAReplica; all flow through expose_text like every other series
and are stamped into bench artifacts next to sli_p99_ms):

  scheduler_restarts_total            restore-protocol runs: crash restarts
                                      AND leader takeovers (each relists +
                                      replays the checkpoint)
  leader_election_transitions_total   leadership changes (HAReplica.tick)
  failover_duration_seconds           blackout per takeover: lease-clock
                                      time past the dead leader's expiry +
                                      real build/restore seconds
  checkpoint_corrupt_total            quarantined checkpoints
                                      (checkpoint.py — <name>.json.corrupt)
"""

from __future__ import annotations

import bisect
import math
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple
from ..analysis.lockcheck import make_lock

try:
    from prometheus_client import Counter, Gauge, Histogram, REGISTRY

    _PROM = True
except Exception:  # pragma: no cover
    _PROM = False


# Fixed exponential bucket upper bounds: 1e-6 * 2^k seconds, k = 0..27
# (1 µs … ~134 s), +Inf implicit.  One layout for every series keeps
# histograms mergeable across waves, schedulers and scrape points; the
# range covers per-plugin extension points (µs) through the 50k×20k device
# step (tens of seconds) and queue-backoff-bounded SLIs.  PARITY.md
# records the layout and the quantile error bound it implies.
DEFAULT_BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    1e-6 * (2.0 ** k) for k in range(28)
)

# Per-pod SLI phase decomposition: adjacent windows of the arrival → bind
# SLI, observed as pod_sli_phase_duration_seconds{phase=...} labeled
# StreamingHists at bind publication (scheduler.py — _observe_sli_phases;
# parallel/pipeline.py observes the wave-uniform analog).  The boundaries
# come from the span machinery's instants:
#   queue_wait     queue admission → activeQ pop (the queue.wait span)
#   wave_wait      pop → the deciding kernel's dispatch (batch.kernel start;
#                  the encode window in the pipelined loop)
#   device_kernel  kernel dispatch → the pod's decision ready (commit-ordinal
#                  estimate; the device.step window in the pipelined loop)
#   bind           decision ready → bind publication (deferred-commit
#                  latency included)
# The four instants are clamped to a monotone chain, so a pod's phases sum
# EXACTLY to its SLI sample — the attribution table's shares are exhaustive.
SLI_PHASES: Tuple[str, ...] = ("queue_wait", "wave_wait", "device_kernel", "bind")


class StreamingHist:
    """Bounded-memory streaming histogram: fixed buckets, O(1)-ish observe,
    mergeable, quantiles within bucket resolution.

    The per-instance lock serializes observers (binding-cycle worker
    threads bump the same series); `stats()` reads count + quantiles in ONE
    critical section so scrapers never see a torn (count, quantile) pair.
    """

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max", "_lock")

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self.bounds: Tuple[float, ...] = tuple(bounds or DEFAULT_BUCKET_BOUNDS)
        # counts[i] pairs with bounds[i] (le); counts[-1] is the +Inf bucket
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.count: int = 0
        self.sum: float = 0.0
        self.min: float = math.inf
        self.max: float = -math.inf
        self._lock = make_lock("StreamingHist._lock")

    def observe(self, v: float, n: int = 1) -> None:
        """Record `v` (n times — a wave of identical per-pod samples costs
        one bucket bump, not n)."""
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += n
            self.count += n
            self.sum += v * n
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def observe_many(self, values) -> None:
        """Bulk-append samples (a batch wave's per-pod latency estimates:
        one observe() per pod would pay 50k lock round-trips).  Buckets the
        whole array outside the lock, then merges in one critical section."""
        import numpy as np

        vs = np.asarray(list(values) if not hasattr(values, "__len__") else values,
                        dtype=np.float64)
        if vs.size == 0:
            return
        idx = np.searchsorted(np.asarray(self.bounds), vs, side="left")
        binned = np.bincount(idx, minlength=len(self.counts))
        total = int(vs.size)
        s = float(vs.sum())
        lo = float(vs.min())
        hi = float(vs.max())
        with self._lock:
            for i in np.nonzero(binned)[0]:
                self.counts[int(i)] += int(binned[int(i)])
            self.count += total
            self.sum += s
            if lo < self.min:
                self.min = lo
            if hi > self.max:
                self.max = hi

    def merge(self, other: "StreamingHist") -> None:
        """Fold another histogram (same bucket layout) into this one —
        cross-wave / cross-shard aggregation."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different buckets")
        with other._lock:
            counts = list(other.counts)
            count, s = other.count, other.sum
            lo, hi = other.min, other.max
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.count += count
            self.sum += s
            if lo < self.min:
                self.min = lo
            if hi > self.max:
                self.max = hi

    # -- queries --
    def _quantile_locked(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                frac = (target - prev) / c
                frac = min(1.0, max(0.0, frac))
                if i >= len(self.bounds):
                    # +Inf bucket: the observed max is the only upper bound
                    return self.max
                hi = self.bounds[i]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if lo <= 0.0:
                    val = hi * frac  # first bucket: linear from 0
                else:
                    val = lo * (hi / lo) ** frac  # log-linear within bucket
                # clamp to the observed envelope: a single-sample bucket
                # must report the sample's bucket, never exceed max/min
                return min(max(val, self.min), self.max)
        return self.max  # pragma: no cover — cum >= target always hits

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def stats(self) -> Tuple[float, float, int]:
        """(p50, p99, count) read atomically — the scrape triple
        (Metrics.snapshot consumes this under the per-hist lock so count
        and quantiles can never tear against a concurrent observe_many)."""
        with self._lock:
            return (
                self._quantile_locked(0.5),
                self._quantile_locked(0.99),
                self.count,
            )

    def reset(self) -> None:
        """Zero every bucket IN PLACE (same object identity): hot paths
        cache hist handles (Scheduler._sli_hist, labeled_hist callers), so
        a run-start reset must clear the histogram they hold, not orphan
        it behind a fresh instance."""
        with self._lock:
            for i in range(len(self.counts)):
                self.counts[i] = 0
            self.count = 0
            self.sum = 0.0
            self.min = math.inf
            self.max = -math.inf

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """(upper_bound, CUMULATIVE count) pairs, +Inf last — the
        Prometheus exposition shape."""
        with self._lock:
            out: List[Tuple[float, int]] = []
            cum = 0
            for i, ub in enumerate(self.bounds):
                cum += self.counts[i]
                out.append((ub, cum))
            out.append((math.inf, cum + self.counts[-1]))
            return out


# Back-compat alias: the registry's histogram type (pre-streaming code and
# tests referred to _Hist).
_Hist = StreamingHist


LabelKey = Tuple[Tuple[str, str], ...]


class Metrics:
    """One instance per scheduler; simple registry (plus labeled-histogram
    series) + optional Prometheus mirroring."""

    def __init__(self, prometheus: bool = False):
        # counters/gauges are bumped from binding-cycle worker threads too
        self._lock = make_lock("Metrics._lock")
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = defaultdict(float)
        self.hists: Dict[str, StreamingHist] = defaultdict(StreamingHist)
        # labeled histogram series: name -> {sorted (k, v) label pairs -> hist}
        # (framework_extension_point_duration_seconds{extension_point, plugin}
        # — metrics.go declares it with exactly these labels)
        self.labeled_hists: Dict[str, Dict[LabelKey, StreamingHist]] = {}
        # labeled counter series, same keying
        # (framework_fault_recovery_total{site, action} — chaos/plan.py)
        self.labeled_counters: Dict[str, Dict[LabelKey, float]] = {}
        self._prom = {}
        if prometheus and _PROM:  # pragma: no cover - optional path
            self._prom = {
                "scheduling_attempt_duration_seconds": Histogram(
                    "scheduling_attempt_duration_seconds", "per-attempt latency"
                ),
                "pending_pods": Gauge("pending_pods", "pods waiting to schedule"),
                "queue_incoming_pods_total": Counter(
                    "queue_incoming_pods_total", "pods entering the queue"
                ),
            }

    def inc(self, name: str, v: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += v
        p = self._prom.get(name)
        if p is not None:
            p.inc(v)

    def set(self, name: str, v: float) -> None:
        with self._lock:
            self.gauges[name] = v
        p = self._prom.get(name)
        if p is not None:
            p.set(v)

    def set_max(self, name: str, v: float) -> None:
        """Raise a gauge to `v` if higher (peak-depth gauges: the queue-pool
        samples keep `<pool>_peak` high-water marks next to the live
        depths, so one scrape answers both 'now' and 'worst this run')."""
        with self._lock:
            if v > self.gauges.get(name, float("-inf")):
                self.gauges[name] = v

    def hist(self, name: str) -> StreamingHist:
        """The (unlabeled) histogram for `name`, created on first use — hot
        paths cache the returned handle so repeat observations skip the
        registry lock entirely (the SLI observes once per bound pod)."""
        with self._lock:
            return self.hists[name]

    def labeled_hist(self, name: str, **labels: str) -> StreamingHist:
        """The histogram for one label combination, created on first use —
        callers on hot paths cache the returned hist so repeat observations
        skip the registry lock entirely."""
        key: LabelKey = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            series = self.labeled_hists.setdefault(name, {})
            h = series.get(key)
            if h is None:
                h = series[key] = StreamingHist()
            return h

    def observe_labeled(self, name: str, v: float, **labels: str) -> None:
        self.labeled_hist(name, **labels).observe(v)

    def inc_labeled(self, name: str, v: float = 1.0, **labels: str) -> None:
        """Labeled counter bump (framework_fault_recovery_total{site,action}
        and friends) — appears in snapshot() under the Prometheus-rendered
        name, one entry per label combination."""
        key: LabelKey = tuple(sorted((k, str(val)) for k, val in labels.items()))
        with self._lock:
            series = self.labeled_counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + v

    def labeled_counter_total(self, name: str) -> float:
        """Sum across all label combinations of one labeled counter."""
        with self._lock:
            return sum(self.labeled_counters.get(name, {}).values())

    def labeled_counter_series(self, name: str) -> Dict[LabelKey, float]:
        """Consistent copy of one labeled counter's series (label key ->
        value) — artifact emitters aggregate from it (e.g. the harness's
        top unschedulable reasons from
        pod_unschedulable_reasons_total{reason})."""
        with self._lock:
            return dict(self.labeled_counters.get(name, {}))

    @staticmethod
    def render_labels(key: LabelKey) -> str:
        """Prometheus exposition form for a label key:
        {extension_point="Filter",plugin="NodeResourcesFit"}."""
        return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"

    def snapshot(self):
        """Consistent copies for scrapers: (counters, gauges,
        {hist: (p50, p99, count)}).  Labeled series appear in the hist dict
        under their Prometheus-rendered name —
        name{label="value",...} — one entry per label combination.  Each
        hist triple is read atomically under that hist's own lock
        (StreamingHist.stats), so count and quantiles never tear against a
        concurrent observe_many."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = dict(self.hists)
            labeled = {
                name: dict(series) for name, series in self.labeled_hists.items()
            }
            for name, series in self.labeled_counters.items():
                for key, v in series.items():
                    counters[name + self.render_labels(key)] = v
        out_hists = {name: h.stats() for name, h in hists.items()}
        for name, series in labeled.items():
            for key, h in series.items():
                out_hists[name + self.render_labels(key)] = h.stats()
        return counters, gauges, out_hists

    def observe(self, name: str, v: float) -> None:
        # called from binding-cycle worker threads: the defaultdict __missing__
        # must be serialized like inc/set; the observe itself takes the
        # hist's own lock
        with self._lock:
            h = self.hists[name]
        h.observe(v)
        p = self._prom.get(name)
        if p is not None:
            p.observe(v)

    def observe_many(self, name: str, values) -> None:
        """Bulk-append samples (a batch wave's per-pod latency estimates:
        one observe() call per pod would serialize 50k lock round-trips)."""
        if not hasattr(values, "__len__"):
            # materialize once: a generator would be exhausted by the hist
            # and the prometheus mirror below would silently observe nothing
            values = list(values)
        with self._lock:
            h = self.hists[name]
        h.observe_many(values)
        p = self._prom.get(name)
        if p is not None:  # pragma: no cover - optional path
            for v in values:
                p.observe(v)

    def reset(self) -> None:
        """Clear every series — the run-start reset hook's metrics half
        (reset_run_state); resident histograms start a fresh run with no
        cross-run bleed.  Histograms are zeroed IN PLACE rather than
        evicted: hot paths cache handles (Scheduler._sli_hist, the
        labeled_hist contract), and a post-reset observation through a
        cached handle must land in the registry's hist, not an orphan."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            for h in self.hists.values():
                h.reset()
            for series in self.labeled_hists.values():
                for h in series.values():
                    h.reset()
            self.labeled_counters.clear()

    # -- Prometheus text exposition --
    @staticmethod
    def _fmt(v: float) -> str:
        if v == math.inf:
            return "+Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(float(v))

    def _render_hist(self, name: str, labels: str, h: StreamingHist,
                     lines: List[str]) -> None:
        with h._lock:
            cum = 0
            buckets: List[Tuple[float, int]] = []
            for i, ub in enumerate(h.bounds):
                cum += h.counts[i]
                buckets.append((ub, cum))
            buckets.append((math.inf, cum + h.counts[-1]))
            total, s = h.count, h.sum
        base = labels[1:-1] if labels else ""  # strip braces for composing
        for ub, c in buckets:
            lab = (base + "," if base else "") + f'le="{self._fmt(ub)}"'
            lines.append(f"{name}_bucket{{{lab}}} {c}")
        lines.append(f"{name}_sum{labels} {self._fmt(s)}")
        lines.append(f"{name}_count{labels} {total}")

    def expose_text(self) -> str:
        """The full registry in Prometheus text exposition format 0.0.4:
        counters (labeled series included), gauges, and streaming
        histograms as cumulative le-buckets + _sum/_count — the body the
        apiserver's /metrics route (scheduler/apiserver.py — MetricsServer)
        and the sidecar HealthServer serve."""
        lines: List[str] = []
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = dict(self.hists)
            labeled_h = {n: dict(s) for n, s in self.labeled_hists.items()}
            labeled_c = {n: dict(s) for n, s in self.labeled_counters.items()}
        for name in sorted(counters):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {self._fmt(counters[name])}")
        for name in sorted(labeled_c):
            lines.append(f"# TYPE {name} counter")
            for key in sorted(labeled_c[name]):
                lines.append(
                    f"{name}{self.render_labels(key)} "
                    f"{self._fmt(labeled_c[name][key])}"
                )
        for name in sorted(gauges):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {self._fmt(gauges[name])}")
        for name in sorted(hists):
            lines.append(f"# TYPE {name} histogram")
            self._render_hist(name, "", hists[name], lines)
        for name in sorted(labeled_h):
            lines.append(f"# TYPE {name} histogram")
            for key in sorted(labeled_h[name]):
                self._render_hist(
                    name, self.render_labels(key), labeled_h[name][key], lines
                )
        return "\n".join(lines) + "\n"


def reset_run_state(metrics: Optional[Metrics] = None,
                    collector=None) -> None:
    """THE run-start reset hook (PR-5 convention, generalized): one call at
    bench/harness run start clears the kernel route counters
    (ops/assign.py — TRACE_COUNTS), the metrics registry (streaming
    histograms + SLI series included) and the trace collector (spans,
    pod contexts AND its spans_dropped counter) — so back-to-back runs in
    one process never report each other's counters, samples or spans."""
    from ..ops.assign import reset_trace_counts

    reset_trace_counts()
    if metrics is not None:
        metrics.reset()
    if collector is not None:
        collector.clear()
