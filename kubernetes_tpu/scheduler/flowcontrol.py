"""API Priority & Fairness — flow classification + shuffle-sharded fair queuing.

reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol —
apf_controller.go (match request -> FlowSchema by precedence -> priority
level) and fairqueuing/queueset/queueset.go (per-priority-level queue set:
a flow is hashed to a `hand_size` shuffle-shard of the level's queues, lands
on the shortest; dispatch picks the queue with the least virtual finish time,
so one elephant flow cannot starve mice sharing the level).  Seats/concurrency
are normalized to 1 seat per request; virtual time advances by 1/width per
dispatch, the reference's R(t) progress with unit service time.
"""

from __future__ import annotations

import hashlib
import math
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..api import cluster as c
from .store import ClusterStore
from ..analysis.lockcheck import make_lock


class RequestRejected(Exception):
    """Queue full (or no schema matched) — HTTP 429 in the reference."""


@dataclass
class Request:
    user: str
    verb: str = "get"
    resource: str = "pods"
    namespace: str = ""
    # set by classify/enqueue
    flow: str = ""
    level: str = ""
    released: bool = False  # set by dispatch() (or immediately when exempt)
    _queue: Optional["_Queue"] = None


@dataclass
class _Queue:
    index: int
    requests: Deque[Request] = field(default_factory=deque)
    virtual_start: float = 0.0
    executing: int = 0


def _hand(flow_key: str, n_queues: int, hand_size: int) -> List[int]:
    """Shuffle-sharding dealer: derive `hand_size` distinct queue indices from
    the flow hash (fairqueuing — shufflesharding.Dealer.DealIntoHand)."""
    h = int.from_bytes(hashlib.sha256(flow_key.encode()).digest()[:8], "big")
    hand: List[int] = []
    remaining = list(range(n_queues))
    for _ in range(min(hand_size, n_queues)):
        h, idx = divmod(h, len(remaining))
        hand.append(remaining.pop(idx))
    return hand


class QueueSet:
    """One priority level's fair-queuing state."""

    def __init__(self, plc: c.PriorityLevelConfiguration, concurrency: int):
        self.plc = plc
        self.concurrency = max(1, concurrency)
        self.queues = [_Queue(i) for i in range(max(1, plc.queues))]
        self.in_flight = 0
        self.virtual_time = 0.0

    def enqueue(self, req: Request) -> None:
        if self.plc.exempt:
            # exempt levels never queue or limit (flowcontrol/v1 Exempt type)
            self.in_flight += 1
            req.released = True
            return
        hand = _hand(req.flow, len(self.queues), self.plc.hand_size)
        q = min((self.queues[i] for i in hand), key=lambda q: len(q.requests))
        if len(q.requests) >= self.plc.queue_length_limit:
            raise RequestRejected(
                f"too many requests for flow {req.flow!r} at level {self.plc.name}"
            )
        if not q.requests and q.executing == 0:
            # empty queue (re)joins at current virtual time (queueset.go —
            # the queue's virtual start clock catches up while idle)
            q.virtual_start = self.virtual_time
        q.requests.append(req)
        req._queue = q

    def dispatch(self) -> List[Request]:
        """Release as many requests as free seats allow, fair-queue order."""
        out: List[Request] = []
        while self.in_flight < self.concurrency:
            nonempty = [q for q in self.queues if q.requests]
            if not nonempty:
                break
            # least virtual finish time of the head request (width 1)
            q = min(nonempty, key=lambda q: (q.virtual_start, q.index))
            req = q.requests.popleft()
            q.virtual_start += 1.0
            q.executing += 1
            self.virtual_time = max(self.virtual_time, q.virtual_start - 1.0)
            self.in_flight += 1
            req.released = True
            out.append(req)
        return out

    def finish(self, req: Request) -> None:
        self.in_flight -= 1
        if req._queue is not None:
            req._queue.executing -= 1

    def cancel(self, req: Request) -> bool:
        """Remove a still-queued request (caller gave up waiting).  Returns
        False if the request was already released — the caller must then
        finish() it to return the seat."""
        q = req._queue
        if q is not None and req in q.requests:
            q.requests.remove(req)
            return True
        return False


DEFAULT_LEVELS = (
    c.PriorityLevelConfiguration(name="exempt", exempt=True),
    c.PriorityLevelConfiguration(name="leader-election", concurrency_shares=10,
                                 queues=16, hand_size=4),
    c.PriorityLevelConfiguration(name="workload-high", concurrency_shares=40),
    c.PriorityLevelConfiguration(name="workload-low", concurrency_shares=100),
    c.PriorityLevelConfiguration(name="catch-all", concurrency_shares=5,
                                 queues=1, hand_size=1),
)

DEFAULT_SCHEMAS = (
    c.FlowSchema(name="system-leader-election", priority_level="leader-election",
                 matching_precedence=100, resources=("leases",)),
    c.FlowSchema(name="kube-scheduler", priority_level="exempt",
                 matching_precedence=100, subjects=("system:kube-scheduler",)),
    c.FlowSchema(name="service-accounts", priority_level="workload-low",
                 matching_precedence=9000),
    c.FlowSchema(name="catch-all", priority_level="catch-all",
                 matching_precedence=10000),
)


class APFController:
    """apf_controller.go — owns the schema/level config and the queue sets.
    total_concurrency is divided between levels by concurrency_shares."""

    def __init__(self, store: ClusterStore, total_concurrency: int = 600):
        self.store = store
        self.total_concurrency = total_concurrency
        self._lock = make_lock("APFController._lock")  # guards all queue-set state
        if not store.objects["PriorityLevelConfiguration"]:
            for plc in DEFAULT_LEVELS:
                store.add_object("PriorityLevelConfiguration", plc)
        if not store.objects["FlowSchema"]:
            for fs in DEFAULT_SCHEMAS:
                store.add_object("FlowSchema", fs)
        self.queue_sets: Dict[str, QueueSet] = {}
        self.resync()

    def resync(self) -> None:
        levels: List[c.PriorityLevelConfiguration] = self.store.list_objects(
            "PriorityLevelConfiguration"
        )
        total_shares = sum(p.concurrency_shares for p in levels if not p.exempt) or 1
        for plc in levels:
            cl = max(1, round(self.total_concurrency * plc.concurrency_shares
                              / total_shares))
            existing = self.queue_sets.get(plc.name)
            if existing is None or existing.plc is not plc or existing.concurrency != cl:
                self.queue_sets[plc.name] = QueueSet(plc, cl)

    def classify(self, req: Request) -> Tuple[c.FlowSchema, QueueSet]:
        schemas = sorted(
            self.store.list_objects("FlowSchema"),
            key=lambda s: (s.matching_precedence, s.name),
        )
        for fs in schemas:
            if "*" not in fs.subjects and req.user not in fs.subjects:
                continue
            if "*" not in fs.resources and req.resource not in fs.resources:
                continue
            if "*" not in fs.verbs and req.verb not in fs.verbs:
                continue
            qs = self.queue_sets.get(fs.priority_level)
            if qs is None:
                continue
            return fs, qs
        raise RequestRejected(f"no FlowSchema matches request from {req.user!r}")

    def admit(self, req: Request) -> None:
        """Classify + enqueue.  Call dispatch() to release runnable requests."""
        fs, qs = self.classify(req)
        req.level = fs.priority_level
        if fs.distinguisher == "ByUser":
            req.flow = f"{fs.name}/{req.user}"
        elif fs.distinguisher == "ByNamespace":
            req.flow = f"{fs.name}/{req.namespace}"
        else:
            req.flow = fs.name
        with self._lock:
            qs.enqueue(req)

    def dispatch(self) -> List[Request]:
        with self._lock:
            out: List[Request] = []
            for qs in self.queue_sets.values():
                out.extend(qs.dispatch())
            return out

    def finish(self, req: Request) -> None:
        with self._lock:
            self.queue_sets[req.level].finish(req)

    def cancel(self, req: Request) -> None:
        """Caller gave up waiting (queue-wait timeout): dequeue, or if a
        concurrent dispatch already released it, return the seat — either way
        no seat leaks."""
        with self._lock:
            if not self.queue_sets[req.level].cancel(req) and req.released:
                self.queue_sets[req.level].finish(req)


# --- the streaming admission valve (overload-graceful open-loop intake) ---
#
# Where the QueueSet machinery above models the apiserver's request path,
# the valve below applies the same flow-control DOCTRINE — bounded queues,
# fair share per priority band, shed instead of unbounded backlog — to the
# scheduler's open-loop POD intake (bench/loadgen.py threads every due
# arrival through it).  Because flow control lives upstream of the
# component it protects, the valve's state legitimately survives a
# scheduler kill: the replay driver (the apiserver stand-in) holds it, and
# a leader takeover resumes against the same parked backlog.

WATERMARK_ENV = "KTPU_ADMIT_WATERMARK"
MAX_PARK_ENV = "KTPU_ADMIT_MAX_PARK_S"

# the counter pair the valve maintains (bench artifacts read them through
# report(); /metrics through the caller's Metrics)
ADMISSION_COUNTERS = (
    "scheduler_admission_parked_total",
    "scheduler_admission_shed_total",
)


class AdmissionValve:
    """Watermark-gated, priority-band fair-share admission over an arrival
    stream.  Items need `.priority` (the FlowSchedule band) and `.t` (their
    arrival instant in the caller's clock domain — the CO-honest base for
    shed waits); `offer()` is called once per driver cycle with that
    cycle's due arrivals and the current scheduler queue depth, in the same
    time domain throughout.  Deterministic by construction: FIFO within a
    band, bands served highest-first, no wall-clock reads — a replay under
    the same trace and knobs admits the identical sequence (the
    decision_crc parity gate covers valve-on runs too).

    Knobs: KTPU_ADMIT_WATERMARK (0 = valve off, the default — existing
    open-loop behavior is untouched), KTPU_ADMIT_MAX_PARK_S (staleness
    bound, default 30 virtual seconds)."""

    def __init__(self, watermark: Optional[int] = None,
                 max_park_s: Optional[float] = None, metrics=None):
        self.watermark = int(
            os.environ.get(WATERMARK_ENV, "0") if watermark is None
            else watermark
        )
        self.max_park_s = float(
            os.environ.get(MAX_PARK_ENV, "30") if max_park_s is None
            else max_park_s
        )
        self.metrics = metrics
        # (admission seq, first-offer instant, item): FIFO within a band is
        # the seq order; first-offer anchors the staleness bound
        self._parked: List[Tuple[int, float, object]] = []
        self._seq = 0
        self.parked_total = 0  # cumulative first-parks
        self.shed_total = 0
        self.shed_items: List[object] = []

    @property
    def enabled(self) -> bool:
        return self.watermark > 0

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    def _floor(self) -> int:
        # the adapted wave never starves entirely: even saturated, a sliver
        # of the highest bands admits (apf's minimum concurrency shares)
        return max(1, self.watermark // 8)

    def _shed(self, entries, now: float) -> None:
        for _, first, item in entries:
            self.shed_total += 1
            self.shed_items.append(item)
            if self.metrics is not None:
                self.metrics.inc("scheduler_admission_shed_total")
                # CO-honest: the wait is measured from the arrival instant
                # the TRACE assigned, not from when the valve got around to
                # deciding — overload sheds with honestly long waits
                t = getattr(item, "t", None)
                self.metrics.hist("pod_admission_shed_wait_seconds").observe(
                    max(0.0, now - (t if t is not None else first))
                )

    def offer(self, items, depth: int, now: float) -> List[object]:
        """One driver cycle: merge `items` (this cycle's due arrivals) with
        the parked backlog and return what admits NOW, given the scheduler
        queue depth.  Under the watermark everything admits (the valve is
        invisible); over it, stale parks shed first, then a fair share per
        priority band of a budget that shrinks as depth grows."""
        if not self.enabled:
            return list(items)
        pool = list(self._parked)
        for item in items:
            pool.append((self._seq, now, item))
            self._seq += 1
        self._parked = []
        if not pool:
            return []
        if depth < self.watermark:
            return [item for _, _, item in pool]
        # saturated: shed past the staleness bound — admitting an arrival
        # whose bound already expired would be serving a request the
        # apiserver told the client to retry
        live, stale = [], []
        for e in pool:
            (stale if now - e[1] > self.max_park_s else live).append(e)
        self._shed(stale, now)
        # wave adaptation: the admitted budget shrinks linearly as depth
        # overshoots the watermark, never below the floor
        budget = max(self._floor(), 2 * self.watermark - depth)
        by_band: Dict[int, List] = {}
        for e in live:
            by_band.setdefault(getattr(e[2], "priority", 0), []).append(e)
        bands = sorted(by_band, reverse=True)
        share = math.ceil(budget / len(bands)) if bands else 0
        admitted: List = []
        # equal fair share per band, FIFO within the band...
        for b in bands:
            take = min(share, budget - len(admitted), len(by_band[b]))
            admitted.extend(by_band[b][:take])
            by_band[b] = by_band[b][take:]
        # ...then any leftover budget spills highest-band-first
        for b in bands:
            room = budget - len(admitted)
            if room <= 0:
                break
            admitted.extend(by_band[b][:room])
            by_band[b] = by_band[b][room:]
        newly_parked = 0
        for b in bands:
            for e in by_band[b]:
                if e[1] == now:  # first offer this cycle — count the park
                    newly_parked += 1
                self._parked.append(e)
        self._parked.sort(key=lambda e: e[0])  # FIFO across cycles
        self.parked_total += newly_parked
        if self.metrics is not None and newly_parked:
            self.metrics.inc("scheduler_admission_parked_total",
                             newly_parked)
        return [item for _, _, item in admitted]

    def flush(self, now: float) -> int:
        """End of stream: every still-parked arrival sheds (the driver is
        terminating; holding them would leak pods out of the accounting
        identity shed + scheduled + unschedulable == arrivals).  Returns
        the number shed."""
        n = len(self._parked)
        self._shed(self._parked, now)
        self._parked = []
        return n

    def report(self) -> Dict[str, float]:
        """Artifact block (bench/loadgen.py stamps it when enabled)."""
        return {
            "watermark": self.watermark,
            "max_park_s": self.max_park_s,
            "parked_total": self.parked_total,
            "shed_total": self.shed_total,
            "parked_now": len(self._parked),
        }
