"""HTTP scheduler extender — the reference's out-of-process extension
protocol, kept wire-compatible.

reference: pkg/scheduler/extender.go — type HTTPExtender (Filter /
Prioritize / Bind over JSON HTTP POST) with config shape
pkg/scheduler/apis/config/types.go — type Extender (urlPrefix, filterVerb,
prioritizeVerb, weight, bindVerb, ignorable).

The gRPC TPUScore sidecar (runtime/) is this framework's *batched*
replacement; this client exists for drop-in compatibility with existing
one-pod-per-call extenders.  Wire shapes:

  POST {urlPrefix}/{filterVerb}    ExtenderArgs{pod, nodenames}
       -> ExtenderFilterResult{nodenames, failedNodes, error}
  POST {urlPrefix}/{prioritizeVerb} ExtenderArgs
       -> HostPriorityList [{host, score}]   (score 0..10, scaled by weight)
  POST {urlPrefix}/{bindVerb}      ExtenderBindingArgs{podName, podNamespace,
       podUID, node} -> ExtenderBindingResult{error}
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..api.serialize import to_manifest

# reference: extenderv1.MaxExtenderPriority
MAX_EXTENDER_PRIORITY = 10.0


class ExtenderError(Exception):
    """Transport/protocol failure from a non-ignorable extender: the pod's
    scheduling attempt fails and it re-queues (extender.go — IsIgnorable)."""


def post_json(url: str, payload: dict, timeout_s: float) -> dict:
    """One JSON POST -> decoded JSON response.  Shared wire helper for the
    extender and admission-webhook clients; raises the urllib/OS/ValueError
    family for the caller's failure policy to classify."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


@dataclass(frozen=True)
class ExtenderConfig:
    """apis/config — type Extender (the fields this client honors)."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    # supportsPreemption: when set, DefaultPreemption offers this extender
    # its candidate victim map (extender.go — ProcessPreemption)
    preempt_verb: str = ""
    weight: float = 1.0
    ignorable: bool = False
    timeout_s: float = 5.0


class HTTPExtender:
    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    def _post(self, verb: str, payload: dict) -> dict:
        url = f"{self.cfg.url_prefix.rstrip('/')}/{verb}"
        return post_json(url, payload, self.cfg.timeout_s)

    # ------------------------------------------------------------- filter
    def filter(
        self, pod: t.Pod, node_names: List[str]
    ) -> Tuple[List[str], Dict[str, str]]:
        """-> (feasible node names, failed {node: reason}).  Raises
        ExtenderError on transport failure (caller applies `ignorable`)."""
        if not self.cfg.filter_verb:
            return node_names, {}
        try:
            out = self._post(
                self.cfg.filter_verb,
                {"pod": to_manifest(pod), "nodenames": list(node_names)},
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"{self.cfg.url_prefix}: {e}") from e
        if out.get("error"):
            raise ExtenderError(out["error"])
        return list(out.get("nodenames") or []), dict(out.get("failedNodes") or {})

    # ---------------------------------------------------------- prioritize
    def prioritize(self, pod: t.Pod, node_names: List[str]) -> Dict[str, float]:
        """-> {node: weighted score}.  A failing prioritize call zeroes the
        extender's contribution (extender.go — Prioritize errors are fatal
        only for non-ignorable extenders; we mirror the filter contract)."""
        if not self.cfg.prioritize_verb:
            return {}
        try:
            out = self._post(
                self.cfg.prioritize_verb,
                {"pod": to_manifest(pod), "nodenames": list(node_names)},
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"{self.cfg.url_prefix}: {e}") from e
        return {
            h["host"]: float(h["score"]) * self.cfg.weight
            for h in out
            if isinstance(h, dict) and "host" in h
        }

    # ---------------------------------------------------------- preemption
    def process_preemption(
        self, pod: t.Pod, node_to_victims: Dict[str, List[t.Pod]]
    ) -> Dict[str, List[t.Pod]]:
        """extender.go — ProcessPreemption: offer the candidate victim map;
        the extender returns the surviving subset (it may drop whole nodes
        or trim a node's victim list).  Wire shape is the reference's
        ExtenderPreemptionArgs / nodeNameToMetaVictims (victims by uid).
        Raises ExtenderError on transport failure (caller applies
        `ignorable`)."""
        if not self.cfg.preempt_verb:
            return node_to_victims
        try:
            out = self._post(
                self.cfg.preempt_verb,
                {
                    "pod": to_manifest(pod),
                    "nodeNameToMetaVictims": {
                        node: {"pods": [{"uid": q.uid} for q in victims]}
                        for node, victims in node_to_victims.items()
                    },
                },
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"{self.cfg.url_prefix}: {e}") from e
        if out.get("error"):
            raise ExtenderError(out["error"])
        result: Dict[str, List[t.Pod]] = {}
        for node, meta in (out.get("nodeNameToMetaVictims") or {}).items():
            orig = node_to_victims.get(node)
            if orig is None:
                continue  # an extender cannot invent candidate nodes
            # ... nor move victims between nodes: only THIS node's own
            # candidates resolve (the reference's convertToVictims rejects
            # unknown uids the same way)
            own = {q.uid: q for q in orig}
            kept = [
                own[m["uid"]]
                for m in (meta or {}).get("pods", [])
                if m.get("uid") in own
            ]
            if kept:
                result[node] = kept
        return result

    # ---------------------------------------------------------------- bind
    def bind(self, pod: t.Pod, node_name: str) -> Optional[str]:
        """-> error string or None.  Only called when bind_verb is set; the
        extender performs the binding POST itself in the reference."""
        try:
            out = self._post(
                self.cfg.bind_verb,
                {
                    "podName": pod.name,
                    "podNamespace": pod.namespace,
                    "podUID": pod.uid,
                    "node": node_name,
                },
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            return str(e)
        return out.get("error") or None
