"""Scheduler cache: watch-driven NodeInfo aggregation + assumed pods.

Analog of pkg/scheduler/backend/cache/cache.go — cacheImpl: consumes store
events, maintains per-node NodeInfo (running pods, aggregated requests), and
an assumed-pod set so a bound-but-unconfirmed pod occupies capacity for later
cycles (AssumePod / FinishBinding / ForgetPod).  UpdateSnapshot produces the
api.Snapshot the encoder and the CPU path both consume.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from .. import chaos
from ..api import types as t
from ..api.snapshot import Snapshot
from .framework import NodeInfo
from .store import ClusterStore, Event, replace_pod_nodename
from ..analysis.lockcheck import make_lock


class SchedulerCache:
    def __init__(self, store: ClusterStore):
        self._lock = make_lock("SchedulerCache._lock")
        # crash-consistency hook (scheduler.py — _checkpoint_state): invoked
        # AFTER every assumed-ledger mutation, outside the cache lock, so the
        # reservation is durable before the bind path proceeds.  None = no
        # checkpointing (the default; KTPU_CHECKPOINT_DIR arms it).
        self.checkpoint_hook: Optional[Callable[[], None]] = None
        # kill-point router (scheduler.py — _kill_point): lets the owning
        # scheduler stamp kill.post_assume injections onto ITS tracer and
        # metrics (and latch _dead) like every other kill site; the bare
        # chaos.poke fallback keeps a standalone cache stormable
        self.kill_point: Optional[Callable[[str], None]] = None
        # registry kinds the snapshot LISTs at build time (StorageClass /
        # ResourceSlice / DeviceClass churn far less than pods; a per-cycle
        # LIST matches the reference's informer-cache read)
        self._store = store
        self.nodes: Dict[str, t.Node] = {}
        self.pods: Dict[str, t.Pod] = {}  # all pods by uid (pending + bound)
        self.assumed: Dict[str, str] = {}  # pod uid -> node (optimistic binds)
        self.pod_groups: Dict[str, t.PodGroup] = {}
        self.pvs: Dict[str, t.PersistentVolume] = {}
        self.pvcs: Dict[str, t.PersistentVolumeClaim] = {}
        store.watch(self._on_event)

    def _on_event(self, ev: Event) -> None:
        with self._lock:
            if ev.obj_type == "PV":
                if ev.kind == "Deleted":
                    self.pvs.pop(ev.obj.name, None)
                else:
                    self.pvs[ev.obj.name] = ev.obj
                return
            if ev.obj_type == "PVC":
                if ev.kind == "Deleted":
                    self.pvcs.pop(ev.obj.key, None)
                else:
                    self.pvcs[ev.obj.key] = ev.obj
                return
            if ev.obj_type == "Node":
                if ev.kind == "Deleted":
                    self.nodes.pop(ev.obj.name, None)
                else:
                    self.nodes[ev.obj.name] = ev.obj
            elif ev.obj_type == "Pod":
                if ev.kind == "Deleted":
                    self.pods.pop(ev.obj.uid, None)
                    self.assumed.pop(ev.obj.uid, None)
                else:
                    self.pods[ev.obj.uid] = ev.obj
                    if ev.obj.node_name and self.assumed.get(ev.obj.uid) == ev.obj.node_name:
                        # bind confirmed by the store: assumption retired
                        self.assumed.pop(ev.obj.uid, None)

    # --- assume cache (cache.go — AssumePod / ForgetPod / FinishBinding) ---
    def assume(self, pod_uid: str, node_name: str) -> None:
        with self._lock:
            self.assumed[pod_uid] = node_name
        # kill.post_assume: the enumerated kill point BETWEEN the in-memory
        # reservation and its durable checkpoint — a restart must requeue
        # the pod (the ledger on disk never saw it)
        kp = self.kill_point
        if kp is not None:
            kp("kill.post_assume")
        elif chaos.enabled():
            chaos.poke("kill.post_assume")
        self._checkpoint()

    def forget(self, pod_uid: str) -> None:
        with self._lock:
            dropped = self.assumed.pop(pod_uid, None) is not None
        if dropped:
            self._checkpoint()

    def _checkpoint(self) -> None:
        """Persist the assumed-pod ledger at every reservation change
        (checkpoint.py — fsync'd atomic-rename; the hook snapshots the
        ledger itself).  Called OUTSIDE the cache lock: the hook reads
        assumed via assumed_snapshot(), and file IO under the cache lock
        would serialize every concurrent binding worker behind fsync."""
        hook = self.checkpoint_hook
        if hook is not None:
            hook()

    def assumed_snapshot(self) -> Dict[str, str]:
        """Lock-consistent copy of the assumed ledger (the checkpoint's
        read side)."""
        with self._lock:
            return dict(self.assumed)

    def _effective_node(self, pod: t.Pod) -> str:
        return pod.node_name or self.assumed.get(pod.uid, "")

    def update_snapshot(self) -> Snapshot:
        """Snapshot for the batch/TPU path: bound = running + assumed pods."""
        # LIST the registry kinds BEFORE taking the cache lock: the store lock
        # is held inside list_objects, and store->watcher->_on_event already
        # acquires cache._lock under the store lock — taking them here in the
        # opposite order would be an ABBA inversion
        storage_classes = {
            sc.name: sc for sc in self._store.list_objects("StorageClass")
        }
        resource_slices = self._store.list_objects("ResourceSlice")
        device_classes = {
            dc.name: dc for dc in self._store.list_objects("DeviceClass")
        }
        with self._lock:
            nodes = list(self.nodes.values())
            pending, bound = [], []
            for p in self.pods.values():
                if p.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                    continue  # terminated pods release their capacity
                node = self._effective_node(p)
                if node:
                    q = p if p.node_name else replace_pod_nodename(p, node)
                    bound.append(q)
                else:
                    pending.append(p)
            return Snapshot(
                nodes=nodes,
                pending_pods=pending,
                bound_pods=bound,
                pod_groups=dict(self.pod_groups),
                pvs=list(self.pvs.values()),
                pvcs=dict(self.pvcs),
                storage_classes=storage_classes,
                resource_slices=resource_slices,
                device_classes=device_classes,
            )

    def node_infos(self, snap: Snapshot) -> List[NodeInfo]:
        from ..api.snapshot import _resource_axis

        resources = _resource_axis(snap)
        infos = {nd.name: NodeInfo(node=nd) for nd in snap.nodes}
        for q in snap.bound_pods:
            if q.node_name in infos:
                infos[q.node_name].add_pod(q, resources)
        return list(infos.values())
