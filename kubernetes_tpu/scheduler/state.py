"""ScaledState — the shared per-cycle view the CPU plugins read.

Bundles what the reference splits between the cache snapshot (NodeInfo list)
and per-plugin PreFilter state: the resource axis, int32-exact rescaled
alloc/used matrices (identical scaling to the encoder and the oracle, so all
three paths agree bit-for-bit), the existing-pod ledger, and a node-selection
cache.  Supports temporary node simulation for preemption's what-if filtering
(framework/preemption/preemption.go — SelectVictimsOnNode's AddPod/RemovePod).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as t
from ..api.snapshot import Snapshot, _resource_axis, _scale_for, pod_effective_requests
from .framework import NodeInfo


class ScaledState:
    def __init__(self, snap: Snapshot, infos: List[NodeInfo]):
        self.infos = infos
        self.nodes = [ni.node for ni in infos]
        self.index: Dict[str, int] = {ni.node.name: i for i, ni in enumerate(infos)}
        self.resources = _resource_axis(snap)
        R, N = len(self.resources), len(infos)
        self.score_idx = [self.resources.index(t.CPU), self.resources.index(t.MEMORY)]

        alloc_raw = np.zeros((N, R), dtype=np.int64)
        for i, nd in enumerate(self.nodes):
            for j, r in enumerate(self.resources):
                from ..api.snapshot import _DEFAULT_POD_LIMIT

                alloc_raw[i, j] = nd.allocatable.get(
                    r, _DEFAULT_POD_LIMIT if r == t.PODS else 0
                )
        req_raw = {
            p.uid: np.array(pod_effective_requests(p, self.resources), dtype=np.int64)
            for p in snap.pending_pods
        }
        used_raw = np.zeros((N, R), dtype=np.int64)
        for i, ni in enumerate(infos):
            for q in ni.pods:
                used_raw[i] += np.array(
                    pod_effective_requests(q, self.resources), dtype=np.int64
                )
        self.scale = np.ones(R, dtype=np.int64)
        for j in range(R):
            vals = (
                [int(x) for x in alloc_raw[:, j]]
                + [int(v[j]) for v in req_raw.values()]
                + [int(x) for x in used_raw[:, j]]
            )
            self.scale[j] = _scale_for(vals)
        self.alloc = alloc_raw // self.scale
        self.used = -(-used_raw // self.scale)
        self._req: Dict[str, np.ndarray] = {
            uid: -(-v // self.scale) for uid, v in req_raw.items()
        }
        self.existing: List[Tuple[t.Pod, int]] = [
            (q, i) for i, ni in enumerate(infos) for q in ni.pods
        ]
        self._sel_cache: Dict[str, List[bool]] = {}
        self._sim_stack: Dict[int, Tuple[np.ndarray, List[Tuple[t.Pod, int]], NodeInfo]] = {}

    def req_of(self, pod: t.Pod) -> np.ndarray:
        r = self._req.get(pod.uid)
        if r is None:
            raw = np.array(pod_effective_requests(pod, self.resources), dtype=np.int64)
            r = -(-raw // self.scale)
            self._req[pod.uid] = r
        return r

    def node_ok_sel(self, pod: t.Pod) -> List[bool]:
        from ..oracle.reference import _node_selection_ok

        sel = self._sel_cache.get(pod.uid)
        if sel is None:
            sel = [_node_selection_ok(pod, nd) for nd in self.nodes]
            self._sel_cache[pod.uid] = sel
        return sel

    # --- commit (assume) ---
    def add_pod(self, pod: t.Pod, i: int) -> None:
        self.used[i] += self.req_of(pod)
        self.existing.append((pod, i))
        self.infos[i].add_pod(pod, self.resources)

    def remove_pod(self, pod: t.Pod, i: int) -> None:
        self.used[i] -= self.req_of(pod)
        self.existing = [(q, n) for q, n in self.existing if q.uid != pod.uid]
        self.infos[i].remove_pod(pod, self.resources)

    # --- preemption what-if simulation ---
    def push_sim(self, i: int, sim: NodeInfo) -> None:
        self._sim_stack[i] = (self.used[i].copy(), list(self.existing), self.infos[i])
        self.infos[i] = sim
        self.refresh_sim(i, sim)

    def refresh_sim(self, i: int, sim: NodeInfo) -> None:
        u = np.zeros(len(self.resources), dtype=np.int64)
        for q in sim.pods:
            u += self.req_of(q)
        self.used[i] = u
        self.existing = [(q, n) for q, n in self.existing if n != i] + [
            (q, i) for q in sim.pods
        ]

    def pop_sim(self, i: int) -> None:
        used, existing, info = self._sim_stack.pop(i)
        self.used[i] = used
        self.existing = existing
        self.infos[i] = info
