"""Admission chain — mutating plugins, then validating plugins.

reference: staging/src/k8s.io/apiserver/pkg/admission (the chain the
apiserver's createHandler runs between decode and storage: mutation first,
validation second) plus the in-tree plugins the scheduling path depends on:

  NamespaceLifecycle   plugin/pkg/admission/namespace/lifecycle — reject
                       creates into missing/terminating namespaces
  LimitRanger          plugin/pkg/admission/limitranger — default container
                       requests from LimitRange.defaultRequest; enforce max
  Priority             plugin/pkg/admission/priority — resolve
                       priorityClassName -> spec.priority; reject unknown
                       classes; apply the global default
  ResourceQuota        plugin/pkg/admission/resourcequota — reject writes
                       that would push aggregate namespace usage over hard
                       caps (pods count + summed requests)

Validating-policy analog (ValidatingAdmissionPolicy / CEL): `PolicyPlugin`
holds named predicates over (attributes) — the expression language is a
Python callable instead of CEL, same shape: match constraints + validation
that must hold (apiserver/pkg/admission/plugin/policy/validating).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Tuple

from ..api import cluster as c
from ..api import types as t
from .store import ClusterStore


class AdmissionDenied(Exception):
    """A validating (or mutating) plugin rejected the request — the Status
    Failure the reference returns as HTTP 4xx."""


@dataclass
class Attributes:
    """admission.Attributes — what every plugin sees."""

    verb: str  # create | update | delete
    kind: str  # Pod | Node | Service | ...
    namespace: str
    obj: object
    user: Optional[c.UserInfo] = None


class AdmissionPlugin:
    name = "plugin"

    def admit(self, attrs: Attributes) -> None:
        """Mutating pass — may replace attrs.obj."""

    def validate(self, attrs: Attributes) -> None:
        """Validating pass — raise AdmissionDenied to reject."""


class NamespaceLifecycle(AdmissionPlugin):
    name = "NamespaceLifecycle"
    _exempt = ("default", "kube-system")

    def __init__(self, store: ClusterStore):
        self.store = store

    def validate(self, attrs: Attributes) -> None:
        if attrs.verb != "create" or not attrs.namespace:
            return
        if attrs.kind == "Namespace":
            return
        ns = self.store.get_object("Namespace", attrs.namespace)
        if ns is None:
            if attrs.namespace in self._exempt:
                return  # implicit system namespaces
            raise AdmissionDenied(f"namespace {attrs.namespace!r} not found")
        if ns.phase == "Terminating":
            raise AdmissionDenied(
                f"namespace {attrs.namespace!r} is terminating: new objects forbidden"
            )


class LimitRanger(AdmissionPlugin):
    name = "LimitRanger"

    def __init__(self, store: ClusterStore):
        self.store = store

    def _ranges(self, namespace: str) -> List[c.LimitRange]:
        return self.store.list_objects("LimitRange", namespace)

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod" or attrs.verb != "create":
            return
        pod: t.Pod = attrs.obj  # type: ignore[assignment]
        for lr in self._ranges(attrs.namespace):
            missing = {
                r: v for r, v in lr.default_request.items() if r not in pod.requests
            }
            if missing:
                pod = copy.copy(pod)
                pod.requests = {**pod.requests, **missing}
                attrs.obj = pod

    def validate(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod" or attrs.verb != "create":
            return
        pod: t.Pod = attrs.obj  # type: ignore[assignment]
        for lr in self._ranges(attrs.namespace):
            for r, cap in lr.max_per_pod.items():
                if pod.requests.get(r, 0) > cap:
                    raise AdmissionDenied(
                        f"maximum {r} usage per Pod is {cap}, but request is "
                        f"{pod.requests[r]} (limitrange {lr.name})"
                    )


class PriorityAdmission(AdmissionPlugin):
    name = "Priority"

    def __init__(self, store: ClusterStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod" or attrs.verb != "create":
            return
        pod: t.Pod = attrs.obj  # type: ignore[assignment]
        if pod.priority_class_name:
            pc = self.store.get_object("PriorityClass", pod.priority_class_name)
            if pc is None:
                raise AdmissionDenied(
                    f"no PriorityClass with name {pod.priority_class_name} was found"
                )
            value = pc.value
        elif pod.priority != 0:
            # the reference rejects user-supplied spec.priority: only this
            # admission plugin may compute it (plugin/pkg/admission/priority)
            raise AdmissionDenied(
                "the integer value of priority must not be provided in pod spec; "
                "priority admission controller computes it from priorityClassName"
            )
        else:
            default = next(
                (
                    pc
                    for pc in self.store.list_objects("PriorityClass")
                    if pc.global_default
                ),
                None,
            )
            if default is None:
                return
            value = default.value
        if pod.priority != value:
            pod = copy.copy(pod)
            pod.priority = value
            attrs.obj = pod


class ResourceQuotaAdmission(AdmissionPlugin):
    name = "ResourceQuota"

    def __init__(self, store: ClusterStore):
        self.store = store

    def _usage(self, namespace: str) -> Dict[str, int]:
        used: Dict[str, int] = {"pods": 0}
        for pod in self.store.list_pods():
            if pod.namespace != namespace or pod.phase in (
                t.PHASE_SUCCEEDED,
                t.PHASE_FAILED,
            ):
                continue
            used["pods"] += 1
            for r, v in pod.requests.items():
                used[r] = used.get(r, 0) + v
        return used

    def validate(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod" or attrs.verb != "create":
            return
        pod: t.Pod = attrs.obj  # type: ignore[assignment]
        quotas = self.store.list_objects("ResourceQuota", attrs.namespace)
        if not quotas:
            return
        used = self._usage(attrs.namespace)
        for q in quotas:
            for r, hard in q.hard.items():
                delta = 1 if r == "pods" else pod.requests.get(r, 0)
                if used.get(r, 0) + delta > hard:
                    raise AdmissionDenied(
                        f"exceeded quota: {q.name}, requested: {r}={delta}, "
                        f"used: {r}={used.get(r, 0)}, limited: {r}={hard}"
                    )
        # all quotas passed: record status through the store (locked write +
        # watch event — the quota controller's updateQuota role)
        for q in quotas:
            new_used = {r: used.get(r, 0) for r in q.hard}
            if new_used != q.used:
                self.store.update_object("ResourceQuota", replace(q, used=new_used))


@dataclass(frozen=True)
class ValidatingPolicy:
    """ValidatingAdmissionPolicy-lite: match by kind, check must hold."""

    name: str
    check: Callable[[Attributes], bool]
    message: str = "policy denied"
    kinds: Tuple[str, ...] = ("*",)


class PolicyPlugin(AdmissionPlugin):
    """apiserver/pkg/admission/plugin/policy/validating — the CEL policy
    evaluator with the expression language swapped for Python callables."""

    name = "ValidatingAdmissionPolicy"

    def __init__(self) -> None:
        self.policies: List[ValidatingPolicy] = []

    def add(self, policy: ValidatingPolicy) -> None:
        self.policies.append(policy)

    def validate(self, attrs: Attributes) -> None:
        for p in self.policies:
            if "*" not in p.kinds and attrs.kind not in p.kinds:
                continue
            if not p.check(attrs):
                raise AdmissionDenied(f"{p.name}: {p.message}")


@dataclass(frozen=True)
class WebhookConfig:
    """admissionregistration.k8s.io — Mutating/ValidatingWebhookConfiguration
    reduced to one webhook: target URL, rule match (kinds/verbs), and
    failurePolicy.  Wire shape is AdmissionReview-like JSON:

      POST url  {"request": {"operation", "kind", "namespace", "object"}}
        -> {"response": {"allowed": bool, "message": str, "object": manifest?}}

    Mutating webhooks return the full mutated object instead of a JSONPatch
    (documented reduction; reinvocationPolicy is likewise not modeled)."""

    url: str
    mutating: bool = False
    kinds: Tuple[str, ...] = ()  # empty = every kind
    verbs: Tuple[str, ...] = ("create", "update")
    failure_policy: str = "Fail"  # Fail | Ignore
    timeout_s: float = 5.0


class Webhook(AdmissionPlugin):
    """apiserver/pkg/admission/plugin/webhook — the HTTP boundary member of
    the chain (mutating/{mutating,validating} dispatchers)."""

    def __init__(self, cfg: WebhookConfig):
        self.cfg = cfg
        self.name = f"webhook[{cfg.url}]"

    def _matches(self, attrs: Attributes) -> bool:
        if attrs.verb not in self.cfg.verbs:
            return False
        return not self.cfg.kinds or attrs.kind in self.cfg.kinds

    def _call(self, attrs: Attributes) -> dict:
        import urllib.error

        from ..api.serialize import to_manifest
        from .extender import post_json

        payload = {
            "request": {
                "operation": attrs.verb.upper(),
                "kind": attrs.kind,
                "namespace": attrs.namespace,
                "object": to_manifest(attrs.obj),
            }
        }
        try:
            resp = post_json(self.cfg.url, payload, self.cfg.timeout_s).get("response")
        except (urllib.error.URLError, OSError, ValueError) as e:
            if self.cfg.failure_policy == "Ignore":
                return {"allowed": True}
            raise AdmissionDenied(f"{self.name}: {e}") from e
        if not isinstance(resp, dict):
            # missing/garbage envelope is a webhook FAILURE (fail-open under
            # Ignore), not a deny verdict
            if self.cfg.failure_policy == "Ignore":
                return {"allowed": True}
            raise AdmissionDenied(f"{self.name}: malformed AdmissionReview response")
        return resp

    def admit(self, attrs: Attributes) -> None:
        if not self.cfg.mutating or not self._matches(attrs):
            return
        resp = self._call(attrs)
        if not resp.get("allowed", False):
            raise AdmissionDenied(f"{self.name}: {resp.get('message', 'denied')}")
        if resp.get("object") is not None:
            from ..api.serialize import DecodeError, from_manifest

            try:
                attrs.obj = from_manifest(resp["object"])
            except DecodeError as e:
                # a webhook returning a malformed object is a webhook failure
                # like any other: classified by failurePolicy
                if self.cfg.failure_policy != "Ignore":
                    raise AdmissionDenied(f"{self.name}: bad mutated object: {e}") from e

    def validate(self, attrs: Attributes) -> None:
        if self.cfg.mutating or not self._matches(attrs):
            return
        resp = self._call(attrs)
        if not resp.get("allowed", False):
            raise AdmissionDenied(f"{self.name}: {resp.get('message', 'denied')}")


class AdmissionChain:
    """admission.NewChainHandler — all mutating admits, then all validates."""

    def __init__(self, plugins: List[AdmissionPlugin]):
        self.plugins = plugins

    @staticmethod
    def default(
        store: ClusterStore,
        policies: Optional[PolicyPlugin] = None,
        webhooks: Tuple[WebhookConfig, ...] = (),
    ) -> "AdmissionChain":
        plugins: List[AdmissionPlugin] = [
            NamespaceLifecycle(store),
            LimitRanger(store),
            PriorityAdmission(store),
            ResourceQuotaAdmission(store),
        ]
        if policies is not None:
            plugins.append(policies)
        # webhooks after in-tree plugins: mutating webhooks see in-tree
        # defaults applied; validating webhooks run in the validate pass
        plugins.extend(Webhook(w) for w in webhooks)
        return AdmissionChain(plugins)

    def run(self, attrs: Attributes) -> object:
        """-> the (possibly mutated) object; raises AdmissionDenied."""
        for p in self.plugins:
            p.admit(attrs)
        for p in self.plugins:
            p.validate(attrs)
        return attrs.obj
