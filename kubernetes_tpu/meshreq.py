"""Mesh-request parsing — deliberately IMPORT-LIGHT (stdlib only).

bench.py must size ``--xla_force_host_platform_device_count`` from the
KTPU_MESH request BEFORE anything can initialize the jax backend — and
with KTPU_COMPILE_CACHE_DIR configured, importing almost any kubernetes_tpu
module initializes it as a side effect (``kubernetes_tpu.parallel``'s
package init pulls ``ops.assign``, whose import-time ``tuned_knob`` calls
resolve the platform name).  This module therefore imports nothing but
``os`` and ``typing``: it is the one piece of the mesh layer that is safe
to import pre-backend.  ``parallel/mesh.py`` re-exports both functions, so
post-backend call sites keep their existing import paths.
"""

from __future__ import annotations

import os
from typing import Optional


def parse_mesh_request(
    raw: Optional[str] = None, source: str = "KTPU_MESH",
):
    """Parse the mesh-request knobs WITHOUT touching a jax backend (bench.py
    must size --xla_force_host_platform_device_count before first backend
    use).  Returns None (single device), an int (1-D node-axis count), or a
    (pods, nodes) tuple (2-D mesh).

    Accepted forms:
      KTPU_MESH=8          1-D, 8 node shards (the legacy form)
      KTPU_MESH=2x4        2-D, 2 pod shards x 4 node shards
      KTPU_MESH_PODS=2 KTPU_MESH_NODES=4   the explicit pair
      KTPU_MESH_PODS=2 KTPU_MESH=8         pods divides the total
    """
    pods_raw = os.environ.get("KTPU_MESH_PODS", "").strip() if raw is None else ""
    nodes_raw = os.environ.get("KTPU_MESH_NODES", "").strip() if raw is None else ""
    if raw is None:
        raw = os.environ.get("KTPU_MESH", "")
    raw = raw.strip()

    def _int(v: str, name: str) -> int:
        try:
            n = int(v)
        except ValueError:
            raise ValueError(
                f"{name}={v!r}: expected an integer (e.g. {name}=8), or "
                f"{source}=<pods>x<nodes> for a 2-D mesh"
            ) from None
        if n < 0:
            raise ValueError(f"{name}={n}: must be >= 0")
        return n

    if pods_raw:
        p = _int(pods_raw, "KTPU_MESH_PODS")
        if nodes_raw:
            n = _int(nodes_raw, "KTPU_MESH_NODES")
            if n == 0:
                raise ValueError("KTPU_MESH_NODES=0: node axis must be >= 1")
        elif raw and "x" not in raw.lower():
            total = _int(raw, source)
            if p == 0 or total % p:
                raise ValueError(
                    f"KTPU_MESH_PODS={p} does not divide {source}={total}"
                )
            n = total // p
        elif p <= 1:
            # KTPU_MESH_PODS<=1 with no nodes count carries no 2-D
            # request of its own — defer to the plain KTPU_MESH parse
            n = None
        else:
            # pods alone: a pod-only grid (p x 1) — one node shard per
            # pod row
            n = 1
        if n is not None:
            if p <= 1:
                return n if n > 1 else None
            return (p, max(1, n))
    if not raw:
        return None
    if "x" in raw.lower():
        parts = raw.lower().split("x")
        if len(parts) != 2:
            raise ValueError(
                f"{source}={raw!r}: a 2-D mesh is <pods>x<nodes> "
                f"(e.g. {source}=2x4)"
            )
        p = _int(parts[0], source)
        n = _int(parts[1], source)
        if n == 0:
            raise ValueError(
                f"{source}={raw!r}: the node axis must be >= 1"
            )
        if p <= 1:
            return n if n > 1 else None
        return (p, n)
    n = _int(raw, source)
    return n if n > 1 else None


def mesh_request_devices(req) -> int:
    """Total device count a parse_mesh_request result needs (1 for None)."""
    if req is None:
        return 1
    if isinstance(req, tuple):
        return int(req[0]) * int(req[1])
    return int(req)
