"""CPU reference scheduler — the parity oracle (L5).

A deliberately naive sequential reimplementation of the reference default
scheduler's semantics (pkg/scheduler/schedule_one.go — ScheduleOne: filter all
nodes, score, select host, assume, next pod), operating on the *object* model
(string label matching, per-node Python loops) rather than the encoded arrays —
so a parity test exercises the encoder AND the kernels end-to-end.

Two framework-level conventions shared with the TPU path (both documented
deviations from the reference, SURVEY.md §7 hard part 1):
  - deterministic tie-break: lowest node index among max-score nodes
    (reference selectHost randomizes among ties);
  - full scoring: no percentageOfNodesToScore sampling;
  - score arithmetic in float32, mirroring the kernels op-for-op.

Resource quantities go through the same int32 rescale as the encoder
(api/snapshot.py — _scale_for), which is part of framework semantics.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as t
from ..api import snapshot as snap_mod
from ..api.snapshot import Snapshot
from ..ops.scores import MAX_NODE_SCORE, ScoreConfig, DEFAULT_SCORE_CONFIG

f32 = np.float32


def _tolerates_all(pod: t.Pod, taints) -> bool:
    # reference: component-helpers scheduling/corev1 — FindMatchingUntoleratedTaint
    for taint in taints:
        if taint.effect == t.PREFER_NO_SCHEDULE:
            continue
        if not any(tol.tolerates(taint) for tol in pod.tolerations):
            return False
    return True


def _bf16r(x) -> f32:
    """Round a raw score onto the device's bf16 storage lattice
    (ops/bitplane.py — identity under the KTPU_SCORE_DTYPE=f32 hatch)."""
    from ..ops.bitplane import bf16_round_np

    return f32(bf16_round_np(np.float32(x)))


def _intolerable_prefer_count(pod: t.Pod, taints) -> int:
    return sum(
        1
        for taint in taints
        if taint.effect == t.PREFER_NO_SCHEDULE
        and not any(tol.tolerates(taint) for tol in pod.tolerations)
    )


def _node_taints(nd: t.Node):
    ts = list(nd.taints)
    if nd.unschedulable:
        ts.append(t.Taint(key="node.kubernetes.io/unschedulable", effect=t.NO_SCHEDULE))
    return ts


def _matches_term(term: t.NodeSelectorTerm, labels: Dict[str, str]) -> bool:
    # reference: component-helpers nodeaffinity — nodeSelectorTermMatches;
    # a null/empty term matches no objects
    if not term.match_expressions:
        return False
    for req in term.match_expressions:
        has, val = req.key in labels, labels.get(req.key)
        if req.operator == t.OP_IN:
            if not has or val not in req.values:
                return False
        elif req.operator == t.OP_NOT_IN:
            if has and val in req.values:
                return False
        elif req.operator == t.OP_EXISTS:
            if not has:
                return False
        elif req.operator == t.OP_DOES_NOT_EXIST:
            if has:
                return False
        elif req.operator in (t.OP_GT, t.OP_LT):
            try:
                x, bound = int(val), int(req.values[0])
            except (TypeError, ValueError, IndexError):
                return False
            if not ((x > bound) if req.operator == t.OP_GT else (x < bound)):
                return False
        else:
            raise ValueError(req.operator)
    return True


def _node_selection_ok(pod: t.Pod, node: t.Node) -> bool:
    for k, v in pod.node_selector:
        if node.labels.get(k) != v:
            return False
    if pod.affinity and pod.affinity.required_node_terms:
        return any(_matches_term(tm, node.labels) for tm in pod.affinity.required_node_terms)
    return True


def _least_allocated(requested: np.ndarray, alloc: np.ndarray, idx) -> f32:
    vals = []
    for j in idx:
        a, r = f32(alloc[j]), f32(requested[j])
        vals.append(max(f32(0.0), (a - r) * f32(MAX_NODE_SCORE) / a) if a > 0 else f32(0.0))
    return f32(np.mean(np.array(vals, dtype=f32)))


def _most_allocated(requested: np.ndarray, alloc: np.ndarray, idx) -> f32:
    # noderesources/most_allocated.go — mostResourceScorer: 0 when alloc == 0
    # OR requested exceeds alloc (no clamp — f32 op-for-op mirror of
    # ops/scores.most_allocated)
    vals = []
    for j in idx:
        a, r = f32(alloc[j]), f32(requested[j])
        if a > 0 and r <= a:
            vals.append(f32(r * f32(MAX_NODE_SCORE) / a))
        else:
            vals.append(f32(0.0))
    return f32(np.mean(np.array(vals, dtype=f32)))


def _interp_shape_f32(util: f32, shape) -> f32:
    # ONE explicit f32 op order — y0 + t*(y1-y0) — mirrored verbatim by
    # ops/scores.interp_shape_f32 and the C++ interp_shape, so all engines
    # agree bit-for-bit.  Clamps outside the shape.
    xs = [f32(p[0]) for p in shape]
    ys = [f32(p[1]) for p in shape]
    if util <= xs[0]:
        return ys[0]
    for i in range(1, len(xs)):
        if util <= xs[i]:
            t = f32(f32(util - xs[i - 1]) / f32(xs[i] - xs[i - 1]))
            return f32(ys[i - 1] + f32(t * f32(ys[i] - ys[i - 1])))
    return ys[-1]


def _rtcr(requested: np.ndarray, alloc: np.ndarray, idx, shape) -> f32:
    # noderesources/requested_to_capacity_ratio.go (mirror of
    # ops/scores.requested_to_capacity_ratio)
    vals = []
    for j in idx:
        a, r = f32(alloc[j]), f32(requested[j])
        if a > 0:
            util = f32(r * f32(100.0) / a)
        else:
            # capacity == 0: the reference's resourceScoringFunction returns
            # rawScoringFunction(maxUtilization) — the shape score at 100% —
            # not 0 (requested_to_capacity_ratio.go)
            util = f32(100.0)
        vals.append(
            f32(_interp_shape_f32(util, shape) * f32(MAX_NODE_SCORE / 10.0))
        )
    return f32(np.mean(np.array(vals, dtype=f32)))


def _fit_score(requested: np.ndarray, alloc: np.ndarray, idx, cfg) -> f32:
    strategy = getattr(cfg, "fit_strategy", "LeastAllocated")
    if strategy == "MostAllocated":
        return _most_allocated(requested, alloc, idx)
    if strategy == "RequestedToCapacityRatio":
        return _rtcr(requested, alloc, idx, cfg.rtcr_shape)
    if strategy != "LeastAllocated":
        raise ValueError(f"unknown fit scoringStrategy {strategy!r}")
    return _least_allocated(requested, alloc, idx)


def _balanced(requested: np.ndarray, alloc: np.ndarray, idx) -> f32:
    fs, cnt = [], 0
    for j in idx:
        if alloc[j] > 0:
            fs.append(min(f32(1.0), f32(requested[j]) / f32(alloc[j])))
            cnt += 1
        else:
            fs.append(f32(0.0))
    n = f32(max(1, cnt))
    f = np.array(fs, dtype=f32)
    mean = f32(f.sum() / n)
    var = f32(np.where(np.array([alloc[j] > 0 for j in idx]), (f - mean) ** 2, f32(0)).sum() / n)
    return f32((f32(1.0) - f32(np.sqrt(var))) * f32(MAX_NODE_SCORE))


def _term_matches_pod(sel: Optional[t.LabelSelector], namespaces, pod: t.Pod) -> bool:
    if sel is None:
        return False
    return pod.namespace in namespaces and sel.matches(pod.labels)


def _aff_namespaces(term: t.PodAffinityTerm, owner: t.Pod):
    return tuple(sorted(term.namespaces)) if term.namespaces else (owner.namespace,)


def _ports_conflict(pod: t.Pod, existing_on_node) -> bool:
    mine = set(pod.host_ports)
    if not mine:
        return False
    for q in existing_on_node:
        if mine & set(q.host_ports):
            return True
    return False


def _spread_eval(pod, nodes, node_ok_sel, existing, n):
    """Per DoNotSchedule constraint feasibility + summed match counts for the
    score, mirroring ops/pairwise.spread_step."""
    ok = True
    raw = f32(0.0)
    for c in pod.topology_spread:
        key = c.topology_key
        # counts per domain over keyed nodes
        counts: Dict[str, int] = {}
        for q, qn in existing:
            val = nodes[qn].labels.get(key)
            if val is not None and _term_matches_pod(c.label_selector, (pod.namespace,), q):
                counts[val] = counts.get(val, 0) + 1
        has_key = key in nodes[n].labels
        if has_key:
            raw = f32(raw + f32(counts.get(nodes[n].labels[key], 0)))
        if c.when_unsatisfiable != t.DO_NOT_SCHEDULE:
            continue
        # minMatch over domains containing >= 1 node passing the node filter
        elig_domains = set()
        for i, nd in enumerate(nodes):
            if node_ok_sel[i] and key in nd.labels:
                elig_domains.add(nd.labels[key])
        if not has_key:
            ok = False
            continue
        min_match = min((counts.get(d, 0) for d in elig_domains), default=0)
        if counts.get(nodes[n].labels[key], 0) + 1 - min_match > c.max_skew:
            ok = False
    return ok, raw


def _interpod_ok(pod, nodes, existing, n) -> bool:
    """Mirrors ops/pairwise.interpod_required_ok."""
    aff = pod.affinity
    nd = nodes[n]
    if aff:
        # required affinity
        terms = aff.required_pod_affinity
        if terms:
            all_ok = True
            total_any = 0
            self_all = True
            for term in terms:
                ns = _aff_namespaces(term, pod)
                matches_in_dom = 0
                anywhere = 0
                for q, qn in existing:
                    val = nodes[qn].labels.get(term.topology_key)
                    if val is None or not _term_matches_pod(term.label_selector, ns, q):
                        continue
                    anywhere += 1
                    if nd.labels.get(term.topology_key) == val:
                        matches_in_dom += 1
                total_any += anywhere
                if term.topology_key not in nd.labels or matches_in_dom == 0:
                    all_ok = False
                if not _term_matches_pod(term.label_selector, ns, pod):
                    self_all = False
            if not all_ok and not (total_any == 0 and self_all):
                return False
        # own required anti-affinity
        for term in aff.required_pod_anti_affinity:
            ns = _aff_namespaces(term, pod)
            val = nd.labels.get(term.topology_key)
            if val is None:
                continue
            for q, qn in existing:
                if nodes[qn].labels.get(term.topology_key) == val and _term_matches_pod(
                    term.label_selector, ns, q
                ):
                    return False
    # existing pods' anti-affinity vs this pod
    for q, qn in existing:
        if not (q.affinity and q.affinity.required_pod_anti_affinity):
            continue
        for term in q.affinity.required_pod_anti_affinity:
            val = nodes[qn].labels.get(term.topology_key)
            if val is None:
                continue
            if nd.labels.get(term.topology_key) != val:
                continue
            if _term_matches_pod(term.label_selector, _aff_namespaces(term, q), pod):
                return False
    return True


def _interpod_pref_raw(pod, nodes, existing, n, hard_w: float = 1.0) -> f32:
    """Mirrors ops/pairwise.interpod_pref_raw: own preferred terms vs existing
    pods (anti negative) + existing pods' preferred terms vs this pod +
    existing pods' REQUIRED affinity terms vs this pod at hardPodAffinityWeight
    (interpodaffinity/scoring.go — processExistingPod)."""
    nd = nodes[n]
    raw = f32(0.0)
    if pod.affinity:
        for wt, sign in [
            *[(x, 1.0) for x in pod.affinity.preferred_pod_affinity],
            *[(x, -1.0) for x in pod.affinity.preferred_pod_anti_affinity],
        ]:
            term = wt.term
            val = nd.labels.get(term.topology_key)
            if val is None:
                continue
            ns = _aff_namespaces(term, pod)
            for q, qn in existing:
                if nodes[qn].labels.get(term.topology_key) == val and _term_matches_pod(
                    term.label_selector, ns, q
                ):
                    raw = f32(raw + f32(sign * wt.weight))
    for q, qn in existing:
        if not q.affinity:
            continue
        for term, w in [
            *[(x.term, float(x.weight)) for x in q.affinity.preferred_pod_affinity],
            *[(x.term, -float(x.weight)) for x in q.affinity.preferred_pod_anti_affinity],
            *(
                [(x, float(hard_w)) for x in q.affinity.required_pod_affinity]
                if hard_w
                else []
            ),
        ]:
            qval = nodes[qn].labels.get(term.topology_key)
            if qval is None:
                continue
            if nd.labels.get(term.topology_key) != qval:
                continue
            if _term_matches_pod(term.label_selector, _aff_namespaces(term, q), pod):
                raw = f32(raw + f32(w))
    return raw


def _preferred_na_raw(pod, nd) -> f32:
    from ..ops.bitplane import bf16_round_np

    raw = f32(0.0)
    if pod.affinity:
        for pt in pod.affinity.preferred_node_terms:
            if pt.preference.match_expressions and _matches_term(pt.preference, nd.labels):
                raw = f32(raw + f32(pt.weight))
    # the device stores this raw plane on the bf16 lattice
    # (ops/assign.py — _preferred_node_affinity_raw quantizes at the
    # producer); round identically so normalization sees the same inputs
    return f32(bf16_round_np(raw))


def _image_score(pod: t.Pod, nd: t.Node) -> f32:
    from ..api.snapshot import image_score_value

    sum_mb = sum(nd.images[im] // (1024 * 1024) for im in pod.images if im in nd.images)
    return image_score_value(np.float32(sum_mb))


def oracle_schedule(
    snap: Snapshot,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    exclude: Optional[set] = None,
) -> List[Tuple[str, Optional[str]]]:
    """Sequentially schedule all pending pods; returns [(pod name, node name | None)]
    in activeQ order.  Pods whose uid is in `exclude` are skipped (used by the
    gang iteration — mirrors pod_valid masking on the device path)."""
    from ..api.volumes import resolve_snapshot

    snap = resolve_snapshot(snap)
    resources = snap_mod._resource_axis(snap)
    nodes = snap.nodes
    n = len(nodes)

    alloc_raw = np.zeros((n, len(resources)), dtype=np.int64)
    for i, nd in enumerate(nodes):
        for j, r in enumerate(resources):
            alloc_raw[i, j] = nd.allocatable.get(
                r, snap_mod._DEFAULT_POD_LIMIT if r == t.PODS else 0
            )
    used_raw = np.zeros((n, len(resources)), dtype=np.int64)
    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    for bp in snap.bound_pods:
        i = node_index.get(bp.node_name)
        if i is not None:
            used_raw[i] += np.array(
                snap_mod.pod_effective_requests(bp, resources), dtype=np.int64
            )
    order = snap_mod.activeq_order(snap.pending_pods)
    req_raw = np.array(
        [snap_mod.pod_effective_requests(snap.pending_pods[i], resources) for i in order],
        dtype=np.int64,
    ).reshape(len(order), len(resources))

    scale = np.ones(len(resources), dtype=np.int64)
    for j in range(len(resources)):
        scale[j] = snap_mod._scale_for(
            [int(x) for x in alloc_raw[:, j]]
            + [int(x) for x in req_raw[:, j]]
            + [int(x) for x in used_raw[:, j]]
        )
    alloc = alloc_raw // scale
    used = -(-used_raw // scale)
    reqs = -(-req_raw // scale)

    idx = list(cfg.score_resources)
    # running "existing pods" ledger: bound + committed (pod, node_index)
    existing: List[Tuple[t.Pod, int]] = [
        (bp, node_index[bp.node_name]) for bp in snap.bound_pods if bp.node_name in node_index
    ]
    existing_by_node: Dict[int, List[t.Pod]] = {}
    for q, qn in existing:
        existing_by_node.setdefault(qn, []).append(q)

    out: List[Tuple[str, Optional[str]]] = []
    for k, src_i in enumerate(order):
        pod = snap.pending_pods[src_i]
        if pod.scheduling_gates:  # held out of activeQ (SchedulingGates PreEnqueue)
            out.append((pod.name, None))
            continue
        if exclude and pod.uid in exclude:
            out.append((pod.name, None))
            continue
        req = reqs[k]
        node_ok_sel = [_node_selection_ok(pod, nd) for nd in nodes]
        feasible, pref_counts, spread_raws = [], {}, {}
        for i, nd in enumerate(nodes):
            taints = _node_taints(nd)
            if not _tolerates_all(pod, taints):
                continue
            if not node_ok_sel[i]:
                continue
            # nodeName pinning: a missing named node leaves every node infeasible
            if pod.node_name and node_index.get(pod.node_name) != i:
                continue
            # zero-request resources never block (reference fitsRequest skips them)
            if np.any((req > 0) & (used[i] + req > alloc[i])):
                continue
            if _ports_conflict(pod, existing_by_node.get(i, [])):
                continue
            spread_ok, spread_raw = _spread_eval(pod, nodes, node_ok_sel, existing, i)
            if not spread_ok:
                continue
            if not _interpod_ok(pod, nodes, existing, i):
                continue
            feasible.append(i)
            # bf16-lattice mirror of the device's stored taint counts
            # (ops/scores.py — taint_prefer_counts quantizes at the producer)
            pref_counts[i] = _bf16r(_intolerable_prefer_count(pod, taints))
            spread_raws[i] = spread_raw
        if not feasible:
            out.append((pod.name, None))
            continue
        max_pref = f32(max(pref_counts[i] for i in feasible))
        na_raws = {i: _preferred_na_raw(pod, nodes[i]) for i in feasible}
        max_na = f32(max(na_raws.values()))
        max_spread = f32(max(spread_raws.values()))
        ip_raws = {
            i: _interpod_pref_raw(
                pod, nodes, existing, i, cfg.hard_pod_affinity_weight
            )
            for i in feasible
        }
        ip_max, ip_min = f32(max(ip_raws.values())), f32(min(ip_raws.values()))
        best_i, best_s = -1, -np.inf
        for i in feasible:
            requested = used[i] + req
            taint_sc = (
                f32(MAX_NODE_SCORE) - f32(MAX_NODE_SCORE) * f32(pref_counts[i]) / max_pref
                if max_pref > 0
                else f32(MAX_NODE_SCORE)
            )
            na_sc = f32(na_raws[i] * f32(MAX_NODE_SCORE) / max_na) if max_na > 0 else f32(0.0)
            spread_sc = (
                f32(MAX_NODE_SCORE) - f32(MAX_NODE_SCORE) * spread_raws[i] / max_spread
                if max_spread > 0
                else f32(MAX_NODE_SCORE)
            )
            s = (
                f32(cfg.fit_weight) * _fit_score(requested, alloc[i], idx, cfg)
                + f32(cfg.balanced_weight) * _balanced(requested, alloc[i], idx)
                + f32(cfg.taint_weight) * taint_sc
                + f32(cfg.node_affinity_weight) * na_sc
                + f32(cfg.spread_weight) * spread_sc
                + f32(cfg.interpod_weight)
                * (
                    f32(f32(MAX_NODE_SCORE) * (ip_raws[i] - ip_min) / (ip_max - ip_min))
                    if ip_max > ip_min
                    else f32(0.0)
                )
                + f32(cfg.image_weight) * _image_score(pod, nodes[i])
            )
            if s > best_s:
                best_s, best_i = s, i
        used[best_i] += req
        existing.append((pod, best_i))
        existing_by_node.setdefault(best_i, []).append(pod)
        out.append((pod.name, nodes[best_i].name))
    return out


def oracle_schedule_with_gangs(
    snap: Snapshot, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG
) -> List[Tuple[str, Optional[str]]]:
    """Gang-aware oracle: iterate, revoking groups that miss minMember, until
    fixpoint — the same rule as ops/gang.schedule_with_gangs."""
    groups: Dict[str, List[t.Pod]] = {}
    for pod in snap.pending_pods:
        if pod.pod_group:
            groups.setdefault(pod.pod_group, []).append(pod)
    min_member = {
        g: (snap.pod_groups[g].min_member if g in snap.pod_groups else len(pods))
        for g, pods in groups.items()
    }
    order = snap_mod.activeq_order(snap.pending_pods)
    queue_rank = {snap.pending_pods[src].uid: k for k, src in enumerate(order)}
    excluded: set = set()
    while True:
        res = oracle_schedule(snap, cfg, exclude=excluded)
        placed = {name for name, node in res if node is not None}
        failed = []
        for g, pods in groups.items():
            active = [p for p in pods if p.uid not in excluded]
            if not active:
                continue
            if sum(1 for p in active if p.name in placed) < min_member[g]:
                failed.append(min(queue_rank[p.uid] for p in active))
        if not failed:
            return res
        # revoke only the failed group earliest in activeQ order, then retry
        first_rank = min(failed)
        first_uid = snap.pending_pods[order[first_rank]].pod_group
        excluded |= {p.uid for p in groups[first_uid] if p.uid not in excluded}
