from .reference import oracle_schedule  # noqa: F401
