// Native sequential commit engine — the C++ half of the framework's runtime.
//
// The reference's performance-critical surface is native Go (the 16-goroutine
// Filter/Score fan-out in pkg/scheduler/framework/parallelize); this file is
// the TPU framework's equivalent for the CPU fallback path: the same
// sequential one-pod-at-a-time semantics as ops/assign.py's lax.scan, over the
// already-encoded columnar snapshot (api/snapshot.py — ClusterArrays), at
// C speed instead of per-pod Python plugin dispatch.
//
// Float32 score arithmetic mirrors the XLA kernels op-for-op (same
// associativity, no FMA — build with -ffp-contract=off), so native, TPU and
// oracle paths return bit-identical decisions.
//
// Build: g++ -O2 -shared -fPIC -ffp-contract=off -o libnative_sched.so scheduler.cpp
// Loaded via ctypes (kubernetes_tpu/native/__init__.py); no pybind11 in image.

#include <cstdint>
#include <cmath>
#include <cstring>
#include <vector>
#include <limits>

namespace {

const float MAXS = 100.0f;

struct View {
  // dims
  int32_t N, P, R, T, K, D1, C, A1, A2, PT, B;
  // nodes
  const int32_t *alloc;     // [N,R]
  int32_t *used;            // [N,R] in/out
  const int32_t *node_dom;  // [K,N]
  uint8_t *ports_used;      // [N,PT] in/out
  // pods
  const int32_t *req;       // [P,R]
  const uint8_t *sf;        // [P,N] static feasibility
  const float *pref;        // [P,N] or null (PreferNoSchedule counts)
  const float *na_raw;      // [P,N] or null (preferred node affinity raw)
  const uint8_t *pod_valid; // [P]
  const uint8_t *nodesel;   // [P,N] or null (spread eligibility)
  const uint8_t *pod_ports; // [P,PT] or null
  // pairwise tables (null when disabled)
  const int32_t *term_key;  // [T]
  const float *m_pend;      // [T,P]
  float *counts;            // [T,D1] in/out
  float *anti_counts;       // [T,D1] in/out
  const int32_t *aff_terms;    // [P,A1]
  const int32_t *anti_terms;   // [P,A2]
  const int32_t *spread_terms; // [P,C]
  const int32_t *spread_skew;  // [P,C]
  const uint8_t *spread_hard;  // [P,C]
  const float *img;            // [P,N] ImageLocality static scores or null
  const int32_t *pref_t;       // [P,B] preferred interpod term ids or null
  const float *pref_w;         // [P,B] signed weights
  float *pref_own;             // [T,D1] in/out
  // config
  float w_fit, w_bal, w_taint, w_na, w_spread, w_img, w_interpod;
  float w_hard;  // hardPodAffinityWeight: committed required-affinity terms
  int32_t r0, r1;  // scored resource indices
  uint8_t enable_pairwise, enable_ports, enable_taint, enable_na, enable_img,
      enable_ip;
  // NodeResourcesFit scoringStrategy: 0 LeastAllocated, 1 MostAllocated,
  // 2 RequestedToCapacityRatio (shape points interpolated like np.interp)
  int32_t fit_strategy;
  int32_t n_shape;            // number of rtcr shape points (<= 8)
  float shape_x[8], shape_y[8];
};

inline float least_alloc(const int32_t *alloc_row, const int64_t *req_tot,
                         int r0, int r1) {
  float v0, v1;
  {
    float a = (float)alloc_row[r0], r = (float)req_tot[r0];
    v0 = a > 0.f ? std::fmax(0.0f, (a - r) * MAXS / a) : 0.0f;
  }
  {
    float a = (float)alloc_row[r1], r = (float)req_tot[r1];
    v1 = a > 0.f ? std::fmax(0.0f, (a - r) * MAXS / a) : 0.0f;
  }
  return (v0 + v1) / 2.0f;  // mean over the two scored resources
}

inline float most_alloc(const int32_t *alloc_row, const int64_t *req_tot,
                        int r0, int r1) {
  // most_allocated.go: 0 when alloc == 0 OR requested > alloc (no clamp)
  float v0, v1;
  {
    float a = (float)alloc_row[r0], r = (float)req_tot[r0];
    v0 = (a > 0.f && r <= a) ? r * MAXS / a : 0.0f;
  }
  {
    float a = (float)alloc_row[r1], r = (float)req_tot[r1];
    v1 = (a > 0.f && r <= a) ? r * MAXS / a : 0.0f;
  }
  return (v0 + v1) / 2.0f;
}

inline float interp_shape(float util, const float *xs, const float *ys,
                          int n) {
  // clamp outside, linear inside.  STRICT > on the upper clamp: at
  // util == xs[n-1] the JAX kernel (interp_shape_f32) and the oracle fall
  // through to the segment formula ys[n-2] + t*(ys[n-1]-ys[n-2]), which in
  // float32 does not round-trip to ys[n-1] for many y-pairs — early-returning
  // here would break three-engine bit-parity at exact-fit utilization.
  if (n <= 0) return 0.0f;
  if (util <= xs[0]) return ys[0];
  if (util > xs[n - 1]) return ys[n - 1];
  for (int i = 1; i < n; i++) {
    if (util <= xs[i]) {
      float t = (util - xs[i - 1]) / (xs[i] - xs[i - 1]);
      return ys[i - 1] + t * (ys[i] - ys[i - 1]);
    }
  }
  return ys[n - 1];
}

inline float rtcr(const int32_t *alloc_row, const int64_t *req_tot, int r0,
                  int r1, const float *xs, const float *ys, int n_shape) {
  // capacity == 0: the reference's resourceScoringFunction returns
  // rawScoringFunction(maxUtilization) — the shape score at 100% — not 0
  // (requested_to_capacity_ratio.go); mirrored by all engines.  This runs
  // once per node in the scoring hot loop, so utilization is folded to
  // 100 for the zero-capacity case instead of branching to a precomputed
  // constant.
  float v0, v1;
  {
    float a = (float)alloc_row[r0], r = (float)req_tot[r0];
    float util = a > 0.f ? r * 100.0f / a : 100.0f;
    v0 = interp_shape(util, xs, ys, n_shape) * (MAXS / 10.0f);
  }
  {
    float a = (float)alloc_row[r1], r = (float)req_tot[r1];
    float util = a > 0.f ? r * 100.0f / a : 100.0f;
    v1 = interp_shape(util, xs, ys, n_shape) * (MAXS / 10.0f);
  }
  return (v0 + v1) / 2.0f;
}

inline float fit_score_strategy(const View *v, const int32_t *alloc_row,
                                const int64_t *req_tot) {
  if (v->fit_strategy == 1) return most_alloc(alloc_row, req_tot, v->r0, v->r1);
  if (v->fit_strategy == 2)
    return rtcr(alloc_row, req_tot, v->r0, v->r1, v->shape_x, v->shape_y,
                v->n_shape);
  return least_alloc(alloc_row, req_tot, v->r0, v->r1);
}

inline float balanced(const int32_t *alloc_row, const int64_t *req_tot,
                      int r0, int r1) {
  float f[2];
  bool present[2];
  int idx[2] = {r0, r1};
  int cnt = 0;
  for (int j = 0; j < 2; j++) {
    float a = (float)alloc_row[idx[j]];
    present[j] = a > 0.f;
    f[j] = present[j] ? std::fmin(1.0f, (float)req_tot[idx[j]] / a) : 0.0f;
    if (present[j]) cnt++;
  }
  float n = (float)(cnt > 0 ? cnt : 1);
  float mean = (f[0] + f[1]) / n;
  float var = 0.f;
  for (int j = 0; j < 2; j++)
    if (present[j]) { float d = f[j] - mean; var += d * d; }
  var = var / n;
  return (1.0f - std::sqrt(var)) * MAXS;
}

}  // namespace

extern "C" int schedule_native(const View *v, int32_t *choices) {
  const int N = v->N, P = v->P, R = v->R, T = v->T, K = v->K, D1 = v->D1;
  const int D = D1 - 1;
  std::vector<int64_t> req_tot(R);
  std::vector<uint8_t> feasible(N);
  std::vector<float> spread_raw(v->enable_pairwise ? N : 0);
  std::vector<float> agg;  // [K, D1] per-pod symmetric-anti aggregation
  if (v->enable_pairwise) agg.resize((size_t)K * D1);
  std::vector<float> ip_raw(v->enable_ip ? N : 0);
  std::vector<float> agg_pref;  // [K, D1] symmetric preferred aggregation
  if (v->enable_ip) agg_pref.resize((size_t)K * D1);

  for (int p = 0; p < P; p++) {
    choices[p] = -1;
    if (!v->pod_valid[p]) continue;
    const int32_t *req = v->req + (size_t)p * R;
    const uint8_t *sf = v->sf + (size_t)p * N;

    // ---- pairwise per-pod precomputation ----
    float min_match[8];  // per spread constraint (C <= 8 enforced by wrapper)
    float total_any = 0.f;
    bool self_all = true, has_aff = false;
    if (v->enable_pairwise) {
      const uint8_t *elig = v->nodesel + (size_t)p * N;
      for (int c = 0; c < v->C; c++) {
        int t = v->spread_terms[(size_t)p * v->C + c];
        if (t < 0) { min_match[c] = 0.f; continue; }
        int k = v->term_key[t];
        const int32_t *dom = v->node_dom + (size_t)k * N;
        float mn = std::numeric_limits<float>::infinity();
        for (int n = 0; n < N; n++) {
          if (elig[n] && dom[n] < D) {
            float cval = v->counts[(size_t)t * D1 + dom[n]];
            if (cval < mn) mn = cval;
          }
        }
        min_match[c] = std::isinf(mn) ? 0.f : mn;
      }
      for (int a = 0; a < v->A1; a++) {
        int t = v->aff_terms[(size_t)p * v->A1 + a];
        if (t < 0) continue;
        has_aff = true;
        const float *row = v->counts + (size_t)t * D1;
        for (int d = 0; d < D; d++) total_any += row[d];
        if (!(v->m_pend[(size_t)t * P + p] > 0.f)) self_all = false;
      }
      // symmetric anti aggregation: agg[k][d] = sum_t(key==k) m[t,p]*anti[t][d]
      std::memset(agg.data(), 0, agg.size() * sizeof(float));
      for (int t = 0; t < T; t++) {
        float m = v->m_pend[(size_t)t * P + p];
        if (m == 0.f) continue;
        float *dst = agg.data() + (size_t)v->term_key[t] * D1;
        const float *src = v->anti_counts + (size_t)t * D1;
        for (int d = 0; d < D; d++) dst[d] += m * src[d];  // column D excluded
      }
      if (v->enable_ip) {
        std::memset(agg_pref.data(), 0, agg_pref.size() * sizeof(float));
        for (int t = 0; t < T; t++) {
          float m = v->m_pend[(size_t)t * P + p];
          if (m == 0.f) continue;
          float *dst = agg_pref.data() + (size_t)v->term_key[t] * D1;
          const float *src = v->pref_own + (size_t)t * D1;
          for (int d = 0; d < D; d++) dst[d] += m * src[d];
        }
      }
    }
    bool waiver = has_aff && total_any == 0.f && self_all;

    // ---- pass A: feasibility (+ raw spread score), maxima over feasible ----
    float max_pref = 0.f, max_na = 0.f, max_spread = 0.f;
    float ip_max = -std::numeric_limits<float>::infinity();
    float ip_min = std::numeric_limits<float>::infinity();
    bool any_feasible = false;
    for (int n = 0; n < N; n++) {
      bool ok = sf[n];
      if (ok) {
        const int32_t *al = v->alloc + (size_t)n * R;
        const int32_t *us = v->used + (size_t)n * R;
        for (int r = 0; r < R && ok; r++)
          if (req[r] != 0 && req[r] > al[r] - us[r]) ok = false;
      }
      if (ok && v->enable_ports) {
        const uint8_t *pp = v->pod_ports + (size_t)p * v->PT;
        const uint8_t *np_ = v->ports_used + (size_t)n * v->PT;
        for (int q = 0; q < v->PT && ok; q++)
          if (pp[q] && np_[q]) ok = false;
      }
      float raw = 0.f;
      if (v->enable_pairwise) {
        // spread
        for (int c = 0; c < v->C; c++) {
          int t = v->spread_terms[(size_t)p * v->C + c];
          if (t < 0) continue;
          int k = v->term_key[t];
          int d = v->node_dom[(size_t)k * N + n];
          bool has_key = d < D;
          float cval = v->counts[(size_t)t * D1 + d];
          if (has_key) raw += cval;
          if (v->spread_hard[(size_t)p * v->C + c]) {
            if (!has_key ||
                cval + 1.0f - min_match[c] >
                    (float)v->spread_skew[(size_t)p * v->C + c])
              ok = false;
          }
        }
        if (ok) {
          // required affinity
          bool all_ok = true;
          for (int a = 0; a < v->A1; a++) {
            int t = v->aff_terms[(size_t)p * v->A1 + a];
            if (t < 0) continue;
            int d = v->node_dom[(size_t)v->term_key[t] * N + n];
            if (d >= D || !(v->counts[(size_t)t * D1 + d] > 0.f)) all_ok = false;
          }
          if (!all_ok && !waiver) ok = false;
          // own anti
          for (int a = 0; a < v->A2 && ok; a++) {
            int t = v->anti_terms[(size_t)p * v->A2 + a];
            if (t < 0) continue;
            int d = v->node_dom[(size_t)v->term_key[t] * N + n];
            if (d < D && v->counts[(size_t)t * D1 + d] > 0.f) ok = false;
          }
          // existing pods' anti vs this pod
          if (ok) {
            float blocked = 0.f;
            for (int k = 0; k < K; k++) {
              int d = v->node_dom[(size_t)k * N + n];
              if (d < D) blocked += agg[(size_t)k * D1 + d];
            }
            if (blocked != 0.f) ok = false;
          }
        }
        spread_raw[n] = raw;
      }
      if (v->enable_ip) {
        // own preferred terms + existing pods' preferred terms toward p
        float r2 = 0.f;
        for (int b = 0; b < v->B; b++) {
          int t = v->pref_t[(size_t)p * v->B + b];
          if (t < 0) continue;
          int d = v->node_dom[(size_t)v->term_key[t] * N + n];
          if (d < D)
            r2 += v->pref_w[(size_t)p * v->B + b] * v->counts[(size_t)t * D1 + d];
        }
        for (int k = 0; k < K; k++) {
          int d = v->node_dom[(size_t)k * N + n];
          if (d < D) r2 += agg_pref[(size_t)k * D1 + d];
        }
        ip_raw[n] = r2;
      }
      feasible[n] = ok;
      if (ok) {
        any_feasible = true;
        if (v->enable_taint) {
          float c = v->pref[(size_t)p * N + n];
          if (c > max_pref) max_pref = c;
        }
        if (v->enable_na) {
          float c = v->na_raw[(size_t)p * N + n];
          if (c > max_na) max_na = c;
        }
        if (v->enable_pairwise && raw > max_spread) max_spread = raw;
        if (v->enable_ip) {
          if (ip_raw[n] > ip_max) ip_max = ip_raw[n];
          if (ip_raw[n] < ip_min) ip_min = ip_raw[n];
        }
      }
    }
    if (!any_feasible) continue;

    // ---- pass B: scores + first-max selection ----
    float best = -std::numeric_limits<float>::infinity();
    int best_n = -1;
    for (int n = 0; n < N; n++) {
      if (!feasible[n]) continue;
      const int32_t *al = v->alloc + (size_t)n * R;
      const int32_t *us = v->used + (size_t)n * R;
      for (int r = 0; r < R; r++) req_tot[r] = (int64_t)us[r] + req[r];
      float total = v->w_fit * fit_score_strategy(v, al, req_tot.data()) +
                    v->w_bal * balanced(al, req_tot.data(), v->r0, v->r1);
      if (v->enable_taint) {
        float c = v->pref[(size_t)p * N + n];
        float sc = max_pref > 0.f ? MAXS - MAXS * c / max_pref : MAXS;
        total = total + v->w_taint * sc;
      }
      if (v->enable_na) {
        float c = v->na_raw[(size_t)p * N + n];
        float sc = max_na > 0.f ? c * MAXS / max_na : 0.0f;
        total = total + v->w_na * sc;
      }
      if (v->enable_pairwise) {
        float sc = max_spread > 0.f ? MAXS - MAXS * spread_raw[n] / max_spread : MAXS;
        total = total + v->w_spread * sc;
      }
      if (v->enable_ip) {
        float sc = ip_max > ip_min
                       ? MAXS * (ip_raw[n] - ip_min) / (ip_max - ip_min)
                       : 0.0f;
        total = total + v->w_interpod * sc;
      }
      if (v->enable_img)
        total = total + v->w_img * v->img[(size_t)p * N + n];
      if (total > best) { best = total; best_n = n; }
    }
    if (best_n < 0) continue;
    choices[p] = best_n;

    // ---- commit ----
    int32_t *us = v->used + (size_t)best_n * R;
    for (int r = 0; r < R; r++) us[r] += req[r];
    if (v->enable_ports) {
      const uint8_t *pp = v->pod_ports + (size_t)p * v->PT;
      uint8_t *np_ = v->ports_used + (size_t)best_n * v->PT;
      for (int q = 0; q < v->PT; q++) np_[q] |= pp[q];
    }
    if (v->enable_pairwise) {
      for (int t = 0; t < T; t++) {
        float m = v->m_pend[(size_t)t * P + p];
        if (m != 0.f) {
          int d = v->node_dom[(size_t)v->term_key[t] * N + best_n];
          v->counts[(size_t)t * D1 + d] += m;
        }
      }
      for (int a = 0; a < v->A2; a++) {
        int t = v->anti_terms[(size_t)p * v->A2 + a];
        if (t < 0) continue;
        int d = v->node_dom[(size_t)v->term_key[t] * N + best_n];
        v->anti_counts[(size_t)t * D1 + d] += 1.0f;
      }
      if (v->enable_ip) {
        for (int b = 0; b < v->B; b++) {
          int t = v->pref_t[(size_t)p * v->B + b];
          if (t < 0) continue;
          int d = v->node_dom[(size_t)v->term_key[t] * N + best_n];
          v->pref_own[(size_t)t * D1 + d] += v->pref_w[(size_t)p * v->B + b];
        }
        if (v->w_hard != 0.f) {
          // committed pod's REQUIRED affinity terms at hardPodAffinityWeight
          for (int a = 0; a < v->A1; a++) {
            int t = v->aff_terms[(size_t)p * v->A1 + a];
            if (t < 0) continue;
            int d = v->node_dom[(size_t)v->term_key[t] * N + best_n];
            v->pref_own[(size_t)t * D1 + d] += v->w_hard;
          }
        }
      }
    }
  }
  return 0;
}
