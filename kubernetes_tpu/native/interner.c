/* C fast path for SpecInterner.group (api/snapshot.py) — the per-pod
 * identity-profile level of the two-level interning.
 *
 * The Python loop costs ~4us/pod (tuple build + dict ops) and dominates the
 * steady-state wave encode at 50k pods (~205ms measured).  This C pass reads
 * the 13 profile fields per pod straight out of the instance __dict__
 * (borrowed refs, interned key strings), hashes the raw pointers into a
 * persistent open-addressing table, and assigns per-call canonical ids in
 * first-occurrence order — bit-identical grouping to the Python loop.
 *
 * Aliasing safety mirrors the Python version's convention (snapshot.py —
 * SpecInterner docstring): every inserted entry holds a strong reference to
 * its pod, so the field objects behind the stored pointers stay alive and a
 * recycled address can never alias a live entry.  Mutating a cached pod's
 * fields in place violates the repo-wide copy-on-write convention in both
 * implementations.
 *
 * Loaded with ctypes.PyDLL (GIL held across calls — required: every function
 * here manipulates Python objects).  The value-level slow path (sorted
 * canonical keys for never-seen profiles) stays in Python.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define NFIELDS 13

static const char *FIELD_NAMES[NFIELDS] = {
    /* pointer-profile of every field _pod_spec_key reads; value-typed
     * fields (namespace/priority/...) profile by object pointer too — a
     * different-object-equal-value miss just takes the Python slow path,
     * which computes the canonical key and maps it to the same key-id */
    "requests",     "labels",          "namespace",  "node_name",
    "priority",     "tolerations",     "node_selector", "affinity",
    "topology_spread", "host_ports",   "scheduling_gates", "pod_group",
    "images",
};

typedef struct {
    void *ptrs[NFIELDS];
    int64_t keyid;   /* -1 = empty slot */
    PyObject *pin;   /* strong ref keeping the profile's pointers alive */
} Entry;

typedef struct {
    Entry *slots;
    size_t cap;      /* power of two */
    size_t count;
    size_t n_prov;   /* unresolved provisional entries (see lookup) */
    size_t n_forced; /* identity-unstable forced misses in the LAST lookup:
                      * these bypass the pointer table entirely, so the
                      * caller polls this to latch such workloads onto the
                      * Python value-level path instead of paying the
                      * per-pod slow path every batch */
    PyObject *names[NFIELDS]; /* interned field-name strings */
} Interner;

static uint64_t profile_hash(void *const ptrs[NFIELDS]) {
    uint64_t h = 1469598103934665603ull; /* FNV-1a over the pointer words */
    for (int i = 0; i < NFIELDS; i++) {
        h ^= (uint64_t)(uintptr_t)ptrs[i];
        h *= 1099511628211ull;
        h ^= h >> 29;
    }
    return h;
}

static int profile_eq(const Entry *e, void *const ptrs[NFIELDS]) {
    return memcmp(e->ptrs, ptrs, sizeof(void *) * NFIELDS) == 0;
}

static int grow(Interner *in, size_t mincap) {
    size_t ncap = in->cap ? in->cap : 1024;
    while (ncap < mincap * 2) ncap <<= 1;
    Entry *ns = (Entry *)calloc(ncap, sizeof(Entry));
    if (!ns) return -1;
    for (size_t i = 0; i < ncap; i++) ns[i].keyid = -1;
    for (size_t i = 0; i < in->cap; i++) {
        Entry *e = &in->slots[i];
        if (e->keyid == -1) continue; /* provisional entries migrate too */
        size_t j = profile_hash(e->ptrs) & (ncap - 1);
        while (ns[j].keyid != -1) j = (j + 1) & (ncap - 1);
        ns[j] = *e;
    }
    free(in->slots);
    in->slots = ns;
    in->cap = ncap;
    return 0;
}

/* Read the profile pointers of one pod.  Returns 0 on success, 1 when the
 * profile is NOT identity-stable — a field had to be read through
 * PyObject_GetAttr, whose result can be a per-read temporary (property /
 * computed attribute): after Py_DECREF its address may be recycled by a
 * DIFFERENT object in the same batch, so storing it as an identity key
 * could falsely merge two pods with different specs.  Such pods are routed
 * to the Python value-level slow path instead (the plain-dataclass fast
 * path never takes this branch).  Returns -1 with a Python error set. */
static int read_profile(Interner *in, PyObject *pod, void *ptrs[NFIELDS]) {
    PyObject **dictp = _PyObject_GetDictPtr(pod);
    if (dictp && *dictp) {
        for (int f = 0; f < NFIELDS; f++) {
            PyObject *v = PyDict_GetItemWithError(*dictp, in->names[f]);
            if (!v) {
                if (PyErr_Occurred()) return -1;
                /* field missing from __dict__ (slots/property/odd
                 * subclass): confirm the attribute exists, then force the
                 * Python slow path — a GetAttr temporary's address is not
                 * an identity */
                v = PyObject_GetAttr(pod, in->names[f]);
                if (!v) return -1;
                Py_DECREF(v);
                return 1;
            }
            ptrs[f] = (void *)v; /* borrowed */
        }
        return 0;
    }
    /* no instance __dict__ at all (slots-backed type): every GetAttr result
     * may be a temporary — same hazard, same slow-path routing */
    return 1;
}

/* exported API (ctypes.PyDLL) ------------------------------------------- */

void *interner_new(void) {
    Interner *in = (Interner *)calloc(1, sizeof(Interner));
    if (!in) return NULL;
    for (int f = 0; f < NFIELDS; f++) {
        in->names[f] = PyUnicode_InternFromString(FIELD_NAMES[f]);
        if (!in->names[f]) return NULL;
    }
    return in;
}

void interner_clear(void *h) {
    Interner *in = (Interner *)h;
    for (size_t i = 0; i < in->cap; i++) {
        if (in->slots[i].keyid != -1) Py_CLEAR(in->slots[i].pin);
        in->slots[i].keyid = -1;
    }
    in->count = 0;
    in->n_prov = 0;
}

void interner_free(void *h) {
    Interner *in = (Interner *)h;
    interner_clear(in);
    free(in->slots);
    for (int f = 0; f < NFIELDS; f++) Py_CLEAR(in->names[f]);
    free(in);
}

int64_t interner_count(void *h) { return (int64_t)((Interner *)h)->count; }

/* Pass 1: out_keyid[i] = persistent key-id (>= 0), or a PROVISIONAL marker
 * -(m)-2 where m is the miss ordinal.  Each UNIQUE missing profile is
 * appended to miss_idx once and inserted provisionally right away, so
 * intra-batch duplicates (the common case: one spec, thousands of pods)
 * resolve to the first occurrence's marker instead of each taking the
 * Python slow path.  The first-occurrence pod is pinned immediately.
 * Returns n_miss (unique misses), or -1 with a Python error set. */
int64_t interner_lookup(void *h, PyObject *pods, int64_t *out_keyid,
                        int64_t *miss_idx) {
    Interner *in = (Interner *)h;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (in->n_prov) {
        /* a previous batch died between lookup and insert (Python slow
         * path raised); its markers would alias this batch's.  Crash-only:
         * drop the table — every profile re-misses and re-resolves through
         * the caller's persistent spec-key registry, so grouping is
         * unaffected. */
        interner_clear(in);
    }
    if (in->cap < (size_t)(in->count + n) * 2 && grow(in, in->count + n) < 0) {
        PyErr_NoMemory();
        return -1;
    }
    int64_t n_miss = 0;
    in->n_forced = 0;
    void *ptrs[NFIELDS];
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);
        int rc = read_profile(in, pod, ptrs);
        if (rc < 0) return -1;
        if (rc == 1) {
            /* identity-unstable profile: forced miss, NOT inserted into the
             * pointer table (no provisional entry, no pin) — the Python
             * slow path resolves it by canonical value key */
            in->n_forced++;
            out_keyid[i] = -n_miss - 2;
            miss_idx[n_miss++] = i;
            continue;
        }
        size_t j = profile_hash(ptrs) & (in->cap - 1);
        while (in->slots[j].keyid != -1) {
            if (profile_eq(&in->slots[j], ptrs)) break;
            j = (j + 1) & (in->cap - 1);
        }
        if (in->slots[j].keyid != -1) {
            out_keyid[i] = in->slots[j].keyid; /* hit or earlier provisional */
        } else {
            memcpy(in->slots[j].ptrs, ptrs, sizeof(ptrs));
            in->slots[j].keyid = -n_miss - 2; /* provisional marker */
            Py_INCREF(pod);
            in->slots[j].pin = pod;
            in->count++;
            in->n_prov++;
            out_keyid[i] = -n_miss - 2;
            miss_idx[n_miss++] = i;
        }
    }
    return n_miss;
}

/* Resolve the provisional entries from this batch: pods[idx[k]] (the first
 * occurrence of unique miss k) gets persistent key-id kid[k]. */
int interner_insert(void *h, PyObject *pods, const int64_t *idx,
                    const int64_t *kid, int64_t n_ins) {
    Interner *in = (Interner *)h;
    if (in->cap < (size_t)(in->count + n_ins) * 2 &&
        grow(in, in->count + n_ins) < 0) {
        PyErr_NoMemory();
        return -1;
    }
    void *ptrs[NFIELDS];
    for (int64_t k = 0; k < n_ins; k++) {
        PyObject *pod = PyList_GET_ITEM(pods, idx[k]);
        int rc = read_profile(in, pod, ptrs);
        if (rc < 0) return -1;
        if (rc == 1) continue; /* forced miss: nothing provisional to resolve */
        size_t j = profile_hash(ptrs) & (in->cap - 1);
        while (in->slots[j].keyid != -1) {
            if (profile_eq(&in->slots[j], ptrs)) {
                if (in->slots[j].keyid < -1) in->n_prov--;
                in->slots[j].keyid = kid[k];
                break;
            }
            j = (j + 1) & (in->cap - 1);
        }
        if (in->slots[j].keyid == -1) {
            /* identity-unstable profile (e.g. a property returning a fresh
             * object per read): insert re-read different pointers than
             * lookup stored.  Store a usable entry under the re-read
             * pointers; the orphaned provisional marker keeps n_prov > 0,
             * which the caller observes via interner_prov and uses to fall
             * back to the Python path rather than thrash. */
            memcpy(in->slots[j].ptrs, ptrs, sizeof(ptrs));
            in->slots[j].keyid = kid[k];
            Py_INCREF(pod);
            in->slots[j].pin = pod;
            in->count++;
        }
    }
    return 0;
}

int64_t interner_prov(void *h) { return (int64_t)((Interner *)h)->n_prov; }

int64_t interner_forced(void *h) { return (int64_t)((Interner *)h)->n_forced; }

/* Pass 2: per-call canonical ids in first-occurrence order.
 * keyid[i] >= 0 for all i.  percall must hold max_kid+1 slots, pre-filled
 * with -1.  Writes inv[i] and rep_idx (first-occurrence pod index per rep);
 * returns n_reps. */
int64_t interner_canonicalize(const int64_t *keyid, int64_t n,
                              int64_t *percall, int64_t *inv,
                              int64_t *rep_idx) {
    int64_t n_reps = 0;
    for (int64_t i = 0; i < n; i++) {
        int64_t kid = keyid[i];
        int64_t cid = percall[kid];
        if (cid < 0) {
            cid = n_reps++;
            percall[kid] = cid;
            rep_idx[cid] = i;
        }
        inv[i] = cid;
    }
    return n_reps;
}
