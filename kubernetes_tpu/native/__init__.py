"""Native (C++) sequential commit engine — ctypes loader + wrapper.

Builds scheduler.cpp on first use (g++, -ffp-contract=off so float math stays
bit-identical to the XLA kernels' f32 semantics) and exposes

    schedule_batch_native(arr, cfg) -> (choices i32[P], used i32[N, R])
    schedule_with_gangs_native(arr, cfg) -> same, honoring PodGroups

decision-parity-tested against both the jitted path and the oracle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from ..api.snapshot import ClusterArrays
from ..ops.scores import ScoreConfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "scheduler.cpp")
_SO = os.path.join(_DIR, "libnative_sched.so")
_lib = None


class _View(ctypes.Structure):
    _fields_ = [
        ("N", ctypes.c_int32), ("P", ctypes.c_int32), ("R", ctypes.c_int32),
        ("T", ctypes.c_int32), ("K", ctypes.c_int32), ("D1", ctypes.c_int32),
        ("C", ctypes.c_int32), ("A1", ctypes.c_int32), ("A2", ctypes.c_int32),
        ("PT", ctypes.c_int32), ("B", ctypes.c_int32),
        ("alloc", ctypes.c_void_p), ("used", ctypes.c_void_p),
        ("node_dom", ctypes.c_void_p), ("ports_used", ctypes.c_void_p),
        ("req", ctypes.c_void_p), ("sf", ctypes.c_void_p),
        ("pref", ctypes.c_void_p), ("na_raw", ctypes.c_void_p),
        ("pod_valid", ctypes.c_void_p), ("nodesel", ctypes.c_void_p),
        ("pod_ports", ctypes.c_void_p),
        ("term_key", ctypes.c_void_p), ("m_pend", ctypes.c_void_p),
        ("counts", ctypes.c_void_p), ("anti_counts", ctypes.c_void_p),
        ("aff_terms", ctypes.c_void_p), ("anti_terms", ctypes.c_void_p),
        ("spread_terms", ctypes.c_void_p), ("spread_skew", ctypes.c_void_p),
        ("spread_hard", ctypes.c_void_p), ("img", ctypes.c_void_p),
        ("pref_t", ctypes.c_void_p), ("pref_w", ctypes.c_void_p),
        ("pref_own", ctypes.c_void_p),
        ("w_fit", ctypes.c_float), ("w_bal", ctypes.c_float),
        ("w_taint", ctypes.c_float), ("w_na", ctypes.c_float),
        ("w_spread", ctypes.c_float), ("w_img", ctypes.c_float),
        ("w_interpod", ctypes.c_float), ("w_hard", ctypes.c_float),
        ("r0", ctypes.c_int32), ("r1", ctypes.c_int32),
        ("enable_pairwise", ctypes.c_uint8), ("enable_ports", ctypes.c_uint8),
        ("enable_taint", ctypes.c_uint8), ("enable_na", ctypes.c_uint8),
        ("enable_img", ctypes.c_uint8), ("enable_ip", ctypes.c_uint8),
        # NodeResourcesFit scoringStrategy (0 Least, 1 Most, 2 RTCR) + shape
        ("fit_strategy", ctypes.c_int32), ("n_shape", ctypes.c_int32),
        ("shape_x", ctypes.c_float * 8), ("shape_y", ctypes.c_float * 8),
    ]


def _strategy_code(cfg) -> int:
    codes = {"LeastAllocated": 0, "MostAllocated": 1,
             "RequestedToCapacityRatio": 2}
    code = codes.get(cfg.fit_strategy)
    if code is None:
        raise ValueError(f"unknown fit scoringStrategy {cfg.fit_strategy!r}")
    if code == 2 and len(cfg.rtcr_shape) > 8:
        # the View struct carries at most 8 points; silent truncation would
        # diverge from the kernels, so refuse loudly
        raise ValueError("rtcr shape supports at most 8 points")
    return code


def _build() -> str:
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-ffp-contract=off",
             "-o", _SO, _SRC],
            check=True, capture_output=True,
        )
    return _SO


def _load():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(_build())
        _lib.schedule_native.restype = ctypes.c_int
        _lib.schedule_native.argtypes = [ctypes.POINTER(_View), ctypes.c_void_p]
    return _lib


def _ptr(a: Optional[np.ndarray]):
    return a.ctypes.data_as(ctypes.c_void_p) if a is not None else None


def schedule_batch_native(
    arr: ClusterArrays, cfg: ScoreConfig
) -> Tuple[np.ndarray, np.ndarray]:
    from .static_np import preferred_na_raw, static_feasible, taint_prefer_counts

    lib = _load()
    if arr.pod_spread_terms.shape[1] > 8:
        raise ValueError("native engine supports at most 8 spread constraints per pod")
    sf, nodesel, tm = static_feasible(arr)
    nodesel = (nodesel & arr.node_valid[None, :].astype(np.uint8)).astype(np.uint8)
    pref = (
        np.ascontiguousarray(taint_prefer_counts(arr)) if cfg.enable_taint_score else None
    )
    na = np.ascontiguousarray(preferred_na_raw(arr, tm)) if cfg.enable_node_pref else None
    enable_img = cfg.enable_image and arr.image_score.shape[1] == arr.N
    img = np.ascontiguousarray(arr.image_score.astype(np.float32)) if enable_img else None

    used = np.ascontiguousarray(arr.node_used.astype(np.int32)).copy()
    counts = np.ascontiguousarray(arr.term_counts0.astype(np.float32)).copy()
    anti = np.ascontiguousarray(arr.anti_counts0.astype(np.float32)).copy()
    pref_own = np.ascontiguousarray(arr.pref_own0.astype(np.float32)).copy()
    ports_used = np.ascontiguousarray(arr.node_ports0.astype(np.uint8)).copy()
    choices = np.full(arr.P, -1, dtype=np.int32)

    c = lambda a, dt: np.ascontiguousarray(a.astype(dt))
    keep = dict(  # keep references alive across the C call
        alloc=c(arr.node_alloc, np.int32), req=c(arr.pod_req, np.int32),
        sf=sf, nodesel=nodesel, pod_valid=c(arr.pod_valid, np.uint8),
        node_dom=c(arr.node_dom, np.int32), term_key=c(arr.term_key, np.int32),
        m_pend=c(arr.m_pend, np.float32),
        aff=c(arr.pod_aff_terms, np.int32), anti_t=c(arr.pod_anti_terms, np.int32),
        st=c(arr.pod_spread_terms, np.int32), sk=c(arr.pod_spread_maxskew, np.int32),
        sh=c(arr.pod_spread_hard, np.uint8), pp=c(arr.pod_ports, np.uint8),
        pt=c(arr.pod_pref_aff_terms, np.int32), pw=c(arr.pod_pref_aff_w, np.float32),
    )
    view = _View(
        N=arr.N, P=arr.P, R=arr.R,
        T=arr.term_key.shape[0], K=arr.node_dom.shape[0],
        D1=arr.term_counts0.shape[1],
        C=arr.pod_spread_terms.shape[1], A1=arr.pod_aff_terms.shape[1],
        A2=arr.pod_anti_terms.shape[1], PT=arr.pod_ports.shape[1],
        B=arr.pod_pref_aff_terms.shape[1],
        alloc=_ptr(keep["alloc"]), used=_ptr(used),
        node_dom=_ptr(keep["node_dom"]), ports_used=_ptr(ports_used),
        req=_ptr(keep["req"]), sf=_ptr(keep["sf"]),
        pref=_ptr(pref), na_raw=_ptr(na),
        pod_valid=_ptr(keep["pod_valid"]), nodesel=_ptr(keep["nodesel"]),
        pod_ports=_ptr(keep["pp"]),
        term_key=_ptr(keep["term_key"]), m_pend=_ptr(keep["m_pend"]),
        counts=_ptr(counts), anti_counts=_ptr(anti),
        aff_terms=_ptr(keep["aff"]), anti_terms=_ptr(keep["anti_t"]),
        spread_terms=_ptr(keep["st"]), spread_skew=_ptr(keep["sk"]),
        spread_hard=_ptr(keep["sh"]), img=_ptr(img),
        pref_t=_ptr(keep["pt"]), pref_w=_ptr(keep["pw"]), pref_own=_ptr(pref_own),
        w_fit=cfg.fit_weight, w_bal=cfg.balanced_weight,
        w_taint=cfg.taint_weight, w_na=cfg.node_affinity_weight,
        w_spread=cfg.spread_weight, w_img=cfg.image_weight,
        w_interpod=cfg.interpod_weight,
        w_hard=cfg.hard_pod_affinity_weight,
        r0=cfg.score_resources[0], r1=cfg.score_resources[1],
        enable_pairwise=int(cfg.enable_pairwise), enable_ports=int(cfg.enable_ports),
        enable_taint=int(cfg.enable_taint_score), enable_na=int(cfg.enable_node_pref),
        enable_img=int(enable_img),
        enable_ip=int(cfg.enable_pairwise and cfg.enable_interpod_score),
        fit_strategy=_strategy_code(cfg),
        n_shape=len(cfg.rtcr_shape),
        shape_x=(ctypes.c_float * 8)(*[p[0] for p in cfg.rtcr_shape]),
        shape_y=(ctypes.c_float * 8)(*[p[1] for p in cfg.rtcr_shape]),
    )
    rc = lib.schedule_native(ctypes.byref(view), _ptr(choices))
    if rc != 0:
        raise RuntimeError(f"native scheduler failed rc={rc}")
    return choices, used


def schedule_with_gangs_native(
    arr: ClusterArrays, cfg: ScoreConfig
) -> Tuple[np.ndarray, np.ndarray]:
    """Gang fixpoint (ops/gang.py semantics) over the native engine."""
    import dataclasses

    from ..ops.gang import failed_groups

    pod_valid = np.asarray(arr.pod_valid).copy()
    while True:
        arr_i = dataclasses.replace(arr, pod_valid=pod_valid)
        choices, used = schedule_batch_native(arr_i, cfg)
        pod_group = np.asarray(arr.pod_group)
        bad = failed_groups(choices, pod_group, np.asarray(arr.group_min), active=pod_valid)
        if not bad.any():
            return choices, used
        in_bad = bad[np.maximum(pod_group, 0)] & (pod_group >= 0) & pod_valid
        first_g = pod_group[int(np.argmax(in_bad))]
        pod_valid = pod_valid & ~((pod_group == first_g) & pod_valid)
