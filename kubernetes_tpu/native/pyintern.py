"""ctypes loader for the C identity-profile interner (interner.c).

Built on demand with the running interpreter's headers and loaded with
ctypes.PyDLL — NOT CDLL: every exported function manipulates Python objects,
so the GIL must stay held across calls.  Python symbols are left undefined in
the .so and resolve against the process at dlopen time; if anything in the
chain fails (no compiler, unresolved symbols), callers fall back to the
pure-Python SpecInterner loop — behavior is identical either way, only the
per-pod constant differs (~4us -> ~0.5us measured at 50k pods).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "interner.c")
_SO = os.path.join(_DIR, "libinterner.so")

_lib = None
_tried = False


def _build() -> None:
    inc = sysconfig.get_paths()["include"]
    # compile to a private temp path, then publish atomically: a concurrent
    # loader must never dlopen a partially written .so (it would latch the
    # slow path for its whole lifetime)
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "gcc", "-O2", "-fPIC", "-shared", f"-I{inc}", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.rename(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load() -> Optional[ctypes.PyDLL]:
    """The loaded library, building it first if needed; None on any failure."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
            _SRC
        ):
            _build()
        lib = ctypes.PyDLL(_SO)
        lib.interner_new.restype = ctypes.c_void_p
        lib.interner_new.argtypes = []
        lib.interner_free.argtypes = [ctypes.c_void_p]
        lib.interner_clear.argtypes = [ctypes.c_void_p]
        lib.interner_count.restype = ctypes.c_int64
        lib.interner_count.argtypes = [ctypes.c_void_p]
        lib.interner_prov.restype = ctypes.c_int64
        lib.interner_prov.argtypes = [ctypes.c_void_p]
        lib.interner_forced.restype = ctypes.c_int64
        lib.interner_forced.argtypes = [ctypes.c_void_p]
        lib.interner_lookup.restype = ctypes.c_int64
        lib.interner_lookup.argtypes = [
            ctypes.c_void_p, ctypes.py_object,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.interner_insert.restype = ctypes.c_int
        lib.interner_insert.argtypes = [
            ctypes.c_void_p, ctypes.py_object,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.interner_canonicalize.restype = ctypes.c_int64
        lib.interner_canonicalize.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib
