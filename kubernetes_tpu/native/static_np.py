"""NumPy mirror of the kernels' static (batch-wide) stage, feeding the native
C++ engine.  Every array it produces is exact-integer-valued in f32 (counts of
matches / weights), so native and XLA paths see bitwise-identical inputs.
"""

from __future__ import annotations

import numpy as np

from ..api import vocab as v
from ..api.snapshot import ClusterArrays


def term_match(sel_mask: np.ndarray, sel_kind: np.ndarray, node_labels: np.ndarray) -> np.ndarray:
    counts = np.einsum("sel,nl->sen", sel_mask, node_labels)
    kind = sel_kind[:, :, None]
    ok = np.where(
        kind == v.KIND_ANY,
        counts > 0,
        np.where(kind == v.KIND_NONE, counts == 0, kind == v.KIND_PAD),
    )
    return ok.all(axis=1)  # [S, N]


def static_feasible(arr: ClusterArrays):
    """(sf [P,N] u8, nodesel [P,N] u8, tm [S,N]) — mirror of ops/filters."""
    tm = term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)
    ids = np.maximum(arr.pod_terms, 0)
    per_term = tm[ids] & (arr.pod_terms >= 0)[:, :, None]
    nodesel = np.where(arr.pod_has_sel[:, None], per_term.any(axis=1), True)
    intolerable = np.einsum(
        "pt,nt->pn",
        (~arr.pod_tol_ns).astype(np.float32),
        arr.node_taint_ns.astype(np.float32),
    )
    pin = arr.pod_nodename[:, None]
    n_idx = np.arange(arr.N, dtype=np.int32)[None, :]
    nodename_ok = np.where(pin == -1, True, pin == n_idx)
    sf = (
        arr.node_valid[None, :]
        & arr.pod_valid[:, None]
        & (intolerable == 0)
        & nodesel
        & nodename_ok
    )
    return sf.astype(np.uint8), nodesel.astype(np.uint8), tm


def taint_prefer_counts(arr: ClusterArrays) -> np.ndarray:
    from ..ops.bitplane import bf16_round_np

    # bf16-lattice mirror of the device producer (ops/scores.py)
    return bf16_round_np(np.einsum(
        "pt,nt->pn",
        (~arr.pod_tol_pref).astype(np.float32),
        arr.node_taint_pref.astype(np.float32),
    ))


def preferred_na_raw(arr: ClusterArrays, tm: np.ndarray) -> np.ndarray:
    P, PW = arr.pod_pref_terms.shape
    S = tm.shape[0]
    ids = np.maximum(arr.pod_pref_terms, 0)
    w = np.where(arr.pod_pref_terms >= 0, arr.pod_pref_weights, 0.0).astype(np.float32)
    from ..ops.bitplane import bf16_round_np

    W = np.zeros((P, S), dtype=np.float32)
    np.add.at(W, (np.arange(P)[:, None], ids), w)
    # bf16-lattice mirror of the device producer (ops/assign.py)
    return bf16_round_np(W @ tm.astype(np.float32))
