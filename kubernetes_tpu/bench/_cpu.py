"""The one place that defeats sitecustomize's platform override.

This machine's sitecustomize force-registers the TPU PJRT plugin and
overwrites jax.config.jax_platforms at interpreter start, so the
JAX_PLATFORMS env var ALONE is ignored — and with the tunnel down, first
backend use hangs indefinitely instead of raising.  Every bench CLI calls
this before its first jit; keeping the convention single-sourced means the
next sitecustomize change is a one-file fix instead of a hunt for silently
hanging benches.
"""

from __future__ import annotations

import os


def force_cpu_from_env(always: bool = False) -> bool:
    """Re-assert the CPU platform IN-PROCESS when JAX_PLATFORMS=cpu is set
    (or unconditionally with always=True).  Returns True if forced.  Must
    run before first backend use; mutates os.environ and jax config."""
    if not always and os.environ.get("JAX_PLATFORMS") != "cpu":
        return False
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
