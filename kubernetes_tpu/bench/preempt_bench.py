"""Batched-preemption benchmark (round-3 verdict item: "a perf number for
~1k failed pods x 20k nodes replacing the per-pod loop").

Builds a saturated cluster — every node full of low-priority victims — then
submits a wave of high-priority preemptors than can only schedule by
evicting.  The batch path fails the wave's fit, and the failure loop runs
victim search through scheduler/preemption.py — BatchedPreemption (device
kernels) instead of the per-pod CPU PostFilter.

The CPU evaluator at this scale was measured >24 s/pod in round 3
(BENCH_MATRIX_r03.json — preemption.cpu_evaluator_bound), so only the
batched number is taken at full scale; decision parity with the CPU
evaluator is proven separately at small scale by
tests/test_preemption_batched.py's randomized suite.

Usage: python -m kubernetes_tpu.bench.preempt_bench [n_nodes] [n_preemptors]
Prints one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

from ._cpu import force_cpu_from_env
from ..api import types as t
from ..scheduler.config import SchedulerConfiguration
from ..scheduler.scheduler import Scheduler
from ..scheduler.store import ClusterStore


def build(n_nodes: int, n_pre: int):
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(
            t.Node(
                name=f"node-{i}",
                allocatable={t.CPU: 4000, t.MEMORY: 16 << 30, t.PODS: 16},
                labels={t.LABEL_ZONE: f"zone-{i % 9}"},
            )
        )
    # saturate: two 2000m low-priority pods per node — a 2000m preemptor
    # schedules by evicting exactly one of them
    for i in range(n_nodes):
        for j in range(2):
            store.add_pod(
                t.Pod(
                    name=f"low-{i}-{j}",
                    requests={t.CPU: 2000, t.MEMORY: 4 << 30},
                    priority=0,
                    node_name=f"node-{i}",
                    labels={"app": "filler"},
                )
            )
    for k in range(n_pre):
        store.add_pod(
            t.Pod(
                name=f"hi-{k}",
                requests={t.CPU: 2000, t.MEMORY: 2 << 30},
                priority=100,
                labels={"app": "hi"},
            )
        )
    return store


def _parity_cross_check(n_nodes: int = 50, n_pre: int = 12) -> bool:
    """Reduced-scale decisions parity embedded in the artifact (round-4
    verdict weak #5): the batched/wave path vs the CPU evaluator on the
    bench's own workload shape — same nominations, same survivors.  The
    randomized suite (tests/test_preemption_batched.py) is the full proof;
    this keeps the bench row self-certifying."""
    results = []
    for gates in ((), (("BatchedPreemption", False),)):
        store = build(n_nodes, n_pre)
        sched = Scheduler(
            store, SchedulerConfiguration(mode="tpu", feature_gates=gates)
        )
        sched.run_until_idle()
        results.append((
            sorted(
                (p.name, p.nominated_node_name)
                for p in store.list_pods()
                if p.labels.get("app") == "hi"
            ),
            sorted(
                p.name for p in store.list_pods()
                if p.labels.get("app") == "filler"
            ),
        ))
    return results[0] == results[1]


def main() -> None:
    force_cpu_from_env()
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    n_pre = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000
    parity_ok = _parity_cross_check()
    t0 = time.perf_counter()
    store = build(n_nodes, n_pre)
    t_setup = time.perf_counter() - t0
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    t0 = time.perf_counter()
    sched.run_until_idle()
    wall = time.perf_counter() - t0
    nominated = sum(
        1 for p in store.list_pods() if p.nominated_node_name
    )
    # one "Preempted" event per successful preemptION; victims counted as
    # fillers actually removed from the store
    preemptions = len(sched.events.by_reason("Preempted"))
    victims = 2 * n_nodes - sum(
        1 for p in store.list_pods() if p.labels.get("app") == "filler"
    )
    print(
        json.dumps(
            {
                "metric": "batched_preemption_wall",
                "n_nodes": n_nodes,
                "n_preemptors": n_pre,
                "setup_s": round(t_setup, 2),
                "wall_s": round(wall, 3),
                "per_preemptor_ms": round(wall * 1e3 / max(1, n_pre), 2),
                "nominated": nominated,
                "preemptions": preemptions,
                "victims_evicted": victims,
                "decisions_parity_vs_cpu_evaluator_small_scale": parity_ok,
                "unit": "s",
            }
        )
    )


if __name__ == "__main__":
    main()
