"""Open-loop load observatory: arrival-trace generation + replay.

The headline ROADMAP metric is `pod_scheduling_sli_duration_seconds` p99
under OPEN-LOOP load — the reference never stops the world (activeQ +
watch semantics), so a load generator that waits for the scheduler before
sending the next pod (closed-loop) measures the wrong thing.  This module
provides the missing half of the measurement plane:

  - **Traces** (`ArrivalTrace`): a seeded, deterministic sequence of
    arrival events, serialized as replayable JSON.  Three named bursty
    scenarios ship built-in (`SCENARIOS`): `rollout` (deployment-rollout
    ramp — geometric surge batches over a Poisson background), `drain`
    (node-drain wave — a burst train of evicted pods re-arriving mid-run)
    and `storm` (scale-to-zero storm — idle trickle, then one
    instantaneous burst, then trailing Poisson).  Same trace + same seed
    → identical arrival sequences, byte for byte.

  - **Replay** (`replay_trace`): feeds a trace open-loop against a real
    Scheduler with a COORDINATED-OMISSION-SAFE clock: every pod's SLI age
    is stamped from the *trace* arrival timestamp via
    `queue.stamp_arrival`, never from the instant the replay loop got
    around to injecting it — so a stalled cycle inflates p99 honestly
    instead of silently shrinking the measured backlog.  The default
    pacing is VIRTUAL (no sleeps): the replay clock advances one quantum
    per scheduling cycle and the queue's injectable FakeClock advances
    with it, so backoff maturation — and therefore every scheduling
    decision — is bit-reproducible across replays (`decision_crc`).
    `KTPU_OPEN_LOOP_PACE=real` sleeps to the trace timeline instead
    (`KTPU_OPEN_LOOP_SPEED` scales it) for wall-clock soak runs.

  - **Attribution** (`sli_attribution` / `render_attribution_table`):
    which phase owns the p99 — per-phase p99 shares over the
    `pod_sli_phase_duration_seconds{phase=...}` decomposition the
    scheduler observes at bind publication, the K worst pods' phase
    vectors, and a Perfetto export of those pods' full span timelines
    (`export_sli_exemplars`).

Knobs: KTPU_OPEN_LOOP_QUANTUM_MS (replay cycle quantum, default 250),
KTPU_OPEN_LOOP_PACE (virtual|real), KTPU_OPEN_LOOP_SPEED (real-pace
multiplier), KTPU_OPEN_LOOP_SCALE (scenario size multiplier),
KTPU_OPEN_LOOP_SEED (scenario seed for the named CLI path),
KTPU_OPEN_LOOP_EXEMPLARS (worst-K, read by the scheduler),
KTPU_ADMIT_WATERMARK / KTPU_ADMIT_MAX_PARK_S (the overload admission
valve threaded over the replay's arrival stream —
scheduler/flowcontrol.AdmissionValve).
"""

from __future__ import annotations

import json
import os
import random
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

MILLI = 1000
GI = 1024 ** 3

TRACE_VERSION = 1

# the weighted spot sizes real workloads request (same palette as
# bench/workloads.py — keeps open-loop pods encodable into the identical
# equivalence classes the closed-loop benches exercise)
_CPU_CHOICES = (100, 250, 500, 1000)
_MEM_MB_CHOICES = (128, 256, 512, 1024)


@dataclass
class ArrivalEvent:
    """One pod arrival: trace-relative time + the pod's resource shape."""

    t: float
    name: str
    cpu_m: int
    mem_mb: int
    priority: int = 0

    def to_json(self) -> dict:
        return {
            "t": round(self.t, 6),
            "name": self.name,
            "cpu_m": self.cpu_m,
            "mem_mb": self.mem_mb,
            "priority": self.priority,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ArrivalEvent":
        return cls(
            t=float(doc["t"]),
            name=str(doc["name"]),
            cpu_m=int(doc["cpu_m"]),
            mem_mb=int(doc["mem_mb"]),
            priority=int(doc.get("priority", 0)),
        )


@dataclass
class ArrivalTrace:
    """A replayable open-loop arrival trace (seeded, deterministic)."""

    name: str
    scenario: str
    seed: int
    nodes: int
    duration_s: float
    events: List[ArrivalEvent] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "version": TRACE_VERSION,
            "name": self.name,
            "scenario": self.scenario,
            "seed": self.seed,
            "nodes": self.nodes,
            "duration_s": round(self.duration_s, 6),
            "events": [e.to_json() for e in self.events],
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ArrivalTrace":
        v = int(doc.get("version", TRACE_VERSION))
        if v > TRACE_VERSION:
            raise ValueError(f"trace version {v} is newer than {TRACE_VERSION}")
        return cls(
            name=str(doc["name"]),
            scenario=str(doc.get("scenario", doc["name"])),
            seed=int(doc.get("seed", 0)),
            nodes=int(doc["nodes"]),
            duration_s=float(doc["duration_s"]),
            events=[ArrivalEvent.from_json(e) for e in doc["events"]],
        )

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path

    @classmethod
    def load(cls, path: str) -> "ArrivalTrace":
        with open(path) as f:
            return cls.from_json(json.load(f))

    def fingerprint(self) -> str:
        """crc32 over the canonical serialization — two generations (or a
        save/load round-trip) with identical arrival sequences fingerprint
        identically."""
        blob = json.dumps(self.to_json(), sort_keys=True).encode()
        return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


# --- arrival-shape primitives ---

def poisson_arrivals(rng: random.Random, rate: float,
                     t0: float, t1: float) -> List[float]:
    """Homogeneous Poisson arrivals at `rate`/s over [t0, t1) —
    exponential inter-arrival gaps from the seeded rng."""
    out: List[float] = []
    if rate <= 0.0:
        return out
    t = t0
    while True:
        t += rng.expovariate(rate)
        if t >= t1:
            return out
        out.append(t)


def burst_train(t0: float, bursts: int, size: int, spacing: float,
                jitter: float = 0.0,
                rng: Optional[random.Random] = None) -> List[float]:
    """`bursts` bursts of `size` near-simultaneous arrivals, `spacing`
    seconds apart, each arrival jittered by U[0, jitter) — the node-drain
    / controller-resync shape (a wave per drained node)."""
    out: List[float] = []
    for b in range(bursts):
        base = t0 + b * spacing
        for _ in range(size):
            dt = rng.uniform(0.0, jitter) if (rng is not None and jitter > 0) else 0.0
            out.append(base + dt)
    return out


def _mk_events(scenario: str, rng: random.Random,
               times: List[float], priorities: Optional[List[int]] = None
               ) -> List[ArrivalEvent]:
    """Times -> named events in chronological order.  Resource shapes are
    drawn from the seeded rng AFTER sorting, so the (time, shape) pairing
    — and hence every downstream scheduling decision — is a pure function
    of (scenario, seed)."""
    order = sorted(range(len(times)), key=lambda i: times[i])
    events = []
    for k, i in enumerate(order):
        events.append(ArrivalEvent(
            t=round(times[i], 6),
            name=f"{scenario}-{k:05d}",
            cpu_m=rng.choice(_CPU_CHOICES),
            mem_mb=rng.choice(_MEM_MB_CHOICES),
            priority=(priorities[i] if priorities is not None else 0),
        ))
    return events


def _scale() -> float:
    try:
        return max(0.01, float(os.environ.get("KTPU_OPEN_LOOP_SCALE", "1")))
    except ValueError:
        return 1.0


# --- the three named scenarios ---

def rollout_trace(seed: int = 0, scale: Optional[float] = None) -> ArrivalTrace:
    """Deployment-rollout ramp: geometric surge batches (a controller
    scaling up replicas wave by wave) over a light Poisson background —
    the load grows faster than a fixed-rate generator would ever drive."""
    scale = _scale() if scale is None else scale
    rng = random.Random(seed)
    times: List[float] = []
    for k in range(10):  # surge wave k: ~3 * 1.4^k pods at t = 0.6k
        size = max(1, round(3 * (1.4 ** k) * scale))
        times.extend(burst_train(0.6 * k, 1, size, 0.0, jitter=0.08, rng=rng))
    times.extend(poisson_arrivals(rng, 4.0 * scale, 0.0, 6.0))
    return ArrivalTrace(
        name=f"rollout-s{seed}", scenario="rollout", seed=seed,
        nodes=max(4, round(24 * min(1.0, scale))), duration_s=6.0,
        events=_mk_events("rollout", rng, times),
    )


def drain_trace(seed: int = 0, scale: Optional[float] = None) -> ArrivalTrace:
    """Node-drain wave: steady Poisson background, then four node-sized
    eviction bursts back to back at t=4 — the drained pods re-arrive at
    elevated priority (they were running; their controllers recreate them
    ahead of new work)."""
    scale = _scale() if scale is None else scale
    rng = random.Random(seed)
    bg = poisson_arrivals(rng, 10.0 * scale, 0.0, 8.0)
    per_node = max(1, round(25 * scale))
    drain = burst_train(4.0, 4, per_node, 0.3, jitter=0.05, rng=rng)
    times = bg + drain
    prios = [0] * len(bg) + [100] * len(drain)
    return ArrivalTrace(
        name=f"drain-s{seed}", scenario="drain", seed=seed,
        nodes=max(4, round(24 * min(1.0, scale))), duration_s=8.0,
        events=_mk_events("drain", rng, times, prios),
    )


def storm_trace(seed: int = 0, scale: Optional[float] = None) -> ArrivalTrace:
    """Scale-to-zero storm: near-idle trickle, then EVERYTHING arrives in
    one instant (a serverless platform waking a scaled-to-zero fleet),
    then a trailing Poisson of stragglers.  The largest shipped trace —
    tier-1 exercises it only under the `slow` marker."""
    scale = _scale() if scale is None else scale
    rng = random.Random(seed)
    trickle = poisson_arrivals(rng, 1.0 * scale, 0.0, 6.0)
    burst = burst_train(6.0, 1, max(1, round(600 * scale)), 0.0)
    tail = poisson_arrivals(rng, 5.0 * scale, 6.0, 10.0)
    return ArrivalTrace(
        name=f"storm-s{seed}", scenario="storm", seed=seed,
        nodes=max(4, round(32 * min(1.0, scale))), duration_s=10.0,
        events=_mk_events("storm", rng, trickle + burst + tail),
    )


SCENARIOS = {
    "rollout": rollout_trace,
    "drain": drain_trace,
    "storm": storm_trace,
}


def load_or_build_trace(spec: str, seed: Optional[int] = None) -> ArrivalTrace:
    """`spec` is a named scenario (rollout|drain|storm; seeded by
    KTPU_OPEN_LOOP_SEED unless `seed` given) or a path to a trace JSON."""
    if spec in SCENARIOS:
        if seed is None:
            try:
                seed = int(os.environ.get("KTPU_OPEN_LOOP_SEED", "0"))
            except ValueError:
                seed = 0
        return SCENARIOS[spec](seed=seed)
    if os.path.exists(spec):
        return ArrivalTrace.load(spec)
    raise ValueError(
        f"unknown trace {spec!r}: not a named scenario "
        f"({'|'.join(sorted(SCENARIOS))}) and no such file"
    )


# --- replay ---

def _mk_nodes(n: int):
    from ..api import types as t

    return [
        t.Node(
            name=f"node-{i}",
            allocatable={t.CPU: 32 * MILLI, t.MEMORY: 128 * GI, t.PODS: 110},
            labels={t.LABEL_ZONE: f"zone-{i % 3}"},
        )
        for i in range(n)
    ]


def _mk_pod(ev: ArrivalEvent):
    from ..api import types as t

    return t.Pod(
        name=ev.name,
        requests={t.CPU: ev.cpu_m, t.MEMORY: ev.mem_mb * 1024 ** 2},
        priority=ev.priority,
    )


def phase_stats(metrics) -> Dict[str, dict]:
    """Per-phase (p50_ms, p99_ms, count, p99_share) over the
    pod_sli_phase_duration_seconds decomposition.  Shares are each phase's
    fraction of the summed per-phase p99s — they sum to ~1.0 by
    construction, and because a pod's phases telescope exactly to its SLI,
    the dominant share genuinely names the window that owns the tail."""
    from ..scheduler.metrics import SLI_PHASES

    out: Dict[str, dict] = {}
    p99s: Dict[str, float] = {}
    for ph in SLI_PHASES:
        p50, p99, count = metrics.labeled_hist(
            "pod_sli_phase_duration_seconds", phase=ph
        ).stats()
        out[ph] = {
            "p50_ms": round(p50 * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3),
            "count": count,
        }
        p99s[ph] = p99
    total = sum(p99s.values())
    for ph in SLI_PHASES:
        out[ph]["p99_share"] = round(p99s[ph] / total, 4) if total > 0 else 0.0
    return out


def sli_attribution(metrics, sched) -> dict:
    """The --sli-attribution block: per-phase shares + dominant phase +
    the worst-K exemplar pods' phase vectors."""
    phases = phase_stats(metrics)
    dominant = max(phases, key=lambda ph: phases[ph]["p99_share"])
    return {
        "phases": phases,
        "dominant_phase": dominant,
        "worst_pods": sched.worst_sli_pods(),
        "exemplar_export": None,  # stamped by the harness after export
    }


def replay_trace(
    trace: ArrivalTrace,
    mode: str = "tpu",
    collector=None,
    quantum_s: Optional[float] = None,
    pace: Optional[str] = None,
    max_barren_cycles: int = 64,
):
    """Replay `trace` open-loop against a fresh Scheduler; returns
    (artifact dict, scheduler).

    Each replay cycle injects every event whose trace time is due, stamps
    its coordinated-omission-safe arrival (see module docstring), runs the
    production cycle driver once (`run_until_idle` — the deferred-commit
    pipeline engages exactly as in a streaming run), then advances both
    the virtual replay clock and the queue's FakeClock by one quantum so
    backoff maturation is replay-deterministic.  After the trace drains,
    `max_barren_cycles` consecutive cycles without a new bind ends the
    run; whatever is still pending is reported as unschedulable rather
    than spinning forever.

    Storm-proofing (two optional planes, both off by default):

    - KILL FAULTS: when the armed chaos plan carries kill.* sites, the
      replay runs under the HA protocol — a lease table outlives every
      incarnation and each ProcessKilled is answered by a standby leader
      takeover (scheduler.ha_takeover) that RETRIES the same driver cycle
      on the restored replacement.  The driver's (v_now, i) cursor is
      authoritative and never rewinds; the checkpointed cursor
      (scheduler._replay_cursor -> restore().restored_cursor) is resume
      evidence, validated to never run ahead of the live driver.  The
      artifact's decision_crc must match an un-killed replay bit-for-bit
      (ci.sh gates it).

    - ADMISSION VALVE: KTPU_ADMIT_WATERMARK>0 threads every due arrival
      through scheduler/flowcontrol.AdmissionValve — over the watermark
      the wave shrinks, lowest bands park fair-share, stale parks shed —
      and the artifact keeps the accounting identity
      scheduled + unschedulable + shed == trace arrivals."""
    from ..scheduler import ClusterStore, Scheduler, SchedulerConfiguration
    from ..scheduler.flightrecorder import fingerprint
    from ..scheduler.metrics import Metrics, reset_run_state
    from ..scheduler.queue import FakeClock
    from ..scheduler.tracing import TraceCollector

    if quantum_s is None:
        try:
            quantum_s = float(
                os.environ.get("KTPU_OPEN_LOOP_QUANTUM_MS", "250")) / 1e3
        except ValueError:
            quantum_s = 0.25
    quantum_s = max(1e-3, quantum_s)
    pace = pace or os.environ.get("KTPU_OPEN_LOOP_PACE", "virtual")
    try:
        speed = max(1e-3, float(os.environ.get("KTPU_OPEN_LOOP_SPEED", "1")))
    except ValueError:
        speed = 1.0

    if collector is None:
        collector = TraceCollector()
    metrics = Metrics()
    reset_run_state(metrics=metrics, collector=collector)
    store = ClusterStore()
    for node in _mk_nodes(trace.nodes):
        store.add_node(node)
    clk = FakeClock()
    sched = Scheduler(
        store, SchedulerConfiguration(mode=mode),
        clock=clk, collector=collector, metrics=metrics,
    )

    from .. import chaos
    from ..scheduler.flowcontrol import AdmissionValve

    # overload-graceful admission: invisible at the default watermark 0,
    # so existing open-loop artifacts replay byte-identically
    valve = AdmissionValve(metrics=metrics)

    # kill.* faults in the armed plan put the replay under the HA
    # protocol: the lease table outlives every incarnation and a standby
    # leader takeover resumes the stream mid-cycle (docstring above)
    leases = leader = None
    inj = chaos.active()
    if inj is not None and any(
        f.site in chaos.ALL_KILL_SITES for f in inj.plan.faults
    ):
        from ..scheduler.leases import LeaderElector, LeaseStore

        leases = LeaseStore()
        leader = LeaderElector(leases, "sched-0", lease_duration_s=0.25)
        leader.tick()  # incarnation 0 is the initial leader

    trace_crc = trace.fingerprint()
    events = sorted(trace.events, key=lambda e: (e.t, e.name))
    t_wall0 = time.perf_counter()
    v_now = 0.0
    i = 0
    cycles = 0
    barren = 0
    bound_prev = 0
    restarts = 0
    resume_cursor = None
    while True:
        due = []
        while i < len(events) and events[i].t <= v_now + 1e-9:
            due.append(events[i])
            i += 1
        admitted = (
            valve.offer(due, sched.queue.pending_total, v_now)
            if valve.enabled else due
        )
        for ev in admitted:
            pod = _mk_pod(ev)
            store.add_pod(pod)  # watch admission stamps a send-time arrival
            # ... which the trace arrival instant immediately back-dates:
            # the CO-safe clock.  Virtual pace: age = how far the replay
            # clock has run past the trace timestamp.  Real pace: the
            # wall instant the trace said the pod arrives.  A valve-parked
            # pod keeps ITS trace instant too — park time lands in
            # queue_wait, honestly.
            if pace == "real":
                sched.queue.stamp_arrival(pod.uid, t_wall0 + ev.t / speed)
            else:
                sched.queue.stamp_arrival(
                    pod.uid, time.perf_counter() - (v_now - ev.t))
        pending = sched.queue.pending_total
        if i >= len(events) and pending == 0 and not valve.parked_count:
            break
        # the replay cursor rides the scheduler's next checkpoint: a
        # post-mortem (or a cold standby process) knows exactly which
        # trace offset the dead leader was serving; the flight recorder
        # carries the same context into any kill dump
        if sched._ckpt is not None:
            sched._replay_cursor = {
                "v_now": round(v_now, 9), "i": i,
                "trace_crc": trace_crc, "scenario": trace.scenario,
            }
            sched._flight.annotate(
                trace_crc=trace_crc, scenario=trace.scenario,
                trace_offset=i, v_now=round(v_now, 6),
            )
        if pending:
            try:
                sched.run_until_idle()
            except chaos.ProcessKilled as e:
                if leader is None:
                    raise  # no HA plane armed: the kill is the caller's
                restarts += 1
                if restarts > 64:
                    raise
                from ..scheduler.scheduler import ha_takeover

                sched, leader = ha_takeover(
                    sched, leases, leader, killed_site=e.fault.site,
                    lease_duration_s=0.25, name=f"sched-{restarts}",
                )
                rc = sched.restored_cursor
                if rc and rc.get("trace_crc") == trace_crc:
                    # written BEFORE the wave that died — it may trail the
                    # live driver but must never run ahead of it
                    if rc.get("i", 0) > i:
                        raise RuntimeError(
                            f"checkpoint cursor i={rc.get('i')} ahead of "
                            f"driver i={i} — checkpoint from the future")
                    resume_cursor = dict(rc)
                continue  # retry the SAME cycle on the new leader
        bound = sum(1 for p in store.list_pods() if p.node_name)
        barren = 0 if bound > bound_prev else barren + 1
        bound_prev = bound
        cycles += 1
        if i >= len(events) and barren >= max_barren_cycles:
            break  # permanently-unschedulable leftovers: report, don't spin
        v_now += quantum_s
        clk.step(quantum_s)
        if pace == "real":
            target = t_wall0 + v_now / speed
            now = time.perf_counter()
            if now < target:
                time.sleep(target - now)
    shed_flush = valve.flush(v_now)  # stream over: parked leftovers shed
    wall_s = time.perf_counter() - t_wall0

    from .harness import ha_fields, sli_fields

    assignments = {
        p.name: p.node_name for p in store.list_pods() if p.node_name
    }
    leftover = sched.queue.pending_total
    ha = ha_fields(metrics)
    artifact = {
        "name": f"open-loop:{trace.name}",
        "latency_mode": "open-loop",
        "platform": _platform(),
        "scenario": trace.scenario,
        "seed": trace.seed,
        "trace_crc": trace_crc,
        "trace_events": len(events),
        "trace_duration_s": trace.duration_s,
        "nodes": trace.nodes,
        "pods": len(events),
        "scheduled": len(assignments),
        "unschedulable": leftover,
        # the admission accounting identity the storm gate asserts:
        # scheduled + unschedulable + shed == trace arrivals
        "shed": valve.shed_total,
        "shed_at_flush": shed_flush,
        "cycles": cycles,
        "quantum_ms": round(quantum_s * 1e3, 3),
        "pace": pace,
        "wall_s": round(wall_s, 4),
        # sorted-name map: replays injecting in a different cycle pattern
        # but deciding identically must fingerprint identically
        "decision_crc": fingerprint(dict(sorted(assignments.items()))),
        # crash-restart accounting: leader takeovers this replay survived
        # (each recovers the cycle's in-flight wave), the HA/failover
        # series next to the SLI, and the last validated resume cursor
        "restarts": restarts,
        "recovered_waves": restarts,
        "ha": ha,
        "resume_cursor": resume_cursor,
        "admission": valve.report() if valve.enabled else None,
        **sli_fields(metrics),
        # failover percentiles stamped top-level next to sli_p99_ms so the
        # regression gate reads them like any other latency scalar (the
        # nested ha block keeps the full HA series)
        **({"failover_p50_ms": ha["failover_p50_ms"],
            "failover_p99_ms": ha["failover_p99_ms"]} if ha else {}),
        "sli_phases": phase_stats(metrics),
        "sli_attribution": sli_attribution(metrics, sched),
    }
    return artifact, sched


def _platform() -> str:
    """Artifact platform label, same vocabulary as bench.py/matrix.py
    (cross-platform latencies differ 20-40x; the regression gate skips
    mismatched priors)."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no jax, no devices: still an artifact
        backend = "cpu"
    return backend if backend != "cpu" else "cpu-sim-fallback"


# --- reports ---

def render_attribution_table(artifact: dict) -> str:
    """Human-readable --sli-attribution report: the per-phase share table,
    the dominant phase, and the worst-pod exemplars."""
    att = artifact.get("sli_attribution", {})
    phases = att.get("phases", {})
    lines = [
        f"open-loop SLI attribution — scenario={artifact.get('scenario')} "
        f"seed={artifact.get('seed')} pods={artifact.get('pods')} "
        f"p50={artifact.get('sli_p50_ms')}ms p99={artifact.get('sli_p99_ms')}ms "
        f"(n={artifact.get('sli_count')})",
        f"{'phase':<14} {'p50_ms':>10} {'p99_ms':>10} {'p99_share':>10}",
    ]
    for ph, st in phases.items():
        lines.append(
            f"{ph:<14} {st['p50_ms']:>10.3f} {st['p99_ms']:>10.3f} "
            f"{st['p99_share']:>10.4f}"
        )
    dom = att.get("dominant_phase")
    if dom in phases:
        lines.append(
            f"dominant phase: {dom} "
            f"(owns {phases[dom]['p99_share'] * 100:.1f}% of the p99)"
        )
    worst = att.get("worst_pods") or []
    if worst:
        lines.append("worst pods (exemplars):")
        for w in worst:
            vec = "  ".join(
                f"{ph}={v:.3f}ms" for ph, v in w["phases_ms"].items()
            )
            lines.append(f"  {w['pod']}  sli={w['sli_ms']:.3f}ms  {vec}")
    if att.get("exemplar_export"):
        lines.append(f"exemplar Perfetto export: {att['exemplar_export']}")
    return "\n".join(lines)


def export_sli_exemplars(collector, pod_uids, path: str) -> Optional[str]:
    """Perfetto/chrome-trace export of the exemplar pods' FULL span
    timelines: every span on a trace chain that touched one of the worst-K
    pods (queue.wait, batch.* cycle spans, bind instants, pipeline
    overlap spans), so the attribution table's tail numbers can be read
    against real timelines.  Returns the path, or None with no spans."""
    uids = set(pod_uids)
    if not uids:
        return None
    trace_ids = {
        s.trace_id
        for s in collector.spans()
        if s.trace_id and s.attributes.get("pod") in uids
    }
    if not trace_ids:
        return None
    doc = collector.chrome_trace()
    events = [
        ev for ev in doc.get("traceEvents", [])
        if ev.get("ph") == "M"
        or (ev.get("args") or {}).get("trace_id") in trace_ids
    ]
    doc = dict(doc, traceEvents=events)
    doc["otherData"] = dict(
        doc.get("otherData", {}),
        exemplar_pods=sorted(uids),
        exemplar_spans=sum(1 for ev in events if ev.get("ph") != "M"),
    )
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
