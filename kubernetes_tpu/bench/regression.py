"""Bench regression gate over the BENCH_* trajectory.

Loads the repo's BENCH_r*.json artifacts (both shapes: the driver wrapper
{"n":…, "parsed": {…}} and the bare bench.py JSON line), normalizes
per-box — runs are only comparable WITHIN one platform (a real TPU v5 run
and the cpu-sim fallback differ by 20-40×, so cross-box deltas are noise,
not regressions) AND one shard topology (an 8-device sim run timeshares
one core, and per-shard metrics are divided by the grid — `n_shards`
joins the comparability key; pre-mesh artifacts normalize to 1) — and
exits nonzero when the newest run regressed more than --threshold
against the BEST prior same-box run.

Exit codes:
  0  pass (improved, within threshold, or no comparable prior run)
  1  regression beyond threshold
  2  the current run is unusable (missing metric/file, no records)

Usage:
  python -m kubernetes_tpu.bench.regression [--dir .]
      [--glob 'BENCH_r[0-9]*.json'] [--metric step_s] [--higher-is-better]
      [--threshold 0.1] [--current FILE]

Default metric is step_s (lower is better — the warm device step the
BENCH_r01–r06 trajectory tracks); --metric value --higher-is-better gates
on throughput instead, and --metric comm_bytes gates the per-route
collective-traffic budget the shard pass measures (the harness stamps the
worst mesh route's measured bytes top-level under --verify-shard, so an
accidental extra all-gather regression-gates alongside step time).  The
device cost observatory stamps three more gated scalars the same way:
--metric round_loop_fraction (the measured share of kernel time inside
the prefix-commit round loop, `bench.harness --profile` — ROADMAP-1's
burn-down number), and --metric device_flops / device_hbm_bytes (the
analytic ledger's modeled kernel cost, analysis/costmodel.py — a kernel
that silently grew its FLOP or byte footprint regression-gates even
before it slows a wall clock).
The HBM telemetry plane stamps the MEASURED device-memory high-water the
same way: --metric hbm_peak_bytes (scheduler/memwatch.py — the live
peak the cycle-boundary ledger observed, stamped top-level by bench.py
and every --stream artifact), so a kernel or cache change that silently
doubles peak HBM fails the gate like a step-time regression.
Dotted metric names traverse nested blocks (e.g. verify.n_unbaselined).
Prior runs missing the metric or on another box are skipped with a note
(the r01/r02 real-TPU artifacts predate step_s), never failed on — only
the CURRENT run's record is load-bearing.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple


def _natural_key(name: str):
    """Digit-aware sort key: BENCH_r99 sorts before BENCH_r100 (plain
    lexicographic order would misplace three-digit rounds, making the gate
    pick the wrong 'newest' run)."""
    return [int(tok) if tok.isdigit() else tok
            for tok in re.split(r"(\d+)", name)]


def load_record(path: str) -> Tuple[Optional[Dict], Optional[str]]:
    """One BENCH artifact -> (record, error).  Unwraps the driver's
    {"parsed": …} envelope; a bare bench.py JSON line loads as-is."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return None, f"{path}: unreadable ({e})"
    if not isinstance(doc, dict):
        return None, f"{path}: not a JSON object"
    rec = doc.get("parsed", doc)
    if not isinstance(rec, dict):
        return None, f"{path}: 'parsed' is not an object"
    return rec, None


def load_trajectory(dir_: str, pattern: str) -> List[Tuple[str, Dict]]:
    """(name, record) pairs in trajectory order (digit-aware file-name
    sort — BENCH_r01 < … < BENCH_r99 < BENCH_r100)."""
    out: List[Tuple[str, Dict]] = []
    for path in sorted(_glob.glob(os.path.join(dir_, pattern)),
                       key=lambda p: _natural_key(os.path.basename(p))):
        rec, err = load_record(path)
        if rec is None:
            print(f"regression: skipping {err}", file=sys.stderr)
            continue
        out.append((os.path.basename(path), rec))
    return out


def _metric(rec: Dict, name: str) -> Optional[float]:
    """Numeric metric from a record.  `name` may be a dotted path into
    nested blocks (e.g. `verify.device.n_traced`); the flat top-level form
    covers the stamped scalars — `step_s`, `value`, and `comm_bytes` (the
    worst per-route measured collective bytes the harness stamps from the
    shard pass, so the all-gather budget regression-gates exactly like
    step time: `--metric comm_bytes`)."""
    v: object = rec
    for part in name.split("."):
        if not isinstance(v, dict):
            return None
        v = v.get(part)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


# latency-metric basenames whose values are only comparable between runs
# DRIVEN the same way: a batch artifact's p99 is a per-wave wall (p50==p99
# degenerate) and an open-loop p99 includes trace-timed queue backlog —
# comparing either against a closed-loop distribution gates noise.  The
# guard keys on the metric's last path segment so nested forms
# (`foo.sli_p99_ms`) get it too, and applies ONLY to these metrics: value /
# step_s / comm_bytes comparisons stay valid across driver modes (and
# across old artifacts that predate the latency_mode stamp).
LATENCY_METRICS = ("sli_p50_ms", "sli_p99_ms", "p50_ms", "p90_ms", "p99_ms",
                   "failover_p50_ms", "failover_p99_ms")


def check_regression(
    trajectory: List[Tuple[str, Dict]],
    current: Tuple[str, Dict],
    metric: str = "step_s",
    higher_is_better: bool = False,
    threshold: float = 0.1,
) -> Dict:
    """The gate: compare `current` against the best PRIOR same-platform,
    same-n_shards run on `metric` (same latency_mode too, for latency
    metrics — see LATENCY_METRICS).  Returns a machine-readable verdict dict with
    `status` in {"pass", "regression", "error"}."""
    cur_name, cur = current
    cur_v = _metric(cur, metric)
    if cur_v is None:
        return {
            "status": "error",
            "reason": f"current run {cur_name} has no numeric {metric!r}",
            "current": cur_name,
        }
    platform = cur.get("platform", "unknown")
    # shard topology is part of the box identity: on the cpu-sim fallback an
    # 8-device run timeshares one core (wall clocks ~8x a 1-device run of
    # the same kernel), and per-shard quantities (per_shard_hbm_bytes,
    # comm_bytes) are divided by the grid — so cross-topology deltas are
    # configuration changes, not regressions, in BOTH directions.  Artifacts
    # that predate the n_shards stamp were all single-device.
    cur_shards = int(cur.get("n_shards") or 1)
    guard_mode = metric.split(".")[-1] in LATENCY_METRICS
    latency_mode = cur.get("latency_mode")
    prior: List[Tuple[str, float]] = []
    skipped: List[str] = []
    for name, rec in trajectory:
        if name == cur_name:
            continue
        if rec.get("platform", "unknown") != platform:
            skipped.append(f"{name} (platform {rec.get('platform', 'unknown')!r})")
            continue
        if int(rec.get("n_shards") or 1) != cur_shards:
            skipped.append(
                f"{name} (n_shards {int(rec.get('n_shards') or 1)} != "
                f"{cur_shards})")
            continue
        if guard_mode and rec.get("latency_mode") != latency_mode:
            skipped.append(
                f"{name} (latency_mode {rec.get('latency_mode')!r} != "
                f"{latency_mode!r})")
            continue
        v = _metric(rec, metric)
        if v is None:
            skipped.append(f"{name} (no {metric})")
            continue
        prior.append((name, v))
    if not prior:
        return {
            "status": "pass",
            "reason": f"no comparable prior {platform!r} run with {metric!r}",
            "current": cur_name, "platform": platform,
            "current_value": cur_v, "skipped": skipped,
        }
    best_name, best_v = (
        max(prior, key=lambda t: t[1]) if higher_is_better
        else min(prior, key=lambda t: t[1])
    )
    if higher_is_better:
        # regression = current fell below best by more than threshold
        ratio = (best_v - cur_v) / best_v if best_v > 0 else 0.0
    else:
        ratio = (cur_v - best_v) / best_v if best_v > 0 else 0.0
    status = "regression" if ratio > threshold else "pass"
    return {
        "status": status,
        "current": cur_name, "platform": platform,
        "metric": metric, "higher_is_better": higher_is_better,
        "current_value": cur_v,
        "best_prior": best_name, "best_prior_value": best_v,
        "regression_fraction": round(ratio, 4),
        "threshold": threshold,
        "skipped": skipped,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH trajectory regression gate (nonzero exit on "
        "regression beyond --threshold vs the best prior same-box run)"
    )
    ap.add_argument("--dir", default=".", help="directory of BENCH artifacts")
    ap.add_argument("--glob", default="BENCH_r[0-9]*.json",
                    help="artifact pattern, trajectory-ordered by name")
    ap.add_argument("--metric", default="step_s",
                    help="record field to gate on (default: step_s)")
    ap.add_argument("--higher-is-better", action="store_true",
                    help="gate treats larger metric values as better "
                         "(e.g. --metric value for pods/s)")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="allowed worsening fraction vs best prior "
                         "same-box run (default 0.1 = 10%%)")
    ap.add_argument("--current", metavar="FILE",
                    help="candidate artifact (default: the trajectory's "
                         "newest entry)")
    args = ap.parse_args(argv)

    trajectory = load_trajectory(args.dir, args.glob)
    if args.current:
        rec, err = load_record(args.current)
        if rec is None:
            print(f"regression: ERROR — {err}", file=sys.stderr)
            return 2
        current = (os.path.basename(args.current), rec)
    else:
        if not trajectory:
            print(f"regression: ERROR — no artifacts match "
                  f"{args.glob!r} in {args.dir!r}", file=sys.stderr)
            return 2
        current = trajectory[-1]

    verdict = check_regression(
        trajectory, current, metric=args.metric,
        higher_is_better=args.higher_is_better, threshold=args.threshold,
    )
    print(json.dumps(verdict))
    if verdict["status"] == "error":
        print(f"regression: ERROR — {verdict['reason']}", file=sys.stderr)
        return 2
    if verdict["status"] == "regression":
        print(
            f"regression: FAIL — {verdict['current']} {args.metric}="
            f"{verdict['current_value']} is "
            f"{verdict['regression_fraction']:.1%} worse than "
            f"{verdict['best_prior']} ({verdict['best_prior_value']}) "
            f"on {verdict['platform']} (threshold "
            f"{verdict['threshold']:.1%})",
            file=sys.stderr,
        )
        return 1
    print(f"regression: PASS — {verdict.get('reason', '')}"
          f"{verdict.get('current')} ok on {verdict.get('platform')}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
