"""Synthetic workload generators for the BASELINE.md benchmark configs.

Analog of scheduler_perf's createNodes/createPods ops (test/integration/
scheduler_perf/config/performance-config.yaml): deterministic (seeded) cluster
generators at the five target scales.
"""

from __future__ import annotations

import random
from typing import Optional

from ..api import types as t
from ..api.snapshot import Snapshot

MILLI = 1000
GI = 1024**3


def basic(n_nodes: int, n_pods: int, seed: int = 0) -> Snapshot:
    """Config 1/2: homogeneous nodes, cpu+mem-requesting pods (SchedulingBasic /
    NodeResourcesFit-only)."""
    rng = random.Random(seed)
    nodes = [
        t.Node(
            name=f"node-{i}",
            allocatable={t.CPU: 32 * MILLI, t.MEMORY: 128 * GI, t.PODS: 110},
            labels={t.LABEL_ZONE: f"zone-{i % 3}"},
        )
        for i in range(n_nodes)
    ]
    pods = [
        t.Pod(
            name=f"pod-{i}",
            requests={
                t.CPU: rng.choice([100, 250, 500, 1000]),
                t.MEMORY: rng.choice([128, 256, 512, 1024]) * 1024**2,
            },
        )
        for i in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


def spread_affinity(n_nodes: int, n_pods: int, seed: int = 0, zones: int = 3) -> Snapshot:
    """Config 3: PodTopologySpread + InterPodAffinity across zones."""
    rng = random.Random(seed)
    nodes = [
        t.Node(
            name=f"node-{i}",
            allocatable={t.CPU: 32 * MILLI, t.MEMORY: 128 * GI, t.PODS: 110},
            labels={t.LABEL_ZONE: f"zone-{i % zones}"},
        )
        for i in range(n_nodes)
    ]
    apps = [f"svc-{i}" for i in range(max(4, n_pods // 250))]
    pods = []
    for i in range(n_pods):
        app = rng.choice(apps)
        kind = rng.random()
        spread = ()
        aff = None
        if kind < 0.5:
            spread = (
                t.TopologySpreadConstraint(
                    max_skew=rng.choice([1, 2]),
                    topology_key=t.LABEL_ZONE,
                    when_unsatisfiable=t.DO_NOT_SCHEDULE if kind < 0.25 else t.SCHEDULE_ANYWAY,
                    label_selector=t.LabelSelector.of(app=app),
                ),
            )
        elif kind < 0.7:
            if kind < 0.6:
                term = t.PodAffinityTerm(
                    topology_key=t.LABEL_ZONE, label_selector=t.LabelSelector.of(app=app)
                )
                aff = t.Affinity(required_pod_affinity=(term,))
            else:
                # anti-affinity at hostname scope: "one replica per node"
                term = t.PodAffinityTerm(
                    topology_key=t.LABEL_HOSTNAME, label_selector=t.LabelSelector.of(app=app)
                )
                aff = t.Affinity(required_pod_anti_affinity=(term,))
        pods.append(
            t.Pod(
                name=f"pod-{i}",
                labels={"app": app},
                requests={
                    t.CPU: rng.choice([100, 250, 500]),
                    t.MEMORY: rng.choice([128, 256, 512]) * 1024**2,
                },
                topology_spread=spread,
                affinity=aff,
            )
        )
    return Snapshot(nodes=nodes, pending_pods=pods)


def gang(n_groups: int, group_size: int, n_nodes: int, seed: int = 0) -> Snapshot:
    """Config 5: gang-scheduled ML jobs (PodGroups, all-or-nothing)."""
    rng = random.Random(seed)
    nodes = [
        t.Node(
            name=f"node-{i}",
            allocatable={t.CPU: 64 * MILLI, t.MEMORY: 256 * GI, t.PODS: 256},
            labels={t.LABEL_ZONE: f"zone-{i % 4}"},
        )
        for i in range(n_nodes)
    ]
    pods, groups = [], {}
    for g in range(n_groups):
        name = f"job-{g}"
        groups[name] = t.PodGroup(name=name, min_member=group_size)
        cpu = rng.choice([500, 1000, 2000])
        for m in range(group_size):
            pods.append(
                t.Pod(
                    name=f"{name}-w{m}",
                    labels={"job": name},
                    requests={t.CPU: cpu, t.MEMORY: 2 * GI},
                    pod_group=name,
                    priority=rng.choice([0, 10]),
                )
            )
    return Snapshot(nodes=nodes, pending_pods=pods, pod_groups=groups)


def heterogeneous(n_nodes: int, n_pods: int, seed: int = 0) -> Snapshot:
    """Config 4: heterogeneous capacities + extended resources + taints/tolerations."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        alloc = {
            t.CPU: rng.choice([8, 16, 32, 64]) * MILLI,
            t.MEMORY: rng.choice([32, 64, 128, 256]) * GI,
            t.PODS: rng.choice([64, 110, 256]),
        }
        taints = ()
        if i % 5 == 0:
            alloc["example.com/accel"] = rng.choice([4, 8])
            taints = (t.Taint(key="accel", value="true", effect=t.NO_SCHEDULE),)
        nodes.append(
            t.Node(
                name=f"node-{i}",
                allocatable=alloc,
                labels={t.LABEL_ZONE: f"zone-{i % 9}", "pool": f"pool-{i % 17}"},
                taints=taints,
            )
        )
    pods = []
    for i in range(n_pods):
        req = {
            t.CPU: rng.choice([100, 250, 500, 1000, 2000]),
            t.MEMORY: rng.choice([128, 256, 512, 2048, 4096]) * 1024**2,
        }
        tols = ()
        sel = ()
        if i % 10 == 0:
            req["example.com/accel"] = 1
            tols = (t.Toleration(key="accel", operator=t.OP_EXISTS),)
        pods.append(
            t.Pod(
                name=f"pod-{i}",
                requests=req,
                tolerations=tols,
                node_selector=sel,
                priority=rng.choice([0, 0, 0, 100]),
            )
        )
    return Snapshot(nodes=nodes, pending_pods=pods)


def heterogeneous_storage(n_nodes: int, n_pods: int, seed: int = 0) -> Snapshot:
    """Config 4 + storage: the heterogeneous shape where a slice of pods
    claim volumes — some bound to zone-restricted static PVs, some unbound
    through a WaitForFirstConsumer StorageClass with zone topology, plus
    CSI attach limits on a slice of nodes.  Exercises volume resolution
    (api/volumes.resolve_snapshot) and the delta encoder\'s storage-state
    conditioning every cycle — the round-2 verdict\'s "config-4-plus-
    storage" gap: storage-using clusters must keep the incremental encode
    rather than silently rebuilding."""
    from ..api.cluster import StorageClass

    snap = heterogeneous(n_nodes, n_pods, seed=seed)
    snap.storage_classes["fast-wffc"] = StorageClass(
        name="fast-wffc",
        provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer",
        allowed_topology=((t.LABEL_ZONE, "zone-0"), (t.LABEL_ZONE, "zone-1")),
    )
    # static zone-pinned PVs, one per 40 nodes, pre-bound to claims
    n_static = max(1, n_nodes // 40)
    for v in range(n_static):
        zone = f"zone-{v % 9}"
        snap.pvs.append(
            t.PersistentVolume(
                name=f"pv-{v}",
                capacity=100 * GI,
                storage_class="static",
                allowed_topology=((t.LABEL_ZONE, zone),),
                claim_ref=f"default/claim-static-{v}",
            )
        )
        snap.pvcs[f"default/claim-static-{v}"] = t.PersistentVolumeClaim(
            name=f"claim-static-{v}",
            request=50 * GI,
            storage_class="static",
            volume_name=f"pv-{v}",
        )
    # unbound WFFC claims for a slice of pods
    n_wffc = max(1, n_pods // 50)
    for c in range(n_wffc):
        snap.pvcs[f"default/claim-wffc-{c}"] = t.PersistentVolumeClaim(
            name=f"claim-wffc-{c}",
            request=10 * GI,
            storage_class="fast-wffc",
            wait_for_first_consumer=True,
        )
    # attach limits on a slice of nodes
    for i, nd in enumerate(snap.nodes):
        if i % 7 == 0:
            nd.volume_attach_limit = 16
    # pods claiming volumes: every 50th pod a WFFC claim, every 97th a
    # static claim (claims may be shared — ReadWriteMany semantics)
    for i, p in enumerate(snap.pending_pods):
        if i % 50 == 0:
            p.pvcs = (f"claim-wffc-{(i // 50) % n_wffc}",)
        elif i % 97 == 0:
            p.pvcs = (f"claim-static-{(i // 97) % n_static}",)
    return snap
