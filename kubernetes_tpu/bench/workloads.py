"""Synthetic workload generators for the BASELINE.md benchmark configs.

Analog of scheduler_perf's createNodes/createPods ops (test/integration/
scheduler_perf/config/performance-config.yaml): deterministic (seeded) cluster
generators at the five target scales.
"""

from __future__ import annotations

import random
from typing import Optional

from ..api import types as t
from ..api.snapshot import Snapshot

MILLI = 1000
GI = 1024**3


def basic(n_nodes: int, n_pods: int, seed: int = 0) -> Snapshot:
    """Config 1/2: homogeneous nodes, cpu+mem-requesting pods (SchedulingBasic /
    NodeResourcesFit-only)."""
    rng = random.Random(seed)
    nodes = [
        t.Node(
            name=f"node-{i}",
            allocatable={t.CPU: 32 * MILLI, t.MEMORY: 128 * GI, t.PODS: 110},
            labels={t.LABEL_ZONE: f"zone-{i % 3}"},
        )
        for i in range(n_nodes)
    ]
    pods = [
        t.Pod(
            name=f"pod-{i}",
            requests={
                t.CPU: rng.choice([100, 250, 500, 1000]),
                t.MEMORY: rng.choice([128, 256, 512, 1024]) * 1024**2,
            },
        )
        for i in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


def heterogeneous(n_nodes: int, n_pods: int, seed: int = 0) -> Snapshot:
    """Config 4: heterogeneous capacities + extended resources + taints/tolerations."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        alloc = {
            t.CPU: rng.choice([8, 16, 32, 64]) * MILLI,
            t.MEMORY: rng.choice([32, 64, 128, 256]) * GI,
            t.PODS: rng.choice([64, 110, 256]),
        }
        taints = ()
        if i % 5 == 0:
            alloc["example.com/accel"] = rng.choice([4, 8])
            taints = (t.Taint(key="accel", value="true", effect=t.NO_SCHEDULE),)
        nodes.append(
            t.Node(
                name=f"node-{i}",
                allocatable=alloc,
                labels={t.LABEL_ZONE: f"zone-{i % 9}", "pool": f"pool-{i % 17}"},
                taints=taints,
            )
        )
    pods = []
    for i in range(n_pods):
        req = {
            t.CPU: rng.choice([100, 250, 500, 1000, 2000]),
            t.MEMORY: rng.choice([128, 256, 512, 2048, 4096]) * 1024**2,
        }
        tols = ()
        sel = ()
        if i % 10 == 0:
            req["example.com/accel"] = 1
            tols = (t.Toleration(key="accel", operator=t.OP_EXISTS),)
        pods.append(
            t.Pod(
                name=f"pod-{i}",
                requests=req,
                tolerations=tols,
                node_selector=sel,
                priority=rng.choice([0, 0, 0, 100]),
            )
        )
    return Snapshot(nodes=nodes, pending_pods=pods)
