"""Data-driven knob autotuner — the sweep half of `ops/tuning.py`.

The chunked-kernel shape knobs (KTPU_INC_CHUNK and the commit-wave family
KTPU_WAVE_BLOCK / KTPU_WAVE_ITERS / KTPU_WAVE_K) are TRACE-TIME constants
read once at `ops.assign` import, so every candidate runs in a FRESH
subprocess (the bench/rounds_proof.py KTPU_REPAIR_ITERS discipline) with
the candidate's env pinned.  Each probe drives the REAL runtime through
bench.harness — ClusterStore -> watch -> queue -> batched cycle -> bind —
so a candidate is scored on what production would see, not on a bare
kernel call, and additionally traces the incremental route's jaxpr through
the analytic roofline ledger (analysis/costmodel.py) so the scorecard
records the MODELED cost shape next to the measured wall.

Winner selection is measured-first: best pods/s wins, but candidates
within --noise of the best re-rank by the analytic ledger's modeled
kernel seconds (deterministic — repeated sweeps on a noisy box converge
to one winner instead of flapping the persisted file).  The winner lands
next to the compile cache as ktpu-tuned-<platform>.json
(ops/tuning.py — save_tuned); any later process on the box resolves every
knob env > winner > default at import, so the tuned shape is picked up
with zero call-site changes.  None of these knobs changes DECISIONS
(PARITY.md — chunk size and wave shape move only commit ordinals and wall
time), which is what makes persisting a perf winner safe.

Usage:
  python -m kubernetes_tpu.bench.autotune --nodes 500 --pods 2048 \\
      --candidates 32:48:12:256,32:64:14:256 --tuning-dir /path/cache
  python -m kubernetes_tpu.bench.autotune probe --nodes 500 --pods 2048

Candidate syntax: INC_CHUNK:WAVE_BLOCK:WAVE_ITERS:WAVE_K:PACK:DTYPE:MESH
(ints except DTYPE = "bf16" | "f32"; MESH = KTPU_MESH_PODS pod-shard
count, 1 = legacy 1-D).  Shorter legacy candidates (the 4-field
pre-packing or 6-field pre-mesh forms) fill the missing tail with
defaults.  The
`probe` subcommand is the internal per-candidate child; it prints one
JSON line with the RESOLVED knob values (proving the env > winner >
default resolution the CI smoke asserts on), the measured harness
numbers, and the analytic ledger summary.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..ops.tuning import TUNABLE_KNOBS

# candidate field order in the colon syntax (parallel to TUNABLE_KNOBS).
# The packed-data-plane pair (ops/bitplane.py) rides the same sweep: both
# are trace-time constants, both change only perf (decisions stay
# bit-identical to the oracle on every setting — tests/test_packed_masks.py),
# so a measured winner is safe to persist exactly like the shape knobs.
# The trailing MESH field is the 2-D pod-shard count (KTPU_MESH_PODS, 1 =
# the legacy 1-D mesh): decisions are bit-identical on every mesh shape
# (tests/test_sharded_routed.py), so a measured mesh winner persists under
# the same safety argument.
_FIELDS = ("KTPU_INC_CHUNK", "KTPU_WAVE_BLOCK", "KTPU_WAVE_ITERS",
           "KTPU_WAVE_K", "KTPU_PACK_MASKS", "KTPU_SCORE_DTYPE",
           "KTPU_MESH_PODS")
# defaults appended when a candidate uses a legacy shorter syntax (the
# 4-field pre-packing form or the 6-field pre-mesh form)
_FIELD_DEFAULTS = ("1", "bf16", "1")

DEFAULT_CANDIDATES = (
    "32:48:12:256:1:bf16:1,32:64:14:256:1:bf16:1,32:32:6:256:1:bf16:1,"
    "64:48:12:512:1:bf16:1,32:48:12:256:0:f32:1"
)


def _field_value(name: str, raw: str):
    from ..ops.tuning import _coerce

    return _coerce(name, raw)


def parse_candidates(spec: str) -> List[Dict[str, Any]]:
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        parts = tok.split(":")
        n_required = len(_FIELDS) - len(_FIELD_DEFAULTS)
        if n_required <= len(parts) < len(_FIELDS):
            # legacy shorter candidates keep working (scripts predating the
            # packed-plane knobs or the mesh field): the missing tail rides
            # at its defaults
            parts = parts + list(_FIELD_DEFAULTS[len(parts) - n_required:])
        if len(parts) != len(_FIELDS):
            raise SystemExit(
                f"autotune: candidate {tok!r} needs "
                f"{len(_FIELDS)} fields {':'.join(_FIELDS)} "
                f"(or a legacy prefix of at least {n_required})"
            )
        out.append({
            f: _field_value(f, p) for f, p in zip(_FIELDS, parts)
        })
    return out


def run_probe(args) -> None:
    """One candidate, THIS process: harness-measured wall + analytic
    ledger, knobs as resolved by ops.assign at import (env > persisted
    winner > default)."""
    from ._cpu import force_cpu_from_env

    force_cpu_from_env()
    import jax

    from ..api.delta import DeltaEncoder
    from ..analysis.costmodel import jaxpr_ledger
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops import assign
    from ..ops.incremental import HoistCache
    from .harness import run_snapshot_workload
    from .workloads import heterogeneous

    from ..ops import bitplane
    from ..ops.tuning import tuned_knob

    snap = heterogeneous(args.nodes, args.pods, seed=args.seed)
    resolved = {
        "KTPU_INC_CHUNK": assign._INC_CHUNK,
        "KTPU_WAVE_BLOCK": assign._WAVE_BLOCK,
        "KTPU_WAVE_ITERS": assign._WAVE_ITERS,
        "KTPU_WAVE_K": assign._WAVE_K,
        # the packed-data-plane pair, as resolved at ops.bitplane import
        # (env > persisted winner > default — the CI smoke asserts these)
        "KTPU_PACK_MASKS": int(bitplane.PACK_MASKS),
        "KTPU_SCORE_DTYPE": bitplane.SCORE_DTYPE,
        # the 2-D mesh knob (env > persisted winner > default): probes run
        # single-device so the shipped candidates pin 1, but a sweep on a
        # multi-chip box may carry >1 and the winner persists like any knob
        "KTPU_MESH_PODS": int(tuned_knob("KTPU_MESH_PODS", 1) or 1),
    }

    # measured half: the real runtime loop (includes compile on the first
    # wave; run_snapshot_workload warms once in tpu mode before measuring)
    perf = run_snapshot_workload("autotune_probe", snap, "tpu")

    # analytic half: the ledger of the exact program these knobs trace
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    ledger: Optional[Dict[str, Any]] = None
    try:
        jaxpr = jax.make_jaxpr(
            lambda a, i: assign.schedule_batch_ordinals_impl(a, cfg, inc=i)
        )(arr, inc)
        full = jaxpr_ledger(jaxpr)
        ledger = {
            "total_flops": full["total_flops"],
            "total_hbm_bytes": full["total_hbm_bytes"],
            "modeled_s": round(sum(
                r["modeled_s"] for r in full["subphases"].values()
            ), 9),
            "round_loop_fraction": full["round_loop_fraction"],
            "commit_batch_fraction": full["subphases"].get(
                "commit_batch", {}
            ).get("fraction", 0.0),
            "dominant": full["dominant"],
        }
    except Exception as e:  # noqa: BLE001 — the analytic half is advisory;
        # a tracing failure must not void the measured result
        ledger = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps({
        "knobs": resolved,
        "n_nodes": args.nodes, "n_pods": args.pods,
        "pods_per_sec": perf.pods_per_sec,
        "wall_s": perf.wall_s,
        "scheduled": perf.scheduled,
        "p99_ms": perf.p99_ms,
        "analytic": ledger,
        "platform": jax.default_backend(),
    }))


def _sub_probe(knobs: Dict[str, int], args, timeout_s: int) -> Dict:
    env = dict(os.environ, **{k: str(v) for k, v in knobs.items()})
    cmd = [sys.executable, "-u", "-m", "kubernetes_tpu.bench.autotune",
           "probe", "--nodes", str(args.nodes), "--pods", str(args.pods),
           "--seed", str(args.seed)]
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return {"knobs": knobs, "error": f"timeout after {timeout_s}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        if line.strip().startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {"knobs": knobs,
            "error": f"rc={r.returncode} tail={r.stderr.strip()[-500:]}",
            "elapsed_s": round(time.time() - t0, 1)}


def pick_winner(rows: List[Dict], noise: float) -> Optional[Dict]:
    """Measured-first with analytic tie-break: candidates within `noise`
    of the best pods/s re-rank by LOWER modeled analytic seconds (falling
    back to measured order when a ledger is missing)."""
    ok = [r for r in rows if "error" not in r and r.get("pods_per_sec")]
    if not ok:
        return None
    best = max(ok, key=lambda r: r["pods_per_sec"])
    near = [r for r in ok
            if r["pods_per_sec"] >= best["pods_per_sec"] * (1.0 - noise)]

    def modeled(r):
        a = r.get("analytic") or {}
        m = a.get("modeled_s")
        return m if isinstance(m, (int, float)) else float("inf")

    near.sort(key=lambda r: (modeled(r), -r["pods_per_sec"]))
    return near[0]


def run_sweep(args) -> int:
    from ..ops import tuning

    if args.tuning_dir:
        os.environ["KTPU_TUNING_DIR"] = args.tuning_dir
    candidates = parse_candidates(args.candidates)
    rows: List[Dict] = []
    for knobs in candidates:
        row = _sub_probe(knobs, args, args.timeout)
        rows.append(row)
        tag = ":".join(str(knobs[f]) for f in _FIELDS)
        if "error" in row:
            print(f"autotune: {tag} ERROR {row['error']}", file=sys.stderr)
        else:
            a = row.get("analytic") or {}
            print(
                f"autotune: {tag} {row['pods_per_sec']:.0f} pods/s "
                f"wall {row['wall_s']:.2f}s "
                f"modeled {a.get('modeled_s', '?')}s",
                file=sys.stderr,
            )
    winner = pick_winner(rows, args.noise)
    if winner is None:
        print("autotune: FAIL — no candidate produced a measurement",
              file=sys.stderr)
        print(json.dumps({"winner": None, "candidates": rows}))
        return 1
    from ..ops.tuning import _coerce

    knobs = {k: _coerce(k, v) for k, v in winner["knobs"].items()
             if k in TUNABLE_KNOBS}
    score = {
        "pods_per_sec": winner["pods_per_sec"],
        "wall_s": winner["wall_s"],
        "analytic": winner.get("analytic"),
        "n_nodes": args.nodes, "n_pods": args.pods,
        "n_candidates": len(candidates),
    }
    path = tuning.save_tuned(knobs, score,
                             platform=winner.get("platform"))
    print(json.dumps({"winner": knobs, "score": score,
                      "persisted": path, "candidates": rows}))
    if path:
        print(f"autotune: winner {knobs} -> {path}", file=sys.stderr)
    else:
        print("autotune: winner "
              f"{knobs} (no tuning dir configured; not persisted)",
              file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sweep chunk/wave knob candidates in fresh "
        "subprocesses; persist the per-platform winner (ops/tuning.py)"
    )
    ap.add_argument("cmd", nargs="?", default="sweep",
                    choices=["sweep", "probe"])
    ap.add_argument("--nodes", type=int, default=500)
    ap.add_argument("--pods", type=int, default=2048)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--candidates", default=DEFAULT_CANDIDATES,
                    help="comma list of INC_CHUNK:WAVE_BLOCK:WAVE_ITERS:"
                         "WAVE_K")
    ap.add_argument("--tuning-dir",
                    help="winner directory (else KTPU_TUNING_DIR / "
                         "KTPU_COMPILE_CACHE_DIR)")
    ap.add_argument("--noise", type=float, default=0.03,
                    help="measured-throughput band treated as a tie "
                         "(analytic ledger breaks it; default 3%%)")
    ap.add_argument("--timeout", type=int, default=1200,
                    help="per-candidate subprocess timeout seconds")
    args = ap.parse_args(argv)
    if args.cmd == "probe":
        run_probe(args)
        return 0
    return run_sweep(args)


if __name__ == "__main__":
    sys.exit(main())
