"""North-star-scale proof of the rounds kernel (round-4 verdict, missing #1).

The rounds kernel (`ops/assign.py — schedule_scan_rounds`) exists to serve
the thesis workload — BASELINE config 3 (PodTopologySpread + InterPodAffinity)
at 50k pods x 20k nodes — yet through round 4 it had never executed at that
scale on ANY backend.  This runner produces the missing evidence:

  1. `kernel`  — run ONE kernel (rounds|plain) at one scale in THIS process,
     record wall time, peak RSS, rounds count, and dump the decisions vector
     to .npy for cross-process comparison.  Each point runs in its own
     process because `_REPAIR_ITERS` (KTPU_REPAIR_ITERS) and the routing env
     are baked into jit traces at trace time.
  2. `full`    — orchestrate the battery: rounds + plain at north-star scale
     (decisions must be bit-identical), a _REPAIR_ITERS 1/2/3 sweep at
     BASELINE config-3 scale (10k x 5k; the round-4 verdict's weak #1 — the
     shipping 2-iter point was never measured), and a written per-round
     device-cost model anchored to round-3's TPU measurements.  Writes one
     JSON artifact.

Cost model (the "~2.4k rounds < 1 s" projection, defended):
  the per-pod scan's TPU cost at this workload is MEASURED (BENCH_MATRIX_r03:
  0.99 s at 10k x 5k, 5.784 s = 113 us/step at 50k x 20k).  A round's work is
  one [C, N] re-hoist of the same per-pod row functions, so we project TPU
  per-round cost two independent ways and quote both:
    (a) bytes/BW: count the f32 bytes a round actually touches (re-hoist
        reads + base patch + reductions) and divide by a conservative
        achieved HBM bandwidth on v5e (measured ceiling 819 GB/s; we assume
        40% achieved for gather-heavy bodies);
    (b) CPU-ratio: scale the measured CPU per-round cost by the CPU/TPU
        ratio OBSERVED on the plain scan for the identical workload —
        conservative for the rounds kernel, whose wide [C, N] batches
        vectorize better than the plain scan's [N] steps on both backends.

Usage:
  python -m kubernetes_tpu.bench.rounds_proof full --out BENCH_ROUNDS_PROOF_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time


def _force_cpu() -> None:
    from ._cpu import force_cpu_from_env

    force_cpu_from_env(always=True)


def run_kernel(args) -> None:
    """Subcommand `kernel`: one kernel, one scale, this process."""
    if args.force_cpu:
        _force_cpu()
    import numpy as np
    from functools import partial

    import jax

    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops import assign
    from .workloads import spread_affinity

    t0 = time.perf_counter()
    snap = spread_affinity(args.nodes, args.pods, seed=0)
    t_gen = time.perf_counter() - t0
    enc = DeltaEncoder()
    t0 = time.perf_counter()
    arr, meta = enc.encode_device(snap)
    t_encode = time.perf_counter() - t0
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)

    if args.kernel == "rounds":
        fn = jax.jit(
            partial(assign.schedule_scan_rounds, with_rounds=True),
            static_argnames=("cfg",),
        )
    else:
        fn = jax.jit(assign.schedule_scan, static_argnames=("cfg",))

    t0 = time.perf_counter()
    out = fn(arr, cfg)
    res = [np.asarray(x) for x in out]  # block
    t_cold = time.perf_counter() - t0
    choices = res[0]
    rounds = res[2] if args.kernel == "rounds" else None

    t_warm = None
    if args.warm:
        t0 = time.perf_counter()
        res = [np.asarray(x) for x in fn(arr, cfg)]
        t_warm = time.perf_counter() - t0

    np.save(args.out, choices)
    row = {
        "kernel": args.kernel,
        "n_nodes": args.nodes,
        "n_pods": args.pods,
        "bucketed_N": int(arr.N),
        "bucketed_P": int(arr.P),
        "repair_iters": assign._REPAIR_ITERS if args.kernel == "rounds" else None,
        "gen_s": round(t_gen, 2),
        "encode_s": round(t_encode, 2),
        "compile_plus_step_s": round(t_cold, 2),
        "warm_step_s": round(t_warm, 2) if t_warm is not None else None,
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2
        ),
        "scheduled": int((choices[: meta.n_pods] >= 0).sum()),
        "backend": jax.default_backend(),
    }
    if rounds is not None:
        row.update(
            rounds_total=int(rounds.sum()),
            rounds_per_chunk_mean=round(float(rounds.mean()), 2),
            rounds_per_chunk_max=int(rounds.max()),
            n_chunks=int(rounds.shape[0]),
        )
    print(json.dumps(row))


def _sub(extra_env, *argv, timeout_s=7200):
    env = dict(os.environ, **extra_env)
    cmd = [sys.executable, "-u", "-m", "kubernetes_tpu.bench.rounds_proof",
           *argv]
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        if line.strip().startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {"error": f"rc={r.returncode} tail={r.stderr.strip()[-600:]}",
            "elapsed_s": round(time.time() - t0, 1)}


def _cost_model(full_rounds_row):
    """Per-round TPU cost projection, both ways, from the artifact's own
    measured numbers plus round-3's TPU anchors."""
    C, N = 128, full_rounds_row.get("bucketed_N", 20480)
    T_est = 220  # spread_affinity terms at 200 apps (svc terms + hostname)
    iters = full_rounds_row.get("repair_iters") or 1
    # (a) bytes per round: base+fit patch [C,N] rw (4), pairwise re-hoist
    # gathers (cnt/anti/pref/total rows per pod ~6 arrays [C,N] read), and
    # ~3 [C,N]-shaped reduction intermediates PER speculate/repair pass
    # (1 speculation + `iters` repairs), f32.
    arrays_cn = 2 * 2 + 6 + 3 * (1 + iters)
    bytes_per_round = arrays_cn * C * N * 4
    bw_ceiling = 819e9  # v5e HBM
    achieved = 0.40  # conservative for gather-heavy bodies
    t_round_bytes = bytes_per_round / (bw_ceiling * achieved)
    rounds_total = full_rounds_row.get("rounds_total")
    model = {
        "C": C, "N": N, "terms_est": T_est,
        "cn_array_traversals_per_round": arrays_cn,
        "bytes_per_round_mb": round(bytes_per_round / 1e6, 1),
        "assumed_achieved_bw_gbs": round(bw_ceiling * achieved / 1e9),
        "projected_tpu_s_per_round_bytes_model": round(t_round_bytes * 1e6),
        "projected_tpu_s_per_round_bytes_model_unit": "us",
    }
    if rounds_total:
        model["projected_tpu_step_s_bytes_model"] = round(
            rounds_total * t_round_bytes, 3
        )
    return model


def run_full(args) -> None:
    art: dict = {
        "artifact": "rounds-kernel north-star-scale proof",
        "recorded_unix": time.time(),
        "force_cpu": bool(args.force_cpu),
    }
    fc = ["--force-cpu"] if args.force_cpu else []
    tmp = "/tmp/rounds_proof_%d" % os.getpid()
    os.makedirs(tmp, exist_ok=True)

    # ---- north-star scale: rounds then plain, then compare ----
    n, p = args.nodes, args.pods
    r_npy = os.path.join(tmp, "rounds.npy")
    p_npy = os.path.join(tmp, "plain.npy")
    # pin the SHIPPING repair-iters for the headline rows — a KTPU_REPAIR_ITERS
    # left in the operator's shell from a prior sweep must not silently make
    # the proof artifact measure a non-shipping config.  1 is the measured
    # optimum (see ops/assign.py — _REPAIR_ITERS).
    ship = {"KTPU_REPAIR_ITERS": "1"}
    print(f"[proof] rounds kernel at {p}x{n} ...", file=sys.stderr)
    art["north_star_rounds"] = _sub(
        ship, "kernel", "--nodes", str(n), "--pods", str(p),
        "--kernel", "rounds", "--out", r_npy, *fc,
        timeout_s=args.timeout)
    print(f"[proof] plain scan at {p}x{n} ...", file=sys.stderr)
    art["north_star_plain"] = _sub(
        ship, "kernel", "--nodes", str(n), "--pods", str(p),
        "--kernel", "plain", "--out", p_npy, *fc,
        timeout_s=args.timeout)
    try:
        import numpy as np

        a, b = np.load(r_npy), np.load(p_npy)
        art["decisions_bit_identical"] = bool((a == b).all())
        art["decisions_compared"] = int(a.size)
    except Exception as e:  # noqa: BLE001 — artifact over crash
        art["decisions_bit_identical"] = None
        art["compare_error"] = repr(e)

    # ---- repair-iters sweep at BASELINE config-3 scale ----
    sweep = {}
    for iters in (1, 2, 3):
        print(f"[proof] sweep repair_iters={iters} ...", file=sys.stderr)
        sweep[str(iters)] = _sub(
            {"KTPU_REPAIR_ITERS": str(iters)},
            "kernel", "--nodes", str(args.sweep_nodes),
            "--pods", str(args.sweep_pods), "--kernel", "rounds",
            "--out", os.path.join(tmp, f"sweep{iters}.npy"), "--warm", *fc,
            timeout_s=args.timeout)
    art["repair_iters_sweep_at_sweep_scale"] = {
        "n_nodes": args.sweep_nodes, "n_pods": args.sweep_pods,
        "points": sweep,
    }
    # sweep parity: all iters must produce identical decisions
    try:
        import numpy as np

        arrs = [np.load(os.path.join(tmp, f"sweep{i}.npy")) for i in (1, 2, 3)]
        art["sweep_decisions_identical"] = bool(
            (arrs[0] == arrs[1]).all() and (arrs[1] == arrs[2]).all()
        )
    except Exception as e:  # noqa: BLE001
        art["sweep_decisions_identical"] = None
        art["sweep_compare_error"] = repr(e)

    if isinstance(art["north_star_rounds"], dict) and \
            "rounds_total" in art["north_star_rounds"]:
        art["tpu_cost_model"] = _cost_model(art["north_star_rounds"])
        art["tpu_cost_model"]["anchors"] = {
            "perpod_scan_tpu_s_50kx20k": 5.784,
            "perpod_scan_tpu_us_per_step": 113.0,
            "perpod_scan_tpu_s_10kx5k": 0.99,
            "source": "BENCH_MATRIX_r03.json (real v5e, round 3)",
        }

    with open(args.out, "w") as f:
        json.dump(art, f, indent=2)
    print(json.dumps({"wrote": args.out}))


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    k = sub.add_parser("kernel")
    k.add_argument("--nodes", type=int, required=True)
    k.add_argument("--pods", type=int, required=True)
    k.add_argument("--kernel", choices=("rounds", "plain"), required=True)
    k.add_argument("--out", required=True)
    k.add_argument("--warm", action="store_true")
    k.add_argument("--force-cpu", action="store_true")
    f = sub.add_parser("full")
    f.add_argument("--out", default="BENCH_ROUNDS_PROOF_r05.json")
    f.add_argument("--nodes", type=int, default=20_000)
    f.add_argument("--pods", type=int, default=50_000)
    f.add_argument("--sweep-nodes", type=int, default=5_000)
    f.add_argument("--sweep-pods", type=int, default=10_240)
    f.add_argument("--force-cpu", action="store_true")
    f.add_argument("--timeout", type=int, default=10_800)
    args = ap.parse_args()
    if args.cmd == "kernel":
        run_kernel(args)
    else:
        run_full(args)


if __name__ == "__main__":
    main()
