"""scheduler_perf-style benchmark harness — L6.

Analog of test/integration/scheduler_perf/scheduler_perf.go: YAML-described
workloads (createNodes / createPods / measure ops) run against the REAL
in-process runtime — ClusterStore -> watch -> queue -> batched TPU cycle ->
bind — measuring SchedulingThroughput (pods/s) and attempt-latency quantiles
from the scheduler's own metrics, emitting perfdata JSON.

Workload YAML:

  name: Config3
  ops:
    - {op: createCluster, generator: spread_affinity, nodes: 5000, pods: 10000}
    - {op: measure}

Generators live in bench/workloads.py (the performance-config.yaml analog).

Usage: python -m kubernetes_tpu.bench.harness [--config FILE] [--out FILE]
       (no --config: runs the five BASELINE.md configs at reduced scale
        unless --full is given).  --trace captures a span trace per round
       and writes Perfetto-loadable JSON next to --out (--trace-device DIR
       additionally records the jax.profiler device trace).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api.snapshot import Snapshot
from ..scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from ..scheduler.tracing import TraceCollector, device_trace
from . import workloads


@dataclass
class PerfData:
    name: str
    n_nodes: int
    n_pods: int
    scheduled: int
    unschedulable: int
    wall_s: float
    pods_per_sec: float
    # quantiles over PER-POD scheduling latency.  cpu mode records real
    # per-attempt durations; batch (tpu/native) modes — gang fixpoints
    # included — record per-pod ESTIMATES from each pod's commit ordinal
    # (the sequential device sweep that decided it) scaled by the kernel
    # wall (scheduler._observe_wave_latency).  latency_source says which;
    # "batch" (per-wave durations, p50==p99) remains only for waves that
    # produced no per-pod data (e.g. sidecar offload).
    p50_ms: float
    p90_ms: float
    p99_ms: float
    batches: int = 1  # waves (batch-duration samples), NOT latency samples
    amortized_ms_per_pod: float = 0.0
    latency_source: str = "batch"
    # how the run was DRIVEN, for the regression gate's comparability
    # guard: "closed-loop" (snapshot/churn/stream rounds), "batch" (the
    # latency_source=="batch" degenerate case — per-wave walls, p50==p99,
    # never comparable against a real distribution) or "open-loop"
    # (bench/loadgen.py replay artifacts).  bench/regression.py skips
    # priors whose latency_mode differs when gating a latency metric.
    latency_mode: str = "closed-loop"
    # error bar on per-pod-estimate latencies: the uniform-sweep assumption
    # was calibrated against true cumulative wall at chunk-prefix
    # boundaries (bench/latency_calibration.py, round 5: max |measured -
    # estimated| wall fraction = 0.055 over 4 probes at config-3 scale)
    latency_estimate_error: Optional[str] = None
    # the headline SLI: TRUE per-pod arrival -> bind latency
    # (pod_scheduling_sli_duration_seconds — stamped at queue admission,
    # observed at bind publication; deferred commits included)
    sli_p50_ms: float = 0.0
    sli_p99_ms: float = 0.0
    sli_count: int = 0
    # cycle attribution report (scheduler/attribution.py) when the round
    # captured a span trace with --attribution
    attribution: Optional[Dict] = None
    # crash-restart accounting (kill.* chaos storms): process restarts the
    # round survived, and the HA/failover series next to the SLI —
    # scheduler_restarts_total / leader_election_transitions_total /
    # failover p50/p99 + checkpoint_corrupt_total (ha_fields)
    restarts: int = 0
    ha: Optional[Dict] = None
    # explainability plane (ISSUE 13), stamped next to the event counts:
    # API-object event publications the recorder's token bucket refused
    # (events_publish_dropped_total — without it the drop is silent), and
    # the run's top unschedulable reasons from
    # pod_unschedulable_reasons_total{reason} (KTPU_EXPLAIN=1 device
    # cycles + every CPU-path failure)
    events_publish_dropped: int = 0
    unschedulable_reasons: Optional[Dict[str, int]] = None
    # queue-pool depth observability (scheduler/queue.py — depths(), sampled
    # at each cycle boundary): {pool: {"final": gauge, "peak": high-water}}
    # for activeQ / backoff / unschedulable / parked, stamped next to
    # sli_p99_ms — today's single pending_pods gauge cannot tell a retry
    # storm from an event-starved park
    queue_depths: Optional[Dict] = None

    def to_json(self) -> Dict:
        return self.__dict__


def _aot_warm(snap: Snapshot) -> bool:
    """AOT-compile the batch kernels for this snapshot's shape (ops/aot.py —
    lower().compile()).  Only worth it when the persistent compile cache is
    on: the compiled executable lands on disk, so the measured scheduler's
    first call is a cache-hit load instead of a recompile — and the warmup
    no longer costs a full throwaway run.  Returns True when it ran.

    Limitation vs the throwaway-run warmup: only the FIRST cycle's bucketed
    shape is lowered here — a workload whose retry cycles re-bucket to a
    smaller P pays those (far smaller) compiles inside the measured run the
    first time a given cache dir sees them; later processes load them from
    disk like every other shape."""
    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops.aot import compile_cache_dir, warm_kernels

    if compile_cache_dir() is None:
        return False
    enc = DeltaEncoder()
    arr, _meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    # batch=False: the measured scheduler only routes the ordinals (and
    # gang) kernels — never pay schedule_batch's compile here
    warm_kernels(arr, cfg, gang=bool(snap.pod_groups), batch=False)
    return True


def run_snapshot_workload(
    name: str, snap: Snapshot, mode: str = "tpu", warmup: bool = True,
    collector=None, device_trace_dir: Optional[str] = None,
) -> PerfData:
    """Measure one workload.  warmup=True first seeds the XLA compile cache
    so the timed run measures a long-lived scheduler, not binary start-up
    (scheduler_perf does the same): with the persistent compile cache
    enabled (KTPU_COMPILE_CACHE_DIR) an AOT lower().compile() pass
    suffices; otherwise an identical throwaway scheduler run.

    collector: a TraceCollector capturing the measured run's span trace
    (the warmup run never traces); device_trace_dir additionally wraps the
    run in the jax.profiler device trace (scheduler/tracing.py —
    device_trace), pairing host spans with the XLA timeline."""
    import contextlib

    if warmup and mode == "tpu" and not _aot_warm(snap):
        run_snapshot_workload(name, snap, mode, warmup=False)
    sched = _setup_cluster(snap, mode, collector=collector)

    cm = (
        device_trace(device_trace_dir)
        if device_trace_dir
        else contextlib.nullcontext()
    )
    from .. import chaos as chaos_mod

    t0 = time.perf_counter()
    restarts = 0
    with cm:
        if chaos_mod.enabled():
            # chaos-armed rounds run the full HA protocol: a kill.* fault
            # fells the leader and a standby's leader-elected takeover
            # (lease CAS past expiry -> build + restore()) resumes the run,
            # so the blackout lands in failover_duration_seconds and the
            # artifact's ha block (metrics and collector are shared across
            # incarnations — the SLI spans the blackouts honestly).  Storms
            # without kill sites never raise, so this is run_until_idle
            # plus one lease write.
            from ..scheduler import run_ha_restartable

            sched, restarts = run_ha_restartable(sched)
        else:
            sched.run_until_idle()
    wall = time.perf_counter() - t0
    return _perfdata(name, snap, sched, len(snap.pending_pods), wall,
                     restarts=restarts)


# the registry KTPU_METRICS scrapes: whichever run is currently measuring
# publishes its Metrics here (one harness process measures one run at a
# time; the /metrics route always reflects the live run)
_CURRENT_METRICS: Dict[str, Optional[object]] = {"m": None}


def sli_fields(metrics) -> Dict:
    """The headline-SLI artifact triple — sli_p50_ms/sli_p99_ms/sli_count —
    read atomically from the registry (one definition shared by every
    emitter: both streaming branches, PerfData, bench.py)."""
    h = metrics.hists.get("pod_scheduling_sli_duration_seconds")
    p50, p99, count = h.stats() if h is not None else (0.0, 0.0, 0)
    return {
        "sli_p50_ms": round(p50 * 1e3, 2),
        "sli_p99_ms": round(p99 * 1e3, 2),
        "sli_count": count,
    }


def commit_wave_fields(arr, cfg, meta, inc=None, mesh=None) -> Dict:
    """The commit-wave anatomy pair stamped next to unique_classes /
    dirty_node_fraction (ops/assign.py — class-batched commit waves):

    - ``rounds_executed``: the kernel's total sweep count for one warm
      wave (wave blocks + any stage-B repair rounds on the batched route;
      the full prefix-commit round count when KTPU_CLASS_WAVES=0).  This
      is THE number the class batching collapses, so ci.sh regression-
      gates it over the BENCH_r*.json trajectory like step_s.
    - ``classes_committed_per_round``: mean distinct equivalence classes
      committed per sweep over the scheduled pods — the class-level
      batching factor a wave buys over the one-pod-frontier round loop
      (≈1.0 there by construction).  None on routes without class state.

    One untimed ordinal probe of the routed kernel (decisions are
    bit-identical to the timed runs — PARITY.md), shared by bench.py and
    the --stream artifact."""
    import numpy as np

    from ..ops.assign import schedule_batch_ordinals_routed

    c, _, o, s = schedule_batch_ordinals_routed(
        arr, cfg, donate=False, mesh=mesh, inc=inc
    )
    c = np.asarray(c)[: meta.n_pods]
    o = np.asarray(o)[: meta.n_pods]
    cpr = None
    cls = getattr(inc, "cls", None)
    m = c >= 0
    if cls is not None and m.any():
        pairs = np.stack([o[m], np.asarray(cls)[: meta.n_pods][m]])
        n_rounds = len(np.unique(o[m]))
        cpr = round(np.unique(pairs, axis=1).shape[1] / max(1, n_rounds), 2)
    return {
        "rounds_executed": int(s),
        "classes_committed_per_round": cpr,
    }


def _commit_wave_probe(snap: "Snapshot", mesh) -> Dict:
    """commit_wave_fields over a raw Snapshot: encode + warm the class
    hoist exactly like the pipelined loop does, then run the one untimed
    ordinal probe (the streaming artifact's stamping path)."""
    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops.assign import inc_route_applies
    from ..ops.incremental import HoistCache

    arr, meta = DeltaEncoder().encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = (
        HoistCache(mesh=mesh).ensure(arr, meta, cfg)
        if inc_route_applies(arr, cfg) else None
    )
    return commit_wave_fields(arr, cfg, meta, inc=inc, mesh=mesh)


def ha_fields(metrics) -> Optional[Dict]:
    """The failover-observability artifact block, stamped next to the SLI
    triple: restart/transition counters plus the failover_duration_seconds
    quantiles (leases.py — HAReplica takeover blackout).  None when the run
    never restarted, took over, or quarantined a checkpoint — untouched
    rounds keep their artifact shape."""
    counters, _gauges, _hists = metrics.snapshot()
    h = metrics.hists.get("failover_duration_seconds")
    p50, p99, count = h.stats() if h is not None else (0.0, 0.0, 0)
    out = {
        "scheduler_restarts_total": counters.get("scheduler_restarts_total", 0.0),
        "leader_election_transitions_total": counters.get(
            "leader_election_transitions_total", 0.0
        ),
        "checkpoint_corrupt_total": counters.get("checkpoint_corrupt_total", 0.0),
        "failover_p50_ms": round(p50 * 1e3, 2),
        "failover_p99_ms": round(p99 * 1e3, 2),
        "failover_count": count,
    }
    if not any(out.values()):
        return None
    return out


def event_fields(metrics) -> Dict:
    """The explainability artifact pair next to the event counts:
    events_publish_dropped (token-bucket refusals — scheduled/unschedulable
    counts read the COMPLETE in-memory log, so a nonzero value here means
    `kubectl get events` undercounts them) and the run's top unschedulable
    reasons (one definition shared by PerfData and the streaming artifact)."""
    counters, _gauges, _hists = metrics.snapshot()
    dropped = counters.get("events_publish_dropped_total", 0.0)
    series = metrics.labeled_counter_series("pod_unschedulable_reasons_total")
    reasons = {
        dict(key).get("reason", ""): int(v)
        for key, v in sorted(series.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    }
    return {
        "events_publish_dropped": int(dropped),
        "unschedulable_reasons": reasons or None,
    }


def queue_fields(metrics) -> Dict:
    """The queue-pool depth artifact block — final + peak depth per pool
    from the cycle-boundary gauges (scheduler.py — _sample_queue_depths).
    None when the run never sampled (no batch cycle ran), so untouched
    rounds keep their artifact shape."""
    _counters, gauges, _hists = metrics.snapshot()
    out = {}
    for pool in ("active", "backoff", "unschedulable", "parked"):
        name = f"queue_pool_{pool}_pods"
        if name in gauges or f"{name}_peak" in gauges:
            out[pool] = {
                "final": int(gauges.get(name, 0)),
                "peak": int(gauges.get(f"{name}_peak", 0)),
            }
    return {"queue_depths": out or None}


def memwatch_fields(loop, metrics, n_shards: int,
                    mesh_shape: Optional[Tuple[int, int]] = None) -> Dict:
    """The HBM telemetry artifact block (scheduler/memwatch.py): the
    loop's ledger summary — `hbm_peak_bytes` / `hbm_resident_bytes`
    stamped top-level so `bench.regression --metric hbm_peak_bytes` gates
    the measured HBM trajectory like step time — plus the PR-4 scale-out
    numbers as LIVE gauges (`n_shards`, `per_shard_hbm_bytes`), so a
    /metrics scrape sees the same story the artifact tells.  `mesh_shape`
    is the 2-D (pod_shards, node_shards) grid; when None it is taken from
    the ledger's own mesh so 1-D callers need no change.  Empty when
    KTPU_MEMWATCH=0 disabled the ledger."""
    mw = getattr(loop, "memwatch", None)
    if mw is None:
        return {}
    fields = mw.summary()
    if mesh_shape is None:
        mesh_shape = (getattr(mw, "pod_shards", 1),
                      getattr(mw, "node_shards", n_shards))
    fields["mesh_shape"] = [int(mesh_shape[0]), int(mesh_shape[1])]
    if metrics is not None:
        metrics.set("n_shards", n_shards)
        metrics.set("mesh_pod_shards", int(mesh_shape[0]))
        metrics.set("mesh_node_shards", int(mesh_shape[1]))
    est = mw.per_shard_hbm_estimate()
    if est is not None:
        fields["per_shard_hbm_bytes"] = est
        if metrics is not None:
            metrics.set("per_shard_hbm_bytes", est)
    return fields


def _export_trace(collector, path: str) -> None:
    """Write the Perfetto export and print the one-line trace summary —
    flagging an INCOMPLETE trace (ring wrapped, spans dropped) so
    downstream attribution is never silently under-counted."""
    out_path = collector.export_chrome_trace(path)
    dropped = (
        f", {collector.spans_dropped} dropped — INCOMPLETE"
        if collector.spans_dropped else ""
    )
    print(f"trace: {out_path} ({len(collector.spans())} spans{dropped}; "
          "open in Perfetto)", file=sys.stderr)


def _setup_cluster(snap: Snapshot, mode: str, collector=None):
    """Store + scheduler seeded from a snapshot (pod groups, pre-bound pods,
    AND storage/DRA objects) — shared by the measure and churn ops.  The
    storage seeding matters: without it Config4S's claimant pods resolve
    their PVCs as missing (unsatisfiable) and the measured wall is
    unschedulable-retry churn, not storage-path cost."""
    store = ClusterStore()
    for nd in snap.nodes:
        store.add_node(nd)
    for sc in snap.storage_classes.values():
        store.add_object("StorageClass", sc)
    for pv in snap.pvs:
        store.add_pv(pv)
    for pvc in snap.pvcs.values():
        store.add_pvc(pvc)
    for sl in snap.resource_slices:
        store.add_object("ResourceSlice", sl)
    for dc in snap.device_classes.values():
        store.add_object("DeviceClass", dc)
    # default: a disabled collector, so an untraced bench run pays zero span
    # allocation (and never routes through the shared process collector)
    if collector is None:
        collector = TraceCollector(enabled=False)
    sched = Scheduler(store, SchedulerConfiguration(mode=mode),
                      collector=collector)
    _CURRENT_METRICS["m"] = sched.metrics  # the KTPU_METRICS scrape target
    for g, pg in snap.pod_groups.items():
        sched.cache.pod_groups[g] = pg
    for p in snap.pending_pods:
        store.add_pod(p)
    for p in snap.bound_pods:
        store.add_pod(p)
    return sched


def _perfdata(name: str, snap: Snapshot, sched, n_pods: int, wall: float,
              restarts: int = 0) -> PerfData:
    scheduled = len(sched.events.by_reason("Scheduled"))
    failed = len(sched.events.by_reason("FailedScheduling"))
    source = "attempt"
    hist = sched.metrics.hists.get("scheduling_attempt_duration_seconds")
    if not (hist and hist.count):
        source = "per-pod-estimate"
        hist = sched.metrics.hists.get(
            "scheduling_attempt_duration_estimate_seconds"
        )
    if not (hist and hist.count):
        source = "batch"
        hist = sched.metrics.hists.get("batch_scheduling_duration_seconds")
    q = (lambda p: hist.quantile(p) * 1e3) if hist else (lambda p: 0.0)
    batch_hist = sched.metrics.hists.get("batch_scheduling_duration_seconds")
    sli = sli_fields(sched.metrics)
    return PerfData(
        name=name,
        n_nodes=len(snap.nodes),
        n_pods=n_pods,
        scheduled=scheduled,
        unschedulable=failed,
        wall_s=round(wall, 3),
        pods_per_sec=round(scheduled / wall, 1) if wall > 0 else 0.0,
        p50_ms=round(q(0.50), 2),
        p90_ms=round(q(0.90), 2),
        p99_ms=round(q(0.99), 2),
        batches=batch_hist.count if batch_hist else 0,
        amortized_ms_per_pod=round(wall * 1e3 / scheduled, 3) if scheduled else 0.0,
        latency_source=source,
        # per-wave batch walls are p50==p99 degenerate: label them so the
        # regression gate never compares them against a real distribution
        latency_mode="batch" if source == "batch" else "closed-loop",
        latency_estimate_error=(
            "±5.5% wall fraction (cpu-sim, config-3 scale, r05; re-measure"
            " per backend/shape: bench/latency_calibration.py)"
            if source == "per-pod-estimate" else None
        ),
        **sli,
        restarts=restarts,
        ha=ha_fields(sched.metrics),
        **event_fields(sched.metrics),
        **queue_fields(sched.metrics),
    )


def _analytic_ledger(waves: List[Snapshot], mesh) -> Optional[Dict]:
    """The analytic half of the --profile reconciliation: trace the SAME
    routed kernel the measured run executes (first wave's shape, the
    resident incremental state included) and run the costmodel over its
    jaxpr.  make_jaxpr only traces — no compile — so this is cheap even at
    bench scale."""
    import jax

    from ..analysis.costmodel import jaxpr_ledger
    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops import assign as A
    from ..ops.incremental import HoistCache

    enc = DeltaEncoder()
    arr, meta = enc.encode(waves[0])
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = A.inc_applicable(
        arr, cfg, HoistCache(mesh=mesh).ensure(arr, meta, cfg)
    )
    if inc is not None:
        closed = jax.make_jaxpr(
            lambda a, i: A.schedule_batch_impl(a, cfg, i))(arr, inc)
    else:
        closed = jax.make_jaxpr(
            lambda a: A.schedule_batch_impl(a, cfg, None))(arr)
    return jaxpr_ledger(closed)


def _profile_block(out: Dict, profile_dir: str, waves: List[Snapshot],
                   mesh, collector) -> None:
    """Join the --profile capture into the streaming artifact: the measured
    sub-phase table (device_subphases + the regression-gated
    round_loop_fraction), the analytic roofline ledger, their KTPU019-style
    reconciliation, and the sub-phase spans merged into the host trace as
    children of device.step (bench/profiling.py)."""
    from ..analysis.costmodel import reconcile
    from .profiling import (
        load_profile_events, merge_profile_spans, parse_hlo_dumps,
        subphase_table,
    )

    op_map = parse_hlo_dumps(os.path.join(profile_dir, "hlo"))
    events = load_profile_events(profile_dir)
    table = subphase_table(events, op_map)
    out["device_subphases"] = table
    out["round_loop_fraction"] = table["round_loop_fraction"]
    analytic = _analytic_ledger(waves, mesh)
    out["cost_analytic"] = analytic
    if analytic is not None:
        out["subphase_reconciliation"] = reconcile(
            analytic["round_loop_fraction"], table["round_loop_fraction"]
        )
        # the regression-gated modeled-cost pair (`bench.regression --metric
        # device_flops` / `device_hbm_bytes`), next to round_loop_fraction;
        # a --verify-device run re-stamps them from the 12-route ledgers
        out["device_flops"] = analytic["total_flops"]
        out["device_hbm_bytes"] = analytic["total_hbm_bytes"]
    if collector is not None:
        merge_profile_spans(collector, events, op_map)


def run_streaming_workload(
    name: str,
    waves: List[Snapshot],
    warmup: bool = True,
    pipeline: bool = True,
    donate: Optional[bool] = None,
    collector=None,
    profile_dir: Optional[str] = None,
) -> Dict:
    """Measure the pipelined batch loop (parallel/pipeline.py —
    PipelinedBatchLoop) against the serial encode→run→block loop on a
    stream of independent snapshot waves — the PP-analog overlap benchmark.
    Returns both wall times, the identical-verdict check, the measured
    overlap fraction (host encode/commit/decode hidden under device steps)
    and the kernel-route trace counts.

    pipeline=False (the --no-pipeline escape hatch) runs ONLY the serial
    loop, so pre-pipeline numbers remain reproducible bit-for-bit.

    Kill storms: when the armed chaos plan carries kill.* sites, every
    pass (warmup included — its pokes consume the same global per-site
    ordinals) is driven by pipeline.run_stream_restartable, which answers
    each ProcessKilled with a fresh loop replaying exactly the waves the
    stream wave WAL has not committed; the measured pass owns the durable
    WAL (KTPU_CHECKPOINT_DIR) and the artifact stamps restarts /
    recovered_waves / the ha failover series next to the SLI."""
    from .. import chaos as chaos_mod
    from ..ops.assign import TRACE_COUNTS
    from ..parallel.mesh import mesh_from_env
    from ..parallel.pipeline import (
        STREAM_WAL,
        PipelinedBatchLoop,
        run_stream_restartable,
    )
    from ..scheduler.metrics import Metrics, reset_run_state
    from ..scheduler.tracing import Tracer

    # THE run-start reset hook: route counters + metrics + collector all
    # clear together, so back-to-back invocations in one process never
    # report each other's counters, SLI samples or spans
    metrics = Metrics()
    reset_run_state(metrics=metrics, collector=collector)
    _CURRENT_METRICS["m"] = metrics  # the KTPU_METRICS scrape target
    mesh = mesh_from_env()  # KTPU_MESH: sharded routed step under the loop
    inj = chaos_mod.active()
    kills_armed = inj is not None and any(
        f.site in chaos_mod.ALL_KILL_SITES for f in inj.plan.faults
    )
    ckpt = None
    if kills_armed and os.environ.get("KTPU_CHECKPOINT_DIR"):
        from ..scheduler.checkpoint import CheckpointManager

        ckpt = CheckpointManager(os.environ["KTPU_CHECKPOINT_DIR"],
                                 metrics=metrics)
        # a fresh bench measurement: a stale stream WAL from an earlier
        # run would silently skip waves as already-committed
        stale = os.path.join(ckpt.directory, f"{STREAM_WAL}.json")
        if os.path.exists(stale):
            os.remove(stale)
    if warmup:  # hit the XLA cache so the timed runs measure steady state
        if kills_armed:
            run_stream_restartable(
                waves[:1],
                lambda commit, wal: PipelinedBatchLoop(
                    donate=donate, mesh=mesh, commit=commit, wal=wal),
            )
        else:
            for _ in PipelinedBatchLoop(donate=donate, mesh=mesh).run(waves[:1]):
                pass
    import contextlib

    tracer = Tracer(collector, component="pipeline") if collector else None

    def _maybe_profile(measured: bool):
        # the MEASURED pass runs inside the jax.profiler device trace when
        # --profile asked for one (scheduler/tracing.py — device_trace);
        # the warmup/serial-reference passes never profile
        if profile_dir and measured:
            return device_trace(profile_dir)
        return contextlib.nullcontext()

    t0 = time.perf_counter()
    # --no-pipeline runs have no later pipelined pass, so the serial loop
    # itself is the traced+metered run (attribution + SLI still emit);
    # when pipelining, the serial pass stays untraced/unmetered — its
    # spans and SLI samples would pollute the pipelined run's report.
    # Built as an explicit depth-0 loop (run_serial's exact dataflow) so
    # the --no-pipeline branch can read the loop's memwatch ledger.
    serial_kw = dict(
        donate=donate, mesh=mesh, depth=0,
        tracer=None if pipeline else tracer,
        metrics=None if pipeline else metrics,
        # when pipelining, only the pipelined runner's ledger is ever
        # stamped — sampling the reference pass would be pure waste
        # inside the timed serial_s window
        memwatch=None if not pipeline else False,
    )
    serial_loop = PipelinedBatchLoop(**serial_kw)
    serial_restarts = 0
    with _maybe_profile(not pipeline):
        if kills_armed:
            holder = [serial_loop]

            def _serial_factory(commit, wal):
                holder[0] = PipelinedBatchLoop(**serial_kw, commit=commit,
                                               wal=wal)
                return holder[0]

            serial, serial_restarts = run_stream_restartable(
                waves, _serial_factory,
                # the MEASURED pass owns the durable WAL and the HA
                # series; when pipelining, this serial pass is only the
                # unmetered reference oracle
                checkpoint=None if pipeline else ckpt,
                metrics=None if pipeline else metrics,
            )
            serial_loop = holder[0]  # memwatch/stats read the last loop
        else:
            serial = list(serial_loop.run(waves))
    t_serial = time.perf_counter() - t0
    out = {
        "name": name,
        "waves": len(waves),
        "n_pods": sum(len(w.pending_pods) for w in waves),
        "serial_s": round(t_serial, 3),
        "pipeline": pipeline,
        "n_shards": int(mesh.size) if mesh is not None else 1,
        "route_trace_counts": dict(TRACE_COUNTS),
    }
    # commit-wave anatomy (rounds_executed / classes_committed_per_round):
    # one untimed ordinal probe of the last wave, stamped next to the
    # hoist summary's unique_classes / dirty_node_fraction below — outside
    # both measured loops, so it never pollutes serial_s or pipelined_s
    out.update(_commit_wave_probe(waves[-1], mesh))
    pods = out["n_pods"]
    if not pipeline:
        out.update(
            pipelined_s=None, overlap_gain=None, overlap_fraction=0.0,
            pods_per_sec=round(pods / t_serial, 1) if t_serial > 0 else 0.0,
            # crash-restart accounting: fresh-loop restarts + within-loop
            # serial-replay recoveries, and the HA/failover series next
            # to the SLI (same contract as the snapshot rounds)
            restarts=serial_restarts,
            recovered_waves=(serial_restarts
                             + int(serial_loop.stats["recovered"])),
            ha=ha_fields(metrics),
            **sli_fields(metrics),
            **event_fields(metrics),
            # measured HBM telemetry (scheduler/memwatch.py):
            # hbm_peak_bytes / hbm_resident_bytes + the sentinel block
            **memwatch_fields(serial_loop, metrics, out["n_shards"]),
        )
        if out["ha"]:
            # failover quantiles top-level next to sli_p99_ms, so
            # `bench.regression --metric failover_p99_ms` gates them
            out["failover_p50_ms"] = out["ha"]["failover_p50_ms"]
            out["failover_p99_ms"] = out["ha"]["failover_p99_ms"]
        if profile_dir:
            _profile_block(out, profile_dir, waves, mesh, collector)
        if collector is not None:
            from ..scheduler.attribution import attribute_spans

            out["attribution"] = attribute_spans(
                collector, device_subphases=out.get("device_subphases"))
        return out
    runner = PipelinedBatchLoop(donate=donate, tracer=tracer, mesh=mesh,
                                metrics=metrics)
    restarts = 0
    t0 = time.perf_counter()
    with _maybe_profile(True):
        if kills_armed:
            holder = [runner]

            def _pipe_factory(commit, wal):
                holder[0] = PipelinedBatchLoop(donate=donate, tracer=tracer,
                                               mesh=mesh, metrics=metrics,
                                               commit=commit, wal=wal)
                return holder[0]

            pipelined, restarts = run_stream_restartable(
                waves, _pipe_factory, checkpoint=ckpt, metrics=metrics,
            )
            runner = holder[0]  # overlap/hoist/memwatch read the last loop
        else:
            pipelined = list(runner.run(waves))
    t_pipe = time.perf_counter() - t0
    assert pipelined == serial, "pipelined verdicts diverged from serial"
    out.update(
        pipelined_s=round(t_pipe, 3),
        overlap_gain=round(t_serial / t_pipe, 3) if t_pipe > 0 else 0.0,
        overlap_fraction=round(runner.overlap_fraction(), 3),
        donated_waves=int(runner.stats["donated"]),
        pods_per_sec=round(pods / t_pipe, 1) if t_pipe > 0 else 0.0,
        route_trace_counts=dict(TRACE_COUNTS),
        # crash-restart accounting (same contract as the snapshot rounds):
        # fresh-loop restarts + within-loop serial-replay recoveries and
        # the HA/failover series next to the SLI
        restarts=restarts,
        recovered_waves=restarts + int(runner.stats["recovered"]),
        ha=ha_fields(metrics),
        # the headline SLI next to throughput: per-pod arrival -> bind
        **sli_fields(metrics),
        **event_fields(metrics),
        # incremental warm-cycle attribution (ops/incremental.py)
        **runner.hoist.summary(),
        # measured HBM telemetry (scheduler/memwatch.py): hbm_peak_bytes
        # / hbm_resident_bytes stamped top-level (regression-gated) + the
        # sentinel block; the scale-out gauges mirror the artifact
        **memwatch_fields(runner, metrics, out["n_shards"]),
    )
    if out["ha"]:
        # failover quantiles top-level next to sli_p99_ms, so
        # `bench.regression --metric failover_p99_ms` gates them
        out["failover_p50_ms"] = out["ha"]["failover_p50_ms"]
        out["failover_p99_ms"] = out["ha"]["failover_p99_ms"]
    if profile_dir:
        _profile_block(out, profile_dir, waves, mesh, collector)
    if collector is not None:
        # cycle attribution from the captured spans, embedded next to
        # route_trace_counts (scheduler/attribution.py); with a --profile
        # capture the kernel-interior sub-phase table nests below
        # device_kernel in the same report
        from ..scheduler.attribution import attribute_spans

        out["attribution"] = attribute_spans(
            collector, device_subphases=out.get("device_subphases"))
    return out


GENERATORS = {
    "basic": lambda **kw: workloads.basic(kw["nodes"], kw["pods"], kw.get("seed", 0)),
    "spread_affinity": lambda **kw: workloads.spread_affinity(
        kw["nodes"], kw["pods"], kw.get("seed", 0), kw.get("zones", 3)
    ),
    "heterogeneous": lambda **kw: workloads.heterogeneous(
        kw["nodes"], kw["pods"], kw.get("seed", 0)
    ),
    "heterogeneous_storage": lambda **kw: workloads.heterogeneous_storage(
        kw["nodes"], kw["pods"], kw.get("seed", 0)
    ),
    "gang": lambda **kw: workloads.gang(
        kw["groups"], kw["group_size"], kw["nodes"], kw.get("seed", 0)
    ),
}


def run_churn_workload(
    name: str,
    snap: Snapshot,
    rounds: int = 5,
    churn_fraction: float = 0.2,
    mode: str = "tpu",
    seed: int = 0,
    warmup: bool = True,
) -> PerfData:
    """scheduler_perf's churn workloads: after the initial wave binds, each
    round deletes a fraction of the bound pods and re-creates equivalents —
    measuring steady-state throughput under arrival/departure pressure, not
    just the cold bulk placement."""
    import copy
    import random

    if warmup and mode == "tpu":  # same steady-state rule as the measure op
        run_snapshot_workload(name, snap, mode, warmup=False)
    rng = random.Random(seed)
    sched = _setup_cluster(snap, mode)
    store = sched.store
    t0 = time.perf_counter()
    sched.run_until_idle()
    for r in range(rounds):
        bound = [p for p in store.list_pods() if p.node_name]
        if not bound:
            break  # nothing scheduled: nothing to churn
        k = min(len(bound), max(1, int(len(bound) * churn_fraction)))
        for v in rng.sample(bound, k):
            store.delete_pod(v.uid)
            q = copy.copy(v)
            q.name = f"{v.name}-r{r}"
            q.uid = ""
            q.node_name = ""
            q.__post_init__()
            store.add_pod(q)
        sched.run_until_idle()
    wall = time.perf_counter() - t0
    scheduled = len(sched.events.by_reason("Scheduled"))
    return _perfdata(name, snap, sched, scheduled, wall)


def run_yaml(text: str, mode: str = "tpu", trace_base: Optional[str] = None,
             device_trace_dir: Optional[str] = None,
             attribution: bool = False) -> List[PerfData]:
    """trace_base != None captures one span trace per measured round and
    writes Perfetto-loadable JSON next to the perfdata artifact
    (<trace_base>.<round name>.trace.json).  attribution=True additionally
    runs the cycle attribution engine over each round's spans and embeds
    the report in the round's PerfData (a collector is captured per round
    even without --trace)."""
    import yaml

    results = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        snap = None
        for op in doc.get("ops", []):
            kind = op.get("op")
            if kind == "createCluster":
                gen = GENERATORS[op["generator"]]
                snap = gen(**{k: v for k, v in op.items() if k not in ("op", "generator")})
            elif kind == "measure":
                assert snap is not None, "createCluster must precede measure"
                name = doc.get("name", "unnamed")
                collector = (
                    TraceCollector() if (trace_base or attribution) else None
                )
                results.append(
                    run_snapshot_workload(
                        name, snap, mode, warmup=op.get("warmup", True),
                        collector=collector,
                        device_trace_dir=(
                            f"{device_trace_dir}/{name}" if device_trace_dir else None
                        ),
                    )
                )
                if collector is not None and attribution:
                    from ..scheduler.attribution import (
                        attribute_spans,
                        render_attribution,
                    )

                    report = attribute_spans(collector)
                    results[-1].attribution = report
                    print(render_attribution(report), file=sys.stderr)
                if collector is not None and trace_base:
                    _export_trace(collector,
                                  f"{trace_base}.{name}.trace.json")
            elif kind == "churn":
                assert snap is not None, "createCluster must precede churn"
                results.append(
                    run_churn_workload(
                        doc.get("name", "unnamed") + "_churn",
                        snap,
                        rounds=op.get("rounds", 5),
                        churn_fraction=op.get("fraction", 0.2),
                        mode=mode,
                        seed=op.get("seed", 0),
                    )
                )
    return results


# The five BASELINE.md configs (full scale), and a reduced smoke variant.
BASELINE_CONFIGS = """
name: Config1_SchedulingBasic
ops:
  - {op: createCluster, generator: basic, nodes: 100, pods: 100}
  - {op: measure}
---
name: Config2_NodeResourcesFit
ops:
  - {op: createCluster, generator: basic, nodes: 1000, pods: 5000}
  - {op: measure}
---
name: Config3_SpreadAffinity
ops:
  - {op: createCluster, generator: spread_affinity, nodes: 5000, pods: 10000, zones: 3}
  - {op: measure}
---
name: Config4_Heterogeneous
ops:
  - {op: createCluster, generator: heterogeneous, nodes: 20000, pods: 20000}
  - {op: measure}
---
name: Config5_Gang
ops:
  - {op: createCluster, generator: gang, groups: 1000, group_size: 64, nodes: 2000}
  - {op: measure}
---
name: Config4S_HeterogeneousStorage
ops:
  - {op: createCluster, generator: heterogeneous_storage, nodes: 20000, pods: 20000}
  - {op: measure}
"""

SMOKE_CONFIGS = """
name: Config1_SchedulingBasic
ops:
  - {op: createCluster, generator: basic, nodes: 100, pods: 100}
  - {op: measure}
---
name: Config2_NodeResourcesFit
ops:
  - {op: createCluster, generator: basic, nodes: 250, pods: 1000}
  - {op: measure}
---
name: Config3_SpreadAffinity
ops:
  - {op: createCluster, generator: spread_affinity, nodes: 300, pods: 600, zones: 3}
  - {op: measure}
---
name: Config4_Heterogeneous
ops:
  - {op: createCluster, generator: heterogeneous, nodes: 500, pods: 500}
  - {op: measure}
---
name: Config5_Gang
ops:
  - {op: createCluster, generator: gang, groups: 20, group_size: 16, nodes: 100}
  - {op: measure}
---
name: Config4S_HeterogeneousStorage
ops:
  - {op: createCluster, generator: heterogeneous_storage, nodes: 500, pods: 500}
  - {op: measure}
"""


def main(argv=None) -> None:
    import os

    from ._cpu import force_cpu_from_env
    from ..ops.aot import maybe_enable_compile_cache

    # --verify-device/--verify-shard want the mesh routes: force the
    # virtual multi-device CPU platform BEFORE jax initializes (no-op if
    # jax is already up — the skipped mesh routes are then listed with the
    # reason).  Must precede force_cpu_from_env, which imports jax.
    _early_argv = argv if argv is not None else sys.argv[1:]
    if "--verify-device" in _early_argv or "--verify-shard" in _early_argv \
            or "--verify-mem" in _early_argv \
            or os.environ.get("KTPU_VERIFY_DEVICE") == "1" \
            or os.environ.get("KTPU_VERIFY_SHARD") == "1" \
            or os.environ.get("KTPU_VERIFY_MEM") == "1":
        from ..analysis.devicecheck import ensure_devices

        ensure_devices()
    # --profile DIR: arm the XLA HLO text dump NOW — XLA parses the dump
    # flags once per process, and the op->named-scope join needs the dump
    # of every kernel this process compiles (bench/profiling.py)
    if "--profile" in _early_argv:
        try:
            _pdir = _early_argv[_early_argv.index("--profile") + 1]
        except IndexError:
            _pdir = ""
        if _pdir and not _pdir.startswith("-"):
            from .profiling import enable_hlo_dump

            enable_hlo_dump(os.path.join(_pdir, "hlo"))
        # the observatory's target is the production round loop: route the
        # chunked kernels even on the CPU sim (the device pass's _pass_env
        # and every BENCH soak run force the same routing); an explicit
        # operator setting still wins
        os.environ.setdefault("KTPU_FORCE_CHUNKED", "1")
    force_cpu_from_env()
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", help="workload YAML file")
    ap.add_argument("--out", help="perfdata JSON output path")
    ap.add_argument("--mode", default="tpu", choices=["tpu", "native", "cpu"])
    ap.add_argument("--full", action="store_true", help="run BASELINE configs at full scale")
    ap.add_argument("--stream", type=int, metavar="WAVES",
                    help="run the host<->device pipelining benchmark instead")
    ap.add_argument("--open-loop", metavar="TRACE",
                    help="replay an arrival trace OPEN-LOOP against the "
                         "scheduler (bench/loadgen.py): a named scenario "
                         "(rollout|drain|storm, seeded by "
                         "KTPU_OPEN_LOOP_SEED) or a path to a trace JSON.  "
                         "SLI ages are stamped from the trace arrival "
                         "timestamps (coordinated-omission-safe) and the "
                         "artifact stamps sli_p50_ms/sli_p99_ms, the "
                         "per-phase p99 shares and a decision_crc; the "
                         "worst pods' span timelines export as a Perfetto "
                         "trace next to --out")
    ap.add_argument("--sli-attribution", action="store_true",
                    help="with --open-loop: print the which-phase-owns-"
                         "the-p99 table (per-phase p99 shares over "
                         "pod_sli_phase_duration_seconds, the dominant "
                         "phase and the worst-pod exemplars) to stderr")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="serial encode->run->block loop and synchronous "
                         "batch commits (pre-pipeline numbers stay "
                         "reproducible)")
    ap.add_argument("--compile-cache", metavar="DIR",
                    help="persistent XLA compile cache dir (also via "
                         "KTPU_COMPILE_CACHE_DIR): later processes load "
                         "compiled kernels instead of re-paying the cold "
                         "compile")
    ap.add_argument("--trace", action="store_true",
                    help="capture a span trace per bench round and write "
                         "Perfetto JSON next to the --out artifact")
    ap.add_argument("--attribution", action="store_true",
                    help="run the cycle attribution engine over each "
                         "round's span trace (scheduler/attribution.py) "
                         "and embed the per-phase breakdown in the "
                         "artifact next to route_trace_counts")
    ap.add_argument("--trace-device", metavar="DIR",
                    help="with --trace: also capture a jax.profiler device "
                         "trace per round under DIR (TensorBoard format)")
    ap.add_argument("--profile", metavar="DIR",
                    help="with --stream: the device cost observatory — "
                         "capture the measured pass's jax.profiler device "
                         "trace under DIR plus an XLA HLO dump (DIR/hlo), "
                         "map every compiled op back to its owning "
                         "named-scope sub-phase (ops/scopes.py), and emit "
                         "the kernel-interior sub-phase self-time table "
                         "(device_subphases + the regression-gated "
                         "round_loop_fraction) with the analytic roofline "
                         "reconciliation (analysis/costmodel.py) in the "
                         "artifact; sub-phase spans join the --trace "
                         "Perfetto export as children of device.step.  "
                         "Needs a fresh process (XLA parses dump flags "
                         "once); exits 1 on a failed capture or "
                         "reconciliation")
    ap.add_argument("--chaos", type=int, metavar="SEED",
                    help="arm the fault injector with FaultPlan.from_seed "
                         "(also via KTPU_CHAOS_SEED / KTPU_FAULT_PLAN): the "
                         "run must survive the storm and the artifact "
                         "reports injected/recovered counts so recovery "
                         "overhead is priced")
    ap.add_argument("--chaos-sites", metavar="GLOB",
                    help="with --chaos: restrict the seeded storm to sites "
                         "matching the comma-separated fnmatch globs "
                         "('kill.*' = just the crash-restart kill points; "
                         "'*,!kill.*' = everything else; '!g' excludes).  "
                         "Kill storms default KTPU_CHECKPOINT_DIR to a temp "
                         "dir so restarts replay a real checkpoint")
    ap.add_argument("--verify", action="store_true",
                    help="run the ktpu-verify static-analysis pass "
                         "(python -m kubernetes_tpu.analysis) before the "
                         "workload and embed its JSON report in the "
                         "artifact; exits with the analyzer's code (1 "
                         "unbaselined findings / 2 unusable) on failure")
    ap.add_argument("--verify-device", action="store_true",
                    help="with (or implying) --verify: also run the "
                         "ktpu-verify DEVICE pass (KTPU007..012 — trace "
                         "every production kernel route, check dtype flow, "
                         "donation aliasing, collective order, cache-key "
                         "stability, transfer guard, HBM budget; "
                         "analysis/devicecheck.py); the per-route report "
                         "rides the artifact's verify block and the exit "
                         "contract is shared (also via KTPU_VERIFY_DEVICE=1)")
    ap.add_argument("--verify-shard", action="store_true",
                    help="with (or implying) --verify: also run the "
                         "ktpu-verify SHARD pass (KTPU014..018 — the "
                         "partition-rule-table authority scan plus "
                         "replicated-giant, axis-consistency, collective-"
                         "bytes reconciliation and out-sharding gates over "
                         "the traced routes; analysis/shardcheck.py); the "
                         "per-route shard report rides the artifact's "
                         "verify block, the route traces are shared with "
                         "--verify-device, and the exit contract is shared "
                         "(also via KTPU_VERIFY_SHARD=1)")
    ap.add_argument("--verify-mem", action="store_true",
                    help="with (or implying) --verify: also run the "
                         "ktpu-verify MEM pass (KTPU020 — the HBM "
                         "telemetry plane's measured-vs-analytic "
                         "reconciliation: live peak within tolerance of "
                         "shard_hbm_estimate, resident census == the "
                         "FIELD_DIMS size model, leak sentinel clean; "
                         "analysis/memrules.py); the per-route mem report "
                         "rides the artifact's verify block, the route "
                         "traces are shared with --verify-device/"
                         "--verify-shard, and the exit contract is shared "
                         "(also via KTPU_VERIFY_MEM=1)")
    args = ap.parse_args(argv)
    if args.chaos_sites and args.chaos is None:
        ap.error("--chaos-sites requires --chaos (it shapes the seeded storm)")
    if args.sli_attribution and not args.open_loop:
        ap.error("--sli-attribution pairs with --open-loop (the report "
                 "reads the open-loop phase decomposition)")
    if args.open_loop and args.stream:
        ap.error("--open-loop and --stream are different drivers — pick one")
    if args.trace_device and not args.trace:
        ap.error("--trace-device requires --trace (the device trace pairs "
                 "with the host-span trace)")
    if args.profile and not args.stream:
        ap.error("--profile pairs with --stream (the warm pipelined loop is "
                 "what the sub-phase table attributes; snapshot rounds keep "
                 "--trace-device for raw captures)")
    if args.profile and (args.compile_cache
                         or os.environ.get("KTPU_COMPILE_CACHE_DIR")):
        ap.error("--profile cannot combine with --compile-cache / "
                 "KTPU_COMPILE_CACHE_DIR: a compile-cache hit compiles "
                 "nothing, so the HLO dump (the op -> sub-phase join "
                 "source) would be empty")
    # --verify: the hack/verify-* analog gates the bench run itself — a
    # perf artifact produced by a package that fails its own invariants
    # is not evidence.  The report rides the artifact; failure exits with
    # the analyzer's 1/2 code BEFORE any workload spends device time.
    verify_block = None
    verify_device = (args.verify_device
                     or os.environ.get("KTPU_VERIFY_DEVICE") == "1")
    verify_shard = (args.verify_shard
                    or os.environ.get("KTPU_VERIFY_SHARD") == "1")
    verify_mem = (args.verify_mem
                  or os.environ.get("KTPU_VERIFY_MEM") == "1")
    if verify_device or verify_shard or verify_mem:
        args.verify = True  # the trace-pass flags imply the gate
    if args.verify:
        from ..analysis.__main__ import run_verify
        from ..analysis.engine import BaselineError

        try:
            verify_report = run_verify(device=verify_device,
                                       shard=verify_shard,
                                       mem=verify_mem)
        except BaselineError as e:
            print(f"ktpu-verify: unusable baseline: {e}", file=sys.stderr)
            sys.exit(2)
        verify_block = verify_report.to_dict()
        print(f"ktpu-verify: {verify_report.files_scanned} files, "
              f"{len(verify_report.unbaselined)} unbaselined findings",
              file=sys.stderr)
        if verify_report.exit_code != 0:
            print(verify_report.render_text(), file=sys.stderr)
            sys.exit(verify_report.exit_code)
    # run-start reset (scheduler/metrics.py — reset_run_state): route
    # counters are per-run; back-to-back harness invocations in one
    # process must not report each other's kernel routes, metrics or spans
    from ..scheduler.metrics import reset_run_state

    reset_run_state()
    # KTPU_METRICS=<port>: serve the run's metrics registries in Prometheus
    # text format for the duration of the run (scheduler/apiserver.py —
    # MetricsServer; port 0 picks an ephemeral one, printed to stderr)
    metrics_srv = None
    if os.environ.get("KTPU_METRICS"):
        from ..scheduler.apiserver import MetricsServer

        try:
            port = int(os.environ["KTPU_METRICS"])
        except ValueError:
            port = 0
        metrics_srv = MetricsServer(
            lambda: (_CURRENT_METRICS["m"].expose_text()
                     if _CURRENT_METRICS["m"] is not None else "\n"),
            port=port,
        )
        print(f"metrics: http://127.0.0.1:{metrics_srv.start()}/metrics",
              file=sys.stderr)
    if args.compile_cache:
        # publish to the env too: Scheduler.__init__ re-resolves from
        # KTPU_COMPILE_CACHE_DIR, and a conflicting stale env value would
        # otherwise fail the enable-once check mid-run
        os.environ["KTPU_COMPILE_CACHE_DIR"] = args.compile_cache
    maybe_enable_compile_cache(args.compile_cache)
    if args.no_pipeline:
        # the scheduler reads this at construction: batch commits stay
        # fully synchronous, exactly the pre-pipeline loop
        os.environ["KTPU_PIPELINE"] = "0"
    from .. import chaos as chaos_mod

    if args.chaos is not None:
        sites = None
        if args.chaos_sites:
            sites = chaos_mod.sites_matching(args.chaos_sites)
            if not sites:
                ap.error(f"--chaos-sites {args.chaos_sites!r} matches no "
                         f"chaos site (known: {', '.join(chaos_mod.SITE_ACTIONS)})")
        inj = chaos_mod.install(
            chaos_mod.FaultPlan.from_seed(args.chaos, sites=sites)
        )
    else:
        inj = chaos_mod.maybe_install_from_env()
    if inj is not None:
        print(f"chaos plan: {inj.plan.describe()}", file=sys.stderr)
        has_kills = any(
            f.site in chaos_mod.ALL_KILL_SITES for f in inj.plan.faults
        )
        # every driver survives kill.* now: snapshot rounds via the HA
        # takeover (run_ha_restartable), --stream via the stream wave WAL
        # (parallel/pipeline.run_stream_restartable) and --open-loop via
        # the mid-stream leader failover inside replay_trace
        if has_kills and not os.environ.get("KTPU_CHECKPOINT_DIR"):
            # a kill storm without a checkpoint dir would still pass parity
            # (crash-only rebuild), but the point of the storm is to exercise
            # the WAL/ledger replay — default one so restarts are real
            import tempfile

            os.environ["KTPU_CHECKPOINT_DIR"] = tempfile.mkdtemp(
                prefix="ktpu-ckpt-"
            )
            print(f"checkpoint dir: {os.environ['KTPU_CHECKPOINT_DIR']} "
                  "(kill-storm default)", file=sys.stderr)

    def _chaos_report():
        if inj is None:
            return None
        rep = inj.report()
        rep["seed"] = inj.plan.seed
        rep["sites"] = sorted({f.site for f in inj.plan.faults})
        return rep

    def _stamp_analysis(doc):
        """ktpu-verify blocks on the artifact: the embedded static-analysis
        report (--verify) and, under KTPU_LOCK_CHECK=1, the runtime
        lock-order graph observed during the run — a storm that closed a
        cycle ships the witnesses next to its chaos counts.  With the shard
        pass, the worst per-route measured collective bytes are also
        stamped top-level as `comm_bytes`, so `bench.regression --metric
        comm_bytes --higher-is-better=no` gates the all-gather budget
        alongside step time."""
        if verify_block is not None:
            doc["verify"] = verify_block
            routes = (verify_block.get("device") or {}).get("routes", [])
            comm = [
                r.get("shard", {}).get("comm_bytes_measured", 0)
                for r in routes if r.get("n_shards", 1) > 1
            ]
            if comm:
                doc["comm_bytes"] = max(comm)
            # worst per-route analytic FLOPs / HBM bytes from the cost
            # ledgers (analysis/costmodel.py), stamped top-level so
            # `bench.regression --metric device_flops` / `device_hbm_bytes`
            # gates the kernel's modeled cost exactly like comm_bytes
            costs = [r.get("cost") or {} for r in routes]
            flops = [c.get("total_flops", 0) for c in costs if c]
            hbm = [c.get("total_hbm_bytes", 0) for c in costs if c]
            if flops:
                doc["device_flops"] = max(flops)
            if hbm:
                doc["device_hbm_bytes"] = max(hbm)
            # worst per-route MEASURED HBM peak / resident census from the
            # mem pass's ledgers (scheduler/memwatch.py), stamped like
            # comm_bytes so `bench.regression --metric hbm_peak_bytes`
            # gates the measured trajectory; a --stream run's own ledger
            # summary (workload-scale, already stamped) wins over the
            # trace-scale route numbers
            mems = [r.get("mem") or {} for r in routes]
            peaks = [m.get("measured_peak_bytes", 0) for m in mems if m]
            res = [(m.get("census") or {}).get("resident_bytes", 0)
                   for m in mems if m]
            if peaks:
                doc.setdefault("hbm_peak_bytes", max(peaks))
            if res:
                doc.setdefault("hbm_resident_bytes", max(res))
        from ..analysis import lockcheck

        if lockcheck.enabled():
            doc["lock_check"] = lockcheck.report()

    if args.open_loop:
        # the open-loop load observatory (bench/loadgen.py): replay the
        # trace against a fresh scheduler with CO-safe SLI stamping, then
        # emit ONE artifact — sli fields + phase shares top-level,
        # attribution block, exemplar Perfetto export — through the same
        # print-blob + --out + _stamp_analysis contract as every branch
        from ..scheduler.tracing import TraceCollector
        from .loadgen import (
            SCENARIOS,
            export_sli_exemplars,
            load_or_build_trace,
            render_attribution_table,
            replay_trace,
        )

        try:
            trace = load_or_build_trace(args.open_loop)
        except ValueError as e:
            ap.error(str(e))
        collector = TraceCollector()
        out, sched = replay_trace(trace, mode=args.mode, collector=collector)
        base = (args.out[:-5] if args.out and args.out.endswith(".json")
                else args.out) or f"OPENLOOP_{trace.scenario}"
        if args.open_loop in SCENARIOS:
            # generated traces save next to the artifact so the EXACT run
            # replays from JSON (`--open-loop <path>`)
            out["trace_path"] = trace.save(f"{base}.arrivals.json")
        worst = [w["pod"] for w in out["sli_attribution"]["worst_pods"]]
        out["sli_attribution"]["exemplar_export"] = export_sli_exemplars(
            collector, worst, f"{base}.exemplars.trace.json"
        )
        if args.trace:
            _export_trace(collector, f"{base}.trace.json")
        if inj is not None:
            out["chaos"] = _chaos_report()
        _stamp_analysis(out)
        if args.sli_attribution:
            print(render_attribution_table(out), file=sys.stderr)
        blob = json.dumps(out, indent=2)
        print(blob)
        if args.out:
            with open(args.out, "w") as f:
                f.write(blob + "\n")
        if metrics_srv is not None:
            metrics_srv.stop()
        return

    if args.stream:
        # KTPU_STREAM_SHAPE=PODSxNODES resizes the per-wave workload (the
        # default is the BENCH stream shape; CI's --profile smoke and the
        # profile-capture test shrink it to stay inside their budgets)
        shape = os.environ.get("KTPU_STREAM_SHAPE", "5000x2000")
        try:
            s_pods, s_nodes = (int(x) for x in shape.lower().split("x"))
        except ValueError:
            ap.error(f"KTPU_STREAM_SHAPE={shape!r}: expected PODSxNODES "
                     "(e.g. 5000x2000)")
        waves = [
            workloads.heterogeneous(s_nodes, s_pods, seed=s)
            for s in range(args.stream)
        ]
        collector = (
            TraceCollector() if (args.trace or args.attribution) else None
        )
        out = run_streaming_workload(
            f"stream-{args.stream}x{s_pods}", waves,
            pipeline=not args.no_pipeline,
            collector=collector,
            profile_dir=args.profile,
        )
        profile_failed = None
        if args.profile:
            from .profiling import render_subphases

            tbl = out.get("device_subphases") or {}
            if tbl.get("incomplete", True):
                profile_failed = (
                    "no annotated kernel ops captured — stale process "
                    "(XLA dump flags parse once) or the run never hit a "
                    "placement kernel"
                )
            else:
                print("device sub-phase self-time (within device_kernel):",
                      file=sys.stderr)
                print(render_subphases(tbl), file=sys.stderr)
                rec = out.get("subphase_reconciliation") or {}
                if rec and not rec.get("ok"):
                    profile_failed = (
                        f"analytic round-loop share {rec['analytic']} vs "
                        f"measured {rec['measured']} diverge "
                        f"{rec['ratio']}x (> {rec['tolerance']}x)"
                    )
        # the memwatch smoke gate: a --stream run whose leak sentinel
        # tripped (unaccounted live device bytes rising monotonically
        # across the waves) fails like a profile-capture failure — the
        # artifact, written below, is the evidence
        memwatch_failed = None
        sentinel = (out.get("memwatch") or {}).get("sentinel") or {}
        if sentinel.get("leaking"):
            memwatch_failed = (
                f"leak sentinel: unaccounted live device bytes grew "
                f"{sentinel.get('growth_bytes', '?')} B monotonically "
                f"(> slack {sentinel.get('slack_bytes', '?')} B) across "
                "the stream"
            )
        if args.attribution and "attribution" in out:
            from ..scheduler.attribution import render_attribution

            print(render_attribution(out["attribution"]), file=sys.stderr)
        if args.trace and collector is not None:
            base = args.out.rsplit(".json", 1)[0] if args.out else "BENCH"
            _export_trace(collector, f"{base}.stream.trace.json")
        if inj is not None:
            out["chaos"] = _chaos_report()
        _stamp_analysis(out)
        blob = json.dumps(out)
        print(blob)
        if args.out:  # same artifact contract as the snapshot rounds
            open(args.out, "w").write(blob + "\n")
        if profile_failed:  # artifact written first — it IS the evidence
            print(f"profile: FAIL — {profile_failed}", file=sys.stderr)
            sys.exit(1)
        if memwatch_failed:  # same contract: artifact first, then fail
            print(f"memwatch: FAIL — {memwatch_failed}", file=sys.stderr)
            sys.exit(1)
        return
    if args.config:
        text = open(args.config).read()
    else:
        text = BASELINE_CONFIGS if args.full else SMOKE_CONFIGS
    # trace artifacts land NEXT TO the perfdata artifact (same stem)
    trace_base = None
    if args.trace:
        trace_base = (args.out.rsplit(".json", 1)[0] if args.out else "BENCH")
    results = run_yaml(text, args.mode, trace_base=trace_base,
                       device_trace_dir=args.trace_device,
                       attribution=args.attribution)
    data = [r.to_json() for r in results]
    for r in data:
        print(json.dumps(r), file=sys.stderr)
    doc = {"perfdata": data}
    if inj is not None:
        doc["chaos"] = _chaos_report()
    _stamp_analysis(doc)
    out = json.dumps(doc, indent=2)
    if args.out:
        open(args.out, "w").write(out)
    else:
        print(out)


if __name__ == "__main__":
    main()
