"""Sidecar loopback benchmark — the deployed north-star architecture
end-to-end: real gRPC server + client in one process, real device step,
session/delta wire protocol.

Measures, client-side (including proto build, wire, server decode, resident
delta encode, device step, verdict decode):
  - the cold path: first request -> not_ready + CPU-fallback contract while
    the server warms in the background (encode + compile + one run)
  - N warm waves against a warm cluster (each wave re-binds the previous
    one's placements like bench.py's sustainable cycle)

Usage: python -m kubernetes_tpu.bench.sidecar_bench [n_nodes] [n_pods] [waves]
Prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

from ._cpu import force_cpu_from_env
from ..api.snapshot import Snapshot
from ..runtime.client import SidecarUnavailable, TPUScoreClient
from ..runtime.sidecar import TPUScoreServer
from .workloads import heterogeneous


def main() -> None:
    force_cpu_from_env()
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 else 50_000
    n_waves = int(sys.argv[3]) if len(sys.argv) > 3 else 3

    snap = heterogeneous(n_nodes, n_pods, seed=0)
    server = TPUScoreServer()
    port = server.start()
    cli = TPUScoreClient(f"127.0.0.1:{port}")

    t0 = time.perf_counter()
    cold_fallback = None
    try:
        cli.schedule(snap, deadline_ms=600_000)
    except SidecarUnavailable:
        cold_fallback = time.perf_counter() - t0
    # wait for background warmup (compile included)
    t0 = time.perf_counter()
    while not server.engine.ready:
        time.sleep(0.25)
        if time.perf_counter() - t0 > 600:
            raise SystemExit("warmup never completed")
    warmup_s = time.perf_counter() - t0

    # first warm request gives the placements to bind for the cycle chain
    r = cli.schedule(snap, deadline_ms=600_000)
    # drop the warmup/cold-path phase samples (compile-dominated) so the
    # per-phase report attributes ONLY the timed warm waves below
    with server.engine.metrics._lock:
        server.engine.metrics.hists.clear()
    waves = []
    prev_assign = r
    prev_pods = snap.pending_pods
    for w in range(2, 2 + n_waves):
        bound = [
            dataclasses.replace(p, node_name=prev_assign[p.uid])
            for p in prev_pods
            if prev_assign.get(p.uid)
        ]
        wave = [
            dataclasses.replace(p, name=f"w{w}-{p.name}", uid="")
            for p in snap.pending_pods
        ]
        s2 = Snapshot(nodes=snap.nodes, pending_pods=wave, bound_pods=bound)
        t0 = time.perf_counter()
        prev_assign = cli.schedule(s2, deadline_ms=600_000)
        waves.append(time.perf_counter() - t0)
        prev_pods = wave
    # per-phase attribution (decode/encode/dispatch/step) from the engine's
    # histograms — the round-3 warm-wave variance had no attribution
    _, _, hists = server.engine.metrics.snapshot()
    phases = {
        name: {"p50_s": round(p50, 4), "p99_s": round(p99, 4), "n": n}
        for name, (p50, p99, n) in sorted(hists.items())
    }
    server.stop()
    med = sorted(waves)[len(waves) // 2]
    print(
        json.dumps(
            {
                "metric": "sidecar_loopback_warm_wave",
                "n_nodes": n_nodes,
                "n_pods": n_pods,
                "cold_fallback_s": round(cold_fallback, 3)
                if cold_fallback is not None
                else None,
                "warmup_s": round(warmup_s, 1),
                "warm_wave_s": [round(x, 3) for x in waves],
                "warm_wave_median_s": round(med, 3),
                "pass_1s": med < 1.0,
                "client_stats": cli.stats,
                "server_phases": phases,
                "unit": "s",
            }
        )
    )


if __name__ == "__main__":
    main()
