"""One-shot benchmark matrix — every headline number in one artifact.

The round-3 verdict's missing #3: when the TPU returns, re-record
EVERYTHING host-only in one artifact with no "pending TPU" rows.  This
runner probes the backend the same way bench.py does (subprocess probe —
a downed tunnel hangs, it doesn't raise), then runs the full battery:

  north_star        bench.py's flow (50k x 20k heterogeneous, delta cycles)
  baseline_configs  harness --full (all six BASELINE configs + latency
                    distributions from commit ordinals)
  pairwise_north_star_scale
                    spread_affinity 50k x 20k through the ROUNDS kernel
                    (the round-3 thesis workload, 5.78 s then)
  preemption        preempt_bench 1k preemptors x 20k nodes
  sidecar_loopback  sidecar_bench warm waves (wire + session deltas)

On the CPU fallback the harness configs run at smoke scale and the
pairwise rounds row at 10k x 5k, while the north-star, preemption,
sidecar, and calibration rows run at FULL scale (round 5 made them
affordable there); the artifact labels all of it (platform:
cpu-sim-fallback, scales embedded) — a labeled number beats an empty
file.  Writes ONE json file (default BENCH_MATRIX_rNN.json style path
given by --out).

Usage: python -m kubernetes_tpu.bench.matrix --out BENCH_MATRIX_r04.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _run_json(cmd, timeout_s, env=None):
    """Run a bench CLI; return (last JSON line or None, elapsed, error)."""
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s, env=env
        )
    except subprocess.TimeoutExpired:
        return None, time.time() - t0, f"timeout after {timeout_s}s"
    out = None
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                out = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    err = None if r.returncode == 0 and out is not None else (
        f"rc={r.returncode} tail={r.stderr.strip()[-400:]}"
    )
    return out, time.time() - t0, err


def _rounds_kernel_row(n_nodes, n_pods):
    """The pairwise-at-scale row: spread_affinity through the rounds kernel
    vs the per-pod scan, plus the round-count diagnostic."""
    import numpy as np
    from functools import partial

    import jax

    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops import assign
    from ..ops.assign import schedule_scan, schedule_scan_rounds
    from .workloads import spread_affinity

    snap = spread_affinity(n_nodes, n_pods, seed=0)
    enc = DeltaEncoder()
    t0 = time.perf_counter()
    arr, meta = enc.encode_device(snap)
    t_encode = time.perf_counter() - t0
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    f = jax.jit(
        partial(schedule_scan_rounds, with_rounds=True),
        static_argnames=("cfg",),
    )
    ch, _, rounds = (np.asarray(x) for x in f(arr, cfg))  # compile
    t_step = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        ch, _, rounds = (np.asarray(x) for x in f(arr, cfg))
        t_step = min(t_step, time.perf_counter() - t0)
    g = jax.jit(schedule_scan, static_argnames=("cfg",))
    np.asarray(g(arr, cfg)[0])  # compile
    t0 = time.perf_counter()
    plain = np.asarray(g(arr, cfg)[0])
    t_plain = time.perf_counter() - t0
    np.testing.assert_array_equal(ch, plain)  # decisions identical
    return {
        "n_nodes": n_nodes,
        "n_pods": n_pods,
        "encode_s": round(t_encode, 3),
        "rounds_step_s": round(t_step, 3),
        "plain_scan_step_s": round(t_plain, 3),
        "speedup": round(t_plain / t_step, 2) if t_step > 0 else None,
        "rounds_total": int(rounds.sum()),
        "rounds_per_chunk_mean": round(float(rounds.mean()), 2),
        "rounds_per_chunk_max": int(rounds.max()),
        "decisions_bit_identical_to_plain_scan": True,
        "scheduled": int((ch[: meta.n_pods] >= 0).sum()),
        "note": (
            f"shipping kernel config: _RCHUNK={assign._RCHUNK}, "
            f"_REPAIR_ITERS={assign._REPAIR_ITERS}"
        ),
    }


def main() -> None:
    # stray sweep/smoke overrides must not silently change the scale or
    # kernel shape of a "full"-labeled artifact — sanitize FIRST, before ANY
    # project import: kernel constants (ops/assign.py _RCHUNK etc.) read
    # os.environ at import time, so a project import landing above this loop
    # would bake the stray values in for the in-process pairwise row
    for var in ("KTPU_BENCH_NODES", "KTPU_BENCH_PODS", "KTPU_CHUNK",
                "KTPU_RCHUNK", "KTPU_REPAIR_ITERS", "KTPU_FORCE_CHUNKED",
                "KTPU_PREEMPT_WAVE", "KTPU_PREEMPT_WAVE_BYTES"):
        os.environ.pop(var, None)
    assert "kubernetes_tpu.ops.assign" not in sys.modules, (
        "kubernetes_tpu.ops.assign imported before env sanitation: its "
        "import-time kernel constants may carry stray KTPU_* overrides"
    )

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_MATRIX_r04.json")
    ap.add_argument("--skip-sidecar", action="store_true")
    args = ap.parse_args()

    sys.path.insert(0, os.getcwd())
    import bench as bench_mod  # repo-root bench.py (the probe lives there)

    backend = bench_mod._probe_backend()
    platform = backend or "cpu-sim-fallback"
    env = dict(os.environ)
    if not backend:
        env["JAX_PLATFORMS"] = "cpu"
    tpu = bool(backend)

    # ONE persistent XLA compile cache shared by every row's subprocess
    # (KTPU_COMPILE_CACHE_DIR; ops/aot.py): the rows repeat the same
    # kernel shapes, so only the first process pays each cold compile —
    # the rest load the executable from disk.  Per-user path: a shared
    # /tmp dir owned by another user would silently fail every cache
    # write (JAX downgrades those to warnings) and recompile each row.
    import getpass
    import tempfile

    try:
        user = getpass.getuser()
    except (KeyError, OSError):  # unmapped uid in a container: no passwd entry
        user = str(os.getuid())
    env.setdefault(
        "KTPU_COMPILE_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), f"ktpu-xla-cache-{user}"),
    )
    os.environ.setdefault(
        "KTPU_COMPILE_CACHE_DIR", env["KTPU_COMPILE_CACHE_DIR"]
    )

    result = {
        "artifact": "builder-recorded benchmark matrix",
        "platform": platform,
        "recorded_unix": time.time(),
        # per-row truth on the cpu fallback: preemption/sidecar/calibration
        # run at FULL scale there too (round 5); only the harness configs
        # (smoke) and the pairwise rounds row stay reduced
        "scales": "full" if tpu else (
            "mixed (cpu sim): harness smoke + pairwise reduced; "
            "north-star/preemption/sidecar/calibration full"
        ),
    }

    here = os.getcwd()

    def cli(mod, *argv):
        return [sys.executable, "-u", "-m", mod, *argv]

    # 1. north star (bench.py re-probes internally and self-labels)
    row, dt, err = _run_json(
        [sys.executable, "-u", os.path.join(here, "bench.py")],
        timeout_s=3000, env=env,
    )
    result["north_star"] = row or {"error": err}

    # 2. the five+1 BASELINE configs with latency distributions
    out_path = os.path.join(
        "/tmp", f"matrix_perfdata_{os.getpid()}.json"
    )
    if os.path.exists(out_path):
        os.unlink(out_path)  # never report a previous run's data
    hcmd = cli("kubernetes_tpu.bench.harness", "--out", out_path)
    if tpu:
        hcmd.append("--full")
    # the harness reports through --out (its stdout carries progress, not
    # a final JSON line) — judge success by the file, not by stdout
    _, dt, err = _run_json(hcmd, timeout_s=3600, env=env)
    try:
        result["baseline_configs"] = json.load(open(out_path))["perfdata"]
    except Exception as e:  # noqa: BLE001
        # the FILE is the contract: its read error is the informative one
        # (err only says the harness's stdout carried no JSON line, which
        # is true even on success)
        result["baseline_configs"] = {"error": repr(e), "subprocess": err}

    # 3. pairwise at scale through the rounds kernel (in-process: needs the
    # decisions cross-check, not just a wall time)
    if not backend:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        pw_nodes, pw_pods = 5_000, 10_240
    else:
        pw_nodes, pw_pods = 20_000, 50_000
    # the in-process pairwise row shares the subprocess rows' disk cache
    from ..ops.aot import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    try:
        result["pairwise_north_star_scale"] = _rounds_kernel_row(
            pw_nodes, pw_pods
        )
    except Exception as e:  # noqa: BLE001 — artifact over crash
        result["pairwise_north_star_scale"] = {"error": repr(e)}

    # 4. batched preemption — FULL 1k x 20k scale on both backends (the
    # round-5 wave batching + lazy CPU what-if state made the cpu-sim run
    # ~7 ms/preemptor, so the reduced-scale fallback is no longer needed)
    row, dt, err = _run_json(
        cli("kubernetes_tpu.bench.preempt_bench", "20000", "1000"),
        timeout_s=1800, env=env,
    )
    result["preemption"] = row or {"error": err}

    # 4b. per-pod latency estimate calibration (round-4 verdict weak #6):
    # uniform-sweep estimate vs true cumulative wall at chunk boundaries
    row, dt, err = _run_json(
        cli("kubernetes_tpu.bench.latency_calibration", "5000", "10240"),
        timeout_s=3600, env=env,
    )
    result["latency_calibration"] = row or {"error": err}

    # 5. sidecar loopback (wire + session deltas + bind compression) —
    # FULL north-star scale on both backends (round 5 measured the cpu-sim
    # 50k wave at ~60 s; 3 waves + warmup fit the timeout comfortably)
    if not args.skip_sidecar:
        row, dt, err = _run_json(
            cli("kubernetes_tpu.bench.sidecar_bench", "20000", "50000", "3"),
            timeout_s=2400, env=env,
        )
        if row:
            row["note"] = (
                "full north-star scale; host phases (decode/encode/"
                "dispatch) exclude the device step — the <1 s TPU wave "
                "rests on the step once their sum is under ~0.4 s"
            )
        result["sidecar_loopback"] = row or {"error": err}

    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({"wrote": args.out, "platform": platform}))


if __name__ == "__main__":
    main()
