"""Calibrate the per-pod latency estimate against measured sweep boundaries.

The harness derives per-pod scheduling latency from kernel COMMIT ORDINALS
under a uniform-sweep assumption: pod i's decision became available
~(ordinal_i + 1) / sweeps of the way through the kernel wall
(ops/assign.py — schedule_batch_ordinals).  Rounds have unequal real costs
(re-hoist vs commit-only), so the round-4 verdict (weak #6) asked for a
device-timed spot check before quoting the estimated p99 against
BASELINE.md.

Method — zero kernel changes: chunk c's work depends only on pods before
it (the outer lax.scan carries state forward), so running the SAME
workload truncated to its first P' pods measures the true cumulative wall
at that chunk boundary.  For a set of prefix fractions we compare:

  measured fraction   warm wall(prefix) / warm wall(full)
  estimated fraction  sweeps consumed by the prefix / total sweeps
                      (from the full run's per-chunk rounds diagnostic)

The max |measured - estimated| over the probes is the error bar to quote
next to `latency_source: per-pod-estimate`.  Prefixes are chosen on
bucket boundaries so padding adds no phantom chunks.

Usage: python -m kubernetes_tpu.bench.latency_calibration [nodes] [pods]
Prints one JSON line.
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

from ._cpu import force_cpu_from_env


def _warm_wall(snap, kernel_fn, n_runs: int = 2):
    """-> (best warm wall seconds, last run's outputs as numpy)."""
    import numpy as np

    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config

    arr, meta = DeltaEncoder().encode_device(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    out = kernel_fn(arr, cfg)
    res = [np.asarray(x) for x in out]  # compile + first run
    best = float("inf")
    for _ in range(n_runs):
        t0 = time.perf_counter()
        res = [np.asarray(x) for x in kernel_fn(arr, cfg)]
        best = min(best, time.perf_counter() - t0)
    return best, res


def main() -> None:
    force_cpu_from_env()
    import dataclasses

    import jax
    import numpy as np

    from ..ops import assign
    from .workloads import spread_affinity

    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 5_000
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 else 10_240

    kernel = jax.jit(
        partial(assign.schedule_scan_rounds, with_rounds=True),
        static_argnames=("cfg",),
    )
    snap = spread_affinity(n_nodes, n_pods, seed=0)
    full_wall, full_res = _warm_wall(snap, kernel)
    rounds = np.asarray(full_res[2])  # per-chunk round counts
    total_sweeps = int(rounds.sum())
    C = assign._RCHUNK  # the ROUNDS kernel's chunk size

    # prefix fractions on 2048-pod bucket boundaries (api/snapshot._bucket);
    # dedup: at small n_pods several fractions round to the same boundary,
    # and each probe costs a full compile + warm runs
    probes = []
    seen = set()
    for frac in (0.2, 0.4, 0.6, 0.8):
        p_pref = max(2048, int(round(n_pods * frac / 2048)) * 2048)
        if p_pref >= n_pods or p_pref in seen:
            continue
        seen.add(p_pref)
        pref_snap = dataclasses.replace(
            snap, pending_pods=snap.pending_pods[:p_pref]
        )
        wall, _ = _warm_wall(pref_snap, kernel)
        est = float(rounds[: p_pref // C].sum()) / total_sweeps
        probes.append({
            "prefix_pods": p_pref,
            "measured_wall_s": round(wall, 2),
            "measured_fraction": round(wall / full_wall, 4),
            "estimated_fraction": round(est, 4),
            "abs_error": round(abs(wall / full_wall - est), 4),
        })

    err = max((p["abs_error"] for p in probes), default=None)
    print(json.dumps({
        "metric": "latency_estimate_calibration",
        "workload": f"spread_affinity {n_pods}x{n_nodes} (rounds kernel)",
        "full_wall_s": round(full_wall, 2),
        "total_sweeps": total_sweeps,
        "probes": probes,
        "max_abs_fraction_error": err,
        "note": "uniform-sweep per-pod latency estimate vs true cumulative "
                "wall at chunk-prefix boundaries; quote max_abs_fraction_"
                "error as the error bar on per-pod-estimate latencies",
    }))


if __name__ == "__main__":
    main()
