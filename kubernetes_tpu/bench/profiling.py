"""Measured kernel-interior sub-phase attribution — the profiler half of
the device cost observatory (`bench.harness --profile`).

PR 6's attribution engine proves `device_kernel` owns the warm cycle, then
goes blind below the jit boundary.  This module looks inside: the kernels
are annotated with `jax.named_scope` sub-phases (ops/scopes.py), and three
artifacts join into a measured per-sub-phase self-time table:

  1. the jax.profiler device trace the harness already knows how to start
     (scheduler/tracing.py — device_trace) writes a Perfetto-loadable
     `*.trace.json.gz` whose per-op events carry `args.hlo_op` +
     `args.hlo_module` — WHICH compiled op ran for how long;
  2. an XLA HLO text dump (`--xla_dump_to`, armed by enable_hlo_dump
     BEFORE the run's first compilation) carries each op's
     `metadata={op_name="jit(...)/.../<scope>/..."}` — which NAMED SCOPE
     owns it (named scopes survive lowering as op_name components, fusions
     inherit their root op's metadata);
  3. ops.scopes.subphase_of maps the op_name path to its owning sub-phase
     (innermost declared scope) — the same function the analytic ledger
     (analysis/costmodel.py) applies to jaxpr name stacks, so an op can
     never be owned by two different sub-phases across the two halves.

The table follows the attribution engine's contract one level down: every
profiled device op is owned by exactly one sub-phase (`unowned` catches
ops outside every declared scope), fractions sum to 1.0 within
device_kernel, and `round_loop_fraction` is the rollup over every op whose
scope path passes through the round loop — ROADMAP-1's target as one
regression-gated number.  Only modules containing at least one declared
scope count as device-kernel work (encode helpers, tiny convert jits and
host glue never dilute the table).

Fractions are SELF-TIME shares over total device-op time, not wall shares:
on backends with intra-op parallelism op durations may overlap, and a
share-of-op-time table stays exact where a wall sweep would double-count.

Caveat: XLA parses dump flags once per process, so --profile needs the
dump armed before the first compilation — a warm process that already
compiled the kernels yields an empty op map, which the table reports as
`incomplete` instead of silently attributing nothing.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from ..ops.scopes import subphase_of

# instruction lines of an HLO text dump:  [ROOT ]<name> = ...op_name="..."
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s.*op_name=\"([^\"]+)\"")
_HLO_MODULE_RE = re.compile(r"^HloModule\s+([\w.\-]+)")
# CONTAINER instructions: their profiler events span the whole loop/branch
# execution, so counting them would double-charge every interior op — and
# they carry no op_name metadata after optimization.  The table charges
# LEAVES only, exactly as the analytic walk (costmodel._leaf_costs) does.
_HLO_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_HLO_CONTAINER_RE = re.compile(r"\s(?:while|conditional|call)\(")


def enable_hlo_dump(dump_dir: str) -> None:
    """Arm the per-compilation HLO text dump (the op -> named-scope join
    source).  XLA reads the dump flags from XLA_FLAGS at its first parse,
    so this must run before the process compiles anything — bench.harness
    calls it at --profile argument handling, before any workload."""
    os.makedirs(dump_dir, exist_ok=True)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_dump_to" in flags:
        return  # an operator-armed dump wins; never stack two
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_dump_to={dump_dir} --xla_dump_hlo_as_text"
    ).strip()


def parse_hlo_dumps(dump_dir: str) -> Dict[str, Dict[str, Optional[str]]]:
    """{hlo_module: {instruction name: op_name scope path}} from every
    `*after_optimizations.txt` dump — the optimized HLO, whose instruction
    names are exactly what the profiler's `args.hlo_op` reports.  Container
    instructions (while / conditional / call) map to None — their events
    are whole-loop envelopes the table must skip, not leaves to charge."""
    out: Dict[str, Dict[str, Optional[str]]] = {}
    for path in sorted(glob.glob(os.path.join(dump_dir, "*.txt"))):
        base = os.path.basename(path)
        if "after_optimizations" not in base or "-" in base.rsplit(
                "after_optimizations", 1)[1]:
            continue  # buffer-assignment / memory-usage side files
        module = None
        ops: Dict[str, Optional[str]] = {}
        try:
            with open(path) as f:
                for line in f:
                    if module is None:
                        m = _HLO_MODULE_RE.match(line)
                        if m:
                            module = m.group(1)
                        continue
                    m = _HLO_OP_RE.match(line)
                    if m:
                        ops[m.group(1)] = m.group(2)
                        continue
                    if _HLO_CONTAINER_RE.search(line):
                        m = _HLO_NAME_RE.match(line)
                        if m:
                            ops[m.group(1)] = None  # container envelope
        except OSError:
            continue
        if module and ops:
            # later dumps of a re-compiled module win (same name, fresh ops)
            out.setdefault(module, {}).update(ops)
    return out


def load_profile_events(profile_dir: str) -> List[Dict[str, Any]]:
    """Per-op device events [{module, op, ts_us, dur_us}] from the NEWEST
    jax.profiler session under `profile_dir` (start_trace stamps one
    timestamped subdir per capture)."""
    traces = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime,
    )
    if not traces:
        return []
    try:
        doc = json.loads(gzip.open(traces[-1]).read())
    except (OSError, json.JSONDecodeError, EOFError):
        return []
    events: List[Dict[str, Any]] = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        op = args.get("hlo_op")
        if not op:
            continue
        events.append({
            "module": args.get("hlo_module", ""),
            "op": op,
            "ts_us": float(e.get("ts", 0.0)),
            "dur_us": float(e.get("dur", 0.0)),
        })
    return events


def _kernel_modules(op_map: Dict[str, Dict[str, Optional[str]]]) -> set:
    """The ANNOTATED modules — those whose op map carries at least one
    declared scope (the placement kernels).  One definition shared by the
    self-time table and the Perfetto span merge, so the two views can never
    scope to different module sets."""
    return {
        m for m, ops in op_map.items()
        if any(subphase_of(p) for p in ops.values() if p)
    }


def subphase_table(events: List[Dict[str, Any]],
                   op_map: Dict[str, Dict[str, str]]) -> Dict[str, Any]:
    """The measured sub-phase self-time table.

    Scoped to ANNOTATED modules (those whose op map contains at least one
    declared scope — the placement kernels); within them every op is owned
    by exactly one sub-phase via its op_name path (`unowned` for ops
    outside all scopes), so fractions sum to 1.0 within device_kernel by
    construction.  `round_loop_fraction` is the rollup over ops whose path
    passes through the round loop; `dominant` compares that rollup against
    the phases outside the loop (costmodel.dominant_phase — the shared
    definition)."""
    from ..analysis.costmodel import dominant_phase, in_round_loop

    kernel_modules = _kernel_modules(op_map)
    self_us: Dict[str, float] = {}
    rollup_us = 0.0
    total_us = 0.0
    n_ops = 0
    for e in events:
        mod = e["module"]
        if mod not in kernel_modules:
            continue
        path = op_map[mod].get(e["op"], "")
        if path is None:  # container envelope (while/cond): leaves only
            continue
        phase = subphase_of(path) or "unowned"
        self_us[phase] = self_us.get(phase, 0.0) + e["dur_us"]
        if in_round_loop(path):
            rollup_us += e["dur_us"]
        total_us += e["dur_us"]
        n_ops += 1
    fractions = {
        p: (us / total_us if total_us else 0.0) for p, us in self_us.items()
    }
    rl = rollup_us / total_us if total_us else 0.0
    return {
        "subphases": {
            p: {"seconds": round(us / 1e6, 6),
                "fraction": round(fractions[p], 4)}
            for p, us in sorted(self_us.items(), key=lambda kv: -kv[1])
        },
        "round_loop_fraction": round(rl, 4),
        "dominant": dominant_phase(fractions, rl),
        "n_ops": n_ops,
        "kernel_modules": sorted(kernel_modules),
        "total_s": round(total_us / 1e6, 6),
        # empty = the capture failed (no annotated module profiled — warm
        # process without the dump, or a run that never hit a kernel);
        # consumers flag it instead of reporting a vacuous clean table
        "incomplete": n_ops == 0,
    }


def render_subphases(table: Dict[str, Any], indent: str = "") -> str:
    """Human rows for one measured table (nested under device_kernel by
    the attribution renderer)."""
    lines = []
    rl = table.get("round_loop_fraction", 0.0)
    dom = table.get("dominant")
    for p, d in table.get("subphases", {}).items():
        # a dominant round_loop marks the ROLLUP row below, not the self
        # row (the loop's own plumbing is near-zero; its interior phases
        # carry the time)
        mark = "  <- dominant" if p == dom and p != "round_loop" else ""
        lines.append(
            f"{indent}{p:<16} {d['seconds']:>10.4f} {d['fraction']:>9.1%}"
            f"{mark}"
        )
    lines.append(
        f"{indent}{'round_loop(all)':<16} {'':>10} {rl:>9.1%}"
        + ("  <- dominant" if dom == "round_loop" else "")
    )
    return "\n".join(lines)


def merge_profile_spans(collector, events: List[Dict[str, Any]],
                        op_map: Dict[str, Dict[str, str]],
                        max_spans: int = 4096) -> int:
    """Merge the profiled sub-phase ops into the host span trace as
    children of `device.step` / `batch.kernel` spans, so one Perfetto
    export answers both "which phase" and "which kernel region".

    The profiler and the host collector run on different clocks; the merge
    rebases by aligning the FIRST annotated device op to the start of the
    first device-kernel span (an offset, not a scale — both sides are
    monotonic microsecond clocks).  Each synthesized span parents under
    the device-kernel span whose window contains its midpoint (the first
    one otherwise).  Caps at `max_spans` spans, largest first — a Perfetto
    export should not grow by a million one-microsecond ops."""
    from ..scheduler.tracing import Span

    kernel_modules = _kernel_modules(op_map)
    evs = [
        e for e in events
        if e["module"] in kernel_modules
        and op_map[e["module"]].get(e["op"], "") is not None
    ]
    if not evs or collector is None:
        return 0
    anchors = sorted(
        (s for s in collector.spans()
         if s.name in ("device.step", "batch.kernel") and s.end is not None),
        key=lambda s: s.start,
    )
    if not anchors:
        return 0
    t0_prof = min(e["ts_us"] for e in evs) / 1e6
    offset = anchors[0].start - t0_prof
    evs.sort(key=lambda e: -e["dur_us"])
    n = 0
    for e in evs[:max_spans]:
        start = e["ts_us"] / 1e6 + offset
        end = start + e["dur_us"] / 1e6
        mid = (start + end) / 2
        parent = next(
            (a for a in anchors if a.start <= mid <= a.end), anchors[0]
        )
        path = op_map[e["module"]].get(e["op"], "")
        phase = subphase_of(path) or "unowned"
        sp = Span(
            f"device.{phase}", component="device",
            trace_id=parent.trace_id, parent_id=parent.span_id,
            start=start,
            attributes={"hlo_op": e["op"], "hlo_module": e["module"],
                        "op_name": path},
        )
        sp.finish(end)
        collector.add(sp)
        n += 1
    return n
