"""Ring-blockwise pairwise matching + all-to-all resharding — the framework's
sequence/context-parallel layer (SURVEY.md §2.4).

The reference's pairwise hot spot is the (pending-pods x existing-pods) label
match inside InterPodAffinity (interpodaffinity/filtering.go — O(pods x nodes)
with per-pod string work).  This framework normally never materializes that
matrix (interned terms + counts, api/pairwise.py) — but the selector-vs-pod
match matrix M[T, P] itself still scales with the pod axis, and at 100k+ pods
per chip it outgrows HBM next to the [P, N] score matrices.  ring_match
computes it blockwise, ring-attention style: selector rows stay resident
(queries), pod-label blocks rotate around the mesh via lax.ppermute (keys),
each shard filling one [T/d, P/d] output tile per hop.  d hops, peak memory
1/d of the dense product, traffic rides the ICI ring.

all_to_all_pods_to_nodes is the Ulysses-analog reshard: a pods-sharded [P, N]
intermediate (natural layout for the batched static phase) redistributes to
node-sharded (the layout the commit scan wants) with one lax.all_to_all.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from .mesh import NODE_AXIS, shard_map
from .partition_rules import spec_for

PODS_AXIS = NODE_AXIS  # one mesh axis; it shards whichever array axis a stage needs


def _eval_block(sel_mask, sel_kind, labels):
    """[S, E, L] selectors vs [B, L] labels -> bool[S, B] (same evaluation as
    ops/filters.term_match)."""
    counts = jnp.einsum("sel,bl->seb", sel_mask, labels,
                        precision=jax.lax.Precision.HIGHEST)
    kind = sel_kind[:, :, None]
    ok = jnp.where(
        kind == 1, counts > 0, jnp.where(kind == 2, counts == 0, kind == 0)
    )
    return jnp.all(ok, axis=1)


def ring_match(sel_mask: jax.Array, sel_kind: jax.Array, labels: jax.Array, mesh: Mesh):
    """bool[S, P] = selectors x entity labels, computed blockwise on the mesh.

    sel_mask [S, E, L] / sel_kind [S, E] sharded on S; labels [P, L] sharded on
    P; output sharded on S.  Peak per-device memory is the [S/d, P/d] tile.
    """
    d = mesh.shape[PODS_AXIS]
    S, P_total = sel_mask.shape[0], labels.shape[0]
    if S % d or P_total % d:
        raise ValueError(f"S={S} and P={P_total} must divide mesh size {d}")
    p_local = P_total // d

    def f(sel_m, sel_k, lab):
        idx = lax.axis_index(PODS_AXIS)
        perm = [(j, (j - 1) % d) for j in range(d)]

        def body(i, carry):
            from ..ops.scopes import subphase

            lab_blk, out = carry
            src = (idx + i) % d  # origin shard of the block we currently hold
            with subphase("score"):
                tile = _eval_block(sel_m, sel_k, lab_blk)  # [S/d, P/d]
            with subphase("commit"):
                out = lax.dynamic_update_slice(out, tile, (0, src * p_local))
            with subphase("hoist"):
                lab_blk = lax.ppermute(lab_blk, PODS_AXIS, perm)
            return (lab_blk, out)

        zeros = jnp.zeros((sel_m.shape[0], P_total), dtype=jnp.bool_)
        if hasattr(lax, "pcast"):
            out0 = lax.pcast(zeros, (PODS_AXIS,), to="varying")
        elif hasattr(lax, "pvary"):
            out0 = lax.pvary(zeros, (PODS_AXIS,))
        else:
            # jax 0.4.x: no replication-type casts (and no check_rep need
            # for them) — the constant is device-varying implicitly
            out0 = zeros
        _, out = lax.fori_loop(0, d, body, (lab, out0))
        return out

    fn = shard_map(
        f,
        mesh=mesh,
        in_specs=(spec_for("ring.sel_mask"), spec_for("ring.sel_kind"),
                  spec_for("ring.labels")),
        out_specs=spec_for("ring.match_out"),
    )
    return jax.jit(fn)(sel_mask, sel_kind, labels)


def all_to_all_pods_to_nodes(x: jax.Array, mesh: Mesh):
    """[P, N] sharded on the pods axis -> the same values sharded on the node
    axis, via one all_to_all (the §2.4 'Ulysses' re-partitioning)."""
    d = mesh.shape[PODS_AXIS]
    if x.shape[0] % d or x.shape[1] % d:
        raise ValueError(f"both axes of {x.shape} must divide mesh size {d}")

    def f(blk):  # [P/d, N]
        # split the node axis into d chunks, exchange, concat on the pod axis
        return lax.all_to_all(blk, PODS_AXIS, split_axis=1, concat_axis=0, tiled=True)

    fn = shard_map(f, mesh=mesh, in_specs=(spec_for("ring.a2a_in"),),
                   out_specs=spec_for("ring.a2a_out"))
    return jax.jit(fn)(x)
