"""Device mesh construction.

The scale axis of the reference is node count (SURVEY.md §5 long-context note):
its 16-goroutine chunked fan-out over nodes (pkg/scheduler/framework/parallelize/
parallelism.go — Parallelizer.Until) maps to data parallelism over the node axis
of the (pods x nodes) matrices, sharded across TPU chips over ICI.  One mesh
axis "nodes" for now; the pods axis joins when ring/all-to-all stages land.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

# the mesh axis name + rule table live in partition_rules (the single
# source of sharding truth); re-exported here for the existing import sites
from .partition_rules import NODE_AXIS, node_axis_fields  # noqa: F401

# jax moved shard_map out of experimental around 0.5; alias whichever this
# runtime has so the sharded paths work on both (the seed's bare
# jax.shard_map raised AttributeError on 0.4.x and failed tier-1's
# test_sharded/test_ring).
try:
    shard_map = jax.shard_map
except AttributeError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map  # noqa: F401

# ClusterArrays fields carrying the node axis, with (axis, pad fill) —
# DERIVED from the partition rule table (a field is padded on exactly the
# axis the table shards), no longer maintained in parallel with the specs.
# The fill values replicate the encoder's own bucketing padding
# (api/delta.py — _assemble: node_valid False is the master gate, so padded
# nodes are statically infeasible for every pod and can never attain a
# normalization extreme or win an argmax; node_dom's fill is resolved
# per-array to the "key absent" sentinel D).  image_score pads on axis 1
# only when it is a real [P, N] matrix.
NODE_AXIS_FIELDS: Dict[str, Tuple[int, object]] = node_axis_fields()


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (NODE_AXIS,))


def mesh_from_env(raw: Optional[str] = None, source: str = "KTPU_MESH") -> Optional[Mesh]:
    """KTPU_MESH=<n>: build the node-axis mesh over the first n local
    devices.  Unset / 1 / 0 -> None (the single-device path).  Invalid
    values raise a clear ValueError instead of silently running
    single-device; a request beyond the available device count CLAMPS with
    a warning, so one deployment config serves hosts of different sizes.
    The one validated entry for EVERY mesh-count request — config-sourced
    counts (TPUScoreArgs.meshDevices) resolve through it too, with `source`
    naming the knob in errors/warnings."""
    if raw is None:
        raw = os.environ.get("KTPU_MESH", "")
    raw = raw.strip()
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"{source}={raw!r}: expected an integer device count "
            f"(e.g. {source}=8 for a v5e-8)"
        ) from None
    if n < 0:
        raise ValueError(f"{source}={n}: device count must be >= 0")
    if n <= 1:
        return None
    avail = len(jax.devices())
    if n > avail:
        warnings.warn(
            f"{source}={n} exceeds the {avail} available device(s); "
            f"clamping to {avail}",
            stacklevel=2,
        )
        n = avail
    if n <= 1:
        return None
    return make_mesh(n)


def pad_field(name: str, a, pad: int, d_sentinel: int, n: int):
    """Pad ONE ClusterArrays field's node axis by `pad` entries, or return
    it untouched when it carries no node axis.  The single source of the
    fill/axis rules (NODE_AXIS_FIELDS + the [P, N] image_score case) shared
    by pad_nodes below and the resident encoder's placement-time padding
    (api/delta.py — DeltaEncoder._pad_for_mesh)."""
    import numpy as np

    ent = NODE_AXIS_FIELDS.get(name)
    if ent is None:
        if name == "image_score" and a.shape[1] == n:
            ent = (1, 0)
        else:
            return a
    axis, fill = ent
    if fill is None:
        fill = d_sentinel
    a = np.asarray(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return np.pad(a, widths, constant_values=fill)


def pad_nodes(arr, n_shards: int):
    """Pad the node axis of a ClusterArrays to a multiple of `n_shards` with
    permanently invalid nodes — the exact padding the encoder's bucketing
    already applies (zero capacity, valid=False, sentinel domains), so
    decisions are unchanged: padded columns are masked -inf before every
    argmax / top-k / normalization.  Returns (arr, original_N); the input is
    returned untouched when already divisible.  Host-side (numpy): callers
    on the device hot path pad BEFORE placement (api/delta.py —
    DeltaEncoder with a mesh)."""
    n = arr.N
    pad = (-n) % n_shards
    if pad == 0:
        return arr, n
    import dataclasses

    d_sentinel = arr.term_counts0.shape[1] - 1
    repl = {
        name: pad_field(name, getattr(arr, name), pad, d_sentinel, n)
        for name in (*NODE_AXIS_FIELDS, "image_score")
    }
    return dataclasses.replace(arr, **repl), n


def shard_hbm_estimate(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, chunk: int = 128, u_classes: Optional[int] = None,
) -> Dict[str, int]:
    """Per-shard device-memory estimate (bytes) for the routed kernels'
    dominant blocks (PARITY.md HBM budget, sharded): the two [P, Nl] bool
    masks (static feasibility + node-selection) shard column-wise; the
    per-chunk hoist and [T, Nl] count state shard with them; the chunked
    kernel's gathered [C, N] score matrix (plus its transpose) and the
    [N, R] usage/alloc arrays are replicated per shard.

    `round_loop` covers the prefix-commit round machinery's O(C^2) blocks
    — the [C, C, R] exclusive prefix-sum of intra-round requests (input +
    associative-scan carry + output) and the [C, 2C] candidate/validation
    matrices.  Replicated per shard, independent of N: negligible at
    production scale (~1 MB at C=128 vs ~277 MB of masks) but DOMINANT at
    the tiny scales the device pass (analysis/devicecheck.py — KTPU012)
    traces, so the estimate stays honest at every scale the reconciliation
    runs at.

    `u_classes` (incremental routes, ops/incremental.py): adds the
    resident [U1, Nl] class matrices (static/base/fit + the carried copy)
    the IncState pins per shard.

    PACKED DATA PLANE (ops/bitplane.py — KTPU_PACK_MASKS): the boolean
    mask planes store as uint32 bit-plane words, so the `pn_masks` term and
    the mask share of `class_matrices` price at ``ceil(n/32) * 4`` bytes
    per row instead of ``n`` — the 8x HBM-ceiling cut BENCH_r08 lands.
    The estimate keys on the same trace-time knob as the kernels, so the
    analytic budget and the compiled buffers flip together (KTPU012)."""
    from ..ops import bitplane

    nl = -(-n_nodes // n_shards)
    # bytes of one [*, nl] / [*, N] boolean mask ROW under the active plane
    row_l = 4 * bitplane.words_for(nl) if bitplane.PACK_MASKS else nl
    row_n = (
        4 * n_shards * bitplane.words_for(nl)
        if bitplane.PACK_MASKS else n_nodes
    )
    b = {
        "pn_masks": 2 * n_pods * row_l,              # sf + nodesel planes
        "chunk_hoist": 2 * chunk * nl * n_res * 4,   # requested + scores f32
        "count_state": 4 * max(1, n_terms) * nl * 4, # cnt/anti/pref/dom
        "gathered_scores": 2 * chunk * n_nodes * 4,  # [C, N] total0 + .T
        "node_side_replicated": 2 * n_nodes * n_res * 4,  # alloc + used
        # [C, C, R] prefix-sum (x3 live copies) + [C, 2C] f32 (x2)
        "round_loop": 3 * chunk * chunk * n_res * 4
        + 4 * chunk * chunk * 4,
    }
    if u_classes:
        # the gathered [U1, N] f32 score carry (+ its masked copy) the
        # chunk scan rides, plus the gathered stat/fit mask planes (packed:
        # word rows; dense: byte rows) — full N, stitched once per cycle
        b["class_matrices"] = (
            2 * u_classes * n_nodes * 4 + 2 * u_classes * row_n
        )
    # the resident INPUT set (every ClusterArrays field + the IncState
    # matrices), summed from the per-field size model the partition rule
    # table derives — the same model KTPU015's replicated-giant threshold
    # and shard_comm_estimate consume, so the three can never drift onto
    # different field sets (previously these argument bytes were simply
    # missing from the hand-listed term sum)
    from .partition_rules import resident_input_bytes

    b["resident_inputs"] = resident_input_bytes(
        n_pods, n_nodes, n_shards, n_res=n_res, n_terms=n_terms,
        u_classes=u_classes,
    )
    b["total"] = sum(b.values())
    return b


def shard_comm_estimate(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, chunk: int = 128, u_classes: Optional[int] = None,
    kind: str = "chunked",
) -> Dict[str, int]:
    """Analytic per-shard collective-traffic estimate (bytes) for ONE traced
    program of the sharded routed kernels — the KTPU017 reconciliation
    budget, sibling to shard_hbm_estimate (KTPU012).  Bytes are STATIC
    program bytes: each collective in the traced jaxpr counts once at its
    output size (the same definition analysis/shardcheck.collective_bytes
    measures), so the two sides reconcile on one number.

    Terms (what the kernels stitch across shards per program):

      ``gathered_scores``  the shard-local [C, Nl] hoist blocks all-gather
                           to the full [C, N] score matrix the commit scan
                           reads (raw + masked copies ride the same stitch)
      ``commit_psums``     owner-shard psum broadcasts of committed pods'
                           domain/usage columns and the scan's scalar
                           reductions (pmax/pmin argmax stitches) — [C, N]
                           and [C, R]-scale blocks
      ``class_stitch``     incremental routes: the [U1, N] class-matrix
                           gather the per-cycle hoist stitches once

    The estimate models the dominant blocks, not every scalar pmax; the
    KTPU017 tolerance (analysis/shardcheck.COMM_TOLERANCE) absorbs the
    rest, exactly as HBM_TOLERANCE does for KTPU012."""
    b = {
        "gathered_scores": 2 * chunk * n_nodes * 4,
        "commit_psums": 2 * chunk * n_nodes * 4 + 4 * chunk * n_res * 4,
    }
    if u_classes and kind == "inc":
        b["class_stitch"] = 4 * u_classes * n_nodes * 4
    b["total"] = sum(b.values())
    return b


def init_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> Mesh:
    """Multi-host (DCN) entry: join the jax.distributed cluster, then build
    the node-axis mesh over ALL processes' devices.  The reference scales its
    control plane over plain gRPC/HTTP2; here multi-host scheduling shards the
    node axis across hosts with XLA collectives riding DCN between slices
    (SURVEY.md §2.4 distributed-backend mapping).  Single-host callers never
    need this — make_mesh over local devices is the ICI path.

    Verified by tests/test_dcn_distributed.py: a 2-process CPU-sim cluster
    runs the full sharded step with cross-process collectives and matches the
    dense single-process decisions bit-for-bit."""
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_mesh()


def global_arrays(mesh: Mesh, tree):
    """Lift a pytree of process-replicated numpy arrays into global jax.Arrays
    for multi-controller jit: every [*, N]/[N, *] array must enter a global-
    mesh program as a jax.Array spanning processes; each process contributes
    its addressable shards from its full local copy."""
    from .partition_rules import replicated_sharding

    rep = replicated_sharding(mesh)

    def lift(x):
        return jax.make_array_from_process_local_data(rep, x)

    return jax.tree_util.tree_map(lift, tree)
