"""Device mesh construction.

The scale axis of the reference is node count (SURVEY.md §5 long-context note):
its 16-goroutine chunked fan-out over nodes (pkg/scheduler/framework/parallelize/
parallelism.go — Parallelizer.Until) maps to data parallelism over the node axis
of the (pods x nodes) matrices, sharded across TPU chips over ICI.  One mesh
axis "nodes" for now; the pods axis joins when ring/all-to-all stages land.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (NODE_AXIS,))
