"""Device mesh construction.

The scale axis of the reference is node count (SURVEY.md §5 long-context note):
its 16-goroutine chunked fan-out over nodes (pkg/scheduler/framework/parallelize/
parallelism.go — Parallelizer.Until) maps to data parallelism over the node axis
of the (pods x nodes) matrices, sharded across TPU chips over ICI.  One mesh
axis "nodes" for now; the pods axis joins when ring/all-to-all stages land.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (NODE_AXIS,))


def init_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> Mesh:
    """Multi-host (DCN) entry: join the jax.distributed cluster, then build
    the node-axis mesh over ALL processes' devices.  The reference scales its
    control plane over plain gRPC/HTTP2; here multi-host scheduling shards the
    node axis across hosts with XLA collectives riding DCN between slices
    (SURVEY.md §2.4 distributed-backend mapping).  Single-host callers never
    need this — make_mesh over local devices is the ICI path.

    Verified by tests/test_dcn_distributed.py: a 2-process CPU-sim cluster
    runs the full sharded step with cross-process collectives and matches the
    dense single-process decisions bit-for-bit."""
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_mesh()


def global_arrays(mesh: Mesh, tree):
    """Lift a pytree of process-replicated numpy arrays into global jax.Arrays
    for multi-controller jit: every [*, N]/[N, *] array must enter a global-
    mesh program as a jax.Array spanning processes; each process contributes
    its addressable shards from its full local copy."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())

    def lift(x):
        return jax.make_array_from_process_local_data(rep, x)

    return jax.tree_util.tree_map(lift, tree)
