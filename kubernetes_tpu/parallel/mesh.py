"""Device mesh construction.

The scale axis of the reference is node count (SURVEY.md §5 long-context note):
its 16-goroutine chunked fan-out over nodes (pkg/scheduler/framework/parallelize/
parallelism.go — Parallelizer.Until) maps to data parallelism over the node axis
of the (pods x nodes) matrices, sharded across TPU chips over ICI.  One mesh
axis "nodes" for now; the pods axis joins when ring/all-to-all stages land.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

# the mesh axis names + rule table live in partition_rules (the single
# source of sharding truth); re-exported here for the existing import sites
from .partition_rules import (  # noqa: F401
    NODE_AXIS,
    PODS_AXIS,
    node_axis_fields,
    pod_axis_fields,
)

# jax moved shard_map out of experimental around 0.5; alias whichever this
# runtime has so the sharded paths work on both (the seed's bare
# jax.shard_map raised AttributeError on 0.4.x and failed tier-1's
# test_sharded/test_ring).
try:
    shard_map = jax.shard_map
except AttributeError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map  # noqa: F401

# ClusterArrays fields carrying the node axis, with (axis, pad fill) —
# DERIVED from the partition rule table (a field is padded on exactly the
# axis the table shards), no longer maintained in parallel with the specs.
# The fill values replicate the encoder's own bucketing padding
# (api/delta.py — _assemble: node_valid False is the master gate, so padded
# nodes are statically infeasible for every pod and can never attain a
# normalization extreme or win an argmax; node_dom's fill is resolved
# per-array to the "key absent" sentinel D).  image_score pads on axis 1
# only when it is a real [P, N] matrix.
NODE_AXIS_FIELDS: Dict[str, Tuple[int, object]] = node_axis_fields()

# ClusterArrays fields carrying the POD axis, with (axis, fill 0) — the 2-D
# mesh's second padding plane (pad_pods below; padded pods have pod_valid
# False, which gates them out of every stage).
POD_AXIS_FIELDS: Dict[str, Tuple[int, object]] = pod_axis_fields()


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """1-D node-axis mesh by default; ``shape=(pods, nodes)`` builds the
    2-D pods x nodes mesh over the first pods*nodes devices.  A 1-D mesh
    deliberately carries NO pods axis, so sharding_for() strips the pod
    rows and every pre-2-D call site behaves exactly as before."""
    devs = list(devices) if devices is not None else jax.devices()
    import numpy as np

    if shape is not None:
        p, n = int(shape[0]), int(shape[1])
        if p <= 1:
            # a degenerate pods dimension is just the 1-D nodes mesh
            return make_mesh(n_devices=n, devices=devs)
        if len(devs) < p * n:
            raise ValueError(
                f"mesh shape {p}x{n} needs {p * n} devices; "
                f"only {len(devs)} available"
            )
        grid = np.array(devs[: p * n]).reshape(p, n)
        return Mesh(grid, (PODS_AXIS, NODE_AXIS))
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (NODE_AXIS,))


# the request grammar lives in the import-light kubernetes_tpu.meshreq
# (bench.py parses it pre-backend); re-exported here for existing call sites
from ..meshreq import (  # noqa: F401,E402
    mesh_request_devices,
    parse_mesh_request,
)


def mesh_from_env(raw: Optional[str] = None, source: str = "KTPU_MESH") -> Optional[Mesh]:
    """KTPU_MESH=<n>: build the node-axis mesh over the first n local
    devices; KTPU_MESH=<p>x<n> (or the KTPU_MESH_PODS / KTPU_MESH_NODES
    pair) the 2-D pods x nodes mesh.  Unset / 1 / 0 -> None (the
    single-device path).  Invalid values raise a clear ValueError instead
    of silently running single-device; a 1-D request beyond the available
    device count CLAMPS with a warning, so one deployment config serves
    hosts of different sizes — a 2-D shape RAISES instead (there is no
    unambiguous way to shrink a grid).  The one validated entry for EVERY
    mesh-count request — config-sourced counts (TPUScoreArgs.meshDevices)
    resolve through it too, with `source` naming the knob in
    errors/warnings."""
    req = parse_mesh_request(raw, source=source)
    if req is None:
        return None
    if (
        isinstance(req, int)
        and not os.environ.get("KTPU_MESH_PODS", "").strip()
    ):
        # fold a persisted autotune winner (ops/tuning.py — env > winner >
        # default) into the 1-D request: KTPU_MESH_PODS=2 turns KTPU_MESH=8
        # into the 2x4 grid.  Same total device count, so bench.py's
        # jax-free pre-backend sizing (parse_mesh_request) stays correct.
        from ..ops.tuning import tuned_knob

        p = int(tuned_knob("KTPU_MESH_PODS", 0) or 0)
        if p > 1 and req % p == 0 and req // p >= 1:
            req = (p, req // p)
    avail = len(jax.devices())
    if isinstance(req, tuple):
        p, n = req
        if p * n > avail:
            raise ValueError(
                f"{source}={p}x{n} needs {p * n} devices; only {avail} "
                "available (2-D shapes do not clamp)"
            )
        return make_mesh(shape=(p, n))
    n = req
    if n > avail:
        warnings.warn(
            f"{source}={n} exceeds the {avail} available device(s); "
            f"clamping to {avail}",
            stacklevel=2,
        )
        n = avail
    if n <= 1:
        return None
    return make_mesh(n)


def mesh_axis_shards(mesh) -> Tuple[int, int]:
    """(pod_shards, node_shards) of a mesh — (1, 1) for None.  The one
    accessor for code that needs per-axis counts (memwatch's size model,
    the sharded wrappers, the encoder's two padding planes)."""
    if mesh is None:
        return (1, 1)
    shape = dict(mesh.shape)
    return (int(shape.get(PODS_AXIS, 1)), int(shape.get(NODE_AXIS, 1)))


def pad_field(name: str, a, pad: int, d_sentinel: int, n: int):
    """Pad ONE ClusterArrays field's node axis by `pad` entries, or return
    it untouched when it carries no node axis.  The single source of the
    fill/axis rules (NODE_AXIS_FIELDS + the [P, N] image_score case) shared
    by pad_nodes below and the resident encoder's placement-time padding
    (api/delta.py — DeltaEncoder._pad_for_mesh)."""
    import numpy as np

    ent = NODE_AXIS_FIELDS.get(name)
    if ent is None:
        if name == "image_score" and a.shape[1] == n:
            ent = (1, 0)
        else:
            return a
    axis, fill = ent
    if fill is None:
        fill = d_sentinel
    a = np.asarray(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return np.pad(a, widths, constant_values=fill)


def pad_nodes(arr, n_shards: int):
    """Pad the node axis of a ClusterArrays to a multiple of `n_shards` with
    permanently invalid nodes — the exact padding the encoder's bucketing
    already applies (zero capacity, valid=False, sentinel domains), so
    decisions are unchanged: padded columns are masked -inf before every
    argmax / top-k / normalization.  Returns (arr, original_N); the input is
    returned untouched when already divisible.  Host-side (numpy): callers
    on the device hot path pad BEFORE placement (api/delta.py —
    DeltaEncoder with a mesh)."""
    n = arr.N
    pad = (-n) % n_shards
    if pad == 0:
        return arr, n
    import dataclasses

    d_sentinel = arr.term_counts0.shape[1] - 1
    repl = {
        name: pad_field(name, getattr(arr, name), pad, d_sentinel, n)
        for name in (*NODE_AXIS_FIELDS, "image_score")
    }
    return dataclasses.replace(arr, **repl), n


def pad_pod_field(name: str, a, pad: int):
    """Pad ONE ClusterArrays field's pod axis by `pad` entries (fill 0), or
    return it untouched when it carries no pod axis.  image_score pads its
    leading axis in BOTH the [P, N] matrix and [P, 1] broadcast forms.
    Shared by pad_pods below and the resident encoder's placement-time
    padding (api/delta.py)."""
    import numpy as np

    ent = POD_AXIS_FIELDS.get(name)
    if ent is None:
        if name == "image_score":
            ent = (0, 0)
        else:
            return a
    axis, fill = ent
    a = np.asarray(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return np.pad(a, widths, constant_values=fill)


def pad_pods(arr, pod_shards: int):
    """Pad the pod axis of a ClusterArrays to a multiple of `pod_shards`
    with permanently invalid pods — `pod_valid` False is the master gate
    (assignment -1, commits nothing, contributes zero usage), so decisions
    over the real pods are unchanged.  Returns (arr, original_P); the input
    comes back untouched when already divisible.  Mirrors pad_nodes: the
    encoder's pow-of-2 bucketing usually makes this a no-op for pow-of-2
    shard counts."""
    p = arr.P
    pad = (-p) % pod_shards
    if pad == 0:
        return arr, p
    import dataclasses

    repl = {
        name: pad_pod_field(name, getattr(arr, name), pad)
        for name in (*POD_AXIS_FIELDS, "image_score")
    }
    return dataclasses.replace(arr, **repl), p


def shard_hbm_estimate(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, chunk: int = 128, u_classes: Optional[int] = None,
    pod_shards: int = 1,
) -> Dict[str, int]:
    """Per-shard device-memory estimate (bytes) for the routed kernels'
    dominant blocks (PARITY.md HBM budget, sharded): the two [P, Nl] bool
    masks (static feasibility + node-selection) shard column-wise; the
    per-chunk hoist and [T, Nl] count state shard with them; the chunked
    kernel's gathered [C, N] score matrix (plus its transpose) and the
    [N, R] usage/alloc arrays are replicated per shard.

    `round_loop` covers the prefix-commit round machinery's O(C^2) blocks
    — the [C, C, R] exclusive prefix-sum of intra-round requests (input +
    associative-scan carry + output) and the [C, 2C] candidate/validation
    matrices.  Replicated per shard, independent of N: negligible at
    production scale (~1 MB at C=128 vs ~277 MB of masks) but DOMINANT at
    the tiny scales the device pass (analysis/devicecheck.py — KTPU012)
    traces, so the estimate stays honest at every scale the reconciliation
    runs at.

    `u_classes` (incremental routes, ops/incremental.py): adds the
    resident [U1, Nl] class matrices (static/base/fit + the carried copy)
    the IncState pins per shard.

    PACKED DATA PLANE (ops/bitplane.py — KTPU_PACK_MASKS): the boolean
    mask planes store as uint32 bit-plane words, so the `pn_masks` term and
    the mask share of `class_matrices` price at ``ceil(n/32) * 4`` bytes
    per row instead of ``n`` — the 8x HBM-ceiling cut BENCH_r08 lands.
    The estimate keys on the same trace-time knob as the kernels, so the
    analytic budget and the compiled buffers flip together (KTPU012).

    2-D MESH (``pod_shards`` > 1): the resident pod-axis buffers divide by
    ``pod_shards`` (the burned-down KTPU015 replicated-giant set), and the
    kernel's entry all-gather over the pods axis materializes ONE full-size
    transient copy of each gathered pod field — priced honestly as the
    ``pod_gather`` term, so the budget covers the peak, not just the
    at-rest residency win."""
    from ..ops import bitplane

    nl = -(-n_nodes // n_shards)
    # bytes of one [*, nl] / [*, N] boolean mask ROW under the active plane
    row_l = 4 * bitplane.words_for(nl) if bitplane.PACK_MASKS else nl
    row_n = (
        4 * n_shards * bitplane.words_for(nl)
        if bitplane.PACK_MASKS else n_nodes
    )
    b = {
        "pn_masks": 2 * n_pods * row_l,              # sf + nodesel planes
        "chunk_hoist": 2 * chunk * nl * n_res * 4,   # requested + scores f32
        "count_state": 4 * max(1, n_terms) * nl * 4, # cnt/anti/pref/dom
        "gathered_scores": 2 * chunk * n_nodes * 4,  # [C, N] total0 + .T
        "node_side_replicated": 2 * n_nodes * n_res * 4,  # alloc + used
        # [C, C, R] prefix-sum (x3 live copies) + [C, 2C] f32 (x2)
        "round_loop": 3 * chunk * chunk * n_res * 4
        + 4 * chunk * chunk * 4,
    }
    if u_classes:
        # the gathered [U1, N] f32 score carry (+ its masked copy) the
        # chunk scan rides, plus the gathered stat/fit mask planes (packed:
        # word rows; dense: byte rows) — full N, stitched once per cycle
        b["class_matrices"] = (
            2 * u_classes * n_nodes * 4 + 2 * u_classes * row_n
        )
    # the resident INPUT set (every ClusterArrays field + the IncState
    # matrices), summed from the per-field size model the partition rule
    # table derives — the same model KTPU015's replicated-giant threshold
    # and shard_comm_estimate consume, so the three can never drift onto
    # different field sets (previously these argument bytes were simply
    # missing from the hand-listed term sum)
    from .partition_rules import resident_input_bytes

    b["resident_inputs"] = resident_input_bytes(
        n_pods, n_nodes, n_shards, n_res=n_res, n_terms=n_terms,
        u_classes=u_classes, pod_shards=pod_shards,
    )
    if pod_shards > 1:
        b["pod_gather"] = pod_gather_bytes(
            n_pods, n_nodes, n_shards, n_res=n_res, n_terms=n_terms,
            u_classes=u_classes,
        )
    b["total"] = sum(b.values())
    return b


def pod_gather_bytes(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, u_classes: Optional[int] = None,
) -> int:
    """Bytes of the kernels' entry all-gather over the pods axis: each
    pod-sharded resident field is stitched back to its FULL pod extent once
    per program (node-sharded dims stay node-local).  This is both the 2-D
    transient-HBM term of shard_hbm_estimate and the pod-axis collective
    term of shard_comm_estimate — one number, two reconciliations
    (KTPU012 / KTPU017), derived from the same rule table."""
    from .partition_rules import FIELD_DIMS, field_bytes, sharded_on_pods

    env = {"P": n_pods, "N": n_nodes, "R": n_res, "T2": max(1, n_terms),
           "U": u_classes or 1}
    total = 0
    for q in FIELD_DIMS:
        if q.startswith("inc.") and not u_classes:
            continue
        if not sharded_on_pods(q):
            continue
        if q == "arr.image_score":
            # the broadcast [P, 1] form gathers at the score width; the
            # real [P, N] matrix stays node-sharded after the pod gather
            total += (FIELD_DIMS[q][1] // 8) * max(1, n_pods)
            continue
        total += field_bytes(q, env, n_shards, pod_shards=1)
    return total


def shard_comm_estimate(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, chunk: int = 128, u_classes: Optional[int] = None,
    kind: str = "chunked", pod_shards: int = 1,
) -> Dict[str, int]:
    """Analytic per-shard collective-traffic estimate (bytes) for ONE traced
    program of the sharded routed kernels — the KTPU017 reconciliation
    budget, sibling to shard_hbm_estimate (KTPU012).  Bytes are STATIC
    program bytes: each collective in the traced jaxpr counts once at its
    output size (the same definition analysis/shardcheck.collective_bytes
    measures), so the two sides reconcile on one number.

    Terms (what the kernels stitch across shards per program):

      ``gathered_scores``  the shard-local [C, Nl] hoist blocks all-gather
                           to the full [C, N] score matrix the commit scan
                           reads (raw + masked copies ride the same stitch)
      ``commit_psums``     owner-shard psum broadcasts of committed pods'
                           domain/usage columns and the scan's scalar
                           reductions (pmax/pmin argmax stitches) — [C, N]
                           and [C, R]-scale blocks
      ``class_stitch``     incremental routes: the [U1, N] class-matrix
                           gather the per-cycle hoist stitches once
      ``pod_gather``       2-D mesh: the one-time entry all-gather of the
                           pod-sharded resident fields back to full pod
                           extent (pod_gather_bytes — each all_gather's
                           output is the full array, the same bytes the
                           KTPU012 transient term prices)

    The estimate models the dominant blocks, not every scalar pmax; the
    KTPU017 tolerance (analysis/shardcheck.COMM_TOLERANCE) absorbs the
    rest, exactly as HBM_TOLERANCE does for KTPU012."""
    b = {
        "gathered_scores": 2 * chunk * n_nodes * 4,
        "commit_psums": 2 * chunk * n_nodes * 4 + 4 * chunk * n_res * 4,
    }
    if u_classes and kind == "inc":
        b["class_stitch"] = 4 * u_classes * n_nodes * 4
    if pod_shards > 1:
        b["pod_gather"] = pod_gather_bytes(
            n_pods, n_nodes, n_shards, n_res=n_res, n_terms=n_terms,
            u_classes=u_classes if kind == "inc" else None,
        )
    b["total"] = sum(b.values())
    return b


def init_distributed(
    coordinator: str, num_processes: int, process_id: int,
    mesh_shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """Multi-host (DCN) entry: join the jax.distributed cluster, then build
    the node-axis mesh over ALL processes' devices.  The reference scales its
    control plane over plain gRPC/HTTP2; here multi-host scheduling shards the
    node axis across hosts with XLA collectives riding DCN between slices
    (SURVEY.md §2.4 distributed-backend mapping).  Single-host callers never
    need this — make_mesh over local devices is the ICI path.

    ``mesh_shape=(pods, nodes)`` builds the 2-D pods x nodes mesh over the
    global device set instead of the 1-D node axis.

    Verified by tests/test_dcn_distributed.py: a 2-process CPU-sim cluster
    runs the full sharded step (1-D and 2-D) with cross-process collectives
    and matches the dense single-process decisions bit-for-bit."""
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_mesh(shape=mesh_shape)


def global_arrays(mesh: Mesh, tree):
    """Lift a pytree of process-replicated numpy arrays into global jax.Arrays
    for multi-controller jit: every [*, N]/[N, *] array must enter a global-
    mesh program as a jax.Array spanning processes; each process contributes
    its addressable shards from its full local copy."""
    from .partition_rules import replicated_sharding

    rep = replicated_sharding(mesh)

    def lift(x):
        return jax.make_array_from_process_local_data(rep, x)

    return jax.tree_util.tree_map(lift, tree)
