"""The declarative sharding rule table — the SINGLE source of placement truth.

Before this module, per-field ``NamedSharding``/``PartitionSpec`` literals
were hand-spread across ``parallel/sharded.py`` (a 40-line spec pytree),
``ops/incremental.py`` (three placement helpers), ``parallel/mesh.py``
(``NODE_AXIS_FIELDS`` maintained in parallel with the specs), and
``parallel/ring.py`` — four copies of one fact, none checkable.  This module
replaces them with an ordered regex -> ``PartitionSpec`` rule table in the
``match_partition_rules`` style (SNIPPETS.md [2]): every resident-buffer
placement — ``DeltaEncoder`` device buffers, ``HoistCache`` class matrices,
the sharded jit wrappers' in/out specs, the ring stages — resolves through
``spec_for(qualname)``, and the ktpu-verify shard pass
(``analysis/shardcheck.py``, KTPU014..018) proves every compiled program
obeys what the table declares.

Qualname convention (the rule keys):

  ``arr.<field>``    ClusterArrays resident fields (api/snapshot.py)
  ``inc.<field>``    IncState resident class matrices (ops/incremental.py)
  ``out.<name>``     kernel outputs of the sharded routed step
  ``ring.<name>``    ring/all-to-all stage buffers (parallel/ring.py)
  ``hoist.<name>``   HoistCache staging vectors (dirty-column ids)
  ``mesh.replicated``  the multi-host global-array lift (parallel/mesh.py)

Adding a field is ONE row here (regex, spec, dims, itemsize); everything
else — ``NODE_AXIS_FIELDS`` padding, the per-field size model feeding
``shard_hbm_estimate``/``shard_comm_estimate`` and the KTPU015
replicated-giant threshold math, the sharded wrappers' specs — derives from
the row, and the shard pass fails closed on an unmatched qualname instead
of silently replicating.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as _dc_field
from typing import Dict, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

# The mesh axis names.  NODE_AXIS lives here (not parallel/mesh.py) so the
# table is import-cycle-free; mesh.py re-exports it for existing callers.
# PODS_AXIS is the 2-D mesh's second dimension (ROADMAP item 3): pod-scaling
# resident buffers shard over it, node-scaling buffers over NODE_AXIS.  A
# 1-D mesh simply omits the pods axis — sharding_for() strips axes the mesh
# does not carry, so every 1-D call site keeps working unchanged.
NODE_AXIS = "nodes"
PODS_AXIS = "pods"
MESH_AXES = (PODS_AXIS, NODE_AXIS)

# mesh axis -> the scale symbol its sharded dimension must carry (KTPU016's
# axis-maps-to-scale-dim check generalizes over this instead of hardcoding
# the node axis).  inc.cls shards its class-id vector over PODS_AXIS because
# it is pod-aligned ([P]), not class-aligned.
AXIS_SCALE: Dict[str, str] = {NODE_AXIS: "N", PODS_AXIS: "P"}

# Scale-dimension symbols: axes whose size grows with the cluster (pods,
# nodes, equivalence classes).  Everything else ("R", "T", "L", ...) is a
# vocabulary axis bounded by spec diversity, not cluster size.
SCALE_SYMBOLS = ("P", "N", "U")

# ROADMAP-3 target dims for the KTPU015 replicated-giant analysis: the 2-D
# pods x nodes mesh item is sized at 500k pods x 100k nodes; U extrapolates
# the measured class counts (U ~ 101 at 50k pods, BENCH_r06).
SCALE_DIMS: Dict[str, int] = {"P": 500_000, "N": 100_000, "U": 1_024}

# Canonical secondary-dimension sizes for the analytic size model.  These
# deliberately replace the per-workload traced sizes so the KTPU015 finding
# set (and therefore the committed baseline) is workload-independent.
CANONICAL_DIMS: Dict[str, int] = {
    "R": 4, "T": 8, "L": 16, "TT": 2, "PW": 2, "T2": 8, "MM": 2,
    "A1": 1, "A2": 1, "B": 2, "C": 2, "PT": 4, "S": 32, "E": 4,
    "D1": 64, "K": 4, "G": 64,
}


@dataclass(frozen=True)
class PartitionRule:
    """One row of the table: first regex match wins (``match_partition_rules``
    semantics).  ``pad_fill`` is the node-axis padding fill for fields the
    derived ``NODE_AXIS_FIELDS`` covers (None -> the per-array D sentinel)."""

    pattern: str
    spec: P
    pad_fill: object = 0

    def matches(self, qualname: str) -> bool:
        return re.search(self.pattern, qualname) is not None


# --------------------------------------------------------------------------
# THE TABLE.  Ordered; first match wins; no match is an error (fail closed).
# --------------------------------------------------------------------------

PARTITION_RULES: Tuple[PartitionRule, ...] = (
    # --- ClusterArrays node-axis resident fields (shard over the mesh) ---
    PartitionRule(r"^arr\.node_(valid|unsched)$", P(NODE_AXIS)),
    PartitionRule(r"^arr\.node_dom$", P(None, NODE_AXIS), pad_fill=None),
    PartitionRule(
        r"^arr\.(node_alloc|node_used|node_labels|node_taint_ns"
        r"|node_taint_pref|node_ports0)$",
        P(NODE_AXIS, None),
    ),
    # [P, N] image-locality matrix: sharded on BOTH mesh axes when it is a
    # real matrix; clusterarrays_specs() degrades the node axis for the
    # [P, 1] broadcast form (the shape-conditional rule, snippet-style)
    PartitionRule(r"^arr\.image_score$", P(PODS_AXIS, NODE_AXIS)),
    # --- ClusterArrays pod-axis resident fields (shard over the pods mesh
    # axis — the burned-down ROADMAP-3a replicated-giant debt; a 1-D nodes
    # mesh strips the axis and these replicate exactly as before) ---
    PartitionRule(r"^arr\.sel_mask$", P(None, None, None)),
    PartitionRule(
        r"^arr\.(pod_valid|pod_prio|pod_nodename|pod_has_sel|pod_group)$",
        P(PODS_AXIS),
    ),
    # vocabulary vectors (term/group universes — bounded by spec diversity,
    # not cluster size): replicated
    PartitionRule(r"^arr\.(group_min|term_key)$", P()),
    # [T2, P] pending-membership matrix: pod axis is SECOND
    PartitionRule(r"^arr\.m_pend$", P(None, PODS_AXIS)),
    # pod-leading 2-D matrices, ENUMERATED (no catch-all: an unlisted future
    # field must fail spec_for loudly, not replicate silently — the
    # fail-closed contract KTPU014/16 build on)
    PartitionRule(
        r"^arr\.(pod_req|pod_tol_ns|pod_tol_pref|pod_terms"
        r"|pod_pref_terms|pod_pref_weights|pod_match_terms"
        r"|pod_match_vals|pod_aff_self"
        r"|pod_aff_terms|pod_anti_terms|pod_pref_aff_terms|pod_pref_aff_w"
        r"|pod_spread_terms|pod_spread_maxskew|pod_spread_hard"
        r"|pod_ports)$",
        P(PODS_AXIS, None),
    ),
    # vocabulary matrices ([S,E] selector table, [T2,D1] per-term domain
    # counts): bounded by spec diversity, replicated
    PartitionRule(
        r"^arr\.(sel_kind|term_counts0|anti_counts0|pref_own0)$",
        P(None, None),
    ),
    # --- IncState resident class matrices (ops/incremental.py) ---
    # cls is pod-aligned ([P] class ids), so it shards with the pods
    PartitionRule(r"^inc\.cls$", P(PODS_AXIS)),
    PartitionRule(r"^inc\.req_u$", P(None, None)),
    PartitionRule(r"^inc\..*_u$", P(None, NODE_AXIS)),
    # --- sharded routed-step outputs (parallel/sharded.py out_specs) ---
    PartitionRule(r"^out\.node_used_scan$", P(NODE_AXIS, None)),
    PartitionRule(r"^out\.(assignment|node_used|ordinals|n_commits)$", P()),
    # --- ring / all-to-all stages (parallel/ring.py) ---
    PartitionRule(r"^ring\.sel_mask$", P(NODE_AXIS, None, None)),
    PartitionRule(r"^ring\.(sel_kind|labels|match_out|a2a_in)$",
                  P(NODE_AXIS, None)),
    PartitionRule(r"^ring\.a2a_out$", P(None, NODE_AXIS)),
    # --- host-staging vectors + the multi-host replicated lift ---
    PartitionRule(r"^(hoist\.cols|mesh\.replicated)$", P()),
)


# --------------------------------------------------------------------------
# Per-field size model (dims symbols x itemsize) — shared by
# shard_hbm_estimate / shard_comm_estimate (parallel/mesh.py) and the
# KTPU015 replicated-giant threshold math (analysis/shardcheck.py), so the
# analytic budgets and the lint can never drift onto different field sets.
# --------------------------------------------------------------------------

# qualname -> (dims symbols, BITS per element).  Covers the RESIDENT buffer
# set: every ClusterArrays field + every IncState field.  A ClusterArrays
# field added without a row here fails the shard pass's coverage check
# loudly.
#
# BITS MODEL (the packed data plane, ops/bitplane.py): bits >= 8 is a plain
# element width (bytes = count * bits / 8).  bits == 1 marks a BIT-PACKED
# plane: the concrete buffer stores uint32 words along its LAST dims symbol
# (`[..., ceil(n/32)]`, per-shard-local word blocks under the mesh), and
# `field_bytes` prices exactly that word-padded layout — so the one size
# model feeding shard_hbm_estimate, memwatch's census (KTPU020) and the
# KTPU015 threshold math prices packed fields correctly by construction.
# The packed/bf16 rows key on the same trace-time knobs as the kernels
# (KTPU_PACK_MASKS / KTPU_SCORE_DTYPE), so model and buffers flip together.
_MASK_BITS: int
_SCORE_BITS: int


def _plane_bits() -> Tuple[int, int]:
    from ..ops import bitplane

    return (1 if bitplane.PACK_MASKS else 8,
            16 if bitplane.SCORE_DTYPE == "bf16" else 32)


_MASK_BITS, _SCORE_BITS = _plane_bits()

FIELD_DIMS: Dict[str, Tuple[Tuple[str, ...], int]] = {
    "arr.node_valid": (("N",), 8),
    "arr.node_alloc": (("N", "R"), 32),
    "arr.node_used": (("N", "R"), 32),
    "arr.node_unsched": (("N",), 8),
    "arr.node_labels": (("N", "L"), 32),
    "arr.node_taint_ns": (("N", "T"), 8),
    "arr.node_taint_pref": (("N", "T"), 8),
    "arr.node_dom": (("K", "N"), 32),
    "arr.node_ports0": (("N", "PT"), 8),
    "arr.pod_valid": (("P",), 8),
    "arr.pod_req": (("P", "R"), 32),
    "arr.pod_prio": (("P",), 32),
    "arr.pod_tol_ns": (("P", "T"), 8),
    "arr.pod_tol_pref": (("P", "T"), 8),
    "arr.pod_nodename": (("P",), 32),
    "arr.pod_terms": (("P", "TT"), 32),
    "arr.pod_has_sel": (("P",), 8),
    "arr.sel_mask": (("S", "E", "L"), 32),
    "arr.sel_kind": (("S", "E"), 32),
    "arr.pod_pref_terms": (("P", "PW"), 32),
    "arr.pod_pref_weights": (("P", "PW"), 32),
    "arr.term_key": (("T2",), 32),
    "arr.m_pend": (("T2", "P"), 32),
    "arr.pod_match_terms": (("P", "MM"), 32),
    "arr.pod_match_vals": (("P", "MM"), 32),
    "arr.pod_aff_self": (("P", "A1"), 8),
    "arr.term_counts0": (("T2", "D1"), 32),
    "arr.anti_counts0": (("T2", "D1"), 32),
    "arr.pod_aff_terms": (("P", "A1"), 32),
    "arr.pod_anti_terms": (("P", "A2"), 32),
    "arr.pod_pref_aff_terms": (("P", "B"), 32),
    "arr.pod_pref_aff_w": (("P", "B"), 32),
    "arr.pref_own0": (("T2", "D1"), 32),
    "arr.pod_spread_terms": (("P", "C"), 32),
    "arr.pod_spread_maxskew": (("P", "C"), 32),
    "arr.pod_spread_hard": (("P", "C"), 8),
    "arr.pod_ports": (("P", "PT"), 8),
    "arr.pod_group": (("P",), 32),
    "arr.group_min": (("G",), 32),
    "arr.image_score": (("P", "N"), _SCORE_BITS),
    "inc.cls": (("P",), 32),
    "inc.req_u": (("U", "R"), 32),
    "inc.stat_u": (("U", "N"), _MASK_BITS),
    "inc.base_u": (("U", "N"), 32),
    "inc.fit_u": (("U", "N"), _MASK_BITS),
    "inc.elig_u": (("U", "N"), _MASK_BITS),
    "inc.traw_u": (("U", "N"), _SCORE_BITS),
    "inc.naraw_u": (("U", "N"), _SCORE_BITS),
    "inc.img_u": (("U", "N"), _SCORE_BITS),
}


# --------------------------------------------------------------------------
# resolution
# --------------------------------------------------------------------------


def rule_for(qualname: str) -> PartitionRule:
    """First matching rule, ``match_partition_rules`` style.  Fails CLOSED:
    a qualname outside the table raises instead of silently replicating —
    the resolver is how KTPU014 guarantees there is exactly one spec
    authority."""
    for rule in PARTITION_RULES:
        if rule.matches(qualname):
            return rule
    raise ValueError(
        f"no partition rule matches {qualname!r} — add a row to "
        "parallel/partition_rules.PARTITION_RULES (one regex row; the "
        "shard pass proves the rest)"
    )


def spec_for(qualname: str) -> P:
    return rule_for(qualname).spec


def strip_spec(spec: P, axis_names: Sequence[str]) -> P:
    """`spec` with every axis NOT in `axis_names` replaced by None — how a
    1-D nodes mesh (or a pods-only mesh) consumes the 2-D table: rows keep
    declaring the full pods x nodes placement, and each mesh takes exactly
    the axes it carries."""
    names = set(axis_names)
    return P(*(ax if ax in names else None for ax in tuple(spec)))


def spec_for_mesh(mesh, qualname: str) -> P:
    return strip_spec(spec_for(qualname), tuple(mesh.axis_names))


def sharding_for(mesh, qualname: str):
    """NamedSharding over `mesh` for one table row — the ONE constructor
    every placement site routes through (KTPU014 flags NamedSharding
    literals anywhere else in the package).  Axes the mesh does not carry
    are stripped, so the same row serves 1-D and 2-D meshes."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec_for_mesh(mesh, qualname))


def replicated_sharding(mesh):
    """Fully-replicated NamedSharding (the ``mesh.replicated`` row)."""
    return sharding_for(mesh, "mesh.replicated")


def clusterarrays_shardings(mesh, image_sharded: bool) -> Dict[str, object]:
    """field name -> NamedSharding for every ClusterArrays field —
    the construction half of parallel/sharded.field_shardings (which
    memoizes per (mesh, image_sharded)); placement sites receive built
    shardings, never build their own (KTPU014).  The pods axis rides only
    when the mesh carries it (strip_spec)."""
    import dataclasses

    from jax.sharding import NamedSharding

    axes = tuple(mesh.axis_names)
    specs = clusterarrays_specs(image_sharded, pod_sharded=PODS_AXIS in axes)
    return {
        f.name: NamedSharding(mesh, strip_spec(getattr(specs, f.name), axes))
        for f in dataclasses.fields(type(specs))
    }


def clusterarrays_specs(image_sharded: bool, pod_sharded: bool = False):
    """PartitionSpec pytree over every ClusterArrays field, resolved row by
    row from the table (replaces parallel/sharded.py's hand-written
    ``_node_sharding_specs``).  ``image_sharded`` keys the shape-conditional
    image_score rule: the [P, 1] broadcast form drops the node axis (the pod
    axis still shards when ``pod_sharded``).  ``pod_sharded=False`` (every
    1-D caller) strips PODS_AXIS from all rows."""
    import dataclasses

    from ..api.snapshot import ClusterArrays

    keep = MESH_AXES if pod_sharded else (NODE_AXIS,)
    specs = {}
    for f in dataclasses.fields(ClusterArrays):
        spec = spec_for(f"arr.{f.name}")
        if f.name == "image_score" and not image_sharded:
            spec = P(tuple(spec)[0], None)
        specs[f.name] = strip_spec(spec, keep)
    return ClusterArrays(**specs)


def incstate_specs(elig: bool, traw: bool, naraw: bool, img: bool,
                   pod_sharded: bool = False):
    """IncState PartitionSpec pytree for the populated optional structure
    (None leaves drop out of the pytree — parallel/sharded.py in_specs /
    ops/incremental.inc_partition_specs both resolve through here).
    ``pod_sharded=False`` strips PODS_AXIS (1-D callers)."""
    from ..ops.incremental import IncState

    keep = MESH_AXES if pod_sharded else (NODE_AXIS,)

    def sf(q):
        return strip_spec(spec_for(q), keep)

    return IncState(
        cls=sf("inc.cls"),
        req_u=sf("inc.req_u"),
        stat_u=sf("inc.stat_u"),
        base_u=sf("inc.base_u"),
        fit_u=sf("inc.fit_u"),
        elig_u=sf("inc.elig_u") if elig else None,
        traw_u=sf("inc.traw_u") if traw else None,
        naraw_u=sf("inc.naraw_u") if naraw else None,
        img_u=sf("inc.img_u") if img else None,
    )


def node_axis_fields() -> Dict[str, Tuple[int, object]]:
    """field name -> (node axis index, pad fill), DERIVED from the table:
    every ClusterArrays field whose spec carries the node axis, at the axis
    position the spec shards.  Replaces the hand-maintained
    ``parallel/mesh.NODE_AXIS_FIELDS`` dict (one fact, one place).
    image_score stays excluded — its [P, N]-vs-[P, 1] shape conditionality
    is handled at the padding call sites, exactly as before."""
    import dataclasses

    from ..api.snapshot import ClusterArrays

    out: Dict[str, Tuple[int, object]] = {}
    for f in dataclasses.fields(ClusterArrays):
        if f.name == "image_score":
            continue
        rule = rule_for(f"arr.{f.name}")
        if NODE_AXIS in tuple(rule.spec):
            out[f.name] = (tuple(rule.spec).index(NODE_AXIS), rule.pad_fill)
    return out


def pod_axis_fields() -> Dict[str, Tuple[int, object]]:
    """field name -> (pod axis index, pad fill), DERIVED from the table
    exactly like ``node_axis_fields`` — the ``pad_pods`` input.  Pod padding
    always fills 0: a padded pod row has ``pod_valid`` False, which gates it
    out of every stage (assignment -1, commits nothing), so in-vocabulary
    zeros everywhere else are safe.  image_score stays excluded — its
    [P, N]-vs-[P, 1] shape conditionality is handled at the padding call
    sites, same as the node side."""
    import dataclasses

    from ..api.snapshot import ClusterArrays

    out: Dict[str, Tuple[int, object]] = {}
    for f in dataclasses.fields(ClusterArrays):
        if f.name == "image_score":
            continue
        rule = rule_for(f"arr.{f.name}")
        if PODS_AXIS in tuple(rule.spec):
            out[f.name] = (tuple(rule.spec).index(PODS_AXIS), 0)
    return out


# --------------------------------------------------------------------------
# the shared analytic size model
# --------------------------------------------------------------------------


def field_bytes(qualname: str, dims_env: Optional[Dict[str, int]] = None,
                n_shards: int = 1, pod_shards: int = 1) -> int:
    """Analytic PER-SHARD bytes of one resident field under `dims_env`
    (symbol -> size; CANONICAL_DIMS fills the gaps).  A dimension the table
    shards divides by that axis's shard count (``n_shards`` is the NODE
    axis, ``pod_shards`` the PODS axis); replicated fields pay full size on
    every shard — the quantity KTPU015 thresholds and the
    ``resident_inputs`` term of ``shard_hbm_estimate`` sums.

    bits >= 8 rows price as ``count * bits/8``.  bits == 1 (bit-packed)
    rows price the CONCRETE uint32 word layout: the last dims symbol packs
    to ``ceil(size/32)`` words of 4 bytes (after the node-axis shard
    division — per-shard-local word blocks, ops/bitplane.py), so the model
    equals the live buffer byte-for-byte including word padding (KTPU020's
    exact-equality contract)."""
    dims, bits = FIELD_DIMS[qualname]
    env = dict(CANONICAL_DIMS)
    env.update(SCALE_DIMS)
    if dims_env:
        env.update(dims_env)
    div = {NODE_AXIS: max(1, n_shards), PODS_AXIS: max(1, pod_shards)}
    spec = tuple(spec_for(qualname))
    sizes = []
    for i, sym in enumerate(dims):
        size = env[sym]
        if i < len(spec) and spec[i] in div:
            size = -(-size // div[spec[i]])
        sizes.append(max(1, size))
    if bits < 8:
        # packed plane: last axis becomes uint32 words
        words = -(-sizes[-1] // 32)
        total = 4 * words
        for size in sizes[:-1]:
            total *= size
        return total
    total = bits // 8
    for size in sizes:
        total *= size
    return total


def sharded_on_nodes(qualname: str) -> bool:
    return NODE_AXIS in tuple(spec_for(qualname))


def sharded_on_pods(qualname: str) -> bool:
    return PODS_AXIS in tuple(spec_for(qualname))


def resident_input_bytes(
    n_pods: int, n_nodes: int, n_shards: int, n_res: int = 4,
    n_terms: int = 1, u_classes: Optional[int] = None,
    image_sharded: bool = False, pod_shards: int = 1,
) -> int:
    """Per-shard bytes of the resident input set (every ``arr.*`` field,
    plus ``inc.*`` when the incremental route rides) — the table-derived
    term ``shard_hbm_estimate`` adds so the analytic HBM budget covers the
    argument bytes the compiled memory analysis measures."""
    env = {"P": n_pods, "N": n_nodes, "R": n_res, "T2": max(1, n_terms),
           "U": u_classes or 1}
    total = 0
    for q in FIELD_DIMS:
        if q.startswith("inc.") and not u_classes:
            continue
        if q == "arr.image_score" and not image_sharded:
            # the [P, 1] broadcast form: pod axis only, at the score width
            p_local = -(-max(1, n_pods) // max(1, pod_shards))
            total += (FIELD_DIMS[q][1] // 8) * p_local
            continue
        total += field_bytes(q, env, n_shards, pod_shards)
    return total
