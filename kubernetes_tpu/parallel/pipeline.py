"""Pipelined batch-scheduling cycles — host↔device overlap, the PP analog.

SURVEY.md §2.4: the reference has no PP; its counterpart here is overlapping
host work (snapshot delta-encode + H2D transfer of wave k+1, plus the
bind/commit fan-out of wave k−1) with device compute (the filter/score/commit
program still running on wave k), exactly how the reference's binding
goroutine overlaps the next pod's scheduling cycle (schedule_one.go:
bindingCycle runs async under the next schedulingCycle).

JAX dispatch is asynchronous: `schedule_batch` returns device futures
immediately, so the pipeline is expressed with ordinary control flow.  The
core is `PipelinedBatchLoop`, a depth-1 double-buffered submit/collect loop:

    loop = PipelinedBatchLoop()
    prev = loop.submit(wave_1)          # None (nothing in flight yet)
    prev = loop.submit(wave_2)          # wave_1's verdicts; wave_2 runs
    ...                                 # ... while the caller consumes them
    last = loop.drain()                 # final wave's verdicts

`submit(wave_i)` delta-encodes wave_i into a fresh `ClusterArrays` slot and
dispatches its device step WHILE step i−1 still runs, then blocks only on
step i−1's (tiny) choices vector.  The returned verdicts are committed by
the caller (or the loop's `commit` callback) while step i runs on device —
so the steady-state wall is the device step alone and the ~0.5 s of host
encode plus the commit fan-out disappear into device time.  Buffer donation
(ops/assign.py — schedule_batch_donated) rides the same structure: each
wave's input buffers are freshly transferred (true double buffering — two
generations in flight) and handed to XLA, so the [P, N]-scale intermediates
stop doubling peak device memory; the loop never re-reads a dispatched
wave's device arrays.

DEPENDENT wave streams (the scheduler's steady state, bench.py's warm
cycles) feed verdicts back with a one-wave lag: wave i+1's bound set
absorbs the placements of wave i−1 (the newest FETCHED wave), because wave
i is still deciding on device.  The sequential-commit semantics of a wave
live entirely inside the kernel, so the pipeline can never reorder commits
WITHIN a wave; across waves the dataflow (which bound set each wave saw) is
fixed by the lag, and `depth=0` runs the IDENTICAL dataflow serially —
decisions are bit-identical between the two (tests/test_pipeline_parity.py
asserts it; that equality is what proves overlap and donation change
nothing but wall time).

Every host phase is trace-attributed: `encode_overlap` / `commit_overlap`
(+ `decode_overlap`) spans tagged with whether a device step was in flight,
and `overlap_fraction()` reports the fraction of host pipeline work that
executed under a running device step — the "delta-encode fully hidden"
claim as a measured number (>0.8 steady-state, 0.0 at depth=0).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from ..api.delta import DeltaEncoder
from ..api.snapshot import Snapshot
from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
from ..ops.scores import ScoreConfig
from .. import chaos

Verdicts = Dict[str, Optional[str]]


class PipelinedBatchLoop:
    """Depth-1 double-buffered encode→dispatch→commit loop over waves.

    donate=None probes the backend (ops/assign.py — donation_supported);
    depth=0 is the serial oracle: the same dataflow with the previous step
    fetched BEFORE the next encode, so nothing ever overlaps.  `commit`
    (optional) is invoked with each wave's verdicts as soon as they are
    decoded — inside the overlap window of the step just dispatched.
    Gang waves are out of scope here (the gang fixpoint re-reads its input
    arrays, which donation forbids); the scheduler's gang path stays on
    its own cycle."""

    def __init__(
        self,
        encoder: Optional[DeltaEncoder] = None,
        base_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        hard_pod_affinity_weight: float = 1.0,
        donate: Optional[bool] = None,
        depth: int = 1,
        commit: Optional[Callable[[Verdicts], None]] = None,
        tracer=None,
        metrics=None,
        mesh=None,
        memwatch: Optional[bool] = None,
        wal: Optional[Callable[[Dict], None]] = None,
    ):
        from ..ops.assign import donation_supported

        self.enc = encoder or DeltaEncoder(
            hard_pod_affinity_weight=hard_pod_affinity_weight
        )
        # device mesh for the sharded routed step (parallel/sharded.py):
        # the resident encoder places node-axis buffers shard-wise
        # (NamedSharding) so warm-cycle deltas update shards in place and
        # the double-buffered loop overlaps encode/commit against a
        # SHARDED device step
        self.mesh = mesh
        if mesh is not None:
            self.enc.set_mesh(mesh)
        self.base_config = base_config
        self.donate = donation_supported() if donate is None else donate
        self.depth = depth
        self.commit = commit
        # stream wave-WAL hook (run_stream_restartable): called at every
        # dispatch with the in-flight wave's membership record, BEFORE the
        # kill.dispatch death point — durable-then-die ordering, so a
        # restarted driver always knows which wave was in flight
        self.wal = wal
        self.tracer = tracer
        self.metrics = metrics
        # wave-uniform SLI phase decomposition (scheduler/metrics.py —
        # SLI_PHASES): the loop has no queue, so queue_wait is observed as
        # 0, wave_wait is the encode/dispatch window, device_kernel the
        # dispatch -> fetch window and bind the decode + commit fan-out.
        # Cached handles, one bucket bump per phase per wave.
        self._phase_hists = None
        if metrics is not None:
            from ..scheduler.metrics import SLI_PHASES

            self._phase_hists = {
                ph: metrics.labeled_hist("pod_sli_phase_duration_seconds",
                                         phase=ph)
                for ph in SLI_PHASES
            }
        # incremental warm-cycle hoist (ops/incremental.py): equivalence-
        # class deduped scores resident on device across cycles, dirty-node
        # patched per warm delta.  Passed to the routed step as a separate,
        # NEVER-donated argument, so the donating waves' fresh transfers can
        # never alias the cache.  KTPU_INCREMENTAL=0 disables it per cycle.
        from ..ops.incremental import HoistCache

        self.hoist = HoistCache(mesh=mesh, tracer=tracer)
        # HBM telemetry ledger (scheduler/memwatch.py): cycle-boundary
        # live/census samples + leak sentinel; summary() stamps
        # hbm_peak_bytes / hbm_resident_bytes into bench artifacts and
        # the device_hbm_* gauge family onto /metrics.  KTPU_MEMWATCH=0
        # disables the plane; memwatch=False forces it off per loop (the
        # harness's untimed serial-reference pass — its ledger is never
        # read and its sampling would tax the serial baseline the
        # overlap_gain comparison measures against).
        from ..scheduler.memwatch import DeviceMemoryLedger, memwatch_enabled

        arm = memwatch_enabled() if memwatch is None else bool(memwatch)
        self.memwatch = (
            DeviceMemoryLedger(mesh=mesh, metrics=metrics) if arm else None
        )
        if self.memwatch is not None:
            # anchor the measured side NOW, before this loop places
            # anything: the first cycle sample lands after wave 1's
            # resident buffers are live, and a lazy baseline there would
            # fold the loop's own footprint into the zero point —
            # hbm_peak_bytes (regression-gated) would under-report to ~0
            # on live_arrays backends
            self.memwatch.baseline()
        # (choices, meta, inc_attrs, t_arrival, t_dispatch, snap) of the
        # dispatched wave; t_arrival (encode start) anchors the wave's
        # arrival -> bind SLI
        self._inflight: Optional[
            Tuple[object, object, dict, float, float, Snapshot]
        ] = None
        self._wave = 0
        # per-kind host seconds: [total, overlapped-with-an-in-flight-step]
        self.host_seconds: Dict[str, list] = {
            "encode": [0.0, 0.0],
            "commit": [0.0, 0.0],
            "decode": [0.0, 0.0],
        }
        self.stats: Dict[str, float] = {"waves": 0, "donated": 0, "recovered": 0}
        # probes onto the newest donated wave's aliasable input buffers
        # (i32[N,R] / i32[P] leaves — XLA aliases the outputs greedily onto
        # whichever matches first): one of them reading is_deleted() after
        # the step proves donation actually consumed the inputs (tests);
        # host code must never read their VALUES, which the safety test
        # asserts by construction (fresh transfers, empty reuse table)
        self.last_donated_probe = None

    def _kill(self, site: str) -> None:
        """An enumerated process-death point of the STREAMING loop (the
        chaos kill.submit/dispatch/collect/drain family): poke the
        injector; a kill latches the module-wide killed() flag before the
        ProcessKilled unwinds, so run()'s teardown drain and every caller
        finally do nothing a SIGKILL'd process couldn't.  Recovery is a
        FRESH loop re-encoding from host state, driven by
        run_stream_restartable over the stream wave WAL."""
        if chaos.enabled():
            chaos.poke(site, tracer=self.tracer, metrics=self.metrics)

    # -- accounting helpers --
    def _span(self, name: str, start: float, end: float, **attrs):
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.record_span(name, start=start, end=end, **attrs)

    @staticmethod
    def _step_running(probe) -> Optional[bool]:
        """Whether the in-flight step's result is still being computed;
        None when unobservable (no probe / non-jax array)."""
        if probe is None:
            return None
        try:
            return not probe.is_ready()
        except AttributeError:  # numpy choices (native path)
            return None

    def _overlap_credit(self, probe, running_at_start) -> float:
        """Fraction of a host phase credited as hidden under the in-flight
        step, bounded by what is OBSERVABLE: still running at phase end ->
        the whole phase was concurrent (1.0, exact); already finished at
        phase start -> nothing was (0.0, exact); finished mid-phase -> the
        true share is unknowable without a completion timestamp, so credit
        half (error bounded by dt/2).  Unobservable probes keep the old
        in-flight-at-start accounting."""
        if running_at_start is None:
            return 1.0 if probe is not None else 0.0
        if not running_at_start:
            return 0.0
        running_end = self._step_running(probe)
        return 1.0 if running_end else 0.5

    def _host_phase(self, kind: str, dt: float, credit: float) -> None:
        tot = self.host_seconds[kind]
        tot[0] += dt
        tot[1] += dt * credit

    def overlap_fraction(self) -> float:
        """Fraction of host pipeline work (encode + commit + decode) that
        ran while a dispatched device step was still running — credited
        conservatively per phase (see _overlap_credit)."""
        total = sum(v[0] for v in self.host_seconds.values())
        hidden = sum(v[1] for v in self.host_seconds.values())
        return (hidden / total) if total > 0 else 0.0

    # -- the pipeline --
    def _dispatch(self, snap: Snapshot):
        from ..ops.assign import schedule_batch_routed

        probe = self._inflight[0] if self._inflight is not None else None
        running0 = self._step_running(probe)
        if chaos.enabled():
            # slow-host stall: encode-path latency only — decisions and the
            # drain contract must hold regardless (chaos parity asserts it)
            chaos.poke("host.stall", tracer=self.tracer, metrics=self.metrics)
        t0 = time.perf_counter()
        donating = self.donate
        # host arrays first (infer_score_config inspects concrete numpy);
        # donation requires fresh per-wave transfers — a resident buffer
        # handed to a donating kernel would poison later reusing cycles
        arr, meta = self.enc.encode(snap)
        cfg = infer_score_config(arr, self.base_config)
        # resident class-hoist state from the HOST arrays (identity
        # fingerprints + node_used row diff), before device placement —
        # skipped when the wave routes the plain per-pod scan (which takes
        # no inc), so those cycles never pay the class hoist
        from ..ops.assign import inc_route_applies

        inc = (
            self.hoist.ensure(arr, meta, cfg)
            if inc_route_applies(arr, cfg) else None
        )
        arr, meta = self.enc.to_device(arr, meta, fresh=donating)
        if donating:
            self.last_donated_probe = (
                arr.node_alloc, arr.node_used, arr.pod_prio, arr.pod_nodename,
            )
            self.stats["donated"] += 1
        if self.wal is not None:
            # durable-then-die: the wave-WAL record lands before the
            # dispatch (and its kill point), so a death anywhere past here
            # leaves the restart driver evidence of what was in flight
            self.wal({
                "wave": self._wave,
                "pods": [p.name for p in snap.pending_pods],
            })
        choices = schedule_batch_routed(
            arr, cfg, donate=donating, mesh=self.mesh, inc=inc
        )[0]
        # kill.dispatch: process death with the step just dispatched and
        # any donated input buffers in flight — nothing fetched, nothing
        # committed; the whole wave replays on the restarted loop
        self._kill("kill.dispatch")
        t1 = time.perf_counter()
        credit = self._overlap_credit(probe, running0)
        self._host_phase("encode", t1 - t0, credit)
        self._span(
            "encode_overlap", t0, t1, component="pipeline",
            wave=self._wave, overlapped=credit > 0, overlap_credit=credit,
        )
        from ..scheduler.tracing import incremental_attrs

        return choices, meta, incremental_attrs(self.hoist), t0

    def _recover_wave(self, snap: Snapshot, err: BaseException, t0: float):
        """Serial-oracle replay of a wave that died mid-flight (device-step
        exception, poisoned verdicts): re-encode from host state — the
        NON-donated source of truth; any donated device buffers of the dead
        wave are unreadable by contract — and re-run the same kernel
        synchronously without donation.  The encoder is deterministic, so
        the replay's verdicts are bit-identical to what the fault-free wave
        would have produced (the chaos parity invariant)."""
        from ..ops.assign import schedule_batch_routed

        arr, meta = self.enc.encode(snap)
        cfg = infer_score_config(arr, self.base_config)
        # fresh=True: never touch (or populate) the resident-reuse table —
        # the replay must not alias buffers a donating successor wave hands
        # to XLA
        arr, meta = self.enc.to_device(arr, meta, fresh=True)
        ch = np.asarray(
            schedule_batch_routed(arr, cfg, donate=False, mesh=self.mesh)[0]
        )
        if chaos.poisoned_verdicts(ch, len(meta.node_names)):
            raise chaos.PoisonedWave(
                f"wave {self._wave - 1}: serial replay still poisoned"
            ) from err
        self.stats["recovered"] += 1
        chaos.record_recovery(
            "pipeline.step", "serial_replay", tracer=self.tracer,
            metrics=self.metrics, start=t0, wave=self._wave - 1,
            error=type(err).__name__,
        )
        return ch, meta

    def _collect(self) -> Optional[Verdicts]:
        if self._inflight is None:
            return None
        choices, meta, inc_attrs, t_arrival, t_dispatch, snap = self._inflight
        self._inflight = None
        t0 = time.perf_counter()
        try:
            if chaos.enabled():
                # kill.mid_step: process death while the dispatched step
                # (and its donated input buffers) is still in flight — a
                # BaseException, so the wave-recovery except below cannot
                # catch it and run()'s teardown drain stays off (a SIGKILL'd
                # process fetches nothing); only a fresh loop re-encoding
                # from host state recovers
                chaos.poke("kill.mid_step", tracer=self.tracer,
                           metrics=self.metrics)
            fault = (
                chaos.poke("pipeline.step", tracer=self.tracer,
                           metrics=self.metrics)
                if chaos.enabled() else None
            )
            ch = np.asarray(choices)  # the sync point: wait on the device step
            if fault is not None and fault.action == "nan":
                ch = chaos.poison(ch)
            if chaos.poisoned_verdicts(ch, len(meta.node_names)):
                raise chaos.PoisonedWave(f"wave {self._wave - 1}")
        except Exception as e:  # noqa: BLE001 — any mid-wave death recovers
            ch, meta = self._recover_wave(snap, e, t0)
        t1 = time.perf_counter()
        from ..scheduler.tracing import mesh_attrs

        self._span(
            "device.step", t_dispatch, t1, component="pipeline",
            wave=self._wave - 1, **mesh_attrs(self.mesh), **inc_attrs,
        )
        # decode happens after the blocking fetch, so it overlaps only the
        # NEXT step — dispatched before this collect when pipelining
        probe = self._pending_choices
        d_run0 = self._step_running(probe)
        verdicts = {
            meta.pod_names[k]: (
                meta.node_names[int(ch[k])] if int(ch[k]) >= 0 else None
            )
            for k in range(meta.n_pods)
        }
        t2 = time.perf_counter()
        credit = self._overlap_credit(probe, d_run0)
        self._host_phase("decode", t2 - t1, credit)
        self._span(
            "decode_overlap", t1, t2, component="pipeline",
            wave=self._wave - 1, overlapped=credit > 0, overlap_credit=credit,
        )
        # kill.collect: verdicts fetched and decoded but NOT committed —
        # the wave is gone from process memory, yet nothing published; the
        # restart driver must replay it (and exactly-once publication is
        # its commit ledger's business, not this loop's)
        self._kill("kill.collect")
        if self.commit is not None:
            c_run0 = self._step_running(probe)
            t3 = time.perf_counter()
            self.commit(verdicts)
            t4 = time.perf_counter()
            ccredit = self._overlap_credit(probe, c_run0)
            self._host_phase("commit", t4 - t3, ccredit)
            self._span(
                "commit_overlap", t3, t4, component="pipeline",
                wave=self._wave - 1, overlapped=ccredit > 0,
                overlap_credit=ccredit, pods=len(verdicts),
            )
        self.stats["waves"] += 1
        if self.memwatch is not None:
            # cycle-boundary memory sample: the resident census is the
            # encoder's device-buffer table (empty on donating loops —
            # fresh transfers retire with their wave) plus the hoist
            # cache's class matrices/usage rows/memos; metadata only,
            # never reads buffer values
            self.memwatch.cycle_sample(
                encoder=self.enc, hoist=self.hoist,
                label=f"wave{self._wave - 1}",
            )
        if self.metrics is not None:
            self.metrics.observe("pipeline_cycle_seconds", t2 - t_dispatch)
            # the wave's arrival -> bind SLI: one sample per BOUND pod at
            # the instant its verdict became consumable (commit-callback
            # end when the loop commits, decode end otherwise).  Identical
            # within a wave by construction (the loop has no queue), so a
            # single bucket bump covers the whole wave — O(1), not O(P).
            # Unscheduled pods (verdict None) never bound, so they
            # contribute no sample — matching the scheduler path, which
            # only observes at bind publication.
            n_bound = sum(1 for v in verdicts.values() if v is not None)
            if n_bound:
                # one t_end for the SLI sample AND the bind phase so the
                # wave's phases telescope exactly to its SLI
                t_end = time.perf_counter()
                self.metrics.hist(
                    "pod_scheduling_sli_duration_seconds"
                ).observe(t_end - t_arrival, n=n_bound)
                self._phase_hists["queue_wait"].observe(0.0, n=n_bound)
                self._phase_hists["wave_wait"].observe(
                    max(0.0, t_dispatch - t_arrival), n=n_bound)
                self._phase_hists["device_kernel"].observe(
                    max(0.0, t1 - t_dispatch), n=n_bound)
                self._phase_hists["bind"].observe(
                    max(0.0, t_end - t1), n=n_bound)
        return verdicts

    # the step dispatched after the one being collected (None outside that
    # window): the overlap probe for decode/commit phases
    _pending_choices = None

    def submit(self, snap: Snapshot) -> Optional[Verdicts]:
        """Encode + dispatch `snap`; return the PREVIOUS wave's verdicts
        (None on the first call).  depth=0 collects BEFORE encoding — the
        serial oracle with identical dataflow."""
        # kill.submit: process death with the wave accepted but nothing
        # dispatched — the cheapest kill point (no device state in flight)
        self._kill("kill.submit")
        if self.depth == 0:
            prev = self._collect()
            nxt = self._dispatch(snap)
            t_dispatch = time.perf_counter()
            # strict serial oracle: the step finishes INSIDE submit, so not
            # even caller-side work between submits overlaps the device —
            # the pre-pipeline wall, reproducible for --no-pipeline runs
            try:
                nxt[0].block_until_ready()
            except AttributeError:  # numpy choices (native path)
                pass
            self._inflight = (*nxt, t_dispatch, snap)
            self._wave += 1
            return prev
        nxt = self._dispatch(snap)
        t_dispatch = time.perf_counter()
        self._pending_choices = nxt[0]
        try:
            prev = self._collect()
        finally:
            # the in-flight wave is tracked even when the collect (commit
            # callback included) raises mid-wave: a later drain() still
            # flushes its verdicts instead of leaking the dispatched step
            # (and whatever capacity the caller's commit path reserved)
            self._pending_choices = None
            self._inflight = (*nxt, t_dispatch, snap)
            self._wave += 1
        return prev

    def drain(self) -> Optional[Verdicts]:
        """Fetch the final in-flight wave's verdicts (None if none)."""
        # kill.drain: process death at the stream's end with the final wave
        # still in flight — the classic lost-tail bug this site exists to
        # prove impossible under the restart driver
        self._kill("kill.drain")
        out = self._collect()
        if self.metrics is not None:
            self.metrics.observe(
                "pipeline_overlap_fraction", self.overlap_fraction()
            )
        return out

    def run(self, snapshots: Iterable[Snapshot]) -> Iterator[Verdicts]:
        """Yield one verdict dict per snapshot, in order — the streaming
        form for INDEPENDENT waves (replayed scheduler_perf streams,
        sidecar request replays).  Wave k+1's encode and wave k−1's commit
        overlap wave k's device step."""
        try:
            for snap in snapshots:
                v = self.submit(snap)
                if v is not None:
                    yield v
            v = self.drain()
            if v is not None:
                yield v
        finally:
            if self._inflight is not None and not chaos.killed():
                # abandoned mid-stream (caller exception / generator close):
                # best-effort drain so the final wave's commit callback runs
                # and nothing stays reserved-but-unpublished.  NOT on a kill:
                # a SIGKILL'd process gets no teardown — the in-flight wave
                # dies with it and a restarted loop re-encodes from host
                # state (the crash-restart protocol's business).
                try:
                    self.drain()
                    chaos.record_recovery(
                        "pipeline.step", "abort_drain", tracer=self.tracer,
                        metrics=self.metrics,
                    )
                except Exception:  # noqa: BLE001 — teardown must not mask
                    pass


class PipelinedRunner:
    """Back-compat façade over PipelinedBatchLoop for independent snapshot
    streams (the original double-buffered runner's interface).

    >>> runner = PipelinedRunner()
    >>> for verdicts in runner.run(snapshots):
    ...     apply(verdicts)  # {pod_name: node_name | None}
    """

    def __init__(
        self,
        base_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        hard_pod_affinity_weight: float = 1.0,
        donate: Optional[bool] = None,
        tracer=None,
        metrics=None,
        mesh=None,
    ):
        self.base_config = base_config
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.donate = donate
        self.tracer = tracer
        self.metrics = metrics
        self.mesh = mesh
        self.last_loop: Optional[PipelinedBatchLoop] = None

    def _loop(self, depth: int) -> PipelinedBatchLoop:
        loop = PipelinedBatchLoop(
            base_config=self.base_config,
            hard_pod_affinity_weight=self.hard_pod_affinity_weight,
            donate=self.donate,
            depth=depth,
            tracer=self.tracer,
            metrics=self.metrics,
            mesh=self.mesh,
        )
        self.last_loop = loop
        return loop

    def run(self, snapshots: Iterable[Snapshot]) -> Iterator[Verdicts]:
        return self._loop(depth=1).run(snapshots)


def run_serial(
    snapshots: Iterable[Snapshot],
    base_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
    hard_pod_affinity_weight: float = 1.0,
    donate: Optional[bool] = None,
    mesh=None,
    tracer=None,
    metrics=None,
) -> Iterator[Verdicts]:
    """The unpipelined oracle for the same stream: encode -> run -> block,
    one snapshot at a time (identical dataflow at depth=0 — used by tests
    and the overlap benchmark; the harness's --no-pipeline escape hatch).
    tracer/metrics thread through so a --no-pipeline run can still capture
    spans for attribution and the SLI series (decisions are unaffected)."""
    loop = PipelinedBatchLoop(
        base_config=base_config,
        hard_pod_affinity_weight=hard_pod_affinity_weight,
        donate=donate,
        depth=0,
        mesh=mesh,
        tracer=tracer,
        metrics=metrics,
    )
    return loop.run(snapshots)


# --- the streaming crash-restart driver (chaos kill.* over wave streams) ---
STREAM_WAL = "stream_wal"


def load_stream_wal(checkpoint) -> Dict[int, str]:
    """The committed-wave ledger from the stream wave WAL: {wave index ->
    verdict crc}.  Empty when unarmed, absent, or corrupt (load() already
    quarantined + counted corruption; the crash-only floor is a full
    replay, never a wrong one)."""
    if checkpoint is None:
        return {}
    doc = checkpoint.load(STREAM_WAL)
    if not doc:
        return {}
    return {int(k): str(v) for k, v in (doc.get("committed") or {}).items()}


def run_stream_restartable(
    waves,
    make_loop: Callable[..., PipelinedBatchLoop],
    checkpoint=None,
    metrics=None,
    max_restarts: int = 16,
) -> Tuple[list, int]:
    """Drive a stream of independent waves to completion across kill.*
    chaos: every ProcessKilled is answered by a FRESH loop (the dead one's
    device state is unreadable by contract) replaying exactly the waves the
    commit ledger has not recorded — the streaming analog of
    scheduler.run_restartable.

    Exactly-once publication: each wave's verdicts land in the results
    ledger (the model of the apiserver side, which survives the scheduler's
    death) atomically with a crc append to the stream wave WAL, and commits
    arrive in submit order, so a kill anywhere leaves a committed prefix +
    an uncommitted suffix — the next incarnation replays only the suffix.
    The deterministic encoder makes any accidental replay of a committed
    wave produce the identical verdicts; the crc equality check turns a
    divergence (a real double-publication hazard) into a hard error instead
    of a silent overwrite.

    make_loop(commit, wal) -> PipelinedBatchLoop: the caller configures
    depth/donation/tracing and MUST thread both callbacks through.
    checkpoint (CheckpointManager or None) arms the durable ledger; without
    it the ledger is process-local (still exactly-once within this driver).
    Blackouts (kill -> replacement loop ready) observe into
    `failover_duration_seconds` and restarts into `scheduler_restarts_total`
    — the same HA series the snapshot path stamps (bench ha_fields).
    Returns (verdicts per wave, in order; #restarts)."""
    from ..scheduler.flightrecorder import fingerprint

    waves = list(waves)
    committed: Dict[int, str] = load_stream_wal(checkpoint)
    results: Dict[int, Verdicts] = {}
    inflight: Dict[str, object] = {}
    restarts = 0
    t_dead: Optional[float] = None

    def _persist() -> None:
        if checkpoint is not None:
            checkpoint.save(STREAM_WAL, {
                "committed": {str(k): v for k, v in committed.items()},
                "inflight": dict(inflight),
            })

    while True:
        todo = [k for k in range(len(waves)) if k not in results]
        if not todo:
            return [results[k] for k in range(len(waves))], restarts
        order = list(todo)  # commits arrive in submit order

        def commit(verdicts: Verdicts, _order=order) -> None:
            k = _order.pop(0)
            crc = fingerprint({u: verdicts[u] for u in sorted(verdicts)})
            prior = committed.get(k)
            if prior is not None and prior != crc:
                raise RuntimeError(
                    f"stream wave {k} replay diverged from its committed "
                    f"record: {prior} != {crc} — refusing to double-publish"
                )
            results[k] = verdicts
            committed[k] = crc
            _persist()

        def wal(rec: Dict, _order=order) -> None:
            inflight.clear()
            inflight.update(rec)
            # the global wave index the next commit will land on (the
            # loop's own `wave` field is its local ordinal)
            inflight["stream_wave"] = _order[0] if _order else -1
            _persist()

        loop = make_loop(commit, wal if checkpoint is not None else None)
        if t_dead is not None:
            # the replacement loop is ready: everything since the kill —
            # revive, rebuild, recompile-if-cold — is the stream's takeover
            # blackout, priced on the same series as a leader failover
            blackout = time.perf_counter() - t_dead
            t_dead = None
            if metrics is not None:
                metrics.observe("failover_duration_seconds", blackout)
        try:
            for k in todo:
                loop.submit(waves[k])
            loop.drain()
        except chaos.ProcessKilled as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            t_dead = time.perf_counter()
            chaos.revive()  # the latch belongs to the dead loop
            if metrics is not None:
                metrics.inc("scheduler_restarts_total")
            chaos.record_recovery(
                e.fault.site, "stream_restart", tracer=loop.tracer,
                metrics=metrics, committed_waves=len(results),
            )
