"""Host↔device pipelining — the pipeline-parallelism analog.

SURVEY.md §2.4: the reference has no PP; its counterpart here is overlapping
host work (snapshot encode + H2D transfer of batch k+1) with device compute
(the filter/score/commit program still running on batch k), exactly how the
reference's binding goroutine overlaps the next pod's scheduling cycle
(schedule_one.go: bindingCycle runs async under the next schedulingCycle).

JAX dispatch is asynchronous: `schedule_batch` returns device futures
immediately, so the pipeline is expressed with ordinary control flow — encode
batch k+1 while batch k's program runs, then block on k's (tiny) choices
vector.  Two device programs are never enqueued back-to-back for the same
buffer, so this is classic double-buffering with depth 1.

Use `PipelinedRunner` for streams of INDEPENDENT snapshots (separate virtual
clusters, sidecar request streams, replayed scheduler_perf waves).  When wave
k+1's pending set depends on wave k's placements (the sequential-commit
semantics across waves), the dependency forbids overlap — the scheduler's
in-wave `lax.scan` already covers that case on-device.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional, Tuple

import jax
import numpy as np

from ..api.snapshot import Snapshot, encode_snapshot
from ..ops import DEFAULT_SCORE_CONFIG
from ..ops.scores import ScoreConfig, infer_score_config


def _decode(choices, meta) -> Dict[str, Optional[str]]:
    ch = np.asarray(choices)  # blocks until the device program finishes
    return {
        meta.pod_names[k]: (
            meta.node_names[int(ch[k])] if int(ch[k]) >= 0 else None
        )
        for k in range(meta.n_pods)
    }


class PipelinedRunner:
    """Double-buffered snapshot stream executor.

    >>> runner = PipelinedRunner()
    >>> for verdicts in runner.run(snapshots):
    ...     apply(verdicts)  # {pod_name: node_name | None}
    """

    def __init__(
        self,
        base_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
        hard_pod_affinity_weight: float = 1.0,
    ):
        self.base_config = base_config
        self.hard_pod_affinity_weight = hard_pod_affinity_weight

    def _dispatch(self, snap: Snapshot) -> Tuple[jax.Array, object]:
        from ..ops import schedule_batch

        arr, meta = encode_snapshot(
            snap, hard_pod_affinity_weight=self.hard_pod_affinity_weight
        )
        cfg = infer_score_config(arr, self.base_config)
        arr = jax.device_put(arr)  # async H2D
        choices, _used = schedule_batch(arr, cfg)  # async dispatch
        return choices, meta

    def run(self, snapshots: Iterable[Snapshot]) -> Iterator[Dict[str, Optional[str]]]:
        """Yields one verdict dict per snapshot, in order.  Encode/transfer of
        snapshot k+1 overlaps the device program of snapshot k."""
        prev: Optional[Tuple[jax.Array, object]] = None
        for snap in snapshots:
            nxt = self._dispatch(snap)  # host encodes while prev computes
            if prev is not None:
                yield _decode(*prev)
            prev = nxt
        if prev is not None:
            yield _decode(*prev)


def run_serial(
    snapshots: Iterable[Snapshot],
    base_config: ScoreConfig = DEFAULT_SCORE_CONFIG,
    hard_pod_affinity_weight: float = 1.0,
) -> Iterator[Dict[str, Optional[str]]]:
    """The unpipelined oracle for the same stream: encode -> run -> block,
    one snapshot at a time (used by tests and the overlap benchmark)."""
    runner = PipelinedRunner(base_config, hard_pod_affinity_weight)
    for snap in snapshots:
        yield _decode(*runner._dispatch(snap))
