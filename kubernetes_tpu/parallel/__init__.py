from .mesh import make_mesh, mesh_from_env, pad_nodes, shard_hbm_estimate  # noqa: F401
from .pipeline import PipelinedBatchLoop, PipelinedRunner, run_serial  # noqa: F401
from .sharded import (  # noqa: F401
    field_shardings,
    sharded_schedule_batch,
    sharded_schedule_batch_routed,
)
