from .mesh import make_mesh  # noqa: F401
from .pipeline import PipelinedBatchLoop, PipelinedRunner, run_serial  # noqa: F401
from .sharded import sharded_schedule_batch  # noqa: F401
