from .mesh import (  # noqa: F401
    make_mesh,
    mesh_from_env,
    pad_nodes,
    shard_comm_estimate,
    shard_hbm_estimate,
)
from .partition_rules import (  # noqa: F401
    PARTITION_RULES,
    sharding_for,
    spec_for,
)
from .pipeline import PipelinedBatchLoop, PipelinedRunner, run_serial  # noqa: F401
from .sharded import (  # noqa: F401
    field_shardings,
    sharded_schedule_batch,
    sharded_schedule_batch_routed,
)
