from .mesh import make_mesh  # noqa: F401
from .sharded import sharded_schedule_batch  # noqa: F401
