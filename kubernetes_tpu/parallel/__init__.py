from .mesh import make_mesh  # noqa: F401
from .pipeline import PipelinedRunner, run_serial  # noqa: F401
from .sharded import sharded_schedule_batch  # noqa: F401
