"""Node-axis-sharded scheduling step (shard_map over the device mesh).

The reference fans Filter/Score over nodes with 16 goroutines and reduces
through channels (parallelize/parallelism.go); here the node axis of every
[*, N] array is sharded across chips and the reduce is XLA collectives over
ICI.  The step logic itself lives in ops/assign.py — schedule_scan — shared
verbatim with the single-device path and parameterized on the mesh axis:

  - per-pod NormalizeScore max / spread minMatch  -> pmax / pmin
  - selectHost global argmax                      -> pmax + pmin over the
    global node index attaining the max (deterministic lowest-index tie-break,
    bit-exact vs single-device)
  - committed pod's domain column                 -> owner-shard psum broadcast

Pairwise counts state is replicated (every shard applies identical scatter
updates); per-node score math stays local to the owning shard, so sharded and
unsharded execution produce identical float32 values.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
from jax.sharding import Mesh

from ..api.snapshot import ClusterArrays
from ..ops.assign import pod_unshard, schedule_scan
from ..ops.scores import ScoreConfig
from .mesh import NODE_AXIS, PODS_AXIS, mesh_axis_shards, shard_map
from .partition_rules import (
    clusterarrays_specs,
    incstate_specs,
    spec_for,
    strip_spec,
)


def _node_sharding_specs(
    image_sharded: bool, pod_sharded: bool = False
) -> ClusterArrays:
    """PartitionSpec pytree for every ClusterArrays field, resolved through
    the declarative rule table (parallel/partition_rules.py).  The former
    hand-written 40-line spec literal is gone: adding a field is one table
    row, and the ktpu-verify shard pass (KTPU014..018) proves the compiled
    placements obey it.  ``pod_sharded`` keys the 2-D mesh's pod rows."""
    return clusterarrays_specs(image_sharded, pod_sharded=pod_sharded)


def _out_spec(qualname: str, mesh: Mesh):
    """Table out-spec, stripped to the axes this mesh carries."""
    return strip_spec(spec_for(qualname), tuple(mesh.axis_names))


def sharded_schedule_batch(
    arr: ClusterArrays, cfg: ScoreConfig, mesh: Mesh
) -> Tuple[jax.Array, jax.Array]:
    """Same contract as ops.assign.schedule_batch, sharded over `mesh` —
    node axis on a 1-D mesh, pods x nodes on a 2-D one (the pod-sharded
    inputs stitch back to full pod extent at kernel entry — pod_unshard).

    Returns (assignment i32[P], node_used i32[N, R] — node-sharded).
    """
    pod_shards, n_shards = mesh_axis_shards(mesh)
    if arr.N % n_shards:
        raise ValueError(f"node axis {arr.N} not divisible by mesh size {n_shards}")
    if arr.P % pod_shards:
        raise ValueError(
            f"pod axis {arr.P} not divisible by pod shards {pod_shards}"
        )
    img = arr.image_score.shape[1] == arr.N
    pod_sharded = pod_shards > 1

    def body(a):
        if pod_sharded:
            a, _ = pod_unshard(a, axis_name=PODS_AXIS)
        return schedule_scan(
            a, cfg=cfg, axis_name=NODE_AXIS, image_sharded=img
        )

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(_node_sharding_specs(img, pod_sharded),),
        out_specs=(
            _out_spec("out.assignment", mesh),
            _out_spec("out.node_used_scan", mesh),
        ),
        check_rep=False,
    )
    return jax.jit(fn)(arr)


def field_shardings(mesh: Mesh, image_sharded: bool):
    """field name -> NamedSharding matching the sharded kernels' in_specs,
    so a ClusterArrays placed with these (api/delta.py — DeltaEncoder with a
    mesh) enters the sharded step with zero resharding: resident node-axis
    buffers live shard-wise on their owning devices and warm-cycle deltas
    re-place only the changed fields' shards — no per-cycle gather/scatter.
    Memoized per (mesh, image_sharded): the dict is rebuilt-free on the
    warm-cycle encode hot path."""
    return _field_shardings_cached(mesh, image_sharded)


@lru_cache(maxsize=None)
def _field_shardings_cached(mesh: Mesh, image_sharded: bool):
    from .partition_rules import clusterarrays_shardings

    return clusterarrays_shardings(mesh, image_sharded)


# jit cache for the sharded routed kernels, keyed on everything trace-
# relevant.  cfg is a frozen (hashable) dataclass; Mesh is hashable; the
# shapes key themselves through jit as usual.  inc_sig = None (dense) or
# the tuple of which optional IncState fields are populated — it fixes the
# second argument's pytree/spec structure (ops/incremental.py).
@lru_cache(maxsize=None)
def _sharded_routed_fn(
    mesh: Mesh, image_sharded: bool, kind: str, cfg: ScoreConfig,
    with_ordinals: bool, donate: bool, inc_sig=None,
):
    import jax.numpy as jnp

    from ..ops import assign as A

    pod_shards, n_shards = mesh_axis_shards(mesh)
    pod_sharded = pod_shards > 1
    if kind == "scan":
        def body(a):
            if pod_sharded:
                a, _ = A.pod_unshard(a, axis_name=PODS_AXIS)
            c, u = A.schedule_scan(
                a, cfg=cfg, axis_name=NODE_AXIS, image_sharded=image_sharded
            )
            if with_ordinals:
                return c, u, jnp.arange(a.P, dtype=jnp.int32), jnp.int32(a.P)
            return c, u

        # the scan's used stays node-sharded (table row out.node_used_scan)
        used_spec = _out_spec("out.node_used_scan", mesh)
    else:
        kernel = (
            A.schedule_scan_chunked if kind == "chunked"
            else A.schedule_scan_rounds
        )
        if inc_sig is not None:
            def body(a, inc):
                if pod_sharded:
                    a, inc = A.pod_unshard(a, inc, axis_name=PODS_AXIS)
                return kernel(
                    a, cfg=cfg, with_ordinals=with_ordinals,
                    axis_name=NODE_AXIS, axis_size=n_shards,
                    image_sharded=image_sharded, inc=inc,
                )
        else:
            def body(a):
                if pod_sharded:
                    a, _ = A.pod_unshard(a, axis_name=PODS_AXIS)
                return kernel(
                    a, cfg=cfg, with_ordinals=with_ordinals,
                    axis_name=NODE_AXIS, axis_size=n_shards,
                    image_sharded=image_sharded,
                )

        # chunked/rounds carry usage replicated (table row out.node_used)
        used_spec = _out_spec("out.node_used", mesh)
    in_specs = (_node_sharding_specs(image_sharded, pod_sharded),)
    if kind != "scan" and inc_sig is not None:
        # the resident IncState's populated structure, from the rule table
        in_specs = in_specs + (incstate_specs(*inc_sig, pod_sharded=pod_sharded),)
    out_specs = (_out_spec("out.assignment", mesh), used_spec) + (
        (_out_spec("out.ordinals", mesh), _out_spec("out.n_commits", mesh))
        if with_ordinals else ()
    )
    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )
    if donate:
        # only the per-wave ClusterArrays donates — the IncState argument is
        # the RESIDENT cache and must never be consumed (PARITY.md
        # donation-aliasing rule)
        return jax.jit(fn, donate_argnums=(0,))
    return jax.jit(fn)


def sharded_schedule_batch_routed(
    arr: ClusterArrays, cfg: ScoreConfig, mesh: Mesh, donate: bool = False,
    with_ordinals: bool = False, inc=None,
):
    """The PRODUCTION routed step — chunked / rounds / per-pod scan, the same
    trace-time routing as ops.assign.schedule_batch_routed — node-axis
    sharded over `mesh`, decisions bit-identical to the single-device route
    (tests/test_sharded_routed.py).  The class-batched commit-wave stage
    inside the chunked route runs AFTER the node-axis gather, on replicated
    values only, so arming it adds zero collectives — the per-shard
    collective sequence is KTPU009-identical with waves on or off
    (tests/test_class_waves.py — mesh8 parity).  Node counts not divisible by the mesh
    are padded with permanently invalid nodes (parallel/mesh.py —
    pad_nodes); the returned node_used covers the padded axis (slice to the
    caller's N — padded rows are always zero).

    donate=True hands the (freshly transferred, per-wave) input shards to
    XLA, same contract as schedule_batch_donated: per-shard [P, Nl]-scale
    intermediates stop doubling peak HBM.

    On a 2-D pods x nodes mesh the pod axis pads too (pad_pods — BEFORE the
    route choice and the inc_applicable gate, so both see the padded P the
    kernel will run at), and per-pod outputs slice back to the caller's P
    (padded pods are invalid: assignment -1, zero usage)."""
    from ..ops import assign as A
    from .mesh import pad_nodes, pad_pods

    pod_shards, n_shards = mesh_axis_shards(mesh)
    arr, _n_orig = pad_nodes(arr, n_shards)
    arr, p_orig = pad_pods(arr, pod_shards)
    if A._chunk_routed(arr, cfg):
        kind = "chunked"
    elif A._rounds_routed(arr, cfg):
        kind = "rounds"
    else:
        kind = "scan"
    # the incremental class state applies only to the chunked/rounds routes
    # and must match the PADDED node and pod axes (the HoistCache pads with
    # the same parallel/mesh.py rule set)
    inc = A.inc_applicable(arr, cfg, inc) if kind != "scan" else None
    inc_sig = None
    if inc is not None:
        inc_sig = (
            inc.elig_u is not None, inc.traw_u is not None,
            inc.naraw_u is not None, inc.img_u is not None,
        )
    fn = _sharded_routed_fn(
        mesh, arr.image_score.shape[1] == arr.N, kind, cfg,
        with_ordinals, donate, inc_sig,
    )
    args = (arr,) if inc is None else (arr, inc)
    if donate:
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            out = fn(*args)
    else:
        out = fn(*args)
    if p_orig != arr.P:
        # slice the per-pod outputs back to the caller's pod extent
        # (assignment [+ ordinals]); node_used / n_commits are unaffected
        # by invalid padded pods
        out = (out[0][:p_orig], out[1]) + tuple(
            o[:p_orig] if getattr(o, "ndim", 0) == 1 else o
            for o in out[2:]
        )
    return out
