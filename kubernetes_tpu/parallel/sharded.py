"""Node-axis-sharded scheduling step (shard_map over the device mesh).

The reference fans Filter/Score over nodes with 16 goroutines and reduces
through channels (parallelize/parallelism.go); here the node axis of every
[*, N] array is sharded across chips and the reduce is XLA collectives over
ICI.  The step logic itself lives in ops/assign.py — schedule_scan — shared
verbatim with the single-device path and parameterized on the mesh axis:

  - per-pod NormalizeScore max / spread minMatch  -> pmax / pmin
  - selectHost global argmax                      -> pmax + pmin over the
    global node index attaining the max (deterministic lowest-index tie-break,
    bit-exact vs single-device)
  - committed pod's domain column                 -> owner-shard psum broadcast

Pairwise counts state is replicated (every shard applies identical scatter
updates); per-node score math stays local to the owning shard, so sharded and
unsharded execution produce identical float32 values.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..api.snapshot import ClusterArrays
from ..ops.assign import schedule_scan
from ..ops.scores import ScoreConfig
from .mesh import NODE_AXIS


def _node_sharding_specs(image_sharded: bool) -> ClusterArrays:
    """PartitionSpec pytree: [N, ...] / [*, N] arrays sharded on the node axis,
    pod-axis and vocab-table arrays replicated."""
    return ClusterArrays(
        node_valid=P(NODE_AXIS),
        node_alloc=P(NODE_AXIS, None),
        node_used=P(NODE_AXIS, None),
        node_unsched=P(NODE_AXIS),
        node_labels=P(NODE_AXIS, None),
        node_taint_ns=P(NODE_AXIS, None),
        node_taint_pref=P(NODE_AXIS, None),
        pod_valid=P(),
        pod_req=P(None, None),
        pod_prio=P(),
        pod_tol_ns=P(None, None),
        pod_tol_pref=P(None, None),
        pod_nodename=P(),
        pod_terms=P(None, None),
        pod_has_sel=P(),
        sel_mask=P(None, None, None),
        sel_kind=P(None, None),
        pod_pref_terms=P(None, None),
        pod_pref_weights=P(None, None),
        node_dom=P(None, NODE_AXIS),
        term_key=P(),
        m_pend=P(None, None),
        pod_match_terms=P(None, None),
        pod_match_vals=P(None, None),
        pod_aff_self=P(None, None),
        term_counts0=P(None, None),
        anti_counts0=P(None, None),
        pod_aff_terms=P(None, None),
        pod_anti_terms=P(None, None),
        pod_pref_aff_terms=P(None, None),
        pod_pref_aff_w=P(None, None),
        pref_own0=P(None, None),
        pod_spread_terms=P(None, None),
        pod_spread_maxskew=P(None, None),
        pod_spread_hard=P(None, None),
        pod_ports=P(None, None),
        node_ports0=P(NODE_AXIS, None),
        pod_group=P(),
        group_min=P(),
        image_score=P(None, NODE_AXIS) if image_sharded else P(None, None),
    )


def sharded_schedule_batch(
    arr: ClusterArrays, cfg: ScoreConfig, mesh: Mesh
) -> Tuple[jax.Array, jax.Array]:
    """Same contract as ops.assign.schedule_batch, node axis sharded over `mesh`.

    Returns (assignment i32[P], node_used i32[N, R] — node-sharded).
    """
    n_shards = mesh.shape[NODE_AXIS]
    if arr.N % n_shards:
        raise ValueError(f"node axis {arr.N} not divisible by mesh size {n_shards}")
    fn = jax.shard_map(
        partial(schedule_scan, cfg=cfg, axis_name=NODE_AXIS),
        mesh=mesh,
        in_specs=(_node_sharding_specs(arr.image_score.shape[1] == arr.N),),
        out_specs=(P(), P(NODE_AXIS, None)),
    )
    return jax.jit(fn)(arr)
