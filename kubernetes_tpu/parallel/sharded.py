"""Node-axis-sharded scheduling step (shard_map over the device mesh).

The reference fans Filter/Score over nodes with 16 goroutines and reduces
through channels; here the node axis of every [*, N] array is sharded across
chips and the reduce is XLA collectives over ICI:

  - per-pod NormalizeScore max       -> lax.pmax
  - feasibility "any node fits"      -> lax.pmax over local any
  - selectHost global argmax         -> pmax of local max score, then pmin of
    the global node index attaining it (preserves the deterministic
    lowest-index tie-break bit-exactly vs the single-device path)

Per-node score math stays local to the owning shard, so sharded and unsharded
execution produce identical float32 values — no cross-shard accumulation ever
touches a score.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..api.snapshot import ClusterArrays
from ..ops import filters
from ..ops.scores import (
    MAX_NODE_SCORE,
    ScoreConfig,
    balanced_allocation,
    least_allocated,
    taint_prefer_counts,
)
from .mesh import NODE_AXIS

_INT_MAX = jnp.iinfo(jnp.int32).max


def _node_sharding_specs(arr: ClusterArrays) -> ClusterArrays:
    """PartitionSpec pytree: [N, ...] arrays sharded on the node axis, pod-axis
    and selector-table arrays replicated."""
    return ClusterArrays(
        node_valid=P(NODE_AXIS),
        node_alloc=P(NODE_AXIS, None),
        node_used=P(NODE_AXIS, None),
        node_unsched=P(NODE_AXIS),
        node_labels=P(NODE_AXIS, None),
        node_taint_ns=P(NODE_AXIS, None),
        node_taint_pref=P(NODE_AXIS, None),
        pod_valid=P(),
        pod_req=P(None, None),
        pod_prio=P(),
        pod_tol_ns=P(None, None),
        pod_tol_pref=P(None, None),
        pod_nodename=P(),
        pod_terms=P(None, None),
        pod_has_sel=P(),
        sel_mask=P(None, None, None),
        sel_kind=P(None, None),
    )


def sharded_schedule_batch(
    arr: ClusterArrays, cfg: ScoreConfig, mesh: Mesh
) -> Tuple[jax.Array, jax.Array]:
    """Same contract as ops.assign.schedule_batch, node axis sharded over `mesh`.

    Returns (assignment i32[P], node_used i32[N, R] — sharded).
    """
    n_shards = mesh.shape[NODE_AXIS]
    if arr.N % n_shards:
        raise ValueError(f"node axis {arr.N} not divisible by mesh size {n_shards}")
    local_n = arr.N // n_shards

    def step_fn(a: ClusterArrays):
        # Everything in here sees the LOCAL node shard [N/d, ...].
        shard = lax.axis_index(NODE_AXIS)
        base = shard * local_n
        my_nodes = base + jnp.arange(local_n, dtype=jnp.int32)

        # nodename pinning compares against global node indices
        pin = a.pod_nodename[:, None]
        nodename_ok = jnp.where(pin == -1, True, pin == my_nodes[None, :])
        sf = (
            a.node_valid[None, :]
            & a.pod_valid[:, None]
            & filters.taints_ok(a)
            & filters.node_selection_ok(a)
            & nodename_ok
        )
        pref = taint_prefer_counts(a)

        def step(used, xs):
            req, feas_row, pref_row, valid = xs
            feasible = feas_row & filters.fit_ok(req, used, a.node_alloc)
            requested = used + req[None, :]
            max_pref = lax.pmax(jnp.max(jnp.where(feasible, pref_row, 0.0)), NODE_AXIS)
            taint_sc = jnp.where(
                max_pref > 0,
                MAX_NODE_SCORE - MAX_NODE_SCORE * pref_row / max_pref,
                MAX_NODE_SCORE,
            )
            total = (
                cfg.fit_weight * least_allocated(requested, a.node_alloc, cfg.score_resources)
                + cfg.balanced_weight
                * balanced_allocation(requested, a.node_alloc, cfg.score_resources)
                + cfg.taint_weight * taint_sc
            )
            total = jnp.where(feasible, total, -jnp.inf)
            best = lax.pmax(jnp.max(total), NODE_AXIS)
            schedulable = (best > -jnp.inf) & valid
            # lowest global index attaining the max, across shards
            local_idx = jnp.where(
                (total == best) & feasible, my_nodes, _INT_MAX
            ).min()
            choice = jnp.where(
                schedulable, lax.pmin(local_idx, NODE_AXIS).astype(jnp.int32), -1
            )
            placed = (my_nodes == choice)[:, None]
            return used + placed.astype(used.dtype) * req[None, :], choice

        used_final, choices = lax.scan(
            step, a.node_used, (a.pod_req, sf, pref, a.pod_valid)
        )
        return choices, used_final

    specs = _node_sharding_specs(arr)
    fn = jax.shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(P(), P(NODE_AXIS, None)),
    )
    return jax.jit(fn)(arr)
