"""Interning: strings -> dense integer ids, selectors -> literal-set primitives.

The TPU kernels never see strings.  At snapshot-encoding time every label
``key=value`` pair present on a node (or pod) is interned to a *literal id*; node
label sets become 0/1 rows of a ``[N, L]`` matrix, and every selector operator is
lowered to one of two primitives over literal sets:

  AnyOf(S):  satisfied iff the entity carries >= 1 literal in S
  NoneOf(S): satisfied iff the entity carries 0 literals in S

which the kernels evaluate with a single counting matmul (``mask @ labels.T``) —
the MXU-friendly reformulation of the reference's per-node string matching
(pkg/scheduler/framework/plugins/nodeaffinity/node_affinity.go,
component-helpers — nodeaffinity.RequiredNodeAffinity.Match).

Lowering table (exact, given the vocab contains every literal present in the
cluster snapshot — so "key present" is decidable from literals alone):

  In(k, vs)        -> AnyOf({k=v for v in vs})
  NotIn(k, vs)     -> NoneOf({k=v for v in vs})      # absent key matches, per reference
  Exists(k)        -> AnyOf(all literals with key k)
  DoesNotExist(k)  -> NoneOf(all literals with key k)
  Gt(k, x)/Lt(k,x) -> AnyOf({k=v : int(v) >< x})     # expanded against the vocab

A conjunction of lowered expressions is a *term*; pods referencing structurally
identical terms share one interned term id, so the device-side term-match matrix
is [S_terms, N] regardless of pod count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from . import types as t

# Expression kinds in the packed selector matrix (ops/filters.py consumes these).
KIND_PAD = 0  # padding row: always satisfied
KIND_ANY = 1  # AnyOf: count > 0
KIND_NONE = 2  # NoneOf: count == 0
KIND_FALSE = 3  # constant-false (e.g. In over values absent from the cluster)


class Interner:
    """Assigns dense ids to hashable items in first-seen order."""

    def __init__(self) -> None:
        self._ids: Dict[object, int] = {}
        self._items: List[object] = []

    def intern(self, item) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._items)
            self._ids[item] = i
            self._items.append(item)
        return i

    def get(self, item) -> Optional[int]:
        return self._ids.get(item)

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item) -> bool:
        return item in self._ids

    @property
    def items(self) -> List[object]:
        return self._items


class LabelVocab:
    """Literal (key=value) and key interning over one snapshot's label universe."""

    def __init__(self) -> None:
        self.literals = Interner()  # (key, value) -> lit id
        self.by_key: Dict[str, List[int]] = {}  # key -> lit ids carrying that key

    def add_labels(self, labels: Dict[str, str]) -> List[int]:
        out = []
        for k, v in labels.items():
            fresh = (k, v) not in self.literals
            lid = self.literals.intern((k, v))
            if fresh:
                self.by_key.setdefault(k, []).append(lid)
            out.append(lid)
        return out

    def lit(self, key: str, value: str) -> Optional[int]:
        return self.literals.get((key, value))

    def key_lits(self, key: str) -> List[int]:
        return self.by_key.get(key, [])

    def __len__(self) -> int:
        return len(self.literals)


# A lowered expression: (kind, frozenset of literal ids).
Expr = Tuple[int, FrozenSet[int]]
# A lowered term: sorted tuple of expressions (conjunction).  () = match-all.
Term = Tuple[Expr, ...]

FALSE_TERM: Term = ((KIND_FALSE, frozenset()),)


def lower_node_requirement(req: t.NodeSelectorRequirement, vocab: LabelVocab) -> Optional[Expr]:
    """Lower one NodeSelectorRequirement to a literal-set primitive.

    Returns None when the expression is vacuously true (droppable from the
    conjunction); returns a KIND_FALSE expr when unsatisfiable against this vocab.
    """
    op = req.operator
    if op == t.OP_IN:
        lits = frozenset(l for v in req.values if (l := vocab.lit(req.key, v)) is not None)
        return (KIND_ANY, lits) if lits else (KIND_FALSE, frozenset())
    if op == t.OP_NOT_IN:
        lits = frozenset(l for v in req.values if (l := vocab.lit(req.key, v)) is not None)
        return (KIND_NONE, lits) if lits else None
    if op == t.OP_EXISTS:
        lits = frozenset(vocab.key_lits(req.key))
        return (KIND_ANY, lits) if lits else (KIND_FALSE, frozenset())
    if op == t.OP_DOES_NOT_EXIST:
        lits = frozenset(vocab.key_lits(req.key))
        return (KIND_NONE, lits) if lits else None
    if op in (t.OP_GT, t.OP_LT):
        try:
            bound = int(req.values[0])
        except (IndexError, ValueError):
            return (KIND_FALSE, frozenset())
        lits = set()
        for lid in vocab.key_lits(req.key):
            _, v = vocab.literals.items[lid]
            try:
                x = int(v)
            except ValueError:
                continue
            if (x > bound) if op == t.OP_GT else (x < bound):
                lits.add(lid)
        return (KIND_ANY, frozenset(lits)) if lits else (KIND_FALSE, frozenset())
    raise ValueError(f"bad node selector operator {op}")


def lower_node_term(exprs: Iterable[t.NodeSelectorRequirement], vocab: LabelVocab) -> Term:
    """Lower a conjunction of requirements; collapses to FALSE_TERM if any is false."""
    out: List[Expr] = []
    for req in exprs:
        e = lower_node_requirement(req, vocab)
        if e is None:
            continue
        if e[0] == KIND_FALSE:
            return FALSE_TERM
        out.append(e)
    return tuple(sorted(out, key=lambda e: (e[0], sorted(e[1]))))


def label_selector_to_requirements(sel: t.LabelSelector) -> List[t.NodeSelectorRequirement]:
    """metav1.LabelSelector -> requirement list (shared lowering path with node terms)."""
    reqs = [
        t.NodeSelectorRequirement(key=k, operator=t.OP_IN, values=(v,))
        for k, v in sel.match_labels
    ]
    for e in sel.match_expressions:
        reqs.append(t.NodeSelectorRequirement(key=e.key, operator=e.operator, values=e.values))
    return reqs


def pod_required_node_terms(pod: t.Pod, vocab: LabelVocab) -> Optional[List[Term]]:
    """The pod's hard node-selection constraint as an OR-of-conjunctions, lowered.

    Combines spec.nodeSelector (a single conjunction) AND affinity's
    requiredDuringScheduling terms (ORed), by distributing the nodeSelector
    conjunction into each affinity term — mirroring the reference's two separate
    checks (nodeaffinity plugin checks both; pkg/scheduler/framework/plugins/
    nodeaffinity/node_affinity.go — func (pl *NodeAffinity) Filter).

    Returns None when the pod has no node-selection constraint at all.
    """
    sel_reqs = [
        t.NodeSelectorRequirement(key=k, operator=t.OP_IN, values=(v,))
        for k, v in pod.node_selector
    ]
    aff_terms = list(pod.affinity.required_node_terms) if pod.affinity else []
    if not sel_reqs and not aff_terms:
        return None
    if not aff_terms:
        return [lower_node_term(sel_reqs, vocab)]
    out = []
    for term in aff_terms:
        if not term.match_expressions:
            # An empty/null NodeSelectorTerm matches NO objects (reference:
            # component-helpers nodeaffinity — "null or empty term matches no
            # objects"), so it contributes an unsatisfiable branch to the OR.
            out.append(FALSE_TERM)
        else:
            out.append(lower_node_term(list(term.match_expressions) + sel_reqs, vocab))
    return out


@dataclass
class TermTable:
    """Interned term set + its dense encoding, shared across pods.

    Encoded as [S, E] expression slots; each slot has a kind and a 0/1 literal
    mask row.  ops/filters.py turns this into term_match[S, N] with one matmul.
    """

    terms: Interner = field(default_factory=Interner)

    def intern(self, term: Term) -> int:
        return self.terms.intern(term)

    def encode(self, n_lits: int):
        """-> (mask [S, E, Lpad] f32, kind [S, E] i32); S>=1, E>=1 (padded)."""
        import numpy as np

        S = max(1, len(self.terms))
        E = max(1, max((len(tm) for tm in self.terms.items), default=1))
        L = max(1, n_lits)
        mask = np.zeros((S, E, L), dtype=np.float32)
        kind = np.full((S, E), KIND_PAD, dtype=np.int32)
        for s, term in enumerate(self.terms.items):
            for e, (k, lits) in enumerate(term):
                kind[s, e] = k
                for lid in lits:
                    mask[s, e, lid] = 1.0
        return mask, kind
