"""Volume + DRA resolution: lowers storage and device-claim constraints onto
the core scheduling model, shared by every execution path (TPU kernels, native
engine, CPU plugins, oracle).

reference semantics covered (SURVEY.md §2.2 volume rows + DRA):
  - VolumeZone / bound-PVC topology (volumezone/volume_zone.go,
    volumebinding's feasibility for statically-bound claims): a pod claiming a
    PVC bound to a PV with allowedTopology {zone=a} can only run on nodes
    labeled zone=a -> folded into the pod's required node-affinity terms.
  - VolumeBinding for unbound claims (volumebinding/binder.go): immediate-mode
    unbound claims must have SOME compatible PV (class + capacity); if none
    exists the pod is unschedulable everywhere.  If candidate PVs exist, node
    feasibility is restricted to the union of their topologies.
    WaitForFirstConsumer claims place no scheduling constraint (delayed
    binding happens at Reserve/PreBind in the reference).
  - NodeVolumeLimits (nodevolumelimits/csi.go): per-node attachable-volume
    cap -> a synthetic "attachable-volumes-csi" resource: nodes with a limit
    allocate it, each PVC consumes 1, and NodeResourcesFit enforces the cap.
  - DynamicResources-lite (dynamicresources/): ResourceClaims for counted
    device classes -> extended resources named "claim/<deviceClass>".

resolve_snapshot returns a NEW snapshot with these constraints folded in;
the original objects are not mutated.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from . import types as t
from .snapshot import Snapshot

ATTACH_RESOURCE = "attachable-volumes-csi"
CLAIM_PREFIX = "claim/"


def _topology_term(allowed_topology) -> Optional[t.NodeSelectorTerm]:
    """Allowed-topology pairs (from a PV or a StorageClass) → one conjunction
    term.  Pairs sharing a key merge into one In-expression (values OR
    together — the reference's TopologySelectorTerm.matchLabelExpressions
    carries values[] per key); DISTINCT keys AND together."""
    if not allowed_topology:
        return None
    by_key: dict = {}
    for k, v in allowed_topology:
        by_key.setdefault(k, []).append(v)
    return t.NodeSelectorTerm(
        match_expressions=tuple(
            t.NodeSelectorRequirement(key=k, operator=t.OP_IN, values=tuple(vs))
            for k, vs in by_key.items()
        )
    )


def _pv_topology_term(pv: t.PersistentVolume) -> Optional[t.NodeSelectorTerm]:
    return _topology_term(pv.allowed_topology)


def _unsatisfiable_term() -> t.NodeSelectorTerm:
    return t.NodeSelectorTerm(
        match_expressions=(
            t.NodeSelectorRequirement(
                key="volume.kubernetes.io/unsatisfiable", operator=t.OP_IN, values=("true",)
            ),
        )
    )


def resolve_pod(
    pod: t.Pod,
    pvcs: Dict[str, t.PersistentVolumeClaim],
    pvs: Dict[str, t.PersistentVolume],
    classes: Optional[Dict[str, object]] = None,
    rwop_blocked: Optional[set] = None,
) -> t.Pod:
    """Fold the pod's storage/claim constraints into requests + node affinity.
    `rwop_blocked`: claim names this pod may NOT use right now (another pod
    holds the ReadWriteOncePod claim) — folds an unsatisfiable term."""
    classes = classes or {}
    extra_terms: List[t.NodeSelectorTerm] = []
    attach_count = 0
    req_extra: Dict[str, int] = {}
    for claim_name in pod.pvcs:
        if rwop_blocked and claim_name in rwop_blocked:
            extra_terms.append(_unsatisfiable_term())
            continue
        pvc = pvcs.get(f"{pod.namespace}/{claim_name}")
        if pvc is None:
            extra_terms.append(_unsatisfiable_term())  # missing claim: pending
            continue
        attach_count += 1
        if pvc.volume_name:
            pv = pvs.get(pvc.volume_name)
            term = _pv_topology_term(pv) if pv else _unsatisfiable_term()
            if pv is None:
                extra_terms.append(_unsatisfiable_term())
            elif term is not None:
                extra_terms.append(term)
        else:
            # Unbound claim (binder.go — FindPodVolumes): the node must admit
            # SOME binding option — a compatible static PV, or dynamic
            # provisioning through the claim's StorageClass.
            sc = classes.get(pvc.storage_class)
            wffc = pvc.wait_for_first_consumer or (
                sc is not None and sc.volume_binding_mode == "WaitForFirstConsumer"
            )
            candidates = [
                pv
                for pv in pvs.values()
                if not pv.claim_ref
                and pv.storage_class == pvc.storage_class
                and pv.capacity >= pvc.request
            ]
            provisionable = sc is not None and bool(sc.provisioner)
            # any option with no topology restriction => no constraint at all
            unconstrained = any(not c.allowed_topology for c in candidates) or (
                provisionable and not sc.allowed_topology
            )
            if unconstrained:
                continue
            options = [
                term
                for term in (_pv_topology_term(c) for c in candidates)
                if term is not None
            ]
            if provisionable:
                ct = _topology_term(sc.allowed_topology)
                if ct is not None:
                    options.append(ct)
            if options:
                extra_terms.append(options[0] if len(options) == 1 else _or_marker(tuple(options)))
            elif wffc and sc is None:
                # delayed binding through an unknown class: no constraint can
                # be derived at filter time (the pre-StorageClass behavior)
                continue
            else:
                extra_terms.append(_unsatisfiable_term())
    if attach_count:
        req_extra[ATTACH_RESOURCE] = attach_count
    for rc in pod.resource_claims:
        key = CLAIM_PREFIX + rc.device_class
        req_extra[key] = req_extra.get(key, 0) + rc.count
    if not extra_terms and not req_extra:
        return pod
    q = copy.copy(pod)
    if req_extra:
        q.requests = {**pod.requests}
        for k, v in req_extra.items():
            q.requests[k] = q.requests.get(k, 0) + v
    if extra_terms:
        q.affinity = _and_affinity(pod.affinity, extra_terms)
    return q


class _OrTerms(tuple):
    """Marker: a disjunction of terms that must AND with the pod's own terms."""


def _or_marker(terms: Tuple[t.NodeSelectorTerm, ...]) -> "_OrTerms":
    return _OrTerms(terms)


def _and_affinity(aff: Optional[t.Affinity], extra) -> t.Affinity:
    """AND extra conjunction terms (or OR-groups) into required node affinity.

    required_node_terms is an OR of conjunctions; to AND a new constraint we
    distribute it into every existing term (the same trick the encoder uses
    for spec.nodeSelector — api/vocab.pod_required_node_terms).
    """
    base_terms: List[t.NodeSelectorTerm] = (
        list(aff.required_node_terms) if aff and aff.required_node_terms else [t.NodeSelectorTerm()]
    )
    for item in extra:
        groups = list(item) if isinstance(item, _OrTerms) else [item]
        new_terms = []
        for bt in base_terms:
            for g in groups:
                new_terms.append(
                    t.NodeSelectorTerm(
                        match_expressions=tuple(bt.match_expressions) + tuple(g.match_expressions)
                    )
                )
        base_terms = new_terms
    if aff is None:
        return t.Affinity(required_node_terms=tuple(base_terms))
    return t.Affinity(
        required_node_terms=tuple(base_terms),
        preferred_node_terms=aff.preferred_node_terms,
        required_pod_affinity=aff.required_pod_affinity,
        required_pod_anti_affinity=aff.required_pod_anti_affinity,
        preferred_pod_affinity=aff.preferred_pod_affinity,
        preferred_pod_anti_affinity=aff.preferred_pod_anti_affinity,
    )


def _device_counts(snap: Snapshot) -> Dict[str, Dict[str, int]]:
    """node -> {claim/<class>: count} from published ResourceSlices resolved
    through DeviceClass selectors — the structured-parameter allocator
    (resource.k8s.io) reduced to per-node per-class counting, which is what
    the vectorized Fit kernel consumes."""
    out: Dict[str, Dict[str, int]] = {}
    # devices are allocated exclusively in the reference; a device matching
    # several class selectors counts toward only ONE class here — the first
    # in name order (deterministic reduction of exclusive allocation)
    classes = sorted(snap.device_classes.values(), key=lambda dc: dc.name)
    for sl in snap.resource_slices:
        if not sl.node_name:
            continue
        per = out.setdefault(sl.node_name, {})
        for dev in sl.devices:
            for dc in classes:
                if dc.selector.matches(dev):
                    key = CLAIM_PREFIX + dc.name
                    per[key] = per.get(key, 0) + 1
                    break
    return out


def resolve_snapshot(snap: Snapshot) -> Snapshot:
    """Returns a snapshot with volume/claim constraints folded in (no-op when
    the snapshot has no PVs/PVCs/claims/attach limits/device slices)."""
    has_storage = bool(snap.pvs or snap.pvcs)
    has_claims = False
    if not has_storage:
        # one fused pass: at 50k-pod scale two separate any() generators are
        # measurable host time on the steady-state encode path
        for p in snap.pending_pods:
            if p.pvcs:
                has_storage = True
                break
            if p.resource_claims:
                has_claims = True
        if not has_storage:
            for p in snap.bound_pods:
                if p.pvcs:
                    has_storage = True
                    break
                if p.resource_claims:
                    has_claims = True
    if has_storage and not has_claims:
        has_claims = any(
            p.resource_claims for p in [*snap.pending_pods, *snap.bound_pods]
        )
    has_limits = any(nd.volume_attach_limit for nd in snap.nodes)
    has_devices = bool(snap.resource_slices and snap.device_classes)
    if not (has_storage or has_claims or has_limits or has_devices):
        return snap
    pvs = {pv.name: pv for pv in snap.pvs}
    pvcs = dict(snap.pvcs)
    classes = dict(snap.storage_classes)
    nodes = snap.nodes
    devices = _device_counts(snap) if has_devices else {}
    if has_limits or has_storage or devices:
        nodes = []
        for nd in snap.nodes:
            nd2 = copy.copy(nd)
            # every node advertises the synthetic attach resource: its declared
            # limit, or effectively-unlimited when none (csi.go treats a
            # missing limit as no cap)
            nd2.allocatable = {
                **nd.allocatable,
                ATTACH_RESOURCE: nd.volume_attach_limit or 1_000_000,
                # device inventory from slices overrides any hand-set counts
                **devices.get(nd.name, {}),
            }
            nodes.append(nd2)
    # ReadWriteOncePod (volumerestrictions/volume_restrictions.go): at most
    # one pod cluster-wide may use such a claim.  A live bound user blocks
    # every pending user; otherwise pending users serialize in snapshot
    # (arrival) order — the first keeps the claim, the rest fold an
    # unsatisfiable term, matching the reference's one-at-a-time outcome
    # (documented deviation: arrival order stands in for cycle order).
    rwop_blocked: Dict[str, set] = {}
    rwop_keys = {k for k, c in pvcs.items() if c.read_write_once_pod}
    if rwop_keys:
        held = set()
        for q in snap.bound_pods:
            if q.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                continue
            for cn in q.pvcs:
                ck = f"{q.namespace}/{cn}"
                if ck in rwop_keys:
                    held.add(ck)
        claimed = set(held)
        for q in snap.pending_pods:
            for cn in q.pvcs:
                ck = f"{q.namespace}/{cn}"
                if ck in rwop_keys:
                    if ck in claimed:
                        rwop_blocked.setdefault(q.uid, set()).add(cn)
                    else:
                        claimed.add(ck)
    return Snapshot(
        nodes=nodes,
        pending_pods=[
            resolve_pod(p, pvcs, pvs, classes, rwop_blocked.get(p.uid))
            for p in snap.pending_pods
        ],
        bound_pods=[resolve_pod(p, pvcs, pvs, classes) for p in snap.bound_pods],
        pod_groups=snap.pod_groups,
        pvs=snap.pvs,
        pvcs=snap.pvcs,
        storage_classes=snap.storage_classes,
        resource_slices=snap.resource_slices,
        device_classes=snap.device_classes,
    )
