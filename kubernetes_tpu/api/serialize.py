"""Manifest codec — typed objects ↔ plain dicts ↔ YAML/JSON.

The apimachinery serializer analog (reference:
staging/src/k8s.io/apimachinery/pkg/runtime/serializer/ — the universal
decoder resolves a document's `kind` through the Scheme to a typed object;
encoding round-trips it back).  This framework's Scheme is the KINDS registry
below: one entry per API kind, mapping to the dataclass that models it.

Differences from the reference, by design:
- single version (no conversion webbing — there is one hub type per kind);
- field names are this framework's snake_case scheduling-surface names, not
  the reference's nested spec/status JSON (api/types.py documents the
  reduction);
- decoding is strict (unknown fields are errors), like the reference's
  `strictDecodingError`.

Tuple-of-pairs fields (e.g. Pod.node_selector, LabelSelector.match_labels)
additionally accept YAML mappings for hand-written manifests:
`node_selector: {disk: ssd}` ≡ `node_selector: [[disk, ssd]]`.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple, Union, get_args, get_origin, get_type_hints

import yaml

from . import cluster as c
from . import types as t

# The Scheme: kind name -> dataclass.  (reference: runtime.Scheme — AddKnownTypes)
KINDS: Dict[str, type] = {
    "Pod": t.Pod,
    "Node": t.Node,
    "PodDisruptionBudget": t.PodDisruptionBudget,
    "PodGroup": t.PodGroup,
    "PersistentVolume": t.PersistentVolume,
    "PersistentVolumeClaim": t.PersistentVolumeClaim,
    "ReplicaSet": t.ReplicaSet,
    "Deployment": t.Deployment,
    "Job": t.Job,
    "Service": c.Service,
    "EndpointSlice": c.EndpointSlice,
    "Namespace": c.Namespace,
    "PriorityClass": c.PriorityClass,
    "ResourceQuota": c.ResourceQuota,
    "LimitRange": c.LimitRange,
    "StatefulSet": c.StatefulSet,
    "DaemonSet": c.DaemonSet,
    "CronJob": c.CronJob,
    "HorizontalPodAutoscaler": c.HorizontalPodAutoscaler,
    "Role": c.Role,
    "RoleBinding": c.RoleBinding,
    "FlowSchema": c.FlowSchema,
    "PriorityLevelConfiguration": c.PriorityLevelConfiguration,
    "StorageClass": c.StorageClass,
    "ResourceSlice": c.ResourceSlice,
    "DeviceClass": c.DeviceClass,
    "ResourceClaim": c.ResourceClaim,
    "CertificateSigningRequest": c.CertificateSigningRequest,
    "Event": c.ClusterEvent,
    "ServiceAccount": c.ServiceAccount,
}


def _register_crd_kinds() -> None:
    """CustomResourceDefinition joins the Scheme lazily (scheduler.crd imports
    api modules; a top-level import here would be circular)."""
    from ..scheduler.crd import CustomResourceDefinition

    KINDS.setdefault("CustomResourceDefinition", CustomResourceDefinition)
    _CLASS_TO_KIND.setdefault(CustomResourceDefinition, "CustomResourceDefinition")



# aliases accepted on decode (the store's table name for PodDisruptionBudget)
_KIND_ALIASES = {"PDB": "PodDisruptionBudget"}

_CLASS_TO_KIND: Dict[type, str] = {cls: k for k, cls in KINDS.items()}


class DecodeError(ValueError):
    """Strict-decoding failure (unknown kind/field, wrong shape)."""


def kind_of(obj: object) -> str:
    k = _CLASS_TO_KIND.get(type(obj))
    if k is None:
        # dynamic kinds (CustomResource instances, the CRD object itself)
        # carry their kind on the object — the unstructured path
        k = getattr(obj, "kind", None)
        if isinstance(k, str) and k:
            return k
        raise DecodeError(f"{type(obj).__name__} is not a registered kind")
    return k


# ------------------------------------------------------------------- encoding


def to_plain(obj):
    """Dataclass → JSON-able plain value, omitting default-valued fields
    (the reference's `omitempty` behavior)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            val = getattr(obj, f.name)
            if f.default is not dataclasses.MISSING and val == f.default:
                continue
            if (
                f.default_factory is not dataclasses.MISSING  # type: ignore[misc]
                and val == f.default_factory()  # type: ignore[misc]
            ):
                continue
            out[f.name] = to_plain(val)
        return out
    if isinstance(obj, (list, tuple)):
        return [to_plain(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_plain(v) for k, v in obj.items()}
    return obj


def to_manifest(obj) -> dict:
    return {"kind": kind_of(obj), **to_plain(obj)}


def dump_yaml(objs) -> str:
    """One or many objects → (multi-document) YAML manifest."""
    if dataclasses.is_dataclass(objs) and not isinstance(objs, type):
        objs = [objs]
    return yaml.safe_dump_all(
        [to_manifest(o) for o in objs], sort_keys=False, default_flow_style=None
    )


def dump_json(obj) -> str:
    return json.dumps(to_manifest(obj), indent=2)


# ------------------------------------------------------------------- decoding


def _is_pair_tuple(tp) -> bool:
    """Tuple[Tuple[str, X], ...] — the tuple-of-pairs shape that may be
    written as a mapping in manifests."""
    args = get_args(tp)
    if len(args) != 2 or args[1] is not Ellipsis:
        return False
    inner = get_args(args[0])
    return get_origin(args[0]) in (tuple, Tuple) and len(inner) == 2


def _coerce(tp, val, path: str):
    if val is None:
        return None
    origin = get_origin(tp)
    if origin is Union:  # Optional[X]
        inner = [a for a in get_args(tp) if a is not type(None)]
        return _coerce(inner[0], val, path)
    if dataclasses.is_dataclass(tp):
        if isinstance(val, tp):
            return val
        if not isinstance(val, dict):
            raise DecodeError(f"{path}: expected mapping for {tp.__name__}")
        return from_plain(tp, val, path)
    if origin in (tuple, Tuple):
        args = get_args(tp)
        if isinstance(val, dict):
            if not _is_pair_tuple(tp):
                raise DecodeError(f"{path}: mapping not allowed here")
            return tuple(sorted((str(k), _coerce(get_args(args[0])[1], v, path))
                                for k, v in val.items()))
        if not isinstance(val, (list, tuple)):
            raise DecodeError(f"{path}: expected sequence")
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_coerce(args[0], v, f"{path}[{i}]")
                         for i, v in enumerate(val))
        if len(args) != len(val):
            raise DecodeError(f"{path}: expected {len(args)} items, got {len(val)}")
        return tuple(_coerce(a, v, f"{path}[{i}]")
                     for i, (a, v) in enumerate(zip(args, val)))
    if origin is dict:
        args = get_args(tp)
        if not args:  # bare Dict: free-form mapping (CRD structural schemas)
            if not isinstance(val, dict):
                raise DecodeError(f"{path}: expected mapping")
            return dict(val)
        kt, vt = args
        if not isinstance(val, dict):
            raise DecodeError(f"{path}: expected mapping")
        return {_coerce(kt, k, path): _coerce(vt, v, f"{path}.{k}")
                for k, v in val.items()}
    if tp is float and isinstance(val, int):
        return float(val)
    return val


def from_plain(cls: type, data: dict, path: str = ""):
    """Plain dict → dataclass instance; strict about unknown fields."""
    path = path or cls.__name__
    hints = get_type_hints(cls)
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - names
    if unknown:
        raise DecodeError(f"{path}: unknown field(s) {sorted(unknown)}")
    kwargs = {k: _coerce(hints[k], v, f"{path}.{k}") for k, v in data.items()}
    try:
        return cls(**kwargs)
    except TypeError as e:  # missing required field
        raise DecodeError(f"{path}: {e}") from None


def from_manifest(doc: dict):
    _register_crd_kinds()
    doc = dict(doc)
    api_version = doc.pop("apiVersion", None)  # single-version scheme
    kind = doc.pop("kind", None)
    if not kind:
        raise DecodeError("manifest document has no `kind`")
    kind = _KIND_ALIASES.get(kind, kind)
    if kind == "CustomResourceDefinition" and "names" in doc:
        # the manifest's top-level `kind` is the TYPE discriminator; the
        # CRD's target kind/plural ride in the reference's names block
        # (apiextensions/v1 — CustomResourceDefinitionNames)
        names = dict(doc.pop("names") or {})
        doc.setdefault("kind", names.get("kind", ""))
        doc.setdefault("plural", names.get("plural", ""))
    cls = KINDS.get(kind)
    if cls is None:
        # group-qualified apiVersion + unregistered kind = a custom resource:
        # decode unstructured (apiextensions' Unstructured path); the server
        # validates spec against the CRD's structural schema on write
        if isinstance(api_version, str) and "/" in api_version:
            from ..scheduler.crd import CustomResource

            unknown = set(doc) - {"name", "namespace", "labels", "spec"}
            if unknown:
                raise DecodeError(
                    f"unknown field(s) {sorted(unknown)} on custom kind {kind!r}"
                )
            return CustomResource(
                api_version=api_version,
                kind=kind,
                name=doc.get("name", ""),
                namespace=doc.get("namespace", "default"),
                labels=dict(doc.get("labels") or {}),
                spec=dict(doc.get("spec") or {}),
            )
        raise DecodeError(f"unknown kind {kind!r}")
    return from_plain(cls, doc)


def load_yaml(text: str) -> list:
    """Multi-document YAML manifest → typed objects.  A document of kind
    `List` (or bearing `items`) is flattened, like the reference's v1.List."""
    out = []
    for doc in yaml.safe_load_all(text):
        if doc is None:
            continue
        if isinstance(doc, dict) and (doc.get("kind") == "List" or "items" in doc):
            out.extend(from_manifest(d) for d in doc.get("items", []))
        else:
            out.append(from_manifest(doc))
    return out
