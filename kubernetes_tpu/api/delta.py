"""Incremental (delta) snapshot encoding — the watch-cache analog.

The reference keeps one etcd watch feeding an incremental NodeInfo cache and
re-snapshots per cycle in O(changes) (storage/cacher/cacher.go — type Cacher;
pkg/scheduler/backend/cache — UpdateSnapshot).  This module is the TPU-first
equivalent (SURVEY.md §2.4 "watch fan-out → snapshot-delta streaming",
§7 hard part 4: snapshot deltas, not full re-uploads):

  * The CLUSTER SIDE — node profiles, vocabularies, raw int64 resource usage,
    pairwise term counts, host-port occupancy, per-bound-pod contribution
    records — stays resident in a `ClusterSide` cache across scheduling
    cycles.  Newly bound / deleted pods are absorbed as batched scatter
    updates (np.add.at over the changed rows), never a rebuild.
  * The POD SIDE — everything keyed by the pending wave (requests, selector
    lowering, pairwise term ids, gang masks, image scores) — is (re)built
    per cycle with the spec-interned vectorized path and scattered through
    the wave's inverse index.

Exactness: raw int64 resource sums live in the cache, and the int32 rescale
is re-derived per cycle from raw values, so a delta-updated encode is
BIT-IDENTICAL to a from-scratch encode of the same cluster state (asserted by
tests/test_delta_encoder.py on randomized churn streams).  Whenever a delta
cannot preserve that guarantee — a new vocabulary entry (label key, taint,
pairwise term, host port, resource kind), a node set change, a bound pod the
guards cannot absorb — the encoder falls back to a full cluster-side rebuild,
which IS the one-shot path: `snapshot.encode_snapshot` delegates here, so the
fast path and the fallback share one implementation.

Volume/DRA clusters stay incremental (round 3): the cache is conditioned on
PRE-resolution node identity plus a storage-state fingerprint (PV/PVC/class/
slice object identities — _storage_fp), because volumes.resolve_snapshot
rebuilds node objects every cycle and post-resolution identity would never
match.  While storage state is stable the delta path serves (only
storage-USING bound pods re-absorb per cycle, their resolved copies being
fresh objects); any storage change forces the full rebuild
(tests/test_delta_encoder.py — test_delta_survives_volume_state).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import types as t
from . import vocab as v
from .pairwise import (
    HARD,
    SOFT,
    PairwiseVocab,
    TermKey,
    _match_matrix,
    _term_of_affinity,
    _term_of_spread,
)


# --------------------------------------------------------------------------
# wave fingerprint: what the cluster-side cache is conditioned on
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class WaveFingerprint:
    """Wave-derived inputs the cluster side depends on.  Two waves with equal
    fingerprints (the steady state for template-stamped workloads) can share
    one cluster-side cache; a mismatch forces a rebuild."""

    referenced_keys: frozenset
    resources: Tuple[str, ...]
    term_seq: Tuple[TermKey, ...]  # pairwise terms in first-intern order
    port_seq: Tuple[Tuple[str, int], ...]


def _pod_pairwise_terms(pod: t.Pod):
    """(aff, anti, pref[(term, signed w)], spread[(term, maxSkew, mode)]) as
    TermKey tuples, in the canonical intern order."""
    aff: List[TermKey] = []
    anti: List[TermKey] = []
    pref: List[Tuple[TermKey, float]] = []
    spread: List[Tuple[TermKey, int, int]] = []
    if pod.affinity:
        for term in pod.affinity.required_pod_affinity:
            aff.append(_term_of_affinity(term, pod.namespace))
        for term in pod.affinity.required_pod_anti_affinity:
            anti.append(_term_of_affinity(term, pod.namespace))
        for wt in pod.affinity.preferred_pod_affinity:
            pref.append((_term_of_affinity(wt.term, pod.namespace), float(wt.weight)))
        for wt in pod.affinity.preferred_pod_anti_affinity:
            pref.append((_term_of_affinity(wt.term, pod.namespace), -float(wt.weight)))
    for c in pod.topology_spread:
        spread.append(
            (
                _term_of_spread(c, pod.namespace),
                c.max_skew,
                HARD if c.when_unsatisfiable == t.DO_NOT_SCHEDULE else SOFT,
            )
        )
    return aff, anti, pref, spread


def wave_fingerprint(reps: Sequence[t.Pod], resources: Sequence[str]) -> WaveFingerprint:
    referenced: set = set()
    term_seq: List[TermKey] = []
    seen_terms: set = set()
    port_seq: List[Tuple[str, int]] = []
    seen_ports: set = set()
    for pod in reps:
        for k, _ in pod.node_selector:
            referenced.add(k)
        if pod.affinity:
            for term in pod.affinity.required_node_terms:
                for e in term.match_expressions:
                    referenced.add(e.key)
            for pt in pod.affinity.preferred_node_terms:
                for e in pt.preference.match_expressions:
                    referenced.add(e.key)
        aff, anti, pref, spread = _pod_pairwise_terms(pod)
        for tk in [*aff, *anti, *(tk for tk, _ in pref), *(tk for tk, _, _ in spread)]:
            if tk not in seen_terms:
                seen_terms.add(tk)
                term_seq.append(tk)
        for pp in pod.host_ports:
            if pp not in seen_ports:
                seen_ports.add(pp)
                port_seq.append(pp)
    return WaveFingerprint(
        referenced_keys=frozenset(referenced),
        resources=tuple(resources),
        term_seq=tuple(term_seq),
        port_seq=tuple(port_seq),
    )


# --------------------------------------------------------------------------
# cluster side: resident, delta-updated state
# --------------------------------------------------------------------------


@dataclass
class _WaveView:
    """Snapshot-shaped view for the pregrouped (sidecar) encode path: the
    wire carries no PV/PVC/class/slice schema (D10 — constraints arrive
    pre-resolved), so the storage surfaces are permanently empty."""

    nodes: list
    pending_pods: tuple
    bound_pods: list
    pod_groups: dict
    pvs: tuple = ()
    pvcs: dict = field(default_factory=dict)
    storage_classes: dict = field(default_factory=dict)
    resource_slices: tuple = ()
    device_classes: dict = field(default_factory=dict)


class _Fallback(Exception):
    """A delta cannot be absorbed bit-exactly — rebuild the cluster side."""


# One bound pod's exact contribution, for O(1) reversal on delete — a plain
# tuple (not a dataclass): records are created at wave-bind rates (50k/cycle),
# where dataclass __init__ overhead alone is ~100 ms.
# Layout: (ni, req_u, spec_u, port_ids, anti_ids, pref, obj).  The record holds
# the pod OBJECT (not its id()): the strong reference keeps the object alive,
# so `rec[_OBJ] is q` is a sound unchanged-check — a freed address being
# reallocated to a different pod can never alias it.
_BoundRec = tuple
_NI, _REQ_U, _SPEC_U, _PORT_IDS, _ANTI_IDS, _PREF, _OBJ = range(7)

# shared "nothing changed" dirty set (sync_bound / EncodingMeta.dirty_nodes)
_EMPTY_DIRTY = np.empty(0, dtype=np.int64)


@dataclass
class ClusterSide:
    """Everything derivable from (nodes, bound pods, wave fingerprint); all
    node-axis arrays UNPADDED ([n] rows) — padding happens at assembly."""

    wfp: WaveFingerprint
    hpaw: float
    nodes: List[t.Node]
    nodes_fp: Tuple
    node_index: Dict[str, int]
    # label vocab + rows (filtered to wfp.referenced_keys)
    lab: v.LabelVocab
    node_labels: np.ndarray  # f32[n, L]
    # taints
    taints: v.Interner
    taint_objs: List[t.Taint]
    node_taint_ns: np.ndarray  # bool[n, T]
    node_taint_pref: np.ndarray
    # resources (raw int64; scale derived per cycle)
    alloc_raw: np.ndarray  # i64[n, R]
    used_raw: np.ndarray  # i64[n, R]
    breq_uniq_ids: Dict[Tuple, int]
    breq_uniq: List[List[int]]  # raw effective-request rows of bound specs
    # pairwise
    voc: PairwiseVocab
    terms_list: List[TermKey]
    node_dom: np.ndarray  # i32[K, n]
    term_key: np.ndarray  # i32[T2]
    term_counts0: np.ndarray  # f32[T2, D+1]
    anti_counts0: np.ndarray
    pref_own0: np.ndarray
    # bound-spec match columns (by (labels, ns, affinity) key)
    bspec_ids: Dict[Tuple, int]
    m_cols: List[np.ndarray]  # each f32[T2]
    bspec_anti: List[Tuple[int, ...]]
    bspec_pref: List[Tuple[Tuple[int, float], ...]]
    # host ports (occupancy as counts: OR is not reversible, counts are)
    node_port_count: np.ndarray  # i32[n, PT]
    # per-uid records
    records: Dict[str, _BoundRec] = field(default_factory=dict)
    stats: Dict[str, int] = field(default_factory=lambda: {"rebuilds": 0, "deltas": 0})
    # padded-array cache for _assemble: name -> (key, array).  Returning the
    # SAME numpy object for unchanged state lets encode_device() skip the
    # host->device transfer of resident buffers (true double-buffered device
    # snapshot — SURVEY.md §2.4 watch fan-out row).
    pad_cache: Dict[str, Tuple] = field(default_factory=dict)
    # bumped whenever sync mutates used_raw/ports/counts in place; versioned
    # cache entries copy once per version, so handed-out arrays are immutable
    mut_version: int = 0
    # fast bind-absorb: each wave pod's (own object, unique-spec rep), found
    # by uid.  A pod that binds was a recent wave's pending pod; the rep's
    # spec fields stand in for the bound copy's — record construction becomes
    # O(1) dict lookups instead of per-pod key sorting.  The bound copy is
    # revalidated against the ORIGINAL wave object first (bind copies share
    # field objects, so that's five `is` checks) because pod labels are
    # mutable metadata in the reference API — a label update racing the bind
    # must not reuse the stale spec info (round-2 advisor finding).
    #
    # Layout: uid -> (wave_id << 32 | position), resolved through wave_store
    # [wave_id -> (sorted_pods, reps, inv_list)].  The packed-int index dict
    # fills via dict.update(zip(...)) at C speed — the previous per-pod
    # Python loop building (pod, rep) tuples was HALF the steady-state
    # encode (~167 ms of ~330 at 50k pods, measured).
    wave_ix: Dict[str, int] = field(default_factory=dict)
    wave_store: Dict[int, Tuple[list, list, list]] = field(default_factory=dict)
    wave_next: int = 0
    # bound-side info per wave rep (keyed by id(rep); reps are kept alive by
    # wave_store)
    rep_bound_info: Dict[int, Tuple[int, int, Tuple[int, ...]]] = field(
        default_factory=dict
    )
    # PRE-resolution conditioning (volume/DRA clusters): the raw node-set
    # identity plus a storage-state fingerprint; resolve_snapshot rebuilds
    # node objects per cycle, so post-resolution identity alone would defeat
    # the cache whenever any PV/PVC/class/slice exists.  raw_refs keeps the
    # fingerprinted objects alive so ids cannot be recycled.
    raw_nodes_fp: Tuple = ()
    storage_fp: Tuple = ()
    raw_refs: Tuple = ()
    # node rows whose bound-pod contributions (usage / counts / ports)
    # changed in the LAST sync_bound — the O(changes) dirty-node set the
    # incremental device hoist reports (ops/incremental.py).  None right
    # after a rebuild ("unknown: everything").
    last_dirty_nodes: Optional[np.ndarray] = None


def _nodes_fp(nodes: Sequence[t.Node]) -> Tuple:
    return tuple((nd.name, id(nd)) for nd in nodes)


def raw_fingerprints(snap) -> Tuple:
    """(raw_nodes_fp, storage_fp) — the PRE-resolution cache conditioning,
    shared by the encoder and the sidecar client so the two cannot drift."""
    return (_nodes_fp(snap.nodes), _storage_fp(snap))


def raw_keepalive_refs(snap) -> Tuple:
    """Containers pinning every object the raw fingerprints id() — build
    ONLY when (re)synchronizing, never on steady-state cycles (copying a
    20k-node list per cycle is measurable host time)."""
    return (
        list(snap.nodes), list(snap.pvs), dict(snap.pvcs),
        dict(snap.storage_classes), list(snap.resource_slices),
        dict(snap.device_classes),
    )


def _storage_fp(snap) -> Tuple:
    """Identity fingerprint of every input volumes.resolve_snapshot reads
    beyond nodes/pods: PVs, PVCs, StorageClasses, ResourceSlices,
    DeviceClasses.  Identity-based under the repo-wide copy-on-write
    convention (a state change replaces the object)."""
    return (
        tuple(id(pv) for pv in snap.pvs),
        tuple((k, id(v)) for k, v in snap.pvcs.items()),
        tuple(sorted((k, id(v)) for k, v in snap.storage_classes.items())),
        tuple(id(sl) for sl in snap.resource_slices),
        tuple(sorted((k, id(v)) for k, v in snap.device_classes.items())),
    )


# The pod fields the bound-side absorb reads (what _spec_info/_bound_spec_key
# consume).  Shared with the wire client's drift check (runtime/client.py) so
# the two revalidation sites cannot diverge.
BOUND_SPEC_FIELDS = ("labels", "namespace", "requests", "host_ports", "affinity")


def bound_spec_fields_match(a: t.Pod, b: t.Pod) -> bool:
    """Identity-first equality over BOUND_SPEC_FIELDS (copies made with
    copy/replace share field objects, so the common case is five `is` checks)."""
    for f in BOUND_SPEC_FIELDS:
        x, y = getattr(a, f), getattr(b, f)
        if x is not y and x != y:
            return False
    return True


def _bound_spec_key(q: t.Pod) -> Tuple:
    return (tuple(sorted(q.labels.items())), q.namespace, q.affinity)


def _bound_term_ids(voc: PairwiseVocab, pod: t.Pod, hpaw: float, intern: bool):
    """anti term ids + signed pref (id, w) of a BOUND pod; existing pods'
    REQUIRED affinity terms score toward incoming pods at hardPodAffinityWeight
    (interpodaffinity/scoring.go — processExistingPod)."""
    get = voc.terms.intern if intern else voc.terms.get
    anti: List[int] = []
    pref: List[Tuple[int, float]] = []
    if pod.affinity:
        for term in pod.affinity.required_pod_anti_affinity:
            ti = get(_term_of_affinity(term, pod.namespace))
            if ti is None:
                raise _Fallback("new anti term from bound pod")
            anti.append(ti)
        for wt in pod.affinity.preferred_pod_affinity:
            ti = get(_term_of_affinity(wt.term, pod.namespace))
            if ti is None:
                raise _Fallback("new pref term from bound pod")
            pref.append((ti, float(wt.weight)))
        for wt in pod.affinity.preferred_pod_anti_affinity:
            ti = get(_term_of_affinity(wt.term, pod.namespace))
            if ti is None:
                raise _Fallback("new pref-anti term from bound pod")
            pref.append((ti, -float(wt.weight)))
        if hpaw:
            for term in pod.affinity.required_pod_affinity:
                ti = get(_term_of_affinity(term, pod.namespace))
                if ti is None:
                    raise _Fallback("new req-aff term from bound pod")
                pref.append((ti, float(hpaw)))
    return tuple(anti), tuple(pref)


def build_cluster_side(
    nodes: Sequence[t.Node],
    bound: Sequence[t.Pod],
    wfp: WaveFingerprint,
    hpaw: float,
) -> ClusterSide:
    from .snapshot import _DEFAULT_POD_LIMIT, _node_taints, pod_effective_requests

    n = len(nodes)
    resources = list(wfp.resources)
    R = len(resources)
    node_index = {nd.name: i for i, nd in enumerate(nodes)}

    # --- label vocab over node labels, interned by filtered profile ---
    lab = v.LabelVocab()
    nlab_ids: Dict[Tuple, int] = {}
    nlab_rows: List[List[int]] = []
    nlab_inv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        fk = tuple(
            sorted((k, val) for k, val in nd.labels.items() if k in wfp.referenced_keys)
        )
        u = nlab_ids.get(fk)
        if u is None:
            u = len(nlab_rows)
            nlab_ids[fk] = u
            nlab_rows.append(lab.add_labels(dict(fk)))
        nlab_inv[i] = u
    L = max(1, len(lab))
    node_labels = np.zeros((n, L), dtype=np.float32)
    if n:
        lab_uniq = np.zeros((max(1, len(nlab_rows)), L), dtype=np.float32)
        for u, lits in enumerate(nlab_rows):
            lab_uniq[u, lits] = 1.0
        node_labels[:] = lab_uniq[nlab_inv]

    # --- taints, interned by node profile ---
    taints = v.Interner()
    tprof_ids: Dict[Tuple, int] = {}
    tprof: List[List[t.Taint]] = []
    tinv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        key = (nd.taints, nd.unschedulable)
        u = tprof_ids.get(key)
        if u is None:
            u = len(tprof)
            tprof_ids[key] = u
            ts = _node_taints(nd)
            tprof.append(ts)
            for tn in ts:
                taints.intern((tn.key, tn.value, tn.effect))
        tinv[i] = u
    T = max(1, len(taints))
    node_taint_ns = np.zeros((n, T), dtype=bool)
    node_taint_pref = np.zeros((n, T), dtype=bool)
    if n:
        tns_uniq = np.zeros((max(1, len(tprof)), T), dtype=bool)
        tpref_uniq = np.zeros((max(1, len(tprof)), T), dtype=bool)
        for u, ts in enumerate(tprof):
            for tn in ts:
                tid = taints.get((tn.key, tn.value, tn.effect))
                if tn.effect == t.PREFER_NO_SCHEDULE:
                    tpref_uniq[u, tid] = True
                else:
                    tns_uniq[u, tid] = True
        node_taint_ns[:] = tns_uniq[tinv]
        node_taint_pref[:] = tpref_uniq[tinv]

    # --- allocatable (raw), interned by profile ---
    aprof_ids: Dict[Tuple, int] = {}
    arows: List[List[int]] = []
    ainv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        key = tuple(sorted(nd.allocatable.items()))
        u = aprof_ids.get(key)
        if u is None:
            u = len(arows)
            aprof_ids[key] = u
            arows.append(
                [
                    nd.allocatable.get(r, _DEFAULT_POD_LIMIT if r == t.PODS else 0)
                    for r in resources
                ]
            )
        ainv[i] = u
    alloc_uniq = (
        np.array(arows, dtype=np.int64) if arows else np.zeros((1, R), dtype=np.int64)
    )
    alloc_raw = alloc_uniq[ainv] if n else np.zeros((0, R), dtype=np.int64)

    # --- pairwise vocab: WAVE terms first (their intern order), then bound ---
    voc = PairwiseVocab(v.Interner(), v.Interner(), v.Interner(), v.Interner())
    for tk in wfp.term_seq:
        voc.terms.intern(tk)
    for pp in wfp.port_seq:
        voc.ports.intern(pp)

    # bound pods: requests + spec interning + term interning
    used_raw = np.zeros((n, R), dtype=np.int64)
    breq_uniq_ids: Dict[Tuple, int] = {}
    breq_uniq: List[List[int]] = []
    bspec_ids: Dict[Tuple, int] = {}
    bspec_reps: List[t.Pod] = []
    bspec_anti: List[Tuple[int, ...]] = []
    bspec_pref: List[Tuple[Tuple[int, float], ...]] = []
    records: Dict[str, _BoundRec] = {}
    rec_ni: List[int] = []
    rec_req: List[int] = []
    rec_spec: List[int] = []
    for q in bound:
        ni = node_index.get(q.node_name)
        if ni is None:
            continue
        rkey = tuple(sorted(q.requests.items()))
        ru = breq_uniq_ids.get(rkey)
        if ru is None:
            ru = len(breq_uniq)
            breq_uniq_ids[rkey] = ru
            breq_uniq.append(pod_effective_requests(q, resources))
        skey = _bound_spec_key(q)
        su = bspec_ids.get(skey)
        if su is None:
            su = len(bspec_reps)
            bspec_ids[skey] = su
            bspec_reps.append(q)
            anti, pref = _bound_term_ids(voc, q, hpaw, intern=True)
            bspec_anti.append(anti)
            bspec_pref.append(pref)
        for proto, port in q.host_ports:
            voc.ports.intern((proto, port))
        if q.uid in records:
            # records dedups by uid while rec_ni/rec_req/rec_spec are per-pod:
            # a duplicate would double-count one set of arrays and not the
            # other, and later sync_bound deltas (keyed by uid) would drift
            # from a rebuild — enforce the convention instead of assuming it
            raise ValueError(f"duplicate bound pod uid {q.uid!r} in snapshot")
        records[q.uid] = (
            ni,
            ru,
            su,
            tuple(voc.ports.get(pp) for pp in q.host_ports),
            bspec_anti[su],
            bspec_pref[su],
            q,
        )
        rec_ni.append(ni)
        rec_req.append(ru)
        rec_spec.append(su)

    # --- topology keys + domains over the node set ---
    for tk in [tm.topology_key for tm in voc.terms.items]:
        voc.topo_keys.intern(tk)
    K = max(1, len(voc.topo_keys))
    for nd in nodes:
        for tk in voc.topo_keys.items:
            if tk in nd.labels:
                voc.domains.intern((tk, nd.labels[tk]))
    D = len(voc.domains)
    node_dom = np.full((K, max(1, n)), D, dtype=np.int32)
    for i, nd in enumerate(nodes):
        for k, tk in enumerate(voc.topo_keys.items):
            if tk in nd.labels:
                node_dom[k, i] = voc.domains.get((tk, nd.labels[tk]))
    T2 = max(1, len(voc.terms))
    term_key = np.zeros(T2, dtype=np.int32)
    for ti, term in enumerate(voc.terms.items):
        term_key[ti] = voc.topo_keys.get(term.topology_key)

    terms_list = list(voc.terms.items)
    m_cols: List[np.ndarray] = []
    if bspec_reps and terms_list:
        m_u = _match_matrix(terms_list, bspec_reps)  # [T2, Ub]
        m_cols = [np.ascontiguousarray(m_u[:, j]) for j in range(m_u.shape[1])]
    elif bspec_reps:
        m_cols = [np.zeros(T2, dtype=np.float32) for _ in bspec_reps]

    term_counts0 = np.zeros((T2, D + 1), dtype=np.float32)
    anti_counts0 = np.zeros((T2, D + 1), dtype=np.float32)
    pref_own0 = np.zeros((T2, D + 1), dtype=np.float32)
    PT = max(1, len(voc.ports))
    node_port_count = np.zeros((max(1, n), PT), dtype=np.int32)

    cs = ClusterSide(
        wfp=wfp,
        hpaw=hpaw,
        nodes=list(nodes),
        nodes_fp=_nodes_fp(nodes),
        node_index=node_index,
        lab=lab,
        node_labels=node_labels,
        taints=taints,
        taint_objs=[t.Taint(tk, tv, te) for (tk, tv, te) in taints.items],
        node_taint_ns=node_taint_ns,
        node_taint_pref=node_taint_pref,
        alloc_raw=alloc_raw,
        used_raw=used_raw,
        breq_uniq_ids=breq_uniq_ids,
        breq_uniq=breq_uniq,
        voc=voc,
        terms_list=terms_list,
        node_dom=node_dom,
        term_key=term_key,
        term_counts0=term_counts0,
        anti_counts0=anti_counts0,
        pref_own0=pref_own0,
        bspec_ids=bspec_ids,
        m_cols=m_cols,
        bspec_anti=bspec_anti,
        bspec_pref=bspec_pref,
        node_port_count=node_port_count,
        records=records,
    )
    # batched application of every bound pod's contribution
    _apply_bound_batch(
        cs,
        np.array(rec_ni, dtype=np.int64),
        np.array(rec_req, dtype=np.int64),
        np.array(rec_spec, dtype=np.int64),
        list(records.values()),
        sign=1,
    )
    return cs


def _apply_bound_batch(
    cs: ClusterSide,
    ni: np.ndarray,
    req_u: np.ndarray,
    spec_u: np.ndarray,
    recs: List[_BoundRec],
    sign: int,
) -> None:
    """Scatter-add (sign=+1) or -subtract (sign=-1) a batch of bound-pod
    contributions.  All sums are integer-valued (weights are exact in f32 up
    to 2^24), so addition order cannot change the result — deltas stay
    bit-identical to a rebuild."""
    if len(ni) == 0:
        return
    s = np.int64(sign)
    np.add.at(
        cs.used_raw, ni, s * np.array(cs.breq_uniq, dtype=np.int64)[req_u]
    )
    if cs.terms_list:
        uniq, uinv = np.unique(spec_u, return_inverse=True)
        m_u = np.stack([cs.m_cols[int(u)] for u in uniq], axis=1)  # [T2, Uq]
        m = m_u[:, uinv]  # [T2, B]
        dom_cols = cs.node_dom[cs.term_key][:, ni]  # [T2, B]
        fs = np.float32(sign)
        for ti in np.flatnonzero(m_u.any(axis=1)):
            np.add.at(cs.term_counts0[ti], dom_cols[ti], fs * m[ti])
    for rec in recs:
        ni_r = rec[_NI]
        for ti in rec[_ANTI_IDS]:
            cs.anti_counts0[ti, cs.node_dom[cs.term_key[ti], ni_r]] += np.float32(sign)
        for ti, w in rec[_PREF]:
            cs.pref_own0[ti, cs.node_dom[cs.term_key[ti], ni_r]] += np.float32(
                sign
            ) * np.float32(w)
        for pid in rec[_PORT_IDS]:
            cs.node_port_count[ni_r, pid] += sign


def sync_bound(cs: ClusterSide, bound: Sequence[t.Pod]) -> None:
    """Absorb the bound-pod diff (binds + deletes since last cycle) into the
    resident cluster side.  Raises _Fallback when a new pod needs a vocabulary
    entry the cache lacks (new term / port / resource kind)."""
    from .snapshot import pod_effective_requests

    cur: Dict[str, t.Pod] = {}
    for q in bound:
        if q.node_name in cs.node_index:
            cur[q.uid] = q
    gone: List[str] = []
    new: List[t.Pod] = []
    for uid, rec in cs.records.items():
        q = cur.get(uid)
        if q is None:
            gone.append(uid)
        elif rec[_OBJ] is not q:
            # the pod OBJECT was replaced (update / re-nomination / a
            # volume-resolved copy): remove the old contribution, re-add the
            # new one — identity comparison keeps the steady state O(diff)
            gone.append(uid)
            new.append(q)
    for uid, q in cur.items():
        if uid not in cs.records:
            new.append(q)
    if not gone and not new:
        cs.last_dirty_nodes = _EMPTY_DIRTY
        return
    cs.stats["deltas"] += 1
    cs.mut_version += 1
    dirty_ni: List[int] = []
    if gone:
        recs = [cs.records.pop(uid) for uid in gone]
        dirty_ni.extend(r[_NI] for r in recs)
        _apply_bound_batch(
            cs,
            np.array([r[_NI] for r in recs], dtype=np.int64),
            np.array([r[_REQ_U] for r in recs], dtype=np.int64),
            np.array([r[_SPEC_U] for r in recs], dtype=np.int64),
            recs,
            sign=-1,
        )
    if new:
        resources = list(cs.wfp.resources)
        res_set = set(resources)
        fresh_specs: List[t.Pod] = []
        add_recs: List[_BoundRec] = []

        def _spec_info(q: t.Pod) -> Tuple[int, int, Tuple[int, ...]]:
            """(req_u, spec_u, port_ids) — the sorting-heavy part, computed
            once per unique spec."""
            if any(k not in res_set for k in q.requests):
                raise _Fallback("new resource kind from bound pod")
            rkey = tuple(sorted(q.requests.items()))
            ru = cs.breq_uniq_ids.get(rkey)
            if ru is None:
                ru = len(cs.breq_uniq)
                cs.breq_uniq_ids[rkey] = ru
                cs.breq_uniq.append(pod_effective_requests(q, resources))
            skey = _bound_spec_key(q)
            su = cs.bspec_ids.get(skey)
            if su is None:
                anti, pref = _bound_term_ids(cs.voc, q, cs.hpaw, intern=False)
                su = len(cs.bspec_ids)
                cs.bspec_ids[skey] = su
                cs.bspec_anti.append(anti)
                cs.bspec_pref.append(pref)
                fresh_specs.append(q)
            port_ids = []
            for pp in q.host_ports:
                pid = cs.voc.ports.get(pp)
                if pid is None:
                    raise _Fallback("new host port from bound pod")
                port_ids.append(pid)
            return ru, su, tuple(port_ids)

        # tight loop: a 50k-pod first-wave absorb runs this body 50k times on
        # the steady-state encode path — locals for every hot attribute
        wave_pop = cs.wave_ix.pop
        wave_store = cs.wave_store
        rb_get = cs.rep_bound_info.get
        rb = cs.rep_bound_info
        node_index = cs.node_index
        records = cs.records
        anti_l, pref_l = cs.bspec_anti, cs.bspec_pref
        append = add_recs.append
        for q in new:
            packed = wave_pop(q.uid, None)
            if packed is not None:
                wid = packed >> 32
                went = wave_store[wid]
                i = packed & 0xFFFFFFFF
                rep_i = went[1][went[2][i]]
                # pregrouped waves store no per-pod objects: the rep IS the
                # wave-time object (bind copies clone from it server-side)
                ent_wave = (
                    went[0][i] if went[0] is not None else rep_i, rep_i
                )
                went[3] -= 1  # drained waves release their pod lists
                if went[3] <= 0:
                    del wave_store[wid]
            else:
                ent_wave = None
            if (
                ent_wave is not None
                and not q.pvcs
                and not q.resource_claims
                # The rep stands in for the bound copy only while every field
                # _spec_info reads (BOUND_SPEC_FIELDS) is still equal to the
                # WAVE-TIME object's: pod labels are mutable metadata in the
                # reference API (unlike the spec), so a label update racing
                # the bind must not record a stale affinity contribution.
                and bound_spec_fields_match(q, ent_wave[0])
            ):
                # fast path: the pod was a recent wave's pending pod — its
                # spec is the rep's; bind-absorb is O(1) lookups.
                # Pods with volume/device claims take the slow path: their
                # RESOLVED spec (api/volumes.resolve_pod) can change between
                # pending and bound as PVC/PV state moves, so it must be
                # recomputed from the current resolved object.
                rep = ent_wave[1]
                ent = rb_get(id(rep))
                if ent is None or ent[0] is not rep:
                    # the entry VALUE holds the rep, so a live entry's id key
                    # can never alias a reallocated address; the `is` check
                    # guards the first insertion race all the same
                    ent = (rep, _spec_info(rep))
                    rb[id(rep)] = ent
                ru, su, port_ids = ent[1]
            else:
                ru, su, port_ids = _spec_info(q)
            rec = (
                node_index[q.node_name],
                ru,
                su,
                port_ids,
                anti_l[su],
                pref_l[su],
                q,
            )
            records[q.uid] = rec
            append(rec)
        if fresh_specs and cs.terms_list:
            m_new = _match_matrix(cs.terms_list, fresh_specs)
            for j in range(len(fresh_specs)):
                cs.m_cols.append(np.ascontiguousarray(m_new[:, j]))
        elif fresh_specs:
            cs.m_cols.extend(
                np.zeros(max(1, len(cs.terms_list)), dtype=np.float32)
                for _ in fresh_specs
            )
        dirty_ni.extend(r[_NI] for r in add_recs)
        _apply_bound_batch(
            cs,
            np.array([r[_NI] for r in add_recs], dtype=np.int64),
            np.array([r[_REQ_U] for r in add_recs], dtype=np.int64),
            np.array([r[_SPEC_U] for r in add_recs], dtype=np.int64),
            add_recs,
            sign=1,
        )
    # the O(changes) dirty-node set: every row this sync's scatter updates
    # touched (binds + deletes + replaced objects' re-absorbs)
    cs.last_dirty_nodes = np.unique(np.array(dirty_ni, dtype=np.int64))


# --------------------------------------------------------------------------
# the encoder
# --------------------------------------------------------------------------


def _wave_compatible(cs: ClusterSide, wfp: WaveFingerprint) -> bool:
    """A cached cluster side serves a new wave either EXACTLY (equal
    fingerprint → bit-identical to a fresh full encode) or as a SUPERSET
    (every vocabulary entry the wave needs already exists; surplus label
    literals / terms / ports / resource columns are inert, so the encoding is
    decision-identical — asserted by tests/test_delta_encoder.py)."""
    if cs.wfp == wfp:
        return True
    return (
        wfp.referenced_keys <= cs.wfp.referenced_keys
        and set(wfp.resources) <= set(cs.wfp.resources)
        and all(tk in cs.voc.terms for tk in wfp.term_seq)
        and all(pp in cs.voc.ports for pp in wfp.port_seq)
    )


class DeltaEncoder:
    """Watch-cache-shaped encoder: `encode(snap)` each scheduling cycle.

    Cycle cost is O(wave) + O(bound-pod diff); the cluster side rebuilds only
    on node-set changes, wave-fingerprint changes, or vocabulary growth.
    `encode_snapshot` (snapshot.py) is this class used one-shot."""

    def __init__(
        self,
        *,
        bucket: bool = True,
        hard_pod_affinity_weight: float = 1.0,
        debug_verify: bool = False,
        mesh=None,
    ):
        self.bucket = bucket
        self.hpaw = hard_pod_affinity_weight
        self._cs: Optional[ClusterSide] = None
        self._dev: Dict[str, Tuple] = {}  # field -> (host array, device array)
        self.stats = {"full": 0, "delta": 0, "verified": 0}
        # device mesh for resident-buffer placement (set_mesh): arrays are
        # placed per the partition rule table so the sharded step reads
        # them in place — warm deltas re-place only changed fields' shards
        self._mesh = None
        self._pad_memo: Dict[str, Tuple] = {}
        # memoized per-(width, sharding) device unpackers for the packed
        # bool-plane transfer path (_packed_put)
        self._unpack_jits: Dict[Tuple, object] = {}
        if mesh is not None:
            self.set_mesh(mesh)
        # Cache validity is conditioned on OBJECT IDENTITY (_nodes_fp, record
        # `is` checks) under the repo-wide copy-on-write convention for
        # Node/Pod; an in-place mutation anywhere would silently serve stale
        # encodings.  debug_verify (or KTPU_DELTA_VERIFY=1) cross-checks every
        # delta-path cycle against a fresh rebuild to catch that early.
        import os

        self.debug_verify = debug_verify or os.environ.get("KTPU_DELTA_VERIFY") == "1"
        # persistent identity-profile -> canonical spec key interning:
        # successive waves stamped from the same objects (or wire-interned
        # copies) share field objects, so per-pod canonical keying is paid
        # once per template, not once per pod per cycle
        from .snapshot import SpecInterner

        self._interner = SpecInterner()

    def encode_device(self, snap, fresh: bool = False):
        """encode(), with the ClusterArrays placed on device — fields whose
        host array is IDENTICAL (by object) to the previous cycle's reuse the
        resident device buffer, so a warm cluster re-transfers only the wave's
        pod-side arrays and the delta-touched cluster state.

        fresh=True transfers EVERY field anew and records nothing in the
        resident-buffer table — the donation-safe mode: a donated call
        invalidates its input buffers, so a resident buffer handed to a
        donating kernel would poison every later cycle that reuses it
        (ops/assign.py — schedule_batch_donated).  Fresh transfers are what
        makes the pipeline's two in-flight generations true double
        buffering: slot i's (donated) arrays live on device while slot i+1
        uploads."""
        return self._to_device(*self.encode(snap), fresh=fresh)

    def encode_device_pregrouped(
        self, nodes, bound_pods, pod_groups, uids, reps, inv
    ):
        """encode_pregrouped() + the same resident-device-buffer reuse as
        encode_device()."""
        return self._to_device(
            *self.encode_pregrouped(
                nodes, bound_pods, pod_groups, uids, reps, inv
            )
        )

    def to_device(self, arr, meta, fresh: bool = False):
        """Public device placement for callers that need the HOST arrays
        first (e.g. infer_score_config inspects concrete numpy before the
        transfer): encode() -> inspect -> to_device().  Same resident-buffer
        reuse as encode_device(); fresh=True is the donation-safe mode."""
        return self._to_device(arr, meta, fresh=fresh)

    def drop_device_buffers(self) -> None:
        """Forget every resident device buffer (next encode re-transfers).
        Callers that mix donated and non-donated cycles MUST call this after
        a donated call that consumed resident buffers; the pipeline loop
        avoids the problem entirely with encode_device(fresh=True)."""
        self._dev.clear()

    def set_mesh(self, mesh) -> None:
        """Place all subsequent device buffers over `mesh`: every field's
        sharding resolved through the declarative partition rule table
        (parallel/partition_rules.py, via field_shardings) — so a
        mesh-routed step (ops/assign.py —
        schedule_batch_routed(mesh=)) reads the RESIDENT shards in place and
        a warm-cycle delta re-places only the changed fields, never
        gathering or re-scattering the cluster side.  Node counts not
        divisible by the mesh are padded with permanently invalid nodes at
        placement time (parallel/mesh.py padding semantics), memoized by
        host-array identity so the resident-reuse table still hits.
        Changing the mesh drops the resident buffers (old placement)."""
        if mesh is not self._mesh:
            self._mesh = mesh
            self._dev.clear()
            self._pad_memo.clear()
            self._unpack_jits.clear()

    def _pad_for_mesh(self, name: str, a, pad: int, d_sentinel: int, n: int,
                      pod_pad: int = 0):
        """Per-field node- and pod-axis padding (the one shared rule set —
        parallel/mesh.py pad_field / pad_pod_field), memoized by input-array
        identity so unchanged fields keep one stable padded object across
        cycles (the resident-buffer identity check depends on it).  Padding
        host-side here makes the routed entry's pad_nodes/pad_pods no-ops,
        so the device-resident buffers are never re-padded mid-flight."""
        from ..parallel.mesh import pad_field, pad_pod_field

        memo = self._pad_memo.get(name)
        if memo is not None and memo[0] is a:
            return memo[1]
        p = pad_pod_field(name, a, pod_pad) if pod_pad else a
        p = pad_field(name, p, pad, d_sentinel, n) if pad else p
        if p is a:
            return a
        self._pad_memo[name] = (a, p)
        return p

    def _packed_put(self, a: np.ndarray, sharding):
        """Transfer a wide boolean matrix as PACKED uint32 words and unpack
        on device (ops/bitplane.py): host->device bytes drop 8x while the
        resident buffer stays the dense bool plane the kernels read.  Safe
        for every bool field because none shards its LAST axis (the rule
        table shards leading axes or replicates), so the word transfer can
        ride the target's own sharding and the jitted unpack is shard-local
        (out_shardings pins the dense result in place — no resharding).
        The per-(n, sharding) jitted unpackers are memoized so the warm
        path never re-traces."""
        import jax

        from ..ops import bitplane

        n_last = a.shape[-1]
        words = bitplane.np_pack_lastaxis(a)
        wd = jax.device_put(words, sharding) if sharding is not None \
            else jax.device_put(words)
        key = (n_last, sharding)
        fn = self._unpack_jits.get(key)
        if fn is None:
            kw = {"out_shardings": sharding} if sharding is not None else {}
            fn = jax.jit(lambda w: bitplane.unpack(w, n_last), **kw)
            self._unpack_jits[key] = fn
        return fn(wd)

    def _to_device(self, arr, meta, fresh: bool = False):
        import dataclasses as _dc

        import jax

        from ..ops import bitplane

        mesh = self._mesh
        if mesh is not None:
            from ..parallel.mesh import mesh_axis_shards
            from ..parallel.sharded import field_shardings

            pod_shards, n_shards = mesh_axis_shards(mesh)
            pad = (-arr.N) % n_shards
            pod_pad = (-arr.P) % pod_shards
            d_sentinel = arr.term_counts0.shape[1] - 1
            sh = field_shardings(mesh, arr.image_score.shape[1] == arr.N)
            n = arr.N
        out = {}
        for f in _dc.fields(type(arr)):
            a = getattr(arr, f.name)
            s = sh[f.name] if mesh is not None else None
            if mesh is not None and (pad or pod_pad):
                a = self._pad_for_mesh(
                    f.name, a, pad, d_sentinel, n, pod_pad=pod_pad
                )
            if (
                bitplane.PACK_MASKS
                and isinstance(a, np.ndarray)
                and a.dtype == np.bool_
                and a.ndim >= 2
                and a.shape[-1] >= 64
            ):
                put = lambda x, _s=s: self._packed_put(x, _s)  # noqa: E731
            elif s is not None:
                put = lambda x, _s=s: jax.device_put(x, _s)  # noqa: E731
            else:
                put = jax.device_put
            if fresh:
                out[f.name] = put(a)
                continue
            ent = self._dev.get(f.name)
            if ent is not None and (
                ent[0] is a
                # value dedup: steady-state waves from one template family
                # produce bit-identical pod-side arrays — a host memcmp
                # (~µs/MB) is far cheaper than re-transfer over PCIe/tunnel
                or (
                    ent[0].shape == a.shape
                    and ent[0].dtype == a.dtype
                    and np.array_equal(ent[0], a)
                )
            ):
                out[f.name] = ent[1]
            else:
                d = put(a)
                self._dev[f.name] = (a, d)
                out[f.name] = d
        return type(arr)(**out), meta

    def _group_cached(self, pods):
        """group_by_spec through the encoder-resident SpecInterner: same
        reps/inv as snapshot.group_by_spec (bit-identical arrays), plus each
        rep's canonical key (the pod-side cache key input)."""
        return self._interner.group(pods)

    def encode(self, snap):
        from .snapshot import _resource_axis, activeq_order
        from .volumes import resolve_snapshot

        raw_nodes_fp, storage_fp = raw_fingerprints(snap)
        raw_snap = snap  # rebuilds capture keep-alive refs from the raw snap
        snap = resolve_snapshot(snap)
        pending = snap.pending_pods
        perm = activeq_order(pending)
        sorted_pending = [pending[i] for i in perm]
        reps, inv, rep_keys = self._group_cached(sorted_pending)
        resources = _resource_axis(snap)
        return self._encode_core(
            snap, (raw_nodes_fp, storage_fp), raw_snap, reps, inv, perm,
            rep_keys, resources,
            wave_uids=[p.uid for p in sorted_pending],
            wave_pods=sorted_pending,
        )

    def encode_pregrouped(
        self, nodes, bound_pods, pod_groups, uids, reps, inv
    ):
        """The sidecar session path: the wire ships the wave already
        INTERNED (spec reps + per-pod spec index + uids, convert.py —
        wave_parts_from_proto) and volume/DRA constraints already resolved
        client-side (D10), so the wave is encoded WITHOUT materializing one
        pod object per pending pod — at 50k pods/wave the clone loop alone
        was the largest host cost on the wire path.

        `reps` SHOULD be identity-stable across waves (the sidecar's
        per-session rep cache) so the rep-key memo and the pad cache hit;
        fresh objects only cost re-canonicalization, never correctness."""
        import numpy as np

        from .snapshot import _resource_axis

        inv = np.asarray(inv, dtype=np.int64)
        # activeQ order from rep priorities (activeq_order on materialized
        # pods reads the same field)
        prio = (
            np.array([r.priority for r in reps], dtype=np.int64)[inv]
            if len(reps)
            else np.zeros(len(uids), dtype=np.int64)
        )
        perm = np.argsort(-prio, kind="stable")
        inv_sorted = inv[perm]
        uids_sorted = [uids[i] for i in perm]
        rep_keys = tuple(self._rep_key(r) for r in reps)
        shim = _WaveView(
            nodes=nodes, pending_pods=(), bound_pods=bound_pods,
            pod_groups=pod_groups,
        )
        # resource axis via the one shared first-seen rule (snapshot.py —
        # _resource_axis); reps stand in for the pending pods
        resources = _resource_axis(
            _WaveView(
                nodes=nodes, pending_pods=tuple(reps),
                bound_pods=bound_pods, pod_groups=pod_groups,
            )
        )
        fps = raw_fingerprints(shim)
        return self._encode_core(
            shim, fps, shim, reps, inv_sorted, perm, rep_keys, resources,
            wave_uids=uids_sorted, wave_pods=None,
        )

    def _rep_key(self, rep):
        """Canonical spec key per rep, memoized by object identity (the
        sidecar rep cache keeps reps alive and stable across waves)."""
        memo = getattr(self, "_rep_key_memo", None)
        if memo is None:
            memo = self._rep_key_memo = {}
        ent = memo.get(id(rep))
        if ent is not None and ent[1] is rep:
            return ent[0]
        if len(memo) > 65536:
            memo.clear()
        from .snapshot import _pod_spec_key

        key = _pod_spec_key(rep)
        memo[id(rep)] = (key, rep)
        return key

    def _encode_core(
        self, snap, fps, raw_snap, reps, inv, perm, rep_keys, resources,
        wave_uids, wave_pods,
    ):
        """Shared tail of encode()/encode_pregrouped(): cluster-side reuse or
        rebuild, bound-pod sync, wave bind-absorb bookkeeping, assembly.
        `wave_pods` is None on the pregrouped path — bind-absorb then
        revalidates bound copies against the REP (bind copies are cloned from
        the rep server-side, so the field-identity checks still hold)."""
        raw_nodes_fp, storage_fp = fps
        wfp = wave_fingerprint(reps, resources)

        cs = self._cs
        if (
            cs is not None
            and cs.hpaw == self.hpaw
            and cs.raw_nodes_fp == raw_nodes_fp
            and cs.storage_fp == storage_fp
            and _wave_compatible(cs, wfp)
        ):
            try:
                sync_bound(cs, snap.bound_pods)
                self.stats["delta"] += 1
                if self.debug_verify:
                    self._verify_against_rebuild(cs, snap, wfp)
                    self.stats["verified"] += 1
            except _Fallback:
                cs = None
        else:
            cs = None
        if cs is None:
            cs = build_cluster_side(snap.nodes, snap.bound_pods, wfp, self.hpaw)
            cs.raw_nodes_fp = raw_nodes_fp
            cs.storage_fp = storage_fp
            # keep-alive refs for every id() the fingerprints hold (built only
            # here — steady-state delta cycles must not copy 20k-element lists)
            cs.raw_refs = raw_keepalive_refs(raw_snap)
            cs.stats["rebuilds"] += 1
            self._cs = cs
            self.stats["full"] += 1
        # remember this wave's spec reps so the next cycle's bind-absorb is
        # O(1) per pod; size-capped so never-scheduled uids can't accumulate
        # unboundedly (evicted uids just re-take the per-pod slow path)
        if len(cs.wave_ix) > 4 * (len(cs.records) + len(wave_uids) + 1024):
            cs.wave_ix.clear()
            cs.wave_store.clear()
            cs.rep_bound_info.clear()
        wid = cs.wave_next
        cs.wave_next = wid + 1
        cs.wave_store[wid] = [wave_pods, reps, inv.tolist(), len(wave_uids)]
        base = wid << 32
        cs.wave_ix.update(
            zip(wave_uids, map(base.__or__, range(len(wave_uids))))
        )
        # waves drain by refcount as their pods bind (sync_bound), but a
        # STABLE backlog re-pends the same uids every cycle — wave_ix slots
        # get overwritten, never popped, and the superseded waves' pod lists
        # would accumulate forever.  When more than a handful of waves are
        # retained, sweep the ones no index entry references anymore.
        if len(cs.wave_store) > 8:
            live = {v >> 32 for v in cs.wave_ix.values()}
            for w in [w for w in cs.wave_store if w not in live]:
                del cs.wave_store[w]
        return _assemble(
            cs, snap, reps, inv, perm, self.bucket, rep_keys,
            wave_names=wave_uids if wave_pods is None else None,
        )

    @staticmethod
    def _verify_against_rebuild(cs: ClusterSide, snap, wfp: WaveFingerprint) -> None:
        """debug_verify: the synced cluster side must equal a fresh rebuild
        (catches identity-fingerprint violations — in-place Node/Pod mutation
        that the id()-based cache checks cannot see).  Note the rebuild uses
        the CURRENT wave's fingerprint: under superset reuse (_wave_compatible)
        cs vocab axes may be strict supersets, so compare on the fresh side's
        prefix — decisions are unaffected (documented on EncodingMeta)."""
        fresh = build_cluster_side(snap.nodes, snap.bound_pods, cs.wfp, cs.hpaw)
        for name in ("used_raw", "term_counts0", "anti_counts0", "pref_own0",
                     "node_port_count"):
            a, b = getattr(cs, name), getattr(fresh, name)
            if a.shape != b.shape:
                # vocab drift (e.g. departed bound pods whose terms stay
                # interned in cs) — sizes are legitimately supersets; only
                # equal-shape cycles are comparable
                continue
            if not np.array_equal(a, b):
                raise AssertionError(
                    f"delta debug_verify: {name} diverged from rebuild "
                    "(in-place Node/Pod mutation defeating the identity "
                    "fingerprint?)"
                )


def class_groups(meta, rows):
    """Decode-side class plumbing for the diagnosis plane (ops/explain.py):
    group device pod rows by their equivalence class — every pod of one
    class shares its spec rep bit-for-bit (_pod_side builds pod arrays per
    unique spec), so one diagnosis per class serves all its pods.

    Returns (reps i64[F] — the first seen row of each distinct class, in
    first-appearance order; row -> rep position).  Falls back to one class
    per distinct row when the encode carried no class index (the plain
    encode_snapshot path)."""
    first: dict = {}
    group_of: dict = {}
    reps: list = []
    cls_of = meta.pod_class
    for r in rows:
        r = int(r)
        c = int(cls_of[r]) if cls_of is not None else r
        g = first.get(c)
        if g is None:
            g = first[c] = len(reps)
            reps.append(r)
        group_of[r] = g
    return np.asarray(reps, dtype=np.int64), group_of


def _cached(cs: ClusterSide, name: str, key, builder):
    """Padded-array cache: rebuild only when `key` changes, else return the
    SAME object (numpy identity drives encode_device's transfer skipping).
    Cached arrays are never mutated in place — syncs bump mut_version and the
    next key mismatch builds a fresh copy."""
    ent = cs.pad_cache.get(name)
    if ent is not None and ent[0] == key:
        return ent[1]
    a = builder()
    cs.pad_cache[name] = (key, a)
    return a


def _pod_side(cs, snap, reps, inv, p, P, N, T, L, req_s):
    """All wave-derived (pod-side) arrays as one dict — built per unique
    spec and scattered through inv; cacheable as a unit (see _assemble).
    reference: the per-cycle half of backend/cache/snapshot.go —
    UpdateSnapshot, recast columnar.

    `inv` (scattered into IncState.cls by the hoist cache) is the class
    grouping the commit-wave stage batches on (ops/assign.py —
    _wave_commit_stage): pods sharing a spec share a class row, so the
    wave commits them off one top-k candidate list instead of one
    contention round each.  Nothing here changes for that — the class
    index was already exact — but the grouping is now load-bearing for
    ordinals, so spec-key completeness (pod_group included) is pinned by
    tests/test_class_waves.py in addition to test_incremental.py."""
    from .snapshot import _image_score_matrix, _round_up_pow2

    U = len(reps)
    R = len(cs.wfp.resources)
    pod_valid = np.zeros(P, dtype=bool)
    pod_req = np.zeros((P, R), dtype=np.int32)
    pod_req[:p] = req_s
    pod_prio = np.zeros(P, dtype=np.int32)
    pod_tol_ns = np.ones((P, T), dtype=bool)
    pod_tol_pref = np.ones((P, T), dtype=bool)
    pod_nodename = np.full(P, -1, dtype=np.int32)

    table = v.TermTable()
    pod_term_lists: List[List[int]] = []
    pref_lists: List[List[Tuple[int, float]]] = []
    u_valid = np.empty(max(1, U), dtype=bool)
    u_prio = np.zeros(max(1, U), dtype=np.int32)
    u_tol_ns = np.ones((max(1, U), T), dtype=bool)
    u_tol_pref = np.ones((max(1, U), T), dtype=bool)
    u_nodename = np.full(max(1, U), -1, dtype=np.int32)
    taint_objs = cs.taint_objs
    taint_is_pref = np.array(
        [tn.effect == t.PREFER_NO_SCHEDULE for tn in taint_objs], dtype=bool
    )
    for ui, pod in enumerate(reps):
        u_valid[ui] = not pod.scheduling_gates
        u_prio[ui] = pod.priority
        if pod.tolerations:
            for tid, taint in enumerate(taint_objs):
                tol = any(tol.tolerates(taint) for tol in pod.tolerations)
                if taint.effect == t.PREFER_NO_SCHEDULE:
                    u_tol_pref[ui, tid] = tol
                else:
                    u_tol_ns[ui, tid] = tol
        elif taint_objs:
            u_tol_ns[ui] = taint_is_pref
            u_tol_pref[ui] = ~taint_is_pref
        if pod.node_name:
            u_nodename[ui] = cs.node_index.get(pod.node_name, -2)
        terms = v.pod_required_node_terms(pod, cs.lab)
        pod_term_lists.append(
            [] if terms is None else [table.intern(tm) for tm in terms]
        )
        prefs: List[Tuple[int, float]] = []
        if pod.affinity:
            for pt in pod.affinity.preferred_node_terms:
                if pt.preference.match_expressions:
                    prefs.append(
                        (
                            table.intern(
                                v.lower_node_term(pt.preference.match_expressions, cs.lab)
                            ),
                            float(pt.weight),
                        )
                    )
        pref_lists.append(prefs)
    if p:
        pod_valid[:p] = u_valid[inv]
        pod_prio[:p] = u_prio[inv]
        pod_tol_ns[:p] = u_tol_ns[inv]
        pod_tol_pref[:p] = u_tol_pref[inv]
        pod_nodename[:p] = u_nodename[inv]

    TT = max(1, max((len(x) for x in pod_term_lists), default=1))
    u_terms = np.full((max(1, U), TT), -1, dtype=np.int32)
    u_has_sel = np.zeros(max(1, U), dtype=bool)
    for ui, ids in enumerate(pod_term_lists):
        if ids:
            u_has_sel[ui] = True
            u_terms[ui, : len(ids)] = ids
    pod_terms = np.full((P, TT), -1, dtype=np.int32)
    pod_has_sel = np.zeros(P, dtype=bool)
    if p:
        pod_terms[:p] = u_terms[inv]
        pod_has_sel[:p] = u_has_sel[inv]

    PW = max(1, max((len(x) for x in pref_lists), default=1))
    u_pref_terms = np.full((max(1, U), PW), -1, dtype=np.int32)
    u_pref_weights = np.zeros((max(1, U), PW), dtype=np.float32)
    for ui, prefs in enumerate(pref_lists):
        for a, (tid, w) in enumerate(prefs):
            u_pref_terms[ui, a] = tid
            u_pref_weights[ui, a] = w
    pod_pref_terms = np.full((P, PW), -1, dtype=np.int32)
    pod_pref_weights = np.zeros((P, PW), dtype=np.float32)
    if p:
        pod_pref_terms[:p] = u_pref_terms[inv]
        pod_pref_weights[:p] = u_pref_weights[inv]

    sel_mask, sel_kind = table.encode(L)

    # --- gangs ---
    group_ids = v.Interner()
    u_group = np.full(max(1, U), -1, dtype=np.int32)
    for ui, pod in enumerate(reps):
        if pod.pod_group:
            u_group[ui] = group_ids.intern(pod.pod_group)
    pod_group = np.full(P, -1, dtype=np.int32)
    if p:
        pod_group[:p] = u_group[inv]
    G = max(1, len(group_ids))
    group_min = np.ones(G, dtype=np.int32)
    if len(group_ids):
        counts = np.bincount(pod_group[pod_group >= 0], minlength=G)
        for gi, gname in enumerate(group_ids.items):
            pg = snap.pod_groups.get(gname)
            group_min[gi] = pg.min_member if pg else int(counts[gi])

    # --- pairwise wave side against the resident vocab/counts ---
    T2 = max(1, len(cs.voc.terms))

    pod_aff: List[List[int]] = []
    pod_anti: List[List[int]] = []
    pod_prefp: List[List[Tuple[int, float]]] = []
    pod_spread: List[List[Tuple[int, int, int]]] = []
    for pod in reps:
        aff, anti, pref, spread = _pod_pairwise_terms(pod)
        pod_aff.append([cs.voc.terms.get(tk) for tk in aff])
        pod_anti.append([cs.voc.terms.get(tk) for tk in anti])
        pod_prefp.append([(cs.voc.terms.get(tk), w) for tk, w in pref])
        pod_spread.append(
            [(cs.voc.terms.get(tk), skew, mode) for tk, skew, mode in spread]
        )

    m_pend = np.zeros((T2, P), dtype=np.float32)
    m_uniq = None
    if p and cs.terms_list:
        m_uniq = _match_matrix(cs.terms_list, list(reps))  # [T2, U]
        m_pend[:, :p] = m_uniq[:, inv]

    A1 = max(1, max((len(x) for x in pod_aff), default=1))
    A2 = max(1, max((len(x) for x in pod_anti), default=1))
    B = max(1, max((len(x) for x in pod_prefp), default=1))
    C = max(1, max((len(x) for x in pod_spread), default=1))
    Uq = max(1, U)
    u_aff = np.full((Uq, A1), -1, dtype=np.int32)
    u_anti = np.full((Uq, A2), -1, dtype=np.int32)
    u_pref_t = np.full((Uq, B), -1, dtype=np.int32)
    u_pref_w = np.zeros((Uq, B), dtype=np.float32)
    u_spread_t = np.full((Uq, C), -1, dtype=np.int32)
    u_spread_skew = np.zeros((Uq, C), dtype=np.int32)
    u_spread_hard = np.zeros((Uq, C), dtype=bool)
    for ui in range(U):
        for a, ti in enumerate(pod_aff[ui]):
            u_aff[ui, a] = ti
        for a, ti in enumerate(pod_anti[ui]):
            u_anti[ui, a] = ti
        for a, (ti, w) in enumerate(pod_prefp[ui]):
            u_pref_t[ui, a] = ti
            u_pref_w[ui, a] = np.float32(w)
        for c, (ti, skew, mode) in enumerate(pod_spread[ui]):
            u_spread_t[ui, c] = ti
            u_spread_skew[ui, c] = skew
            u_spread_hard[ui, c] = mode == HARD
    # matched-term slots: per unique spec, the nonzero entries of its m_pend
    # column (M bucketed to a power of two to bound recompiles); plus the
    # self-match bit per own required-affinity slot (the waiver's input)
    MM = 1
    u_mt = np.full((Uq, 1), -1, dtype=np.int32)
    u_mv = np.zeros((Uq, 1), dtype=np.float32)
    u_aself = np.zeros((Uq, A1), dtype=bool)
    if m_uniq is not None:
        nz = [np.flatnonzero(m_uniq[:, ui]) for ui in range(U)]
        MM = _round_up_pow2(max((len(z) for z in nz), default=1), minimum=1)
        u_mt = np.full((Uq, MM), -1, dtype=np.int32)
        u_mv = np.zeros((Uq, MM), dtype=np.float32)
        for ui, z in enumerate(nz):
            u_mt[ui, : len(z)] = z
            u_mv[ui, : len(z)] = m_uniq[z, ui]
        rows, cols = np.nonzero(u_aff[:U] >= 0) if U else (np.array([], int),) * 2
        if len(rows):
            u_aself[rows, cols] = m_uniq[u_aff[rows, cols], rows] > 0
    pod_match_terms = np.full((P, MM), -1, dtype=np.int32)
    pod_match_vals = np.zeros((P, MM), dtype=np.float32)
    pod_aff_self = np.zeros((P, A1), dtype=bool)

    pod_aff_terms = np.full((P, A1), -1, dtype=np.int32)
    pod_anti_terms = np.full((P, A2), -1, dtype=np.int32)
    pod_pref_aff_terms = np.full((P, B), -1, dtype=np.int32)
    pod_pref_aff_w = np.zeros((P, B), dtype=np.float32)
    pod_spread_terms = np.full((P, C), -1, dtype=np.int32)
    pod_spread_maxskew = np.zeros((P, C), dtype=np.int32)
    pod_spread_hard = np.zeros((P, C), dtype=bool)
    if p:
        pod_match_terms[:p] = u_mt[inv]
        pod_match_vals[:p] = u_mv[inv]
        pod_aff_self[:p] = u_aself[inv]
        pod_aff_terms[:p] = u_aff[inv]
        pod_anti_terms[:p] = u_anti[inv]
        pod_pref_aff_terms[:p] = u_pref_t[inv]
        pod_pref_aff_w[:p] = u_pref_w[inv]
        pod_spread_terms[:p] = u_spread_t[inv]
        pod_spread_maxskew[:p] = u_spread_skew[inv]
        pod_spread_hard[:p] = u_spread_hard[inv]

    # --- ports ---
    PT = cs.node_port_count.shape[1]
    u_ports = np.zeros((Uq, PT), dtype=bool)
    for ui, pod in enumerate(reps):
        for pp in pod.host_ports:
            u_ports[ui, cs.voc.ports.get(pp)] = True
    pod_ports = np.zeros((P, PT), dtype=bool)
    if p:
        pod_ports[:p] = u_ports[inv]

    return dict(
        pod_valid=pod_valid,
        pod_req=pod_req,
        pod_prio=pod_prio,
        pod_tol_ns=pod_tol_ns,
        pod_tol_pref=pod_tol_pref,
        pod_nodename=pod_nodename,
        pod_terms=pod_terms,
        pod_has_sel=pod_has_sel,
        sel_mask=sel_mask,
        sel_kind=sel_kind,
        pod_pref_terms=pod_pref_terms,
        pod_pref_weights=pod_pref_weights,
        pod_group=pod_group,
        group_min=group_min,
        image_score=_image_score_matrix(cs.nodes, reps, inv, N, P),
        m_pend=m_pend,
        pod_match_terms=pod_match_terms,
        pod_match_vals=pod_match_vals,
        pod_aff_self=pod_aff_self,
        pod_aff_terms=pod_aff_terms,
        pod_anti_terms=pod_anti_terms,
        pod_pref_aff_terms=pod_pref_aff_terms,
        pod_pref_aff_w=pod_pref_aff_w,
        pod_spread_terms=pod_spread_terms,
        pod_spread_maxskew=pod_spread_maxskew,
        pod_spread_hard=pod_spread_hard,
        pod_ports=pod_ports,
    )


def _assemble(
    cs: ClusterSide,
    snap,
    reps: Sequence[t.Pod],
    inv: np.ndarray,
    perm: np.ndarray,
    bucket: bool,
    rep_keys: Optional[Tuple] = None,
    wave_names: Optional[List[str]] = None,
):
    """Build the wave (pod-side) arrays against the resident cluster side and
    assemble the full ClusterArrays + EncodingMeta.

    When `rep_keys` (each rep's canonical spec key) is given and matches the
    previous cycle's (same specs, same inv, same padding/scale/groups), the
    ENTIRE pod-side array set is reused from cs.pad_cache — steady-state waves
    stamped from one template family cost only the cluster-side sync."""
    from .snapshot import (
        _INT32_MAX,
        _bucket,
        _image_score_matrix,
        _round_up_pow2,
        _scale_for,
        ClusterArrays,
        EncodingMeta,
        pod_effective_requests,
    )

    nodes = cs.nodes
    pending = snap.pending_pods
    n = len(nodes)
    # pregrouped waves carry no per-pod objects: names/uids arrive directly
    # (sorted order — the perm was already applied by the caller)
    p = len(wave_names) if wave_names is not None else len(pending)
    N = _bucket(n) if bucket else max(1, n)
    P = _bucket(p) if bucket else max(1, p)
    resources = list(cs.wfp.resources)
    R = len(resources)
    U = len(reps)

    # --- resources: scale re-derived from raw each cycle (bit-exact) ---
    req_uniq = (
        np.array([pod_effective_requests(rp, resources) for rp in reps], dtype=np.int64)
        if U
        else np.zeros((1, R), dtype=np.int64)
    )
    req_raw = req_uniq[inv] if p else np.zeros((0, R), dtype=np.int64)
    alloc_uniq = np.unique(cs.alloc_raw, axis=0) if n else np.zeros((1, R), np.int64)
    scale = np.ones(R, dtype=np.int64)
    stacked = np.concatenate([alloc_uniq, req_uniq, cs.used_raw], axis=0)
    for j in range(R):
        scale[j] = _scale_for(stacked[:, j])
    req_s = -(-req_raw // scale)
    used_s = -(-cs.used_raw // scale)
    alloc_s = cs.alloc_raw // scale

    skey = tuple(scale.tolist())

    def _pad2(src, dtype, fill=0):
        out = np.full((N, src.shape[1]), fill, dtype=dtype)
        out[:n] = src
        return out

    node_alloc = _cached(cs, "node_alloc", (N, skey), lambda: _pad2(alloc_s, np.int32))
    node_used = _cached(
        cs, "node_used", (N, skey, cs.mut_version), lambda: _pad2(used_s, np.int32)
    )

    def _valid():
        a = np.zeros(N, dtype=bool)
        a[:n] = True
        return a

    node_valid = _cached(cs, "node_valid", N, _valid)

    def _unsched():
        a = np.zeros(N, dtype=bool)
        a[:n] = [nd.unschedulable for nd in nodes]
        return a

    node_unsched = _cached(cs, "node_unsched", N, _unsched)

    L = cs.node_labels.shape[1]
    node_labels = _cached(
        cs, "node_labels", N, lambda: _pad2(cs.node_labels, np.float32)
    )
    T = cs.node_taint_ns.shape[1]
    node_taint_ns = _cached(
        cs, "node_taint_ns", N, lambda: _pad2(cs.node_taint_ns, bool)
    )
    node_taint_pref = _cached(
        cs, "node_taint_pref", N, lambda: _pad2(cs.node_taint_pref, bool)
    )

    # --- pod side (all per unique spec, scattered through inv) ---
    groups_key = tuple(
        sorted((g.name, g.min_member) for g in snap.pod_groups.values())
    )
    pod_key = (
        (rep_keys, inv.tobytes(), P, skey, groups_key)
        if rep_keys is not None
        else None
    )
    ent = cs.pad_cache.get("podside") if pod_key is not None else None
    if ent is not None and ent[0] == pod_key:
        ps = ent[1]
    else:
        ps = _pod_side(cs, snap, reps, inv, p, P, N, T, L, req_s)
        if pod_key is not None:
            cs.pad_cache["podside"] = (pod_key, ps)

    T2 = max(1, len(cs.voc.terms))
    K = cs.node_dom.shape[0]
    D1 = cs.term_counts0.shape[1]

    def _dom():
        a = np.full((K, N), D1 - 1, dtype=np.int32)
        if n:
            a[:, :n] = cs.node_dom[:, :n]
        return a

    node_dom = _cached(cs, "node_dom", N, _dom)
    node_ports0 = _cached(
        cs,
        "node_ports0",
        (N, cs.mut_version),
        lambda: _pad2(cs.node_port_count > 0, bool),
    )

    # --- equivalence classes (ops/incremental.py): per-pod class index +
    # first-occurrence row per class.  inv IS the class map (one class per
    # unique spec rep); bucketing padding gets one extra all-padding class.
    # Cached so the arrays are identity-stable across steady-state waves —
    # the HoistCache's invalidation fingerprint depends on it. ---
    def _class_index():
        pc = np.full(P, U, dtype=np.int32)
        if p:
            pc[:p] = inv
        padded = P > p
        first = np.zeros(U + (1 if padded else 0), dtype=np.int64)
        if U:
            uu, fi = np.unique(inv, return_index=True)
            first[uu] = fi  # every rep occurs in inv by construction
        if padded:
            first[U] = p  # any padded row — all are identical
        return pc, first

    pod_class, class_first = _cached(
        cs, "class_index", (P, U, p, inv.tobytes()), _class_index
    )

    arrays = ClusterArrays(
        node_valid=node_valid,
        node_alloc=node_alloc,
        node_used=node_used,
        node_unsched=node_unsched,
        node_labels=node_labels,
        node_taint_ns=node_taint_ns,
        node_taint_pref=node_taint_pref,
        node_dom=node_dom,
        term_key=_cached(cs, "term_key", 0, cs.term_key.copy),
        term_counts0=_cached(
            cs, "term_counts0", cs.mut_version, cs.term_counts0.copy
        ),
        anti_counts0=_cached(
            cs, "anti_counts0", cs.mut_version, cs.anti_counts0.copy
        ),
        pref_own0=_cached(cs, "pref_own0", cs.mut_version, cs.pref_own0.copy),
        node_ports0=node_ports0,
        **ps,
    )
    meta = EncodingMeta(
        node_names=[nd.name for nd in nodes],
        pod_names=(
            list(wave_names)
            if wave_names is not None
            else [pending[i].name for i in perm]
        ),
        pod_perm=perm,
        resources=resources,
        resource_scale=scale,
        label_vocab=cs.lab,
        taint_vocab=cs.taints,
        pairwise_vocab=cs.voc,
        n_nodes=n,
        n_pods=p,
        pod_class=pod_class,
        class_first_pod=class_first,
        n_classes=int(class_first.shape[0]),
        dirty_nodes=cs.last_dirty_nodes,
    )
    return arrays, meta
